"""AsyncCheckpointer: the double-buffered background snapshot writer.

Acceptance contract (ISSUE 3):

* ``save()`` returns without blocking on serialize+fsync (asserted
  against a slow-serialize fake);
* publication stays atomic under the writer (flush barrier, enqueued vs
  saved journal ordering, ``latest_valid_step`` monotone);
* the compiled training program is bit-identical with the async writer
  (and the supervised-heartbeat recorder) attached or detached;
* a training run checkpointed through the async writer produces
  byte-identical snapshots to the synchronous Checkpointer.

The SIGKILL-mid-background-write end-to-end lives in
``tests/test_checkpoint.py`` next to its kill-resume siblings (slow).
"""

import json
import os
import threading
import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jaxmods():
    import jax

    from fps_tpu.core import checkpoint as ck
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    return dict(jax=jax, ck=ck, num_workers_of=num_workers_of,
                epoch_chunks=epoch_chunks, MFConfig=MFConfig,
                online_mf=online_mf, make_ps_mesh=make_ps_mesh,
                synthetic_ratings=synthetic_ratings)


def _mf(jaxmods, num_shards=4):
    jax = jaxmods["jax"]
    mesh = jaxmods["make_ps_mesh"](
        num_shards=num_shards, num_data=1,
        devices=jax.devices()[:num_shards])
    cfg = jaxmods["MFConfig"](num_users=32, num_items=24, rank=4)
    trainer, store = jaxmods["online_mf"](mesh, cfg, donate=False)
    return mesh, cfg, trainer, store


def _chunks(jaxmods, W=4):
    data = jaxmods["synthetic_ratings"](32, 24, 4 * W * 8 * 2, seed=3)
    return list(jaxmods["epoch_chunks"](
        data, num_workers=W, local_batch=8, steps_per_chunk=2,
        route_key="user", seed=0))[:4]


def _slow_savez(jaxmods, monkeypatch, delay_s, started=None):
    """Monkeypatch the module-level _atomic_savez with a slow wrapper
    (the writer thread resolves it at call time, so this slows the
    BACKGROUND write, not the enqueue)."""
    ck = jaxmods["ck"]
    real = ck._atomic_savez

    def slow(path, arrays, precommit=None):
        if started is not None:
            started.set()
        time.sleep(delay_s)
        return real(path, arrays, precommit)

    monkeypatch.setattr(ck, "_atomic_savez", slow)
    return real


def test_save_returns_without_blocking(tmp_path, jaxmods, devices8,
                                       monkeypatch):
    """THE acceptance assertion: with serialize+fsync faked slow (1s),
    save() returns in a fraction of that; flush() is what waits."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, trainer, store = _mf(jaxmods)
    store.init(jax.random.key(0))
    started = threading.Event()
    _slow_savez(jaxmods, monkeypatch, 1.0, started)

    with ck.AsyncCheckpointer(str(tmp_path / "c"), keep=3) as ckpt:
        t0 = time.perf_counter()
        ckpt.save(1, store, None)
        enqueue_s = time.perf_counter() - t0
        assert enqueue_s < 0.5, f"save blocked for {enqueue_s:.2f}s"
        assert started.wait(5.0)  # the background write is really running
        assert not os.path.exists(ckpt._path(1))  # not yet published
        t0 = time.perf_counter()
        ckpt.flush()
        flush_s = time.perf_counter() - t0
        assert flush_s > 0.3, "flush must be the barrier"
        assert os.path.exists(ckpt._path(1))
    assert ck.Checkpointer(str(tmp_path / "c")).verify_snapshot(1)


def test_at_most_one_in_flight_write(tmp_path, jaxmods, devices8,
                                     monkeypatch):
    """Double buffering: one write in flight + one queued; the THIRD save
    blocks until the slot frees. All three publish, in order."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, trainer, store = _mf(jaxmods)
    store.init(jax.random.key(0))
    _slow_savez(jaxmods, monkeypatch, 0.4)

    with ck.AsyncCheckpointer(str(tmp_path / "c"), keep=5) as ckpt:
        t0 = time.perf_counter()
        ckpt.save(1, store, None)  # -> writer
        ckpt.save(2, store, None)  # -> queue slot
        two_saves_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        ckpt.save(3, store, None)  # must wait for save 1 to finish
        third_save_s = time.perf_counter() - t0
        assert two_saves_s < 0.3, two_saves_s
        assert third_save_s > 0.1, third_save_s
        ckpt.flush()
        assert ckpt.steps() == [1, 2, 3]


def test_writer_failure_surfaces_on_caller(tmp_path, jaxmods, devices8,
                                           monkeypatch):
    """A failed background write re-raises (once) from the next
    flush/save on the training thread — never silently loses snapshots."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, trainer, store = _mf(jaxmods)
    store.init(jax.random.key(0))

    def boom(path, arrays):
        raise OSError("disk on fire")

    monkeypatch.setattr(ck, "_atomic_savez", boom)
    ckpt = ck.AsyncCheckpointer(str(tmp_path / "c"))
    ckpt.save(1, store, None)
    with pytest.raises(RuntimeError, match="background checkpoint"):
        ckpt.flush()
    # Error consumed; the writer thread survives for the next save.
    monkeypatch.undo()
    ckpt.save(2, store, None)
    ckpt.flush()
    assert ckpt.steps() == [2]
    ckpt.close()
    with pytest.raises(RuntimeError, match="closed"):
        ckpt.save(3, store, None)
    ckpt.close()  # idempotent


def test_async_snapshots_byte_identical_to_sync(tmp_path, jaxmods,
                                                devices8):
    """fit_stream + AsyncCheckpointer == fit_stream + Checkpointer: same
    steps, same tables, same local state, same ls_format tag."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    chunks = _chunks(jaxmods)
    dirs = {}
    for name, cls in [("sync", ck.Checkpointer),
                      ("async", ck.AsyncCheckpointer)]:
        _, _, trainer, store = _mf(jaxmods)
        tab, ls = trainer.init_state(jax.random.key(1))
        ckpt = cls(str(tmp_path / name))
        trainer.fit_stream(tab, ls, chunks, jax.random.key(5),
                           checkpointer=ckpt, checkpoint_every=2)
        ckpt.close()
        dirs[name] = str(tmp_path / name)
    a = ck.Checkpointer(dirs["sync"])
    b = ck.Checkpointer(dirs["async"])
    assert a.steps() == b.steps() == [2, 4]
    for s in a.steps():
        sa, ta, la, fa = a.read_snapshot(s)
        sb, tb, lb, fb = b.read_snapshot(s)
        assert (sa, fa) == (sb, fb)
        assert set(ta) == set(tb)
        for k in ta:
            np.testing.assert_array_equal(ta[k], tb[k])
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)


def test_enqueued_before_saved_and_read_side_flushes(tmp_path, jaxmods,
                                                     devices8, monkeypatch):
    """Journal ordering: checkpoint_enqueued precedes checkpoint_saved
    for each step; latest_valid_step (read side) flushes first, so it is
    monotone even while a slow write is in flight."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    from fps_tpu.obs import MemorySink, Recorder, events

    _, _, trainer, store = _mf(jaxmods)
    store.init(jax.random.key(0))
    sink = MemorySink()
    rec = Recorder(sinks=[sink])
    _slow_savez(jaxmods, monkeypatch, 0.3)
    with events.default_recorder(rec):
        with ck.AsyncCheckpointer(str(tmp_path / "c"), keep=5) as ckpt:
            ckpt.save(1, store, None)
            ckpt.flush()
            assert ckpt.latest_valid_step() == 1
            ckpt.save(2, store, None)
            # Read side flushes: sees 2 the moment the call returns.
            assert ckpt.latest_valid_step() == 2
    evs = [(r["event"], r.get("step")) for r in sink.records
           if r.get("kind") == "event"]
    for step in (1, 2):
        assert evs.index(("checkpoint_enqueued", step)) < evs.index(
            ("checkpoint_saved", step)), evs


def test_accepted_saves_survive_midrun_exception(tmp_path, jaxmods,
                                                 devices8, monkeypatch):
    """The drivers flush in a finally: a run killed by a callback raise
    (the sanctioned early-stop pattern) or a health abort must not
    silently drop saves already journaled as checkpoint_enqueued."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    chunks = _chunks(jaxmods)
    _, _, trainer, store = _mf(jaxmods)
    tab, ls = trainer.init_state(jax.random.key(1))
    _slow_savez(jaxmods, monkeypatch, 0.3)
    ckpt = ck.AsyncCheckpointer(str(tmp_path / "c"), keep=5)

    class _Stop(Exception):
        pass

    def stop_after_two(i, _m):
        if i == 1:
            raise _Stop

    with pytest.raises(_Stop):
        trainer.fit_stream(tab, ls, chunks, jax.random.key(5),
                           checkpointer=ckpt, checkpoint_every=1,
                           on_chunk=stop_after_two)
    # Chunk 0's save (step 1) was accepted before the raise (chunk 1's
    # callback raises BEFORE its own checkpoint); the finally-flush makes
    # it durable despite the exception.
    assert ckpt.steps() == [1]
    assert ckpt.latest_valid_step() == 1
    ckpt.close()


def test_compiled_program_identical_with_async_writer_attached(
        tmp_path, jaxmods, devices8):
    """ISSUE acceptance: checkpointer + heartbeat recorder live entirely
    on the host side — the lowered program must be bit-identical with
    them attached or not."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    from fps_tpu.obs import Recorder
    from fps_tpu.parallel.mesh import host_to_sharded, key_to_replicated
    from fps_tpu.supervise import Heartbeat, HeartbeatSink

    chunk = _chunks(jaxmods)[0]

    def lowered_text(attach):
        mesh, _, trainer, store = _mf(jaxmods)
        tab, ls = trainer.init_state(jax.random.key(1))
        if attach:
            hb = Heartbeat(str(tmp_path / "hb.json"))
            trainer.recorder = Recorder(sinks=[HeartbeatSink(hb)])
            ck.AsyncCheckpointer(str(tmp_path / "att")).close()
        sharding = trainer._batch_sharding_for("sync")
        batches = jax.tree.map(lambda x: host_to_sharded(x, sharding), chunk)
        key = key_to_replicated(jax.random.key(1), mesh)
        return trainer._get_compiled("sync").lower(
            tab, ls, batches, key).as_text()

    assert lowered_text(False) == lowered_text(True)


def test_save_deferred_runs_capture_on_writer_thread(tmp_path, jaxmods,
                                                     devices8):
    """save_deferred pays one enqueue on the caller; collect() — the
    device→host capture — runs on the WRITER thread, arbitrarily late,
    and publishes the same snapshot an inline save of the same state
    would."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, trainer, store = _mf(jaxmods)
    store.init(jax.random.key(0))
    seen = {}
    gate = threading.Event()

    with ck.AsyncCheckpointer(str(tmp_path / "a"), keep=3) as ackpt:
        def collect():
            seen["thread"] = threading.current_thread().name
            gate.wait(5.0)  # held capture: the enqueue must not wait on it
            return ackpt._collect(store, None, "raw")

        t0 = time.perf_counter()
        ackpt.save_deferred(1, collect)
        enqueue_s = time.perf_counter() - t0
        assert enqueue_s < 0.5, f"enqueue blocked for {enqueue_s:.2f}s"
        gate.set()
        ackpt.flush()
        assert seen["thread"].startswith("fps-ckpt-writer")
        assert ackpt.steps() == [1]

    sync = ck.Checkpointer(str(tmp_path / "b"), keep=3)
    sync.save(1, store, None)
    _, ta, la, fa = ck.Checkpointer(str(tmp_path / "a")).read_snapshot(1)
    _, tb, lb, fb = sync.read_snapshot(1)
    assert fa == fb and set(ta) == set(tb)
    for k in ta:
        np.testing.assert_array_equal(ta[k], tb[k])


def test_deferred_capture_byte_identical_to_inline(tmp_path, jaxmods,
                                                   devices8, monkeypatch):
    """ISSUE 20 acceptance: fit_stream with prefetch (boundary copies →
    save_deferred, writer-side capture) publishes byte-identical
    snapshots to the inline-capture run — and the deferred path really
    ran (counted at save_deferred)."""
    import dataclasses

    jax, ck = jaxmods["jax"], jaxmods["ck"]
    chunks = _chunks(jaxmods)
    deferred_calls = {"n": 0}
    real_deferred = ck.AsyncCheckpointer.save_deferred

    def counting_deferred(self, *a, **kw):
        deferred_calls["n"] += 1
        return real_deferred(self, *a, **kw)

    monkeypatch.setattr(ck.AsyncCheckpointer, "save_deferred",
                        counting_deferred)
    dirs = {}
    for name, pf in [("inline", 0), ("deferred", 2)]:
        _, _, trainer, store = _mf(jaxmods)
        trainer.config = dataclasses.replace(trainer.config, prefetch=pf)
        tab, ls = trainer.init_state(jax.random.key(1))
        before = deferred_calls["n"]
        with ck.AsyncCheckpointer(str(tmp_path / name)) as ckpt:
            trainer.fit_stream(tab, ls, chunks, jax.random.key(5),
                               checkpointer=ckpt, checkpoint_every=2)
        if name == "deferred":
            assert deferred_calls["n"] > before, \
                "prefetch run never took the writer-capture path"
        else:
            assert deferred_calls["n"] == before
        dirs[name] = str(tmp_path / name)
    a = ck.Checkpointer(dirs["inline"])
    b = ck.Checkpointer(dirs["deferred"])
    assert a.steps() == b.steps() == [2, 4]
    for s in a.steps():
        sa, ta, la, fa = a.read_snapshot(s)
        sb, tb, lb, fb = b.read_snapshot(s)
        assert (sa, fa) == (sb, fb)
        assert set(ta) == set(tb)
        for k in ta:
            np.testing.assert_array_equal(ta[k], tb[k])
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)


def test_when_full_degrade_skips_without_blocking(tmp_path, jaxmods,
                                                  devices8, monkeypatch):
    """when_full="degrade": a save landing while the queue slot is full
    returns immediately as a SKIP (degraded publish + backlog), the next
    landed publish drains the backlog, and when_full="block" per call
    overrides the instance default — the final save always lands."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, trainer, store = _mf(jaxmods)
    store.init(jax.random.key(0))
    started = threading.Event()
    _slow_savez(jaxmods, monkeypatch, 0.4, started)

    with ck.AsyncCheckpointer(str(tmp_path / "c"), keep=8,
                              when_full="degrade") as ckpt:
        ckpt.save(1, store, None)  # -> writer
        # Wait for the writer to TAKE save 1 (degrade mode never waits
        # on a momentarily-full slot, so save 2 must find it empty).
        assert started.wait(5.0)
        ckpt.save(2, store, None)  # -> queue slot
        t0 = time.perf_counter()
        ckpt.save(3, store, None)  # slot full -> skipped, not blocked
        third_save_s = time.perf_counter() - t0
        assert third_save_s < 0.1, third_save_s
        assert ckpt.degraded_publishes == 1
        # Per-call "block" (the driver's final-save spelling) overrides
        # the instance default and waits for the slot.
        t0 = time.perf_counter()
        ckpt.save(4, store, None, when_full="block")
        blocked_save_s = time.perf_counter() - t0
        assert blocked_save_s > 0.1, blocked_save_s
        ckpt.flush()
        # 3 was the degraded skip; 1/2/4 landed, and 2's publish (the
        # first landed write after the skip) drained the backlog.
        assert ckpt.steps() == [1, 2, 4]
    with pytest.raises(ValueError, match="when_full"):
        ck.AsyncCheckpointer(str(tmp_path / "bad"), when_full="drop")


def test_corrupt_quarantine_sweep_bounded(tmp_path, jaxmods, devices8):
    """Satellite: *.corrupt files are bounded by count AND age at
    Checkpointer construction — they no longer accumulate forever."""
    ck = jaxmods["ck"]
    d = tmp_path / "c"
    d.mkdir()
    old = time.time() - 2 * ck.Checkpointer.CORRUPT_SWEEP_AGE_S
    # 6 young corrupt files (count bound: newest 4 survive) + 1 ancient
    # (age bound: goes regardless of rank).
    for i in range(6):
        p = d / (ck.SNAPSHOT_FMT.format(step=i) + ".corrupt")
        p.write_bytes(b"junk")
        os.utime(p, (time.time() - 60 * (6 - i),) * 2)
    ancient = d / "ancient.npz.corrupt"
    ancient.write_bytes(b"junk")
    os.utime(ancient, (old, old))
    ck.Checkpointer(str(d), keep=2)
    left = sorted(f.name for f in d.iterdir() if f.name.endswith(".corrupt"))
    assert len(left) == 4, left
    assert ancient.name not in left
    assert ck.SNAPSHOT_FMT.format(step=5) + ".corrupt" in left  # newest kept
