"""Dataset loader tests: real-file branches, truncation, synthetic shapes.

The synthetic generators are exercised throughout the suite; these tests
cover the host-side loader logic itself — text8 tokenization (vocab capping,
UNK mapping, truncation), the MovieLens numpy fallback, split invariants,
and the streaming generator's bounds.
"""

import numpy as np

from fps_tpu.utils.datasets import (
    load_movielens,
    load_text8,
    streaming_rating_batches,
    synthetic_sparse_classification,
    train_test_split,
)


def test_load_text8_file_branch(tmp_path):
    p = tmp_path / "corpus.txt"
    # 'the' x5, 'cat' x3, 'sat' x2, 'mat' x1 -> vocab keeps top 3 + UNK slot
    p.write_text("the cat the sat the cat the sat the cat mat")
    tokens, vocab, uni = load_text8(str(p), vocab_size=4, num_tokens=None)
    assert vocab == 4
    assert len(tokens) == 11
    # id 0 is UNK; most frequent word gets id 1
    assert uni.shape == (4,)
    assert uni[1] == 5  # 'the'
    assert uni[2] == 3  # 'cat'
    assert uni[3] == 2  # 'sat'
    assert uni[0] == 1  # 'mat' -> UNK
    assert uni.sum() == len(tokens)


def test_load_text8_truncates_real_file(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text(" ".join(f"w{i % 7}" for i in range(100)))
    tokens, vocab, uni = load_text8(str(p), vocab_size=10, num_tokens=25)
    assert len(tokens) == 25
    assert uni.sum() == 25


def test_load_movielens_numpy_fallback(tmp_path, monkeypatch):
    p = tmp_path / "u.data"
    p.write_text("1 5 3\n2 7 4\n3 5 2\n")
    # force the loadtxt branch regardless of the native library
    import fps_tpu.native as native

    monkeypatch.setattr(native, "parse_ratings", lambda path, **kw: None)
    data, nu, ni = load_movielens(str(p))
    np.testing.assert_array_equal(data["user"], [0, 1, 2])  # 1-based -> 0
    np.testing.assert_array_equal(data["item"], [4, 6, 4])
    assert (nu, ni) == (3, 7)


def test_train_test_split_partitions():
    d = synthetic_sparse_classification(1000, 50, 4, seed=0)
    tr, te = train_test_split(d, test_frac=0.2, seed=3)
    n = len(d["label"])
    assert len(tr["label"]) + len(te["label"]) == n
    assert len(te["label"]) == n - int(n * 0.8)
    for k in d:
        assert tr[k].shape[1:] == d[k].shape[1:]


def test_streaming_rating_batches_bounds():
    src = streaming_rating_batches(50, 30, batch=64, max_records=150, seed=0)
    batches = list(src)
    assert [len(b["user"]) for b in batches] == [64, 64, 22]
    for b in batches:
        assert b["user"].max() < 50 and b["item"].max() < 30


# ---------------------------------------------------------------------------
# Sparse real-file loaders: svmlight (RCV1) + Criteo TSV.
# ---------------------------------------------------------------------------

import pytest

from fps_tpu import native
from fps_tpu.utils.datasets import (
    CRITEO_NNZ,
    load_criteo,
    load_sparse,
    load_svmlight,
    sniff_sparse_format,
)

SVM = (
    "# rcv1-style comment\n"
    "+1 3:0.25 7:1 12:0.5\n"
    "-1 1:0.125 3:2.5\n"
    "0 9:1e-2   14:-0.5\n"
    "\n"
    "1 2:1 # trailing comment 99:9\n"
)


@pytest.mark.parametrize("use_native", [True, False])
def test_load_svmlight(tmp_path, use_native):
    if use_native and not native.available():
        pytest.skip("native unavailable")
    p = tmp_path / "rcv1.svm"
    p.write_text(SVM)
    data, nf = load_svmlight(str(p), use_native=use_native)
    assert nf == 15  # max id 14 + 1
    np.testing.assert_array_equal(data["label"], [1, -1, -1, 1])
    assert data["feat_ids"].shape == data["feat_vals"].shape == (4, 3)
    # row 0 fully populated
    np.testing.assert_array_equal(data["feat_ids"][0], [3, 7, 12])
    np.testing.assert_allclose(data["feat_vals"][0], [0.25, 1.0, 0.5])
    # row 3: single feature + padding (id 0 / val 0 = inactive)
    np.testing.assert_array_equal(data["feat_ids"][3], [2, 0, 0])
    np.testing.assert_allclose(data["feat_vals"][3], [1.0, 0.0, 0.0])
    # negative + exponent values parse
    np.testing.assert_allclose(data["feat_vals"][2][:2], [0.01, -0.5])


@pytest.mark.parametrize("use_native", [True, False])
def test_load_svmlight_nnz_cap_and_malformed(tmp_path, use_native):
    if use_native and not native.available():
        pytest.skip("native unavailable")
    p = tmp_path / "a.svm"
    p.write_text("+1 1:1 2:2 3:3\n-1 4:4\n")
    data, _ = load_svmlight(str(p), nnz_cap=2, use_native=use_native)
    np.testing.assert_array_equal(data["feat_ids"][0], [1, 2])  # truncated
    np.testing.assert_array_equal(data["feat_ids"][1], [4, 0])

    bad = tmp_path / "bad.svm"
    bad.write_text("+1 1:1\nnot-a-line\n-1 2:0.5\n")
    with pytest.raises(ValueError, match="malformed"):
        load_svmlight(str(bad), use_native=use_native)
    bad2 = tmp_path / "bad2.svm"
    bad2.write_text("+1 1:1 brokentoken 2:2\n")
    with pytest.raises(ValueError, match="malformed"):
        load_svmlight(str(bad2), use_native=use_native)


def _criteo_line(label, nums, cats):
    num_f = [("" if v is None else str(v)) for v in nums]
    cat_f = list(cats)
    return "\t".join([str(label)] + num_f + cat_f)


@pytest.mark.parametrize("use_native", [True, False])
def test_load_criteo(tmp_path, use_native):
    if use_native and not native.available():
        pytest.skip("native unavailable")
    nums = [5, None, 0, -1] + [None] * 9  # -1 treated as missing
    cats = ["68fd1e64", ""] + [""] * 23 + ["abc123"]
    p = tmp_path / "criteo.tsv"
    p.write_text(
        _criteo_line(1, nums, cats) + "\n" + _criteo_line(0, nums, cats) + "\n"
    )
    data, nf = load_criteo(str(p), num_features=1 << 16,
                           use_native=use_native)
    assert nf == 1 << 16
    np.testing.assert_array_equal(data["label"], [1, -1])
    ids, vals = data["feat_ids"][0], data["feat_vals"][0]
    assert data["feat_ids"].shape == (2, CRITEO_NNZ)
    # numeric: id 0 val log1p(5); id 2 val log1p(0)=0... value 0 is inactive
    # by convention, so only id 0 carries numeric signal here
    assert ids[0] == 0 and np.isclose(vals[0], np.log1p(5))
    # categoricals hash into [13, nf)
    active = vals != 0
    assert ((ids[active] >= 0) & (ids[active] < nf)).all()
    assert (ids[active][1:] >= 13).all()
    # both rows hash identically (deterministic)
    np.testing.assert_array_equal(data["feat_ids"][0], data["feat_ids"][1])


def test_criteo_native_matches_fallback(tmp_path):
    if not native.available():
        pytest.skip("native unavailable")
    rng = np.random.default_rng(0)
    lines = []
    for k in range(50):
        nums = [int(v) if v >= 0 else None
                for v in rng.integers(-2, 1000, 13)]
        cats = [format(int(v), "08x") if v % 5 else ""
                for v in rng.integers(0, 1 << 32, 26)]
        lines.append(_criteo_line(int(k % 2), nums, cats))
    p = tmp_path / "criteo.tsv"
    p.write_text("\n".join(lines) + "\n")
    a, _ = load_criteo(str(p), num_features=1 << 18, use_native=True)
    b, _ = load_criteo(str(p), num_features=1 << 18, use_native=False)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.parametrize("use_native", [True, False])
def test_criteo_malformed_raises(tmp_path, use_native):
    if use_native and not native.available():
        pytest.skip("native unavailable")
    p = tmp_path / "bad.tsv"
    good = _criteo_line(1, [1] * 13, ["aa"] * 26)
    p.write_text(good + "\n2\tnot\tenough\tfields\n")
    with pytest.raises(ValueError, match="malformed"):
        load_criteo(str(p), num_features=1 << 16, use_native=use_native)


def test_sniff_and_dispatch(tmp_path):
    svm = tmp_path / "x.svm"
    svm.write_text("+1 1:0.5\n")
    tsv = tmp_path / "x.tsv"
    tsv.write_text(_criteo_line(0, [1] * 13, ["aa"] * 26) + "\n")
    assert sniff_sparse_format(str(svm)) == "svmlight"
    assert sniff_sparse_format(str(tsv)) == "criteo"
    d1, _ = load_sparse(str(svm))
    d2, _ = load_sparse(str(tsv), num_features=1 << 15)
    assert set(d1) == set(d2) == {"feat_ids", "feat_vals", "label"}


@pytest.mark.parametrize("use_native", [True, False])
def test_degenerate_tokens_classified_identically(tmp_path, use_native):
    """Native scanner and Python fallback must agree on every degenerate
    token: digit-less decimals ("."), Python-only float spellings
    ("1_0", "inf", "nan"), and signed indices ("+5"). All are malformed
    in BOTH loaders — a file must never parse differently depending on
    which parser happened to be available."""
    if use_native and not native.available():
        pytest.skip("native unavailable")
    cases = [
        "1 5:. 6:2\n",        # digit-less value token
        ". 5:1\n",            # digit-less label
        "-. 5:1\n",           # sign-only label
        "1 5:1_0\n",          # Python float() underscore extension
        "1 5:inf\n",          # Python float() inf spelling
        "1 5:nan\n",          # Python float() nan spelling
        "+1 +5:1\n",          # signed feature index
    ]
    for k, text in enumerate(cases):
        bad = tmp_path / f"deg{k}.svm"
        bad.write_text("+1 1:1\n" + text)
        with pytest.raises(ValueError, match="malformed"):
            load_svmlight(str(bad), use_native=use_native)
    # ...while native-accepted shapes stay accepted by both: "1." and "+.5".
    ok = tmp_path / "ok.svm"
    ok.write_text("+1 1:1. 2:+.5 3:-2.e1\n")
    data, _ = load_svmlight(str(ok), use_native=use_native)
    np.testing.assert_allclose(data["feat_vals"][0], [1.0, 0.5, -20.0])


@pytest.mark.parametrize("use_native", [True, False])
def test_criteo_degenerate_numeric_tokens(tmp_path, use_native):
    """Criteo numeric columns: same strict grammar in both loaders."""
    if use_native and not native.available():
        pytest.skip("native unavailable")
    for tok in ["1_0", "inf", "."]:
        p = tmp_path / f"bad_{tok.replace('.', 'dot')}.tsv"
        nums = [tok] + [1] * 12
        p.write_text(_criteo_line(1, nums, ["aa"] * 26) + "\n")
        with pytest.raises(ValueError, match="malformed"):
            load_criteo(str(p), num_features=1 << 16, use_native=use_native)


@pytest.mark.parametrize("use_native", [True, False])
def test_criteo_fixed_slot_layout(tmp_path, use_native):
    """Numeric column j always sits at batch slot j (id j; value 0 when
    missing) — the fixed-slot contract LogRegConfig.dense_features relies
    on. Categoricals append from slot 13."""
    if use_native and not native.available():
        pytest.skip("native unavailable")
    p = tmp_path / "c.tsv"
    nums = [5, None, 7] + [None] * 10  # I2 and I4..I13 missing
    p.write_text(_criteo_line(1, nums, ["aa", "bb"] + [""] * 24) + "\n")
    data, _ = load_criteo(str(p), num_features=1 << 16,
                          use_native=use_native)
    ids, vals = data["feat_ids"][0], data["feat_vals"][0]
    np.testing.assert_array_equal(ids[:13], np.arange(13))
    np.testing.assert_allclose(vals[0], np.log1p(5.0), rtol=1e-6)
    assert vals[1] == 0.0  # missing numeric: inactive, slot preserved
    np.testing.assert_allclose(vals[2], np.log1p(7.0), rtol=1e-6)
    assert (vals[3:13] == 0.0).all()
    # two categoricals at slots 13, 14; the rest padding
    assert (ids[13:15] >= 13).all() and (vals[13:15] == 1.0).all()
    assert (vals[15:] == 0.0).all()
