"""Dataset loader tests: real-file branches, truncation, synthetic shapes.

The synthetic generators are exercised throughout the suite; these tests
cover the host-side loader logic itself — text8 tokenization (vocab capping,
UNK mapping, truncation), the MovieLens numpy fallback, split invariants,
and the streaming generator's bounds.
"""

import numpy as np

from fps_tpu.utils.datasets import (
    load_movielens,
    load_text8,
    streaming_rating_batches,
    synthetic_sparse_classification,
    train_test_split,
)


def test_load_text8_file_branch(tmp_path):
    p = tmp_path / "corpus.txt"
    # 'the' x5, 'cat' x3, 'sat' x2, 'mat' x1 -> vocab keeps top 3 + UNK slot
    p.write_text("the cat the sat the cat the sat the cat mat")
    tokens, vocab, uni = load_text8(str(p), vocab_size=4, num_tokens=None)
    assert vocab == 4
    assert len(tokens) == 11
    # id 0 is UNK; most frequent word gets id 1
    assert uni.shape == (4,)
    assert uni[1] == 5  # 'the'
    assert uni[2] == 3  # 'cat'
    assert uni[3] == 2  # 'sat'
    assert uni[0] == 1  # 'mat' -> UNK
    assert uni.sum() == len(tokens)


def test_load_text8_truncates_real_file(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text(" ".join(f"w{i % 7}" for i in range(100)))
    tokens, vocab, uni = load_text8(str(p), vocab_size=10, num_tokens=25)
    assert len(tokens) == 25
    assert uni.sum() == 25


def test_load_movielens_numpy_fallback(tmp_path, monkeypatch):
    p = tmp_path / "u.data"
    p.write_text("1 5 3\n2 7 4\n3 5 2\n")
    # force the loadtxt branch regardless of the native library
    import fps_tpu.native as native

    monkeypatch.setattr(native, "parse_ratings", lambda path, **kw: None)
    data, nu, ni = load_movielens(str(p))
    np.testing.assert_array_equal(data["user"], [0, 1, 2])  # 1-based -> 0
    np.testing.assert_array_equal(data["item"], [4, 6, 4])
    assert (nu, ni) == (3, 7)


def test_train_test_split_partitions():
    d = synthetic_sparse_classification(1000, 50, 4, seed=0)
    tr, te = train_test_split(d, test_frac=0.2, seed=3)
    n = len(d["label"])
    assert len(tr["label"]) + len(te["label"]) == n
    assert len(te["label"]) == n - int(n * 0.8)
    for k in d:
        assert tr[k].shape[1:] == d[k].shape[1:]


def test_streaming_rating_batches_bounds():
    src = streaming_rating_batches(50, 30, batch=64, max_records=150, seed=0)
    batches = list(src)
    assert [len(b["user"]) for b in batches] == [64, 64, 22]
    for b in batches:
        assert b["user"].max() < 50 and b["item"].max() < 30
