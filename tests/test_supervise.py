"""External run supervisor (fps_tpu.supervise + tools/supervise.py).

Tier-1 keeps the supervisor machinery honest at stub speed — a jax-free
child (``tests/_supervised_stub.py``) that beats, checkpoints its
progress, and misbehaves on demand, driven through the REAL CLI in a
subprocess. The slow marker covers the full-stack version: a real jax
training child (``fps_tpu.testing.supervised_demo``) SIGSTOP-wedged
mid-run must be deadline-aborted, restarted with backoff, and reproduce
the straight run bit-for-bit from ``latest_valid_step``.
"""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STUB = os.path.join(_ROOT, "tests", "_supervised_stub.py")
_CLI = os.path.join(_ROOT, "tools", "supervise.py")


def _run_supervised(state_dir, child_cmd, *flags, timeout=120):
    """tools/supervise.py round trip; returns (rc, digest dict)."""
    r = subprocess.run(
        [sys.executable, _CLI, "--state-dir", str(state_dir), *flags,
         "--", *child_cmd],
        capture_output=True, text=True, timeout=timeout, cwd=_ROOT,
    )
    assert r.stdout.strip(), r.stderr[-2000:]
    return r.returncode, json.loads(r.stdout.strip().splitlines()[-1])


def _stub_cmd(workdir, *extra):
    return [sys.executable, _STUB, "--dir", str(workdir), "--chunks", "8",
            "--chunk-s", "0.05", *extra]


# ---------------------------------------------------------------------------
# The tier-1 smoke: wedge -> deadline-abort -> backoff restart -> resume.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("wedge_mode", ["sigstop", "sleep"])
def test_wedged_child_aborted_and_resumed(tmp_path, wedge_mode):
    """A child that stops beating (SIGSTOP'd whole process, or a host
    loop asleep forever) is deadline-aborted via the TERM->KILL
    escalation, restarted after backoff, and completes from its persisted
    progress with at most one chunk re-run."""
    rc, digest = _run_supervised(
        tmp_path / "state",
        _stub_cmd(tmp_path / "work", "--wedge-at", "3",
                  "--wedge-mode", wedge_mode),
        "--stall-timeout-s", "1.0", "--startup-grace-s", "10",
        "--term-grace-s", "0.5", "--backoff-base-s", "0.1",
        "--max-restarts", "2", "--poll-s", "0.1",
    )
    assert rc == 0 and digest["success"], digest
    assert digest["deadline_aborts"] == 1
    assert digest["restarts"] == 1
    assert digest["quarantined"] == []  # a wedge-once is not poison
    with open(tmp_path / "work" / "result.json", encoding="utf-8") as f:
        result = json.load(f)
    assert result["done"] == 8
    assert result["attempt"] == 1  # finished by the restarted attempt
    # Resumed exactly at the wedged chunk: nothing before it re-ran.
    assert result["ran"] == [3, 4, 5, 6, 7]
    # The journal narrates the abort for obs_report.
    events = [json.loads(line)["event"]
              for line in open(tmp_path / "state" /
                               "journal-supervisor.jsonl")]
    for expected in ("supervisor_start", "deadline_abort",
                     "supervisor_restart", "supervised_run_end"):
        assert expected in events, events


def test_deterministic_crash_is_quarantined(tmp_path):
    """A child that exits nonzero at the same chunk on consecutive
    attempts has that chunk quarantined (persisted, exported via the
    state file) — the crash loop breaks and the run completes without
    the poisoned chunk."""
    rc, digest = _run_supervised(
        tmp_path / "state",
        _stub_cmd(tmp_path / "work", "--crash-at", "2"),
        "--stall-timeout-s", "5", "--backoff-base-s", "0.05",
        "--max-restarts", "3", "--poll-s", "0.05",
    )
    assert rc == 0 and digest["success"], digest
    assert digest["quarantined"] == [2]
    assert digest["restarts"] == 2  # crash, crash+quarantine, success
    with open(tmp_path / "work" / "result.json", encoding="utf-8") as f:
        result = json.load(f)
    assert 2 not in result["ran"]  # the poison chunk was skipped
    state = json.load(open(tmp_path / "state" / "supervisor_state.json"))
    assert state["quarantined"] == [2]
    assert [a["rc"] for a in state["attempts"]] == [3, 3, 0]


def test_first_signal_events_and_recovery_times(tmp_path):
    """Every attempt that produced a liveness signal journals exactly one
    ``attempt_first_signal``, and ``recovery_times`` pairs it with the
    previous ``attempt_end`` into time_to_recovered_s (kill -> first
    post-restart dispatch) — the MTTR datum the chaos sweep records."""
    rc, digest = _run_supervised(
        tmp_path / "state",
        _stub_cmd(tmp_path / "work", "--crash-at", "2"),
        "--stall-timeout-s", "5", "--backoff-base-s", "0.05",
        "--max-restarts", "3", "--poll-s", "0.05",
    )
    assert rc == 0 and digest["success"], digest
    journal = tmp_path / "state" / "journal-supervisor.jsonl"
    events = [json.loads(line) for line in open(journal)]
    firsts = [e["attempt"] for e in events if e["event"] == "attempt_first_signal"]
    # Three attempts (crash, crash+quarantine, success).  An attempt that
    # dies before its first heartbeat (attempt 1 resumes straight into the
    # poisoned chunk) journals no first-signal; the ones that did work
    # journal exactly one each, in attempt order.  Attempt 0 always beats,
    # and the final (successful) attempt always beats.
    assert firsts == sorted(set(firsts)), firsts
    assert firsts[0] == 0 and firsts[-1] == 2, firsts
    from fps_tpu.supervise.supervisor import recovery_times
    times = recovery_times(str(journal))
    # One recovery per post-restart attempt that signalled.
    assert len(times) == len([a for a in firsts if a > 0]), (firsts, times)
    assert times, times
    # Recovery spans the backoff sleep (>= 0) and stays well under the
    # run's own wall clock — a sanity band, not a perf assertion.
    assert all(0 <= t < 60 for t in times), times
    # A missing/garbled journal degrades to no data, never a crash.
    assert recovery_times(str(tmp_path / "nope.jsonl")) == []


def test_wall_deadline_gives_up(tmp_path):
    """An unrecoverable hang (wedges every attempt; quarantine disabled
    so nothing can be skipped around) exhausts the wall budget: the
    supervisor stops restarting and reports failure."""
    rc, digest = _run_supervised(
        tmp_path / "state",
        _stub_cmd(tmp_path / "work", "--wedge-at", "0", "--wedge-always"),
        "--stall-timeout-s", "0.7", "--wall-deadline-s", "4",
        "--term-grace-s", "0.3", "--backoff-base-s", "0.1",
        "--max-restarts", "10", "--poll-s", "0.1",
        "--quarantine-after", "99",
    )
    assert rc == 1 and not digest["success"]
    assert digest["reason"] == "wall_deadline"
    assert digest["wall_s"] < 15  # actually bounded, with abort slack


def test_aborted_attempt_exiting_zero_is_not_success(tmp_path):
    """A SIGTERM-trapping child exits 0 from its graceful-shutdown
    handler when the stall abort fires — rc alone must not count as
    success: the supervisor still restarts, and the run only succeeds
    when an attempt finishes WITHOUT being aborted."""
    rc, digest = _run_supervised(
        tmp_path / "state",
        _stub_cmd(tmp_path / "work", "--wedge-at", "3",
                  "--wedge-mode", "sleep", "--trap-term"),
        "--stall-timeout-s", "1.0", "--startup-grace-s", "10",
        "--term-grace-s", "2", "--backoff-base-s", "0.1",
        "--max-restarts", "2", "--poll-s", "0.1",
    )
    assert rc == 0 and digest["success"], digest
    assert digest["deadline_aborts"] == 1
    assert digest["restarts"] == 1  # the rc=0 aborted attempt restarted
    state = json.load(open(tmp_path / "state" / "supervisor_state.json"))
    assert state["attempts"][0]["rc"] == 0  # the graceful-exit trap fired
    assert state["attempts"][0]["aborted"] == "stall"
    assert os.path.exists(tmp_path / "work" / "result.json")


def test_retry_budget_exhaustion(tmp_path):
    """max-restarts bounds the crash loop when quarantine can't help
    (child dies before any beat => no index to quarantine)."""
    rc, digest = _run_supervised(
        tmp_path / "state",
        [sys.executable, "-c", "import sys; sys.exit(7)"],
        "--stall-timeout-s", "5", "--backoff-base-s", "0.05",
        "--max-restarts", "2", "--poll-s", "0.05",
    )
    assert rc == 1 and not digest["success"]
    assert digest["reason"] == "retry_budget_exhausted"
    assert digest["attempts"] == 3  # first launch + 2 restarts
    assert digest["quarantined"] == []


# ---------------------------------------------------------------------------
# Library pieces (no subprocess).
# ---------------------------------------------------------------------------

def test_env_contract_mirrored():
    """supervisor.py mirrors child.py's env-var names (it cannot import
    them: the supervisor must load by file path with zero fps_tpu
    imports). This is the tripwire for the mirror drifting."""
    from fps_tpu.supervise import child, supervisor

    assert supervisor.HEARTBEAT_ENV == child.HEARTBEAT_ENV
    assert supervisor.STATE_ENV == child.STATE_ENV
    assert supervisor.ATTEMPT_ENV == child.ATTEMPT_ENV
    assert supervisor.HEARTBEAT_VERSION == child.HEARTBEAT_VERSION


def test_backoff_jitter_deterministic(tmp_path):
    """RunSupervisor.backoff_s: bounded jitter in [base, base*(1+j)],
    seeded from the state_dir — the SAME dir replays the exact schedule,
    DIFFERENT dirs (pod members) desynchronize, and jitter=0 restores
    the config's pure exponential."""
    from fps_tpu.supervise import RunSupervisor, SupervisorConfig

    cfg = SupervisorConfig(backoff_base_s=1.0, backoff_factor=2.0,
                           backoff_max_s=8.0, backoff_jitter=0.25)
    a = RunSupervisor(["true"], state_dir=str(tmp_path / "a"), config=cfg)
    a2 = RunSupervisor(["true"], state_dir=str(tmp_path / "a"), config=cfg)
    b = RunSupervisor(["true"], state_dir=str(tmp_path / "b"), config=cfg)
    sched_a = [a.backoff_s(i) for i in range(4)]
    assert sched_a == [a2.backoff_s(i) for i in range(4)]  # replayable
    assert sched_a != [b.backoff_s(i) for i in range(4)]  # desynced
    for i, s in enumerate(sched_a):
        base = cfg.backoff_s(i)
        assert base <= s <= base * 1.25, (i, s, base)
    plain = RunSupervisor(
        ["true"], state_dir=str(tmp_path / "a"),
        config=SupervisorConfig(backoff_jitter=0.0))
    assert plain.backoff_s(1) == plain.config.backoff_s(1)
    with pytest.raises(ValueError):
        SupervisorConfig(backoff_jitter=1.5)


def test_heartbeat_rejected_unknown_version_and_wrong_host(tmp_path):
    """Schema hardening: a beat wearing an unknown version, or a foreign
    host in a host-pinned supervisor, is rejected LOUDLY (journal event
    + persisted counter) and never counts as liveness or progress."""
    from fps_tpu.supervise import RunSupervisor

    sup = RunSupervisor(["true"], state_dir=str(tmp_path), host="h0")

    def write_beat(rec):
        with open(sup.heartbeat_path, "w", encoding="utf-8") as f:
            json.dump(rec, f)
        os.utime(sup.heartbeat_path)

    write_beat({"version": 99, "index": 3})
    assert sup._read_heartbeat() == (None, None, None)
    write_beat({"version": 2, "host": "h1", "index": 4})  # foreign host
    assert sup._read_heartbeat() == (None, None, None)
    state = json.load(open(tmp_path / "supervisor_state.json"))
    assert state["heartbeat_rejected"] == 2
    events = [json.loads(line) for line in
              open(tmp_path / "journal-supervisor.jsonl")]
    rejected = [e for e in events if e["event"] == "heartbeat_rejected"]
    assert len(rejected) == 2
    assert "version" in rejected[0]["reason"]
    assert "host" in rejected[1]["reason"]
    # A valid beat (own host, or no host at all) passes.
    write_beat({"version": 2, "host": "h0", "index": 5})
    assert sup._read_heartbeat()[1] == 5
    write_beat({"version": 2, "index": 6})
    assert sup._read_heartbeat()[1] == 6
    # Un-pinned supervisors accept any host (single-host runs).
    anyhost = RunSupervisor(["true"], state_dir=str(tmp_path / "s2"))
    with open(anyhost.heartbeat_path, "w", encoding="utf-8") as f:
        json.dump({"version": 2, "host": "whoever", "index": 7}, f)
    assert anyhost._read_heartbeat()[1] == 7


def test_state_schema_version_and_migration(tmp_path):
    """Version-less (v1) state files migrate by defaulting; a FUTURE
    schema refuses loudly instead of silently reinterpreting a newer
    supervisor's quarantine evidence."""
    from fps_tpu.supervise import RunSupervisor
    from fps_tpu.supervise.supervisor import STATE_SCHEMA_VERSION

    state_path = tmp_path / "supervisor_state.json"
    state_path.write_text(json.dumps(
        {"restarts": 3, "quarantined": [7], "attempts": []}))  # v1: no schema
    sup = RunSupervisor(["true"], state_dir=str(tmp_path))
    assert sup.state["schema"] == STATE_SCHEMA_VERSION
    assert sup.state["quarantined"] == [7]  # evidence carried over
    assert sup.state["restarts"] == 3

    state_path.write_text(json.dumps({"schema": STATE_SCHEMA_VERSION + 1}))
    with pytest.raises(ValueError):
        RunSupervisor(["true"], state_dir=str(tmp_path))


def test_quarantine_cap_oldest_first(tmp_path):
    """The quarantine list is bounded: past QUARANTINE_CAP entries the
    OLDEST evict first (they protect chunks long replayed past), with a
    journal event recording what was dropped."""
    from fps_tpu.supervise import RunSupervisor
    from fps_tpu.supervise.supervisor import QUARANTINE_CAP

    sup = RunSupervisor(["true"], state_dir=str(tmp_path))
    sup.state["quarantined"] = list(range(QUARANTINE_CAP + 10))
    sup._cap_quarantine()
    assert sup.state["quarantined"] == list(range(10, QUARANTINE_CAP + 10))
    events = [json.loads(line) for line in
              open(tmp_path / "journal-supervisor.jsonl")]
    evicted = [e for e in events if e["event"] == "quarantine_evicted"]
    assert evicted and evicted[0]["evicted"] == list(range(10))
    # Under the cap: a no-op, no event spam.
    sup._cap_quarantine()
    events2 = [json.loads(line) for line in
               open(tmp_path / "journal-supervisor.jsonl")]
    assert len([e for e in events2
                if e["event"] == "quarantine_evicted"]) == 1


def test_supervisor_module_loads_without_fps_tpu(tmp_path):
    """The jax-free contract, enforced: loading supervisor.py by file
    path in a bare interpreter must import neither fps_tpu nor jax."""
    code = (
        "import importlib.util, sys\n"
        f"path = {os.path.join(_ROOT, 'fps_tpu', 'supervise', 'supervisor.py')!r}\n"
        "spec = importlib.util.spec_from_file_location('_sup', path)\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "sys.modules[spec.name] = mod\n"
        "spec.loader.exec_module(mod)\n"
        "mod.SupervisorConfig(stall_timeout_s=1.0)\n"
        "assert not any(m == 'jax' or m.startswith('jax.')"
        " for m in sys.modules), 'jax imported'\n"
        "assert not any(m == 'fps_tpu' or m.startswith('fps_tpu.')"
        " for m in sys.modules), 'fps_tpu imported'\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-2000:]


def test_backoff_schedule_and_validation():
    from fps_tpu.supervise import SupervisorConfig

    cfg = SupervisorConfig(backoff_base_s=1.0, backoff_factor=2.0,
                           backoff_max_s=5.0)
    assert [cfg.backoff_s(i) for i in range(5)] == [1.0, 2.0, 4.0, 5.0, 5.0]
    with pytest.raises(ValueError):
        SupervisorConfig(stall_timeout_s=0)
    with pytest.raises(ValueError):
        SupervisorConfig(max_restarts=-1)
    with pytest.raises(ValueError):
        SupervisorConfig(quarantine_after=0)


def test_heartbeat_beat_and_sink(tmp_path):
    """Heartbeat writes an atomic JSON beacon; HeartbeatSink beats on
    run_start/chunk/epoch events only — carrying the index ABOUT TO BE
    ATTEMPTED (chunk i done -> beat i+1), so a mid-chunk death
    attributes to the doomed chunk."""
    from fps_tpu.supervise import Heartbeat, HeartbeatSink

    hb = Heartbeat(str(tmp_path / "hb.json"))
    hb.beat(index=4, extra="x")
    rec = json.load(open(tmp_path / "hb.json"))
    assert rec["index"] == 4 and rec["extra"] == "x" and rec["pid"]

    sink = HeartbeatSink(hb)
    sink.write({"kind": "metric", "name": "driver.chunks"})  # ignored
    assert json.load(open(tmp_path / "hb.json"))["index"] == 4
    sink.write({"kind": "event", "event": "chunk", "index": 7})
    assert json.load(open(tmp_path / "hb.json"))["index"] == 8  # next up
    sink.write({"kind": "event", "event": "checkpoint_saved", "index": 9})
    assert json.load(open(tmp_path / "hb.json"))["index"] == 8  # not a beat
    sink.write({"kind": "event", "event": "run_start"})
    assert json.load(open(tmp_path / "hb.json"))["index"] is None

    # Heartbeat.on_chunk follows the same beat-before-work convention.
    hb.on_chunk()(3, {})
    assert json.load(open(tmp_path / "hb.json"))["index"] == 4


def test_quarantine_needs_consecutive_failures(tmp_path):
    """A success between two same-index transient deaths resets the
    quarantine evidence — only CONSECUTIVE trailing failures quarantine
    (the attempt history persists across supervisor invocations, so two
    coincidental preemptions in different runs must not poison a healthy
    chunk)."""
    from fps_tpu.supervise import RunSupervisor, SupervisorConfig

    sup = RunSupervisor(["true"], state_dir=str(tmp_path),
                        config=SupervisorConfig(quarantine_after=2))
    fail = {"rc": 1, "last_index": 5}
    sup.state["attempts"] = [dict(fail), {"rc": 0, "last_index": 9},
                             dict(fail)]
    sup._maybe_quarantine(dict(fail))
    assert sup.state["quarantined"] == []  # success broke the streak
    sup.state["attempts"].append(dict(fail))  # now two consecutive
    sup._maybe_quarantine(dict(fail))
    assert sup.state["quarantined"] == [5]
    # Deaths before any indexed beat never quarantine.
    sup.state["quarantined"] = []
    sup.state["attempts"] = [{"rc": 1, "last_index": None}] * 3
    sup._maybe_quarantine({"rc": 1, "last_index": None})
    assert sup.state["quarantined"] == []
    # Deadline-aborted attempts are environment, not poison: stalls at
    # the same index never quarantine (healthy data must not be dropped).
    stall = {"rc": -9, "last_index": 4, "aborted": "stall"}
    sup.state["attempts"] = [dict(stall)] * 3
    sup._maybe_quarantine(dict(stall))
    assert sup.state["quarantined"] == []
    # ...and an interleaved stall neither counts nor resets a CRASH
    # streak: crash, stall, crash at the same index still quarantines.
    sup.state["attempts"] = [dict(fail), dict(stall), dict(fail)]
    sup._maybe_quarantine(dict(fail))
    assert sup.state["quarantined"] == [5]


def test_quarantine_round_trip_through_env(tmp_path, monkeypatch):
    """child.quarantined_from_env reads what the supervisor persists."""
    from fps_tpu.supervise import child, supervisor

    state_path = tmp_path / "supervisor_state.json"
    state_path.write_text(json.dumps({"quarantined": [3, 5]}))
    monkeypatch.setenv(child.STATE_ENV, str(state_path))
    assert child.quarantined_from_env() == frozenset({3, 5})
    monkeypatch.setenv(child.STATE_ENV, str(tmp_path / "missing.json"))
    assert child.quarantined_from_env() == frozenset()
    del supervisor  # only imported for the mirrored-constant neighbors


def test_heartbeat_only_recorder_via_common(tmp_path, monkeypatch):
    """examples/common.attach_obs: a supervised run without --obs-dir
    still gets a (heartbeat-only) recorder so chunk events beat."""
    import argparse

    from fps_tpu.examples import common
    from fps_tpu.supervise import child

    hb_path = tmp_path / "hb.json"
    monkeypatch.setenv(child.HEARTBEAT_ENV, str(hb_path))
    args = argparse.Namespace(obs_dir=None, obs_watchdog_s=None,
                              heartbeat=None)
    rec = common.attach_obs(args)
    assert rec is not None
    rec.event("chunk", index=11)
    assert json.load(open(hb_path))["index"] == 12  # beat-before-work


def test_source_stall_classified_and_surfaced(tmp_path):
    """A stalled ``prefetch``-phase heartbeat is a wedged SOURCE, not a
    wedged driver (ROADMAP open item): the stall abort is classified as
    ``source_stall`` in the attempt record (supervisor_state.json) and
    the journal's ``deadline_abort`` event, counted in the digest, and
    folded by tools/obs_report.py."""
    child_code = (
        "import json, os, time\n"
        "p = os.environ['FPS_TPU_HEARTBEAT']\n"
        "json.dump({'version': 2, 'index': 2, 'phase': 'prefetch'},"
        " open(p, 'w'))\n"
        "time.sleep(120)\n"
    )
    rc, digest = _run_supervised(
        tmp_path / "state", [sys.executable, "-c", child_code],
        "--stall-timeout-s", "0.8", "--startup-grace-s", "10",
        "--term-grace-s", "0.3", "--max-restarts", "0", "--poll-s", "0.1",
        timeout=60,
    )
    assert digest["deadline_aborts"] == 1
    assert digest["source_stalls"] == 1
    with open(tmp_path / "state" / "supervisor_state.json",
              encoding="utf-8") as f:
        state = json.load(f)
    assert state["attempts"][-1]["stall_kind"] == "source_stall"
    assert state["attempts"][-1]["last_phase"] == "prefetch"
    # A stall is environmental evidence, never poison: no quarantine.
    assert digest["quarantined"] == []
    events = [json.loads(line) for line in
              open(tmp_path / "state" / "journal-supervisor.jsonl")]
    aborts = [e for e in events if e.get("event") == "deadline_abort"]
    assert aborts and aborts[0]["stall_kind"] == "source_stall"
    # obs_report folds the supervisor journal into the run digest.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(_ROOT, "tools", "obs_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)
    folded = report.render_digest(str(tmp_path / "state"))
    assert folded["source_stalls"] == 1


def test_driver_stall_not_classified_as_source(tmp_path):
    """A stall whose last beat was a dispatch-phase (or phase-less) beat
    stays a driver stall — the classifier must not over-trigger."""
    child_code = (
        "import json, os, time\n"
        "p = os.environ['FPS_TPU_HEARTBEAT']\n"
        "json.dump({'version': 2, 'index': 1, 'phase': 'dispatch'},"
        " open(p, 'w'))\n"
        "time.sleep(120)\n"
    )
    rc, digest = _run_supervised(
        tmp_path / "state", [sys.executable, "-c", child_code],
        "--stall-timeout-s", "0.8", "--startup-grace-s", "10",
        "--term-grace-s", "0.3", "--max-restarts", "0", "--poll-s", "0.1",
        timeout=60,
    )
    assert digest["deadline_aborts"] == 1
    assert digest["source_stalls"] == 0
    with open(tmp_path / "state" / "supervisor_state.json",
              encoding="utf-8") as f:
        state = json.load(f)
    assert state["attempts"][-1]["stall_kind"] == "driver_stall"


# ---------------------------------------------------------------------------
# Full stack (slow): real jax child, SIGSTOP wedge, bit-identical resume.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_supervised_logreg_resumes_bit_identical(tmp_path):
    """The ISSUE acceptance scenario end-to-end: a SIGSTOP-wedged real
    training run is deadline-aborted, restarted with backoff, resumes
    from latest_valid_step (exactly one chunk of lost work, replayed),
    selects no corrupt snapshot, and lands on final weights BIT-IDENTICAL
    to an unsupervised straight run. One shared implementation with
    tools/chaos_sweep.py's ``supervised`` scenario."""
    from fps_tpu.testing.supervised_demo import run_supervised_scenario

    ok, detail = run_supervised_scenario(str(tmp_path))
    assert ok, detail
