"""Device-resident word2vec: fused pair generation + alias negative sampling.

Invariant-style tests on the 8-device CPU mesh (SURVEY.md §4 approach):
pair-count and windowing semantics of the on-device generator, alias-sampler
distribution correctness, and end-to-end learning through ``run_indexed``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fps_tpu.core.driver import num_workers_of
from fps_tpu.models.word2vec import (
    W2VConfig,
    Word2VecDevicePlan,
    Word2VecWorker,
    _build_alias,
    word2vec,
    word2vec_block,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import synthetic_corpus

V = 300


@pytest.fixture(scope="module")
def mesh(devices8):
    return make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])


def test_alias_tables_match_distribution():
    rng = np.random.default_rng(0)
    p = rng.dirichlet(np.ones(50) * 0.3)
    prob, alias = _build_alias(p)
    # Exact check: total mass routed to each outcome equals p (up to fp).
    mass = prob.copy()
    for j in range(50):
        mass[alias[j]] += 1.0 - prob[j]
    np.testing.assert_allclose(mass / 50.0, p, atol=1e-12)


def test_device_pairs_match_host_window_semantics(mesh):
    """Every ordered adjacency within the dynamic window appears exactly
    twice (both orientations); nothing crosses the kept-stream boundary."""
    W = num_workers_of(mesh)
    tokens = np.arange(1000, dtype=np.int32) % 97  # distinct-ish stream
    uni = np.bincount(tokens, minlength=97).astype(np.float64)
    cfg = W2VConfig(vocab_size=97, window=3, negatives=2, subsample_t=None)
    plan = Word2VecDevicePlan(tokens, uni, cfg, mesh, num_workers=W,
                              block_len=16, seed=0)
    total_pairs = 0.0
    args = plan.epoch_args(0)
    batch_at = jax.jit(plan.local_batch_at)
    for t in range(plan.steps_per_epoch):
        for w in range(W):
            b = batch_at(args, jnp.int32(w), jnp.int32(t))
            wt = np.asarray(b["weight"])
            c = np.asarray(b["center"])[wt > 0]
            x = np.asarray(b["context"])[wt > 0]
            total_pairs += wt.sum()
            # valid pairs are always within `window` of each other in the
            # (unsubsampled) stream: |pos(c) - pos(x)| <= window given the
            # stream is arange % 97, adjacent tokens differ by 1 mod 97.
            d = (x.astype(int) - c.astype(int)) % 97
            assert ((d <= cfg.window) | (d >= 97 - cfg.window)).all()
    # E[pairs] = 2 * E[half] * n_adjacent ~ 2 * 2 * 1000; dynamic windows
    # draw U{1..3} per center so exact count varies with the seed.
    assert 2500 < total_pairs < 5500, total_pairs


def test_subsample_reduces_pairs(mesh):
    W = num_workers_of(mesh)
    tokens = synthetic_corpus(V, 30_000, seed=0)
    uni = np.bincount(tokens, minlength=V).astype(np.float64)
    cfg_all = W2VConfig(vocab_size=V, window=3, subsample_t=None)
    cfg_sub = W2VConfig(vocab_size=V, window=3, subsample_t=1e-3)
    n_all = Word2VecDevicePlan(tokens, uni, cfg_all, mesh, num_workers=W,
                               block_len=64).steps_per_epoch
    n_sub = Word2VecDevicePlan(tokens, uni, cfg_sub, mesh, num_workers=W,
                               block_len=64).steps_per_epoch
    assert n_sub < n_all


def test_fused_w2v_learns(mesh):
    W = num_workers_of(mesh)
    tokens = synthetic_corpus(V, 60_000, num_topics=8, seed=0)
    uni = np.bincount(tokens, minlength=V).astype(np.float64)
    cfg = W2VConfig(vocab_size=V, dim=16, window=3, negatives=4,
                    learning_rate=0.05, subsample_t=None)
    trainer, store = word2vec(mesh, cfg, uni, max_steps_per_call=32)
    tables, ls = trainer.init_state(jax.random.key(0))
    plan = Word2VecDevicePlan(tokens, uni, cfg, mesh, num_workers=W,
                              block_len=64, seed=0)
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=3
    )
    losses = [float(m["loss"].sum() / m["n"].sum()) for m in metrics]
    assert losses[-1] < losses[0] * 0.85, losses
    # multi-call splitting exercised: steps_per_epoch > max_steps_per_call
    assert plan.steps_per_epoch > 32


def test_block_worker_learns_and_tracks_pair_worker(mesh):
    """The block-granularity worker (one pull/push row per block position,
    group-shared negatives) must learn the same task with a comparable
    per-pair loss trajectory to the pair worker."""
    W = num_workers_of(mesh)
    tokens = synthetic_corpus(V, 60_000, num_topics=8, seed=0)
    uni = np.bincount(tokens, minlength=V).astype(np.float64)
    cfg = W2VConfig(vocab_size=V, dim=16, window=3, negatives=4,
                    learning_rate=0.05, subsample_t=None)

    def run(block):
        factory = (lambda: word2vec_block(mesh, cfg, uni, 64)) if block \
            else (lambda: word2vec(mesh, cfg, uni))
        trainer, store = factory()
        tables, ls = trainer.init_state(jax.random.key(0))
        plan = Word2VecDevicePlan(
            tokens, uni, cfg, mesh, num_workers=W, block_len=64, seed=0,
            mode="block" if block else "pairs",
        )
        tables, ls, metrics = trainer.run_indexed(
            tables, ls, plan, jax.random.key(1), epochs=3
        )
        return [float(m["loss"].sum() / m["n"].sum()) for m in metrics]

    block_losses = run(True)
    pair_losses = run(False)
    assert block_losses[-1] < block_losses[0] * 0.85, block_losses
    # Same objective, same data: trajectories track within a loose band
    # (different negative-sampling coupling and combine granularity).
    for b, p in zip(block_losses, pair_losses):
        assert abs(b - p) < 0.35 * max(p, 1e-6), (block_losses, pair_losses)


def test_block_worker_pair_accounting(mesh):
    """Block mode counts exactly the pairs the pair mode emits (same
    blocks, same half-window draws -> identical weighted pair counts)."""
    W = num_workers_of(mesh)
    tokens = np.arange(1000, dtype=np.int32) % 97
    uni = np.bincount(tokens, minlength=97).astype(np.float64)
    cfg = W2VConfig(vocab_size=97, window=3, negatives=2, subsample_t=None,
                    neg_group_size=8)
    counts = {}
    for mode in ("pairs", "block"):
        plan = Word2VecDevicePlan(tokens, uni, cfg, mesh, num_workers=W,
                                  block_len=16, seed=0, mode=mode)
        args = plan.epoch_args(0)
        batch_at = jax.jit(plan.local_batch_at)
        total = 0.0
        for t in range(plan.steps_per_epoch):
            for w in range(W):
                b = batch_at(args, jnp.int32(w), jnp.int32(t))
                if mode == "pairs":
                    total += float(np.asarray(b["weight"]).sum())
                else:
                    half = np.asarray(b["half"]).astype(int)
                    vlen = int(b["valid_len"])
                    L = len(half)
                    for d in range(1, cfg.window + 1):
                        ok = (half >= d) & (np.arange(L) + d < vlen)
                        total += 2.0 * ok.sum()
        counts[mode] = total
    assert counts["pairs"] == counts["block"], counts


def test_block_worker_embedding_quality_parity(mesh):
    """QUALITY gate for the block worker's throughput claim (round-2
    verdict #3): on a planted-synonym corpus (each base word w and its
    partner w+V/2 are used interchangeably, so their true embeddings
    coincide), nearest-neighbor partner recovery after equal epochs must
    be far above chance for BOTH workers and the block worker must not
    trail the pair worker — i.e. the ~10x-fewer-transactions block
    coupling (word2vec.py group-shared negatives) buys throughput without
    buying down embedding quality. Measured during development: @5
    recovery 0.767 (pairs) vs 0.783 (block) at 8 epochs, chance 0.017."""
    from fps_tpu.models.word2vec import nearest_neighbors

    V2 = 150
    VV = 2 * V2
    rng = np.random.default_rng(7)
    base = synthetic_corpus(V2, 80_000, num_topics=8, seed=0)
    tokens = np.where(rng.random(len(base)) < 0.5, base, base + V2).astype(
        np.int32)
    uni = np.bincount(tokens, minlength=VV).astype(np.float64)
    W = num_workers_of(mesh)
    cfg = W2VConfig(vocab_size=VV, dim=16, window=3, negatives=4,
                    learning_rate=0.05, subsample_t=None)

    def recovery(mode):
        factory = (lambda: word2vec_block(mesh, cfg, uni, 64)) \
            if mode == "block" else (lambda: word2vec(mesh, cfg, uni))
        trainer, store = factory()
        tables, ls = trainer.init_state(jax.random.key(0))
        plan = Word2VecDevicePlan(tokens, uni, cfg, mesh, num_workers=W,
                                  block_len=64, seed=0, mode=mode)
        trainer.run_indexed(tables, ls, plan, jax.random.key(1), epochs=8)
        probes = np.argsort(-uni[:V2])[:60]
        ids, _ = nearest_neighbors(store, probes, k=5)
        partner = probes + V2
        return float(np.mean([partner[i] in ids[i]
                              for i in range(len(probes))]))

    rec_block = recovery("block")
    rec_pairs = recovery("pairs")
    # Both must crush chance (5/300 ~ 0.017)...
    assert rec_pairs >= 0.5, rec_pairs
    assert rec_block >= 0.5, rec_block
    # ...and block must be within noise of pairs (no quality-for-speed
    # trade hiding in the coupling).
    assert rec_block >= rec_pairs - 0.1, (rec_block, rec_pairs)


def test_block_path_sketch_tap_tracks_exact(mesh):
    """The co-occurrence sketch must also ride the BLOCK (fused) path: the
    tap reconstructs the block batch's exact pair stream id-only
    (block_pair_stream) and sketches it. Verified two ways against a
    combined tap computing ground truth from the SAME reconstructed
    stream inside the compiled loop: (a) the stream's total pair weight
    equals the worker's own npairs metric (exactness of the
    reconstruction), and (b) tug-of-war similarities track the exact
    co-occurrence inner products (estimator accuracy)."""
    from fps_tpu.models.word2vec import (
        block_pair_stream,
        sketch_similarity,
        _sketch_pair_stream,
    )
    from fps_tpu.sketch import TugOfWarSpec

    W = num_workers_of(mesh)
    V2 = 80
    tokens = synthetic_corpus(V2, 30_000, num_topics=4, seed=5)
    uni = np.bincount(tokens, minlength=V2).astype(np.float64)
    cfg = W2VConfig(vocab_size=V2, dim=8, window=2, negatives=2,
                    subsample_t=None)
    probe = np.argsort(-uni)[:6].astype(np.int32)
    P = len(probe)
    spec = TugOfWarSpec(depth=5, width=512, seed=7)
    probe_j = jnp.asarray(probe)

    def tap(tables, batch, local_state, t):
        del tables, local_state, t
        center, ctx, w = block_pair_stream(batch)
        sk = _sketch_pair_stream(spec, probe_j, center, ctx, w)
        # Exact (P, V2) context counts from the same stream + total weight.
        eq = center[:, None] == probe_j[None, :]
        row = jnp.where(eq.any(axis=1), jnp.argmax(eq, axis=1), -1)
        flat = jnp.where(row >= 0, row * V2 + ctx, -1)
        exact = jnp.zeros(P * V2, jnp.float32).at[
            jnp.where(flat >= 0, flat, P * V2)
        ].add(jnp.where(row >= 0, w, 0.0), mode="drop").reshape(P, V2)
        return {"sketch": sk, "exact": exact, "wsum": jnp.sum(w)}

    trainer, store = word2vec_block(mesh, cfg, uni, 64, step_tap=tap)
    tables, ls = trainer.init_state(jax.random.key(0))
    plan = Word2VecDevicePlan(tokens, uni, cfg, mesh, num_workers=W,
                              block_len=64, seed=0, mode="block")
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=1
    )

    sk_sum = None
    ex_sum = None
    wsum = 0.0
    npairs = 0.0
    for m in metrics:
        sk_sum = (0 if sk_sum is None else sk_sum) + np.asarray(
            m["tap"]["sketch"]).sum(axis=(0, 1))
        ex_sum = (0 if ex_sum is None else ex_sum) + np.asarray(
            m["tap"]["exact"]).sum(axis=(0, 1))
        wsum += float(np.asarray(m["tap"]["wsum"]).sum())
        npairs += float(np.asarray(m["n"]).sum())

    # (a) the reconstructed stream IS the worker's pair stream.
    assert abs(wsum - npairs) < 1e-3 * max(npairs, 1.0), (wsum, npairs)
    assert npairs > 1000

    est = sketch_similarity(sk_sum)
    exact = ex_sum.astype(np.float64) @ ex_sum.astype(np.float64).T
    rel = np.abs(np.diag(est) - np.diag(exact)) / np.maximum(
        np.diag(exact), 1.0
    )
    assert np.median(rel) < 0.15, (np.diag(est), np.diag(exact))
    iu = np.triu_indices(P, k=1)
    r = np.corrcoef(est[iu], exact[iu])[0, 1]
    assert r > 0.9, (r, est[iu], exact[iu])
