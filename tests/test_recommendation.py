"""Top-K recommendation tests (the reference's ...AndTopK MF variant).

Correctness oracle: brute-force numpy ranking over the logical table.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu.core.store import ParamStore, TableSpec
from fps_tpu.models.recommendation import (
    build_topk_fn,
    mf_user_vectors,
    recommend_topk,
)
from fps_tpu.parallel.mesh import SHARD_AXIS, make_ps_mesh


def _store(mesh, num_ids, dim, seed=0):
    rng = np.random.default_rng(seed)
    logical = rng.normal(0, 1, (num_ids, dim)).astype(np.float32)

    def init(key, ids):
        safe = jnp.minimum(ids, num_ids - 1)
        return jnp.take(jnp.asarray(logical), safe, axis=0)

    store = ParamStore(mesh, [TableSpec("items", num_ids, dim, init)])
    store.init(jax.random.key(0))
    return store, logical


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (1, 3)])
def test_topk_matches_bruteforce(devices8, mesh_shape):
    nd, ns = mesh_shape
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devices8[: nd * ns])
    num_ids, dim, B, k = 57, 6, 9, 5
    store, logical = _store(mesh, num_ids, dim)

    rng = np.random.default_rng(1)
    q = rng.normal(0, 1, (B, dim)).astype(np.float32)
    ids, scores = recommend_topk(store, "items", q, k)

    want = np.argsort(-(q @ logical.T), axis=1)[:, :k]
    np.testing.assert_array_equal(ids, want)
    np.testing.assert_allclose(
        scores, np.take_along_axis(q @ logical.T, want, 1), rtol=1e-5
    )


def test_topk_with_exclusions(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1, devices=devices8)
    num_ids, dim, B, k, E = 40, 4, 6, 4, 3
    store, logical = _store(mesh, num_ids, dim, seed=2)

    rng = np.random.default_rng(3)
    q = rng.normal(0, 1, (B, dim)).astype(np.float32)
    full = q @ logical.T
    # Exclude each query's true top-E items: results must be ranks E..E+k-1.
    order = np.argsort(-full, axis=1)
    exclude = order[:, :E].astype(np.int32)
    ids, _ = recommend_topk(store, "items", q, k, exclude=exclude)
    np.testing.assert_array_equal(ids, order[:, E : E + k])

    # -1 slots are ignored.
    none = np.full((B, E), -1, np.int32)
    ids2, _ = recommend_topk(store, "items", q, k, exclude=none)
    np.testing.assert_array_equal(ids2, order[:, :k])


def test_topk_fn_is_jittable_and_reusable(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8)
    store, logical = _store(mesh, 33, 5, seed=4)
    fn = build_topk_fn(store, "items", k=3, exclude_capacity=0)
    repl = NamedSharding(mesh, P())
    for seed in (5, 6):
        q = np.random.default_rng(seed).normal(0, 1, (4, 5)).astype(np.float32)
        ex = jax.device_put(jnp.full((4, 1), -1, jnp.int32), repl)
        ids, _ = fn(store.tables, jax.device_put(jnp.asarray(q), repl), ex)
        want = np.argsort(-(q @ logical.T), axis=1)[:, :3]
        np.testing.assert_array_equal(np.asarray(ids), want)


def test_mf_user_vectors_layout():
    W = 4
    num_users, rank = 10, 3
    rps = -(-num_users // W)
    table = np.zeros((rps * W, rank), np.float32)
    for u in range(num_users):
        table[(u % W) * rps + u // W] = u
    users = np.array([0, 3, 7, 9])
    got = mf_user_vectors(table, W, users)
    np.testing.assert_array_equal(got, np.repeat(users[:, None], rank, 1))


# ---------------------------------------------------------------------------
# Online (in-loop) top-K emission — the streaming AndTopK shape.
# ---------------------------------------------------------------------------

def test_online_topk_tap_interleaves_and_matches_bruteforce(devices8):
    """Top-K events ride the metrics stream interleaved with training, per
    worker, on the tap cadence; with lr=0 (frozen tables) the emitted
    ranking must equal the brute-force oracle over each worker's users."""
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.models.recommendation import (
        make_online_topk_tap,
        mf_topk_query_fn,
    )
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    NU, NI, K, Q, EVERY = 40, 29, 5, 3, 2
    cfg = MFConfig(num_users=NU, num_items=NI, rank=4, learning_rate=0.0,
                   reg=0.0)
    trainer, store = online_mf(mesh, cfg, donate=False)
    trainer.config = __import__("dataclasses").replace(
        trainer.config,
        step_tap=make_online_topk_tap(
            store, "item_factors", K, every=EVERY,
            query_fn=mf_topk_query_fn(W, Q),
        ),
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    data = synthetic_ratings(NU, NI, 8 * 8 * W, seed=0)
    chunk = next(epoch_chunks(data, num_workers=W, local_batch=8,
                              steps_per_chunk=8, route_key="user"))
    tables, ls, m = trainer.run_chunk(tables, ls, chunk, jax.random.key(1))

    tap = {k2: np.asarray(v) for k2, v in m["tap"].items()}
    assert tap["topk_ids"].shape == (8, W, Q, K)
    # Off-cadence steps are filled; on-cadence steps carry real emissions.
    assert (tap["topk_ids"][1] == -1).all()
    assert (tap["topk_ids"][0] >= 0).all()
    assert (tap["topk_query"][1] == -1).all()

    # Oracle: lr=0 so tables never moved — rank initial factors directly.
    items = store.lookup_host("item_factors", np.arange(NI))
    ls_host = np.asarray(ls)
    checked = 0
    for t in range(0, 8, EVERY):
        for w in range(W):
            users = tap["topk_query"][t, w]
            valid = users >= 0  # padded batch slots emit query id -1
            if not valid.any():
                continue
            qvecs = mf_user_vectors(ls_host, W, users[valid])
            want = np.argsort(-(qvecs @ items.T), axis=1)[:, :K]
            np.testing.assert_array_equal(tap["topk_ids"][t, w][valid], want)
            checked += int(valid.sum())
    assert checked > 0


def test_mf_negative_sampling_improves_implicit_ranking(devices8):
    """On positive-only (implicit) feedback every observed target is 1.0,
    so plain MF barely separates unseen-good from unseen-bad items.
    Sampling unrated items as weighted pseudo-negatives (the reference
    MF's optional knob) must improve held-out ranking (AUC of held-out
    positives vs never-interacted items) and widen the score margin
    between interacted and never-interacted items."""
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.models.recommendation import mf_user_vectors
    from fps_tpu.utils.datasets import synthetic_implicit

    mesh = make_ps_mesh(num_shards=8, num_data=1, devices=devices8[:8])
    W = num_workers_of(mesh)
    NU, NI, HELD = 48, 96, 4
    data = synthetic_implicit(NU, NI, 28, rank=3, seed=5)
    data["rating"] = np.ones_like(data["rating"])  # pure implicit

    # Hold out each user's last interactions; novel ones score the model.
    train_mask = np.ones(len(data["user"]), bool)
    held = {}
    for u in range(NU):
        rows = np.flatnonzero(data["user"] == u)
        held[u] = set(int(i) for i in data["item"][rows[-HELD:]])
        train_mask[rows[-HELD:]] = False
    train = {k2: v[train_mask] for k2, v in data.items()}
    seen = {
        u: set(map(int, np.unique(train["item"][train["user"] == u])))
        for u in range(NU)
    }
    held_eff = {u: held[u] - seen[u] for u in range(NU)}

    def run(negatives):
        cfg = MFConfig(num_users=NU, num_items=NI, rank=8,
                       learning_rate=0.08, reg=0.01,
                       negative_samples=negatives, negative_weight=0.5)
        trainer, store = online_mf(mesh, cfg)
        tables, ls = trainer.init_state(jax.random.key(0))
        chunks = multi_epoch_chunks(
            train, 12, num_workers=W, local_batch=16, steps_per_chunk=8,
            route_key="user", seed=2,
        )
        tables, ls, _ = trainer.fit_stream(tables, ls, chunks,
                                           jax.random.key(1))
        P = mf_user_vectors(np.asarray(ls), W, np.arange(NU))
        Q = store.lookup_host("item_factors", np.arange(NI))
        S = P @ Q.T
        aucs, margins = [], []
        for u in range(NU):
            pos = list(held_eff[u])
            neg = [i for i in range(NI)
                   if i not in seen[u] and i not in held[u]]
            if not pos:
                continue
            ns = S[u, neg]
            aucs.append(np.mean([np.mean(p > ns) for p in S[u, pos]]))
            margins.append(S[u, list(seen[u])].mean() - ns.mean())
        return float(np.mean(aucs)), float(np.mean(margins))

    auc0, margin0 = run(0)
    auc4, margin4 = run(4)
    assert auc4 > auc0 + 0.02, (auc0, auc4)
    assert margin4 > margin0 * 1.5, (margin0, margin4)
    assert auc4 > 0.6, auc4


def test_online_topk_tap_k_exceeds_candidates(devices8):
    """k larger than the merged candidate pool (S * min(k, rows_per_shard))
    must not fail at trace time; emitted slots beyond the real item count
    are -1 ids / NEG_INF scores and the real prefix matches brute force.
    Regression for the unclamped final lax.top_k (round-2 advice)."""
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.models.recommendation import (
        NEG_INF,
        make_online_topk_tap,
        mf_topk_query_fn,
        mf_user_vectors,
    )
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    # NI=6 over 4 shards -> rows_per_shard=2 -> merged pool 4*2=8 < K=10.
    NU, NI, K, Q = 16, 6, 10, 2
    cfg = MFConfig(num_users=NU, num_items=NI, rank=4, learning_rate=0.0,
                   reg=0.0)
    trainer, store = online_mf(mesh, cfg, donate=False)
    trainer.config = __import__("dataclasses").replace(
        trainer.config,
        step_tap=make_online_topk_tap(
            store, "item_factors", K, every=1,
            query_fn=mf_topk_query_fn(W, Q),
        ),
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    data = synthetic_ratings(NU, NI, 4 * 4 * W, seed=0)
    chunk = next(epoch_chunks(data, num_workers=W, local_batch=4,
                              steps_per_chunk=4, route_key="user"))
    tables, ls, m = trainer.run_chunk(tables, ls, chunk, jax.random.key(1))

    tap = {k2: np.asarray(v) for k2, v in m["tap"].items()}
    assert tap["topk_ids"].shape == (4, W, Q, K)

    items = store.lookup_host("item_factors", np.arange(NI))
    ls_host = np.asarray(ls)
    checked = 0
    for t in range(4):
        for w in range(W):
            users = tap["topk_query"][t, w]
            valid = users >= 0
            if not valid.any():
                continue
            ids_tw = tap["topk_ids"][t, w][valid]
            scores_tw = tap["topk_scores"][t, w][valid]
            # Real prefix: all NI items ranked exactly as brute force.
            qvecs = mf_user_vectors(ls_host, W, users[valid])
            want = np.argsort(-(qvecs @ items.T), axis=1)[:, :NI]
            np.testing.assert_array_equal(ids_tw[:, :NI], want)
            # Beyond the pool: sentinel slots only.
            assert (scores_tw[:, NI:] <= float(NEG_INF)).all()
            checked += int(valid.sum())
    assert checked > 0
