"""Top-K recommendation tests (the reference's ...AndTopK MF variant).

Correctness oracle: brute-force numpy ranking over the logical table.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu.core.store import ParamStore, TableSpec
from fps_tpu.models.recommendation import (
    build_topk_fn,
    mf_user_vectors,
    recommend_topk,
)
from fps_tpu.parallel.mesh import SHARD_AXIS, make_ps_mesh


def _store(mesh, num_ids, dim, seed=0):
    rng = np.random.default_rng(seed)
    logical = rng.normal(0, 1, (num_ids, dim)).astype(np.float32)

    def init(key, ids):
        safe = jnp.minimum(ids, num_ids - 1)
        return jnp.take(jnp.asarray(logical), safe, axis=0)

    store = ParamStore(mesh, [TableSpec("items", num_ids, dim, init)])
    store.init(jax.random.key(0))
    return store, logical


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (1, 3)])
def test_topk_matches_bruteforce(devices8, mesh_shape):
    nd, ns = mesh_shape
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devices8[: nd * ns])
    num_ids, dim, B, k = 57, 6, 9, 5
    store, logical = _store(mesh, num_ids, dim)

    rng = np.random.default_rng(1)
    q = rng.normal(0, 1, (B, dim)).astype(np.float32)
    ids, scores = recommend_topk(store, "items", q, k)

    want = np.argsort(-(q @ logical.T), axis=1)[:, :k]
    np.testing.assert_array_equal(ids, want)
    np.testing.assert_allclose(
        scores, np.take_along_axis(q @ logical.T, want, 1), rtol=1e-5
    )


def test_topk_with_exclusions(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1, devices=devices8)
    num_ids, dim, B, k, E = 40, 4, 6, 4, 3
    store, logical = _store(mesh, num_ids, dim, seed=2)

    rng = np.random.default_rng(3)
    q = rng.normal(0, 1, (B, dim)).astype(np.float32)
    full = q @ logical.T
    # Exclude each query's true top-E items: results must be ranks E..E+k-1.
    order = np.argsort(-full, axis=1)
    exclude = order[:, :E].astype(np.int32)
    ids, _ = recommend_topk(store, "items", q, k, exclude=exclude)
    np.testing.assert_array_equal(ids, order[:, E : E + k])

    # -1 slots are ignored.
    none = np.full((B, E), -1, np.int32)
    ids2, _ = recommend_topk(store, "items", q, k, exclude=none)
    np.testing.assert_array_equal(ids2, order[:, :k])


def test_topk_fn_is_jittable_and_reusable(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8)
    store, logical = _store(mesh, 33, 5, seed=4)
    fn = build_topk_fn(store, "items", k=3, exclude_capacity=0)
    repl = NamedSharding(mesh, P())
    for seed in (5, 6):
        q = np.random.default_rng(seed).normal(0, 1, (4, 5)).astype(np.float32)
        ex = jax.device_put(jnp.full((4, 1), -1, jnp.int32), repl)
        ids, _ = fn(store.tables, jax.device_put(jnp.asarray(q), repl), ex)
        want = np.argsort(-(q @ logical.T), axis=1)[:, :3]
        np.testing.assert_array_equal(np.asarray(ids), want)


def test_mf_user_vectors_layout():
    W = 4
    num_users, rank = 10, 3
    rps = -(-num_users // W)
    table = np.zeros((rps * W, rank), np.float32)
    for u in range(num_users):
        table[(u % W) * rps + u // W] = u
    users = np.array([0, 3, 7, 9])
    got = mf_user_vectors(table, W, users)
    np.testing.assert_array_equal(got, np.repeat(users[:, None], rank, 1))
