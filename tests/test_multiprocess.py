"""Real multi-process distributed training — the multi-host fabric, tested.

The reference scales across TaskManagers over Flink's Netty fabric; the
TPU-native replacement is multi-controller JAX (`jax.distributed`) with XLA
collectives spanning hosts. These tests run the FULL framework path as TWO
OS processes of 4 CPU devices each over a local gloo coordinator, and
assert the result is bit-identical to the same global (2, 4) mesh driven
by one process — proving the programs, shardings, and placements carry
across process topologies unchanged. Covered paths: device-resident ingest
with fused indexed epochs (sync), and HOST ingest through ``fit_stream``
(numpy chunks placed via ``make_array_from_process_local_data``) in both
sync and SSP modes.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_processes(tmp_path, scenario: str) -> np.ndarray:
    port = _free_port()
    out = str(tmp_path / f"mp_{scenario}.npz")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = _ROOT
    worker = os.path.join(_ROOT, "tests", "_mp_worker.py")
    # Workers write to files, not pipes: the two processes rendezvous in
    # cross-process collectives, so a full OS pipe buffer on one would
    # deadlock the other.
    logs = [str(tmp_path / f"worker{pid}_{scenario}.log") for pid in range(2)]
    procs = []
    for pid in range(2):
        with open(logs[pid], "w") as logf:
            procs.append(subprocess.Popen(
                [sys.executable, worker, str(pid), "2", str(port), out,
                 scenario],
                env=env, cwd=_ROOT, stdout=logf, stderr=subprocess.STDOUT,
            ))
    try:
        for p, log in zip(procs, logs):
            rc = p.wait(timeout=300)
            with open(log) as f:
                text = f.read()
            assert rc == 0, f"worker failed:\n{text[-3000:]}"
    finally:
        for p in procs:  # don't orphan a worker blocked in a collective
            if p.poll() is None:
                p.kill()
    assert os.path.exists(out)
    return np.load(out)["item_factors"]


def _single_process_reference(devices8, scenario: str) -> np.ndarray:
    import jax

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    if scenario == "indexed_shard8":
        mesh = make_ps_mesh(num_shards=8, num_data=1, devices=devices8[:8])
    else:
        mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 2000, seed=0)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    sync_every = 2 if scenario == "host_ssp" else None
    trainer, store = online_mf(mesh, cfg, sync_every=sync_every)
    tables, ls = trainer.init_state(jax.random.key(0))
    if scenario in ("indexed", "indexed_shard8"):
        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(
            ds, num_workers=W, local_batch=32, route_key="user", seed=5
        )
        tables, ls, _ = trainer.run_indexed(
            tables, ls, plan, jax.random.key(1), epochs=2
        )
    else:
        chunks = multi_epoch_chunks(
            data, 2, num_workers=W, local_batch=32, steps_per_chunk=4,
            route_key="user", sync_every=sync_every, seed=5,
        )
        tables, ls, _ = trainer.fit_stream(tables, ls, chunks,
                                           jax.random.key(1))
    return store.dump_model("item_factors")[1]


@pytest.mark.parametrize(
    "scenario", ["indexed", "host_sync", "host_ssp", "indexed_shard8"]
)
def test_two_process_training_matches_single_process(devices8, tmp_path,
                                                     scenario):
    """``indexed_shard8`` is the round-2-verdict topology: a (data=1,
    shard=8) mesh over 2 processes puts the SHARD axis across the process
    boundary, so pull/push collectives, ``dump_model`` replication, and the
    checkpoint save all move shard rows between OS processes (the worker
    also cross-checks checkpoint-vs-dump agreement in-process)."""
    mp_values = _run_two_processes(tmp_path, scenario)
    sp_values = _single_process_reference(devices8, scenario)
    np.testing.assert_array_equal(sp_values, mp_values)
