"""Causal tracing (fps_tpu.obs.trace + tools/trace_export.py).

ISSUE 12 acceptance pins:
* trace on/off lowers BYTE-IDENTICAL programs (HLO asserted) and
  bit-identical numerics on MF + logreg — tracing is host-side only;
* the env-contract mirrors (obs/trace.py vs supervise/child.py vs
  supervise/supervisor.py) cannot drift;
* trace_export reconstructs one causally-linked span tree from pod +
  supervisor + run journals (the full cross-host assertion lives in the
  slow pod chaos scenarios / tools/chaos_sweep.py).
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax

from fps_tpu import obs
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import epoch_chunks
from fps_tpu.obs.trace import (
    PARENT_SPAN_ENV,
    TRACE_ID_ENV,
    TraceContext,
    Tracer,
    new_span_id,
    new_trace_id,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing.workloads import NF, logreg_chunks, logreg_data, weights

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_export():
    spec = importlib.util.spec_from_file_location(
        "trace_export", os.path.join(_ROOT, "tools", "trace_export.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- unit: ids, context, env mirror --------------------------------------


def test_env_contract_mirrors_match():
    """The stdlib-only supervisor/child layers mirror the env names (they
    are loaded by file path, without the package) — the three definitions
    must be identical or propagation silently breaks."""
    from fps_tpu.supervise import child, supervisor

    assert child.TRACE_ID_ENV == TRACE_ID_ENV
    assert child.PARENT_SPAN_ENV == PARENT_SPAN_ENV
    assert supervisor.TRACE_ID_ENV == TRACE_ID_ENV
    assert supervisor.PARENT_SPAN_ENV == PARENT_SPAN_ENV


def test_trace_context_env_round_trip(monkeypatch):
    monkeypatch.delenv(TRACE_ID_ENV, raising=False)
    monkeypatch.delenv(PARENT_SPAN_ENV, raising=False)
    assert not TraceContext.from_env().active
    ctx = TraceContext(trace_id="t" * 32, parent_id="p" * 16)
    for k, v in ctx.child_env("s" * 16).items():
        monkeypatch.setenv(k, v)
    got = TraceContext.from_env()
    assert got.trace_id == "t" * 32
    assert got.parent_id == "s" * 16  # re-parented under the new span

    from fps_tpu.supervise import child

    assert child.trace_from_env() == {"trace_id": "t" * 32,
                                      "parent_id": "s" * 16}


def test_ids_are_fresh_and_well_formed():
    a, b = new_trace_id(), new_trace_id()
    assert a != b and len(a) == 32 and int(a, 16) >= 0
    s, t = new_span_id(), new_span_id()
    assert s != t and len(s) == 16 and int(s, 16) >= 0


def test_tracer_span_records(tmp_path):
    mem = obs.MemorySink()
    rec = obs.Recorder(sinks=[mem])
    tr = Tracer(rec, trace_id="trace1", parent_id="root1")
    with tr.span("work", epoch=3) as sid:
        child_sid = tr.instant("inner", parent_id=sid)
    spans = mem.events("span")
    assert [s["span"] for s in spans] == ["inner", "work"]
    outer = spans[1]
    assert outer["trace_id"] == "trace1"
    assert outer["parent_id"] == "root1"
    assert outer["span_id"] == sid
    assert outer["epoch"] == 3
    assert outer["t1"] >= outer["t0"]
    inner = spans[0]
    assert inner["parent_id"] == sid and inner["span_id"] == child_sid


def test_open_run_carries_trace_context(tmp_path, monkeypatch):
    monkeypatch.setenv(TRACE_ID_ENV, "f" * 32)
    monkeypatch.setenv(PARENT_SPAN_ENV, "a" * 16)
    d = str(tmp_path / "obs")
    rec = obs.open_run(d, config={"x": 1}, install=False)
    with rec.trace.span("custom"):
        pass
    rec.close()
    [journal] = [os.path.join(d, f) for f in os.listdir(d)
                 if f.startswith("journal-")]
    recs = [json.loads(line) for line in open(journal)]
    start = next(r for r in recs if r["event"] == "run_start")
    assert start["trace_id"] == "f" * 32
    assert start["parent_id"] == "a" * 16
    assert start["span_id"]
    span = next(r for r in recs if r["event"] == "span")
    assert span["trace_id"] == "f" * 32
    assert span["parent_id"] == start["span_id"]  # parents under the run


# -- acceptance: trace on/off is invisible to the program ----------------


def _logreg_harness(devices8):
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data(2000)
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)

    def build():
        return logistic_regression(
            mesh, LogRegConfig(num_features=NF, learning_rate=0.5))

    return build, chunks, lambda store: weights(store)


def _mf_harness(devices8):
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    data = synthetic_ratings(96, 64, 3000, rank=3, noise=0.05, seed=3)
    chunks = list(epoch_chunks(data, num_workers=W, local_batch=32,
                               steps_per_chunk=4, route_key="user",
                               seed=11))

    def build():
        return online_mf(mesh, MFConfig(num_users=96, num_items=64,
                                        rank=4, learning_rate=0.08))

    def final(store):
        # MF keeps user factors worker-LOCAL; the canonical table is the
        # item table.
        return store.lookup_host("item_factors", np.arange(64)).ravel()

    return build, chunks, final


@pytest.mark.parametrize("workload", ["logreg", "mf"])
def test_trace_on_off_byte_identical_hlo_and_numerics(
        devices8, tmp_path, monkeypatch, workload):
    """THE tentpole invariant: tracing (env contract + open_run journal
    + Tracer spans) is pure host bookkeeping — the lowered program is
    byte-identical and the trained tables bit-identical with it on or
    off, on MF and logreg."""
    harness = _logreg_harness if workload == "logreg" else _mf_harness
    build, chunks, final = harness(devices8)

    def run(traced: bool):
        if traced:
            monkeypatch.setenv(TRACE_ID_ENV, new_trace_id())
            monkeypatch.setenv(PARENT_SPAN_ENV, new_span_id())
            rec = obs.open_run(str(tmp_path / f"obs-{workload}"),
                               config={"w": workload})
        else:
            monkeypatch.delenv(TRACE_ID_ENV, raising=False)
            monkeypatch.delenv(PARENT_SPAN_ENV, raising=False)
            rec = None
        trainer, store = build()
        trainer.recorder = rec
        hlo = trainer.lowered_chunk_text(chunks[0], "sync")
        tables, ls = trainer.init_state(jax.random.key(0))
        if rec is not None:
            with rec.trace.span("fit", workload=workload):
                trainer.fit_stream(tables, ls, iter(chunks),
                                   jax.random.key(1))
            rec.close()
        else:
            trainer.fit_stream(tables, ls, iter(chunks),
                               jax.random.key(1))
        return hlo, final(store)

    hlo_off, out_off = run(False)
    hlo_on, out_on = run(True)
    assert hlo_on == hlo_off  # byte-identical lowered program
    np.testing.assert_array_equal(out_on, out_off)  # bit-identical


# -- trace_export: journals -> one causal tree ---------------------------


def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _synthetic_pod_dir(tmp_path):
    """A minimal 2-host pod trail: pod journal with launch + one
    coordinated restart, per-host supervisor journals with attempts
    parented to the decisions, one host's run journal with a chunk."""
    pod = str(tmp_path / "pod")
    trace = "t" * 32
    _write_jsonl(os.path.join(pod, "journal-pod.jsonl"), [
        {"kind": "event", "t": 100.0, "event": "pod_start", "host": "h0",
         "trace_id": trace, "span_id": "root", "roster": ["h0", "h1"],
         "pod_size": 2, "elastic": False},
        {"kind": "event", "t": 100.5, "event": "fence_written",
         "host": "h0", "trace_id": trace, "span_id": "f1",
         "parent_id": "d1", "min_epoch": 1, "step": 0},
        {"kind": "event", "t": 101.0, "event": "pod_launch", "host": "h0",
         "trace_id": trace, "span_id": "d1", "parent_id": "root",
         "epoch": 1, "step": 0, "world": 2, "members": ["h0", "h1"],
         "failed": [], "reason": "start", "restarts": 0},
        {"kind": "event", "t": 110.0, "event": "member_failed",
         "host": "h0", "trace_id": trace, "failed_host": "h1",
         "fail_kind": "crash", "epoch": 1},
        {"kind": "event", "t": 110.2, "event": "fence_written",
         "host": "h0", "trace_id": trace, "span_id": "f2",
         "parent_id": "d2", "min_epoch": 2, "step": 3},
        {"kind": "event", "t": 110.5, "event": "pod_restart",
         "host": "h0", "trace_id": trace, "span_id": "d2",
         "parent_id": "root", "epoch": 2, "step": 3, "world": 2,
         "members": ["h0", "h1"], "failed": ["h1"], "reason": "failure",
         "restarts": 1},
        {"kind": "event", "t": 130.0, "event": "pod_shutdown",
         "host": "h0", "trace_id": trace, "span_id": "end",
         "parent_id": "root", "epoch": 3, "reason": "complete"},
    ])
    for host, a1 in (("h0", "a0"), ("h1", "a1")):
        _write_jsonl(os.path.join(pod, host, "journal-supervisor.jsonl"), [
            {"kind": "event", "t": 101.2, "event": "attempt_start",
             "attempt": 0, "pid": 1, "trace_id": trace,
             "span_id": a1 + "x", "parent_id": "d1", "pod_epoch": 1},
            {"kind": "event", "t": 110.4, "event": "attempt_end",
             "attempt": 0, "rc": 1, "trace_id": trace,
             "span_id": a1 + "x", "parent_id": "d1", "pod_epoch": 1},
            {"kind": "event", "t": 110.8, "event": "attempt_start",
             "attempt": 1, "pid": 2, "trace_id": trace,
             "span_id": a1 + "y", "parent_id": "d2", "pod_epoch": 2},
            {"kind": "event", "t": 129.0, "event": "attempt_end",
             "attempt": 1, "rc": 0, "trace_id": trace,
             "span_id": a1 + "y", "parent_id": "d2", "pod_epoch": 2},
        ])
    _write_jsonl(os.path.join(pod, "h0", "journal-p0.jsonl"), [
        {"kind": "event", "t": 111.0, "event": "run_start",
         "run_id": "r1", "trace_id": trace, "span_id": "run0",
         "parent_id": "a0y", "host": "h0", "process": 0},
        {"kind": "event", "t": 112.0, "event": "chunk", "index": 3,
         "run_id": "r1",
         "phases": {"ingest": 0.1, "dispatch": 0.3, "host_sync": 0.1,
                    "prefetch": 0.2}},
        {"kind": "event", "t": 112.5, "event": "checkpoint_saved",
         "run_id": "r1", "step": 4, "seconds": 0.2, "bytes": 1024},
        {"kind": "event", "t": 128.0, "event": "run_end", "run_id": "r1"},
    ])
    return pod, trace


def test_trace_export_builds_one_restart_tree(tmp_path):
    te = _load_trace_export()
    pod, trace = _synthetic_pod_dir(tmp_path)
    spans = te.collect_spans([pod])
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    # The coordinated restart: ONE tree, both hosts' attempts under it,
    # every child carrying the fencing epoch.
    trees = te.coordinated_restart_trees(spans)
    assert len(trees) == 1
    tree = trees[0]
    assert tree["epoch"] == 2
    kids = tree["children"]
    attempts = [c for c in kids if c["cat"] == "attempt"]
    assert sorted(c["host"] for c in attempts) == ["h0", "h1"]
    assert all(c["attrs"]["pod_epoch"] == 2 for c in attempts)
    fence = [c for c in kids if c["name"] == "fence_written"]
    assert len(fence) == 1 and fence[0]["attrs"]["min_epoch"] == 2

    # Decision spans are closed by the next decision; the pod root spans
    # the whole run; the run journal hangs under the attempt.
    launch = by_name["pod_launch"][0]
    assert launch["t1"] == pytest.approx(110.5)
    assert by_name["pod"][0]["t1"] >= 130.0
    run = by_name["run"][0]
    assert run["parent_id"] == "a0y"
    chunk = by_name["chunk"][0]
    assert chunk["parent_id"] == run["span_id"]
    # Phase children reconstruct the PhaseTimer breakdown: serial phases
    # tile [t-serial, t], the overlapped prefetch rides alongside.
    assert chunk["t0"] == pytest.approx(112.0 - 0.5)
    phases = [s for s in spans if s["cat"] == "phase"
              and s["parent_id"] == chunk["span_id"]]
    assert sorted(p["name"] for p in phases) == [
        "dispatch", "host_sync", "ingest", "prefetch"]
    pre = next(p for p in phases if p["name"] == "prefetch")
    assert pre["attrs"] == {"overlapped": True}
    ckpt = by_name["checkpoint_publish"][0]
    assert ckpt["t1"] - ckpt["t0"] == pytest.approx(0.2)

    # Every span carries the one trace id it inherited.
    assert {s["trace_id"] for s in spans if s["trace_id"]} == {trace}


def test_trace_export_chrome_and_cli(tmp_path, capsys):
    te = _load_trace_export()
    pod, _ = _synthetic_pod_dir(tmp_path)
    spans = te.collect_spans([pod])
    doc = te.export_chrome(spans)
    events = doc["traceEvents"]
    named = [e for e in events if e.get("ph") == "X"]
    # Valid Chrome trace: parseable strict JSON, metadata names the
    # hosts, micros are ints, args carry the causal links.
    json.loads(json.dumps(doc, allow_nan=False))
    procs = {e["args"]["name"] for e in events if e.get("ph") == "M"}
    assert {"h0", "h1"} <= procs
    restart = next(e for e in named if e["name"] == "pod_restart")
    assert isinstance(restart["ts"], int) and restart["dur"] >= 1
    assert restart["args"]["span_id"] == "d2"

    out = str(tmp_path / "trace.json")
    assert te.main([pod, "-o", out]) == 0
    assert json.load(open(out))["traceEvents"]
    # Empty input dir: loud nonzero exit, not an empty trace.
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert te.main([empty]) == 2


def test_supervisor_attempts_carry_trace(tmp_path, monkeypatch):
    """A real (stub-speed) RunSupervisor run: attempt events carry
    trace/span ids, the child inherits them via env, and the exported
    spans parent child-run -> attempt -> supervisor."""
    from fps_tpu.supervise.supervisor import RunSupervisor, SupervisorConfig

    monkeypatch.setenv(TRACE_ID_ENV, "e" * 32)
    monkeypatch.setenv(PARENT_SPAN_ENV, "b" * 16)
    state = str(tmp_path / "state")
    probe = str(tmp_path / "env.json")
    import sys

    code = (
        "import json,os;"
        "json.dump({k: os.environ.get(k) for k in "
        "('" + TRACE_ID_ENV + "', '" + PARENT_SPAN_ENV + "')}, "
        "open(" + repr(probe) + ", 'w'))"
    )
    sup = RunSupervisor([sys.executable, "-c", code], state_dir=state,
                        config=SupervisorConfig(stall_timeout_s=30,
                                                max_restarts=0,
                                                poll_interval_s=0.05))
    digest = sup.run()
    assert digest["success"]
    env = json.load(open(probe))
    assert env[TRACE_ID_ENV] == "e" * 32  # inherited, not re-minted
    recs = [json.loads(line) for line in open(sup.journal_path)]
    start = next(r for r in recs if r["event"] == "attempt_start")
    end = next(r for r in recs if r["event"] == "attempt_end")
    assert start["trace_id"] == "e" * 32
    assert start["span_id"] == end["span_id"] == env[PARENT_SPAN_ENV]
    sup_start = next(r for r in recs if r["event"] == "supervisor_start")
    assert start["parent_id"] == sup_start["span_id"]
    assert sup_start["parent_id"] == "b" * 16

    te = _load_trace_export()
    spans = te.collect_spans([state])
    attempt = next(s for s in spans if s["name"] == "attempt")
    supv = next(s for s in spans if s["name"] == "supervise")
    assert attempt["parent_id"] == supv["span_id"]
    assert attempt["t1"] >= attempt["t0"]
