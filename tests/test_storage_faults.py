"""Hostile-filesystem survival (docs/resilience.md "Hostile
filesystem"): errno classification, bounded deterministic retry/backoff
(``fps_tpu.core.retry``), seed-replayable fault injection
(``fps_tpu.testing.faultfs``), and degraded-mode storage across the
planes — the async writer skips-not-crashes, the watcher/fleet polls
serve last-good, the sidecar degrades, the lease steps down.

The satellite acceptance contract (ISSUE 15):

* the retryable/fatal errno split is EXACT (ENOSPC/EIO/ETIMEDOUT
  retry; EACCES/EROFS fatal);
* a retried-then-successful async publish is byte-identical to an
  unfaulted one;
* first-error retention survives interleaved retries.
"""

import errno
import os
import threading
import time

import numpy as np
import pytest

from fps_tpu.core import retry as retry_mod
from fps_tpu.core.retry import (
    RetryPolicy,
    call_with_retry,
    classify_error,
    classify_path,
)
from fps_tpu.testing import faultfs
from fps_tpu.testing.faultfs import FaultFS, FaultRule


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test leaves the process injector uninstalled — a leaked
    schedule would fault unrelated tests' checkpoints."""
    yield
    faultfs.uninstall()


# ---------------------------------------------------------------------------
# Errno classification + retry policy units.
# ---------------------------------------------------------------------------


def test_errno_classification_exact():
    for code in (errno.ENOSPC, errno.EIO, errno.ETIMEDOUT):
        assert classify_error(OSError(code, "x")) == "retryable", code
    for code in (errno.EACCES, errno.EROFS):
        assert classify_error(OSError(code, "x")) == "fatal", code
    # Non-OSError, errno-less OSError, and unknown-errno exceptions are
    # all fatal: retrying what we do not understand hides bugs.
    assert classify_error(ValueError("x")) == "fatal"
    assert classify_error(OSError("no errno")) == "fatal"


def test_backoff_deterministic_jittered_bounded():
    p = RetryPolicy(seed="s", base_s=0.1, factor=2.0, max_backoff_s=0.5,
                    jitter=0.25)
    seq = [p.backoff_s(i) for i in range(6)]
    assert seq == [p.backoff_s(i) for i in range(6)]  # replayable
    for i, b in enumerate(seq):
        base = min(0.1 * 2.0 ** i, 0.5)
        assert base <= b <= base * 1.25  # jitter bounded
    q = RetryPolicy(seed="t", base_s=0.1, factor=2.0, max_backoff_s=0.5,
                    jitter=0.25)
    assert q.backoff_s(0) != p.backoff_s(0)  # seeds desynchronize


def test_call_with_retry_transient_then_success():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(errno.EIO, "flaky")
        return "ok"

    sleeps = []
    assert call_with_retry(
        flaky, policy=RetryPolicy(retries=3, base_s=0.0, jitter=0.0),
        sleep=sleeps.append) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2


def test_call_with_retry_fatal_immediate_and_budget():
    def eacces():
        raise OSError(errno.EACCES, "nope")

    with pytest.raises(PermissionError):
        call_with_retry(eacces, policy=RetryPolicy(retries=5, base_s=0.0))

    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError(errno.ENOSPC, "full")

    with pytest.raises(OSError):
        call_with_retry(always, policy=RetryPolicy(retries=2, base_s=0.0,
                                                   jitter=0.0),
                        sleep=lambda _s: None)
    assert calls["n"] == 3  # retries + 1 attempts, bounded


def test_call_with_retry_deadline_cap():
    clock = {"t": 0.0}

    def tick():
        return clock["t"]

    def sleep(s):
        clock["t"] += s

    def always():
        clock["t"] += 1.0
        raise OSError(errno.EIO, "slow and failing")

    calls_before = clock["t"]
    with pytest.raises(OSError):
        call_with_retry(always,
                        policy=RetryPolicy(retries=100, base_s=1.0,
                                           factor=1.0, jitter=0.0,
                                           deadline_s=5.0),
                        clock=tick, sleep=sleep)
    # Bounded by the deadline, not the huge retry budget.
    assert clock["t"] - calls_before <= 7.0


def test_classify_path_planes():
    assert classify_path("/a/ckpt_000000000001.npz") == "snapshot"
    assert classify_path("/a/delta_000000000002_000000000001.npz") \
        == "snapshot"
    assert classify_path("/a/xyz123.tmp.npz") == "snapshot"
    assert classify_path("/a/ckpt_000000000001.npz.corrupt") == "snapshot"
    assert classify_path("/a/pod_lease.json") == "lease"
    assert classify_path("/a/pod_fence.json") == "fence"
    assert classify_path("/a/serve_fence.json") == "fence"
    assert classify_path("/a/ready_r0.json") == "fence"
    assert classify_path("/a/tiering-00000003.npz") == "sidecar"
    assert classify_path("/a/pod_control.json") == "control"
    assert classify_path("/a/journal-p0.jsonl") == "journal"
    assert classify_path("/a/whatever.txt") == "other"


def test_env_name_mirror():
    # faultfs must stay loadable by file path (zero package imports),
    # so it mirrors the env name; the two must never drift.
    assert faultfs.FAULTFS_ENV == retry_mod.FAULTFS_ENV


# ---------------------------------------------------------------------------
# FaultFS: deterministic scheduling + the directive faults.
# ---------------------------------------------------------------------------


def _drive(fs, n=20, cls="snapshot", op="write", path="/d/ckpt_x.npz"):
    out = []
    for _ in range(n):
        try:
            d = fs.check(op, cls, path)
            out.append(("ok", d))
        except OSError as e:
            out.append(("err", e.errno))
    return out


def test_faultfs_schedule_replayable():
    rules = [FaultRule("snapshot", "write", "errno", errno_name="EIO",
                       start=3, count=4, every=2),
             FaultRule("snapshot", "write", "errno", errno_name="ENOSPC",
                       start=10, count=None, every=5, prob=0.5)]
    a = _drive(FaultFS(rules, seed=7), 40)
    b = _drive(FaultFS(rules, seed=7), 40)
    assert a == b  # same seed + same op stream = same faults
    c = _drive(FaultFS(rules, seed=8), 40)
    # The windowed deterministic rule fires identically; only the
    # probabilistic tail may differ with the seed.
    assert a[:10] == c[:10]
    # Window semantics: [start, start+count) hitting every 2nd.
    errs = [i for i, (k, _) in enumerate(a[:10]) if k == "err"]
    assert errs == [3, 5]


def test_faultfs_class_isolation():
    fs = FaultFS([FaultRule("lease", "*", "errno", errno_name="EIO",
                            count=None)])
    assert fs.check("write", "snapshot", "/d/ckpt_x.npz") is None
    with pytest.raises(OSError):
        fs.check("replace", "lease", "/d/pod_lease.json")


def test_faultfs_spec_roundtrip(tmp_path):
    fs = FaultFS([FaultRule("snapshot", "read", "stale", start=2)],
                 seed=9)
    clone = FaultFS.from_spec(fs.to_spec())
    assert clone.seed == 9 and clone.rules == fs.rules
    env = fs.to_env({})
    assert retry_mod.FAULTFS_ENV in env
    # File-path form of the env value.
    p = tmp_path / "spec.json"
    p.write_text(fs.to_spec())
    assert FaultFS.from_spec(str(p)).rules == fs.rules


def test_stale_read_serves_pre_rename_content(tmp_path):
    target = tmp_path / "ckpt_000000000001.npz"
    target.write_bytes(b"OLD")
    fs = FaultFS([FaultRule("snapshot", "read", "stale", start=0,
                            count=1)])
    # The injector snoops the replace and shadows the pre-rename bytes.
    fs.check("replace", "snapshot", str(target))
    target.write_bytes(b"NEW")
    d = fs.check("read", "snapshot", str(target))
    assert isinstance(d, tuple) and d[0] == "redirect"
    assert open(d[1], "rb").read() == b"OLD"
    fs.close()


# ---------------------------------------------------------------------------
# Degraded-mode storage on the async writer (the ISSUE's test triad).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def jaxmods():
    import jax

    from fps_tpu.core import checkpoint as ck
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=1, num_data=1,
                        devices=jax.devices()[:1])
    return dict(jax=jax, ck=ck, mesh=mesh, LogRegConfig=LogRegConfig,
                logistic_regression=logistic_regression)


def _store(jaxmods, seed=0):
    cfg = jaxmods["LogRegConfig"](num_features=32, learning_rate=0.5)
    trainer, store = jaxmods["logistic_regression"](jaxmods["mesh"], cfg)
    trainer.init_state(jaxmods["jax"].random.key(seed))
    return store


def test_retried_publish_byte_identical_to_unfaulted(jaxmods, tmp_path):
    """A publish that fails transiently twice and lands on its third
    attempt must leave EXACTLY the bytes an unfaulted publish leaves."""
    ck = jaxmods["ck"]
    store = _store(jaxmods)
    clean = ck.Checkpointer(str(tmp_path / "clean"), keep=3)
    clean.save(1, store, None)
    faultfs.install([FaultRule("snapshot", "write", "errno",
                               errno_name="EIO", start=0, count=2)])
    faulted = ck.AsyncCheckpointer(str(tmp_path / "faulted"), keep=3)
    faulted.save(1, store, None)
    faulted.close()
    faultfs.uninstall()
    a = np.load(str(tmp_path / "clean" / "ckpt_000000000001.npz"))
    b = np.load(str(tmp_path / "faulted" / "ckpt_000000000001.npz"))
    assert set(a.files) == set(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])
    assert faulted.degraded_publishes == 0  # retried, never degraded


def test_degrade_skips_transient_failure_without_crashing(jaxmods,
                                                          tmp_path):
    ck = jaxmods["ck"]
    store = _store(jaxmods)
    # The whole retry budget (4 attempts) fails -> the publish degrades.
    faultfs.install([FaultRule("snapshot", "write", "errno",
                               errno_name="ENOSPC", start=0, count=4)])
    ckpt = ck.AsyncCheckpointer(str(tmp_path / "d"), keep=5)
    ckpt.save(1, store, None)
    ckpt.flush()  # must NOT raise: degraded, not failed
    assert ckpt.degraded_publishes == 1
    assert ckpt._publish_backlog == 1
    assert ckpt.steps() == []
    # Storage recovered: the next save lands and drains the backlog.
    ckpt.save(2, store, None)
    ckpt.flush()
    assert ckpt.steps() == [2]
    assert ckpt._publish_backlog == 0
    ckpt.close()


def test_fatal_error_keeps_first_error_retention(jaxmods, tmp_path):
    """First-error retention survives interleaved retries: a FATAL
    failure (EROFS) raises on the caller — and keeps raising the FIRST
    error even when later (retried, transient) failures interleave."""
    ck = jaxmods["ck"]
    store = _store(jaxmods)
    faultfs.install([
        FaultRule("snapshot", "write", "errno", errno_name="EROFS",
                  start=0, count=1),
        # The next publish fails transiently once, then retries fine —
        # its retry traffic must not displace the pending EROFS.
        FaultRule("snapshot", "write", "errno", errno_name="EIO",
                  start=1, count=1),
    ])
    ckpt = ck.AsyncCheckpointer(str(tmp_path / "d"), keep=5)
    ckpt.save(1, store, None)
    with ckpt._cv:
        while ckpt._queued is not None or ckpt._writing:
            ckpt._cv.wait(0.05)
    with pytest.raises(RuntimeError, match="background checkpoint"):
        ckpt.save(2, store, None)
    # The pending error was consumed; the writer keeps working (the
    # EIO rule retries through).
    ckpt.save(3, store, None)
    ckpt.flush()
    assert ckpt.steps() == [3]
    ckpt.close()


def test_degraded_delta_chain_resets_to_full(jaxmods, tmp_path):
    """A degraded (skipped) publication must never become a delta
    base: the next save publishes a FULL, and no delta on disk chains
    from the skipped step."""
    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.core.checkpoint import load_rows

    ck = jaxmods["ck"]
    store = _store(jaxmods)
    ckpt = ck.AsyncCheckpointer(str(tmp_path / "d"), keep=10,
                                delta=ck.DeltaPolicy(full_every=10))
    ckpt.save(1, store, None)
    ckpt.flush()
    # Sparse perturbations so deltas are genuinely smaller than fulls.
    load_rows(store, "weights", np.arange(2),
              np.ones((2, store.specs["weights"].dim), np.float32))
    faultfs.install([FaultRule("snapshot", "write", "errno",
                               errno_name="EIO", start=0, count=4)])
    ckpt.save(2, store, None)  # planned as delta vs 1; degrades
    ckpt.flush()
    faultfs.uninstall()
    assert ckpt.degraded_publishes == 1
    load_rows(store, "weights", np.arange(2, 4),
              np.ones((2, store.specs["weights"].dim), np.float32))
    ckpt.save(3, store, None)
    ckpt.flush()
    pubs = fmt.publications(str(tmp_path / "d"))
    assert pubs[3].kind == "full"  # chain reset: never an orphan base
    assert all(p.base != 2 for p in pubs.values() if p.kind == "delta")
    ckpt.close()


def test_transient_stale_read_never_quarantines_valid_snapshot(jaxmods,
                                                               tmp_path):
    """A stale read of pre-rename (truncated) content must not make the
    auto-resolve restore quarantine the durable, VALID snapshot: the
    failing link is re-verified on a fresh read before any rename —
    faults cost recency, never state."""
    ck = jaxmods["ck"]
    store = _store(jaxmods)
    d = str(tmp_path / "d")
    ckpt = ck.Checkpointer(d, keep=5)
    ckpt.save(1, store, None)
    target = os.path.join(d, "ckpt_000000000001.npz")
    # Shadow truncated pre-rename content, then schedule ONE stale read.
    fs = faultfs.install([FaultRule("snapshot", "read", "stale",
                                    start=0, count=1)])
    good = open(target, "rb").read()
    shadowed = str(tmp_path / "shadow.npz")
    with open(shadowed, "wb") as f:
        f.write(good[: len(good) // 3])
    fs._shadows[os.path.abspath(target)] = shadowed
    step, tables, _, _ = ckpt.read_snapshot()  # first read is stale
    assert step == 1
    assert not [f for f in os.listdir(d) if f.endswith(".corrupt")]
    faultfs.uninstall()


def test_transient_enoent_restore_falls_back_not_crash(jaxmods,
                                                       tmp_path):
    """A transient ENOENT on the newest snapshot's read (stale mount /
    sweep race) must not crash an auto-resolve restore that has intact
    older snapshots: retry once, then fall back — and quarantine
    NOTHING (the file is invisible, not corrupt)."""
    ck = jaxmods["ck"]
    store = _store(jaxmods)
    d = str(tmp_path / "d")
    ckpt = ck.Checkpointer(d, keep=5)
    ckpt.save(1, store, None)
    ckpt.save(2, store, None)
    faultfs.install([FaultRule("snapshot", "read", "errno",
                               errno_name="ENOENT", start=0, count=2)])
    step, _tables, _, _ = ckpt.read_snapshot()
    assert step == 1  # fell back past the invisible newest
    assert not [f for f in os.listdir(d) if f.endswith(".corrupt")]
    faultfs.uninstall()
    assert ckpt.read_snapshot()[0] == 2  # recovered


def test_persistent_enoent_on_writes_raises_not_degrades(jaxmods,
                                                         tmp_path):
    """ENOENT persisting past the whole retry budget means the
    checkpoint DIRECTORY is gone — that must raise on the caller, not
    quietly degrade every publish into a checkpoint-free 'success'."""
    ck = jaxmods["ck"]
    store = _store(jaxmods)
    ckpt = ck.AsyncCheckpointer(str(tmp_path / "d"), keep=3)
    faultfs.install([FaultRule("snapshot", "write", "errno",
                               errno_name="ENOENT", start=0, count=8)])
    ckpt.save(1, store, None)
    with pytest.raises(RuntimeError, match="background checkpoint"):
        ckpt.flush()
    assert ckpt.degraded_publishes == 0
    faultfs.uninstall()
    ckpt.close()


def test_stale_read_does_not_pin_valid_publish_rejected(jaxmods,
                                                        tmp_path):
    """One stale read that makes a valid publish LOOK torn must not pin
    it in the watcher's permanent rejection cache — the next poll's
    fresh read serves it."""
    from fps_tpu.serve import SnapshotWatcher

    ck = jaxmods["ck"]
    store = _store(jaxmods)
    d = str(tmp_path / "d")
    ckpt = ck.Checkpointer(d, keep=5)
    ckpt.save(1, store, None)
    target = os.path.join(d, "ckpt_000000000001.npz")
    fs = faultfs.install([FaultRule("snapshot", "read", "stale",
                                    start=0, count=1)])
    good = open(target, "rb").read()
    shadowed = str(tmp_path / "shadow.npz")
    with open(shadowed, "wb") as f:
        f.write(good[: len(good) // 3])
    fs._shadows[os.path.abspath(target)] = shadowed
    w = SnapshotWatcher(d)
    assert w.poll() is None  # stale read: looks torn, rejected once
    snap = w.poll()  # fresh read: served, never pinned
    assert snap is not None and snap.step == 1
    faultfs.uninstall()


# ---------------------------------------------------------------------------
# Read-plane degradation: watcher + fleet polls survive brownouts.
# ---------------------------------------------------------------------------


def test_watcher_poll_degrades_to_last_good(jaxmods, tmp_path):
    from fps_tpu.serve import SnapshotWatcher

    ck = jaxmods["ck"]
    store = _store(jaxmods)
    d = str(tmp_path / "d")
    ckpt = ck.Checkpointer(d, keep=5)
    ckpt.save(1, store, None)
    w = SnapshotWatcher(d)
    snap = w.poll()
    assert snap is not None and snap.step == 1
    # Brownout: the directory scan fails transiently — the poll must
    # degrade (count, serve last-good), never raise or unpublish.
    faultfs.install([FaultRule("snapshot", "listdir", "errno",
                               errno_name="EIO", start=0, count=2)])
    assert w.poll() is None
    assert w.poll_errors == 1
    assert w.current is not None and w.current.step == 1
    faultfs.uninstall()
    ckpt.save(2, store, None)
    snap = w.poll()
    assert snap is not None and snap.step == 2  # recovered


def test_fleet_reader_poll_survives_fence_io_errors(jaxmods, tmp_path):
    from fps_tpu.serve.fleet import FleetReader

    ck = jaxmods["ck"]
    store = _store(jaxmods)
    d = str(tmp_path / "d")
    ckpt = ck.Checkpointer(d, keep=5)
    ckpt.save(1, store, None)
    r = FleetReader(d, "r0", quorum=1)
    assert r.poll() == 1
    # Fence/readiness writes fail for a stretch: polls degrade but the
    # reader keeps serving and recovers.
    faultfs.install([FaultRule("fence", "*", "errno", errno_name="EIO",
                               start=0, count=6)])
    ckpt.save(2, store, None)
    for _ in range(4):
        served = r.poll()
        assert served in (1, 2)  # never None, never wedged
    faultfs.uninstall()
    for _ in range(4):
        served = r.poll()
    assert served == 2
    assert (r.poll_errors + r.fence.io_errors
            + r.watcher.poll_errors) > 0


def test_sidecar_write_degrades(jaxmods, tmp_path, caplog):
    """A sidecar write that fails transiently through its retry budget
    is SKIPPED (advisory state), never a crash — and the retry budget
    (with its backoff sleeps) runs on the background retrier thread,
    costing the caller only the single inline attempt."""
    import logging
    import time

    from fps_tpu.tiering.retier import Retierer

    rt = Retierer.__new__(Retierer)  # only the sidecar path under test
    rt.state_dir = str(tmp_path / "sc")
    rt.keep = 2
    rt.tick = 1
    rt.planned = False
    rt.plans = None
    rt.state, rt.hot_ids = {}, {}
    faultfs.install([FaultRule("sidecar", "write", "errno",
                               errno_name="EIO", start=0, count=8)])
    with caplog.at_level(logging.WARNING, logger="fps_tpu.tiering"):
        t0 = time.perf_counter()
        rt._save_sidecar(3, {})
        inline_s = time.perf_counter() - t0
        rt.sidecar_flush(timeout=30.0)
    # The inline attempt raises EIO immediately; the retry backoff
    # (>= 0.02 + 0.04 + 0.08 s of sleeps) must NOT have run here.
    assert inline_s < 0.1
    assert "DEGRADED" in caplog.text
    assert not os.listdir(rt.state_dir)
    faultfs.uninstall()
    rt._save_sidecar(4, {})
    assert os.listdir(rt.state_dir) == ["tiering-00000004.npz"]


# ---------------------------------------------------------------------------
# Fleet SLO rollup: storage staleness + fence lag (satellite 2).
# ---------------------------------------------------------------------------


def _write_events(d, records):
    os.makedirs(d, exist_ok=True)
    import json

    with open(os.path.join(d, "events-p0.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_fleet_rollup_degraded_publishes_and_fence_lag(tmp_path):
    from fps_tpu.obs.fleet import DEFAULT_SLOS, evaluate_slos, rollup

    d = str(tmp_path / "h0")
    t0 = 1000.0
    _write_events(d, [
        {"kind": "metric", "t": t0 + 1, "name": "driver.examples",
         "mtype": "counter", "value": 100.0},
        {"kind": "event", "t": t0 + 1, "event": "checkpoint_saved",
         "step": 10, "path": "x"},
        {"kind": "metric", "t": t0 + 2, "name": "serve.fence_step",
         "mtype": "gauge", "value": 4.0},
        {"kind": "metric", "t": t0 + 3,
         "name": "storage.degraded_publishes", "mtype": "counter",
         "value": 2.0},
        {"kind": "event", "t": t0 + 3, "event": "checkpoint_degraded",
         "step": 11, "backlog": 1},
    ])
    roll = rollup([d], window_s=10.0)
    tot = roll["totals"]
    # Counter/event dedup rule: both sources fire together -> max().
    assert tot["degraded_publishes"] == 2
    assert tot["fence_lag_steps"] == 6.0  # newest published 10, fence 4
    slo = evaluate_slos(roll, DEFAULT_SLOS)
    assert slo["storage_staleness_budget"]["bad_windows"] >= 1
    assert not slo["storage_staleness_budget"]["ok"]
    assert slo["serve_fence_lag"]["windows_evaluated"] >= 1


def test_fleet_rollup_fence_lag_within_budget_ok(tmp_path):
    from fps_tpu.obs.fleet import DEFAULT_SLOS, evaluate_slos, rollup

    d = str(tmp_path / "h0")
    t0 = 2000.0
    _write_events(d, [
        {"kind": "event", "t": t0 + 1, "event": "checkpoint_saved",
         "step": 10, "path": "x"},
        {"kind": "metric", "t": t0 + 2, "name": "serve.fence_step",
         "mtype": "gauge", "value": 9.0},
    ])
    roll = rollup([d], window_s=10.0)
    assert roll["totals"]["fence_lag_steps"] == 1.0
    slo = evaluate_slos(roll, DEFAULT_SLOS)
    assert slo["serve_fence_lag"]["ok"]
    assert slo["storage_staleness_budget"]["ok"]  # nothing degraded


# ---------------------------------------------------------------------------
# End-to-end (slow): the chaos scenarios, shared with the sweep.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_storage_brownout_scenario_end_to_end(tmp_path):
    from fps_tpu.testing.supervised_demo import (
        run_storage_brownout_scenario,
    )

    ok, detail = run_storage_brownout_scenario(str(tmp_path))
    assert ok, detail


@pytest.mark.slow
def test_slow_lease_scenario_end_to_end(tmp_path):
    from fps_tpu.testing.supervised_demo import (
        run_slow_lease_near_ttl_scenario,
    )

    ok, detail = run_slow_lease_near_ttl_scenario(str(tmp_path))
    assert ok, detail
