"""fps_tpu.obs — first-class telemetry for the TPU parameter server.

One subsystem, four altitudes (see ``docs/observability.md``):

* **schema** — :class:`MetricsRegistry` / :class:`MetricSpec` name and
  type every metrics leaf; :class:`Recorder` validates emissions and fans
  them out to pluggable sinks (:class:`JsonlSink`,
  :class:`PrometheusSink`, :class:`MemorySink`).
* **timing** — :class:`PhaseTimer` splits each chunk into host phases
  (ingest/place/dispatch/host_sync/checkpoint/callback);
  :class:`Throughput` and :func:`trace` complete the clock set.
* **alerting** — :class:`HealthMonitor` thresholds the guard's health
  channel (observe→mask escalation, poison abort);
  :class:`StepWatchdog` deadline-flags stalled chunks/stragglers.
* **journal** — :class:`RunJournal` writes the per-process run narrative
  that ``tools/obs_report.py`` renders into a digest.
* **tracing** — :mod:`fps_tpu.obs.trace` mints trace/span ids propagated
  through the supervised-child env contract, so supervisor decisions,
  pod restarts, attempts, and chunk phases link into ONE causal tree
  (``tools/trace_export.py`` renders Chrome/Perfetto JSON).
* **fleet** — :mod:`fps_tpu.obs.fleet` tails N per-host obs dirs into
  windowed rollups with declarative SLO burn-rate evaluation
  (``tools/obs_report.py --fleet``).
* **drift** — :mod:`fps_tpu.obs.drift` checks the live data plane's
  measured collective traffic against the budgets pinned in
  ``AUDIT_r*.json`` (``analysis.budget_drift`` + incidents).

Everything is host-side: attaching a recorder never changes the compiled
program (tested), and ``recorder=None`` costs nothing.
"""

from __future__ import annotations

import os

from fps_tpu.obs import events
from fps_tpu.obs.drift import BudgetDriftDetector, load_pinned_budgets
from fps_tpu.obs.fleet import (
    DEFAULT_SLOS,
    SLO,
    evaluate_slos,
    fleet_digest,
)
from fps_tpu.obs.health import (
    HEALTH_ABORT,
    HEALTH_ESCALATE,
    HEALTH_OK,
    HealthMonitor,
    StepWatchdog,
)
from fps_tpu.obs.journal import (
    RunJournal,
    config_digest,
    new_run_id,
    process_index,
)
from fps_tpu.obs.registry import (
    MetricSpec,
    MetricsRegistry,
    Recorder,
    default_registry,
)
from fps_tpu.obs.sinks import JsonlSink, MemorySink, PrometheusSink, Sink
from fps_tpu.obs.timing import DRIVER_PHASES, PhaseTimer, Throughput, trace
from fps_tpu.obs.trace import (
    PARENT_SPAN_ENV,
    TRACE_ID_ENV,
    TraceContext,
    Tracer,
    new_span_id,
    new_trace_id,
)

__all__ = [
    "MetricSpec", "MetricsRegistry", "Recorder", "default_registry",
    "Sink", "JsonlSink", "MemorySink", "PrometheusSink",
    "PhaseTimer", "Throughput", "trace", "DRIVER_PHASES",
    "HealthMonitor", "StepWatchdog",
    "HEALTH_OK", "HEALTH_ESCALATE", "HEALTH_ABORT",
    "RunJournal", "new_run_id", "config_digest", "process_index",
    "TraceContext", "Tracer", "new_trace_id", "new_span_id",
    "TRACE_ID_ENV", "PARENT_SPAN_ENV",
    "BudgetDriftDetector", "load_pinned_budgets",
    "SLO", "DEFAULT_SLOS", "evaluate_slos", "fleet_digest",
    "events", "open_run",
]


def open_run(obs_dir: str, *, config=None, run_id: str | None = None,
             meta: dict | None = None, registry: MetricsRegistry | None = None,
             install: bool = True) -> Recorder:
    """Standard on-disk telemetry for one training run (the ``--obs-dir``
    CLI path): under ``obs_dir`` this process writes

    * ``events-p<K>.jsonl``  — every metric sample + event (JSONL),
    * ``journal-p<K>.jsonl`` — events only, bracketed run_start/run_end,
    * ``metrics-p<K>.prom``  — Prometheus text exposition (rewritten at
      flush; point a file scrape at it),

    where ``<K>`` is the process index (multi-host: one set per process;
    ``tools/obs_report.py`` joins on the shared run id). ``config`` is
    digested into the journal's run_start record; ``install=True`` also
    makes this the process-default recorder so checkpoint/rollback events
    flow without explicit plumbing. Close (or ``with``-scope) the
    recorder to get the run_end record and final flush.
    """
    run_id = run_id or new_run_id()
    proc = process_index()
    os.makedirs(obs_dir, exist_ok=True)
    # Causal tracing (fps_tpu.obs.trace): inherit the trace/parent-span
    # from the supervisor env contract (or mint a standalone trace) and
    # mint this run's own span — the journal's run_start is the causal
    # anchor everything in this obs dir hangs under when
    # tools/trace_export.py renders the tree. Host-side only: these are
    # env vars and journal fields, never traced into a program.
    ctx = TraceContext.from_env()
    run_span = new_span_id()
    run_meta = {"process": proc, "config_digest": config_digest(config),
                "trace_id": ctx.trace_id or new_trace_id(),
                "span_id": run_span, "parent_id": ctx.parent_id}
    if meta:
        run_meta.update(meta)
    journal = RunJournal(
        os.path.join(obs_dir, f"journal-p{proc}.jsonl"),
        run_id=run_id, meta=run_meta,
    )
    rec = Recorder(
        registry,
        sinks=[
            JsonlSink(os.path.join(obs_dir, f"events-p{proc}.jsonl")),
            PrometheusSink(os.path.join(obs_dir, f"metrics-p{proc}.prom")),
            journal,
        ],
        run_id=run_id,
        base_labels={"process": str(proc)},
    )
    # The run's tracer: explicit spans emitted through it parent under
    # this run's span by default (rec.trace.span("my_phase"): ...).
    rec.trace = Tracer(rec, trace_id=run_meta["trace_id"],
                       parent_id=run_span)
    if install:
        events.set_default_recorder(rec)
        _prev_close = rec.close

        def close_and_uninstall():
            if events.get_default_recorder() is rec:
                events.set_default_recorder(None)
            _prev_close()

        rec.close = close_and_uninstall
    return rec
