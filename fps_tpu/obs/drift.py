"""Budget-drift detection: live data-plane traffic vs certified budgets.

PR 6 pinned exact per-workload collective count/byte budgets in
``AUDIT_r*.json`` (``tools/audit_programs.py``), but nothing at RUNTIME
checks the live data plane against them: a re-plan, a route demotion, or
a silent cold-budget regression changes what the fleet actually moves
while the pinned file stays green. Parallax's core argument (PAPERS.md)
is that placement must follow **measured** traffic, not a static plan —
this module closes the loop from the other side: it folds the measured
shape of what the trainer is dispatching (the lowered programs' profiles
from ``fps_tpu.analysis.collective_profile``, weighted by the live
dispatch counters the data plane already emits —
``cold_route.compact_chunks`` / ``overflow_chunks`` / ``driver.chunks``)
against the pinned budgets, and emits:

* ``analysis.budget_drift{program=...}`` — gauge: measured/pinned byte
  ratio per observed program (1.0 = on budget);
* a ``budget_drift`` incident event whenever measured traffic departs
  from the certified shape (byte ratio outside tolerance, collective
  count mismatch, or an observed program with no pinned row).

Host-side only: the detector re-reads text and counters the run already
produced — it never touches the compiled program or the hot path.
Stdlib-only (the profile objects are duck-typed), so fleet tooling can
load it jax-free.
"""

from __future__ import annotations

import dataclasses
import json
import math

__all__ = [
    "load_pinned_budgets", "profile_budget", "DriftReport",
    "BudgetDriftDetector",
]


def load_pinned_budgets(path: str) -> dict:
    """Pinned per-program budgets from a ``tools/audit_programs.py``
    output file (``AUDIT_r*.json``): ``{program: {"count": int,
    "bytes": int, "per_kind": {kind: {"count", "bytes"}}}}``."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    rows = doc.get("audit_programs", doc)
    out = {}
    for name, row in rows.items():
        colls = (row or {}).get("collectives")
        if not isinstance(colls, dict):
            continue
        out[name] = {
            "count": int(colls.get("count", 0)),
            "bytes": int(colls.get("bytes", 0)),
            "per_kind": {k: {"count": int(v.get("count", 0)),
                             "bytes": int(v.get("bytes", 0))}
                         for k, v in (colls.get("per_kind") or {}).items()},
        }
    return out


def profile_budget(profile) -> dict:
    """Normalize a live program's collective profile — an iterable of
    ``fps_tpu.analysis`` Collective objects, ``(kind, payload_bytes)``
    tuples, or ``{"kind", "payload_bytes"}`` dicts — into the same
    ``{"count", "bytes", "per_kind"}`` shape as the pinned rows."""
    count, total = 0, 0
    per_kind: dict = {}
    for c in profile:
        if isinstance(c, dict):
            kind, b = c.get("kind", "?"), int(c.get("payload_bytes", 0))
        elif isinstance(c, (tuple, list)):
            kind, b = c[0], int(c[1])
        else:
            kind, b = c.kind, int(c.payload_bytes)
        count += 1
        total += b
        pk = per_kind.setdefault(kind, {"count": 0, "bytes": 0})
        pk["count"] += 1
        pk["bytes"] += b
    return {"count": count, "bytes": total, "per_kind": per_kind}


@dataclasses.dataclass(frozen=True)
class DriftReport:
    """One observed program's measured-vs-pinned verdict."""

    program: str
    chunks: int
    pinned_bytes: int | None
    measured_bytes: int
    pinned_count: int | None
    measured_count: int
    byte_ratio: float | None  # measured / pinned (None when unpinned)
    ok: bool
    reasons: tuple[str, ...] = ()

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class BudgetDriftDetector:
    """Folds live program observations against pinned budgets.

    Args:
      pinned: ``{program: {"count", "bytes", "per_kind"}}`` — the
        :func:`load_pinned_budgets` shape (an ``AUDIT_r*.json`` file
        loaded via ``fps_tpu.analysis``'s audit pipeline).
      recorder: optional ``fps_tpu.obs.Recorder``; when given,
        ``evaluate()`` emits the gauge/incident telemetry (falls back to
        the process-default recorder otherwise).
      byte_rel_tol: allowed relative departure of measured from pinned
        payload bytes before an incident fires (floats-per-row payloads
        are exact, so the default tolerance only absorbs pinning noise
        like replica-group padding).
      count_tol: allowed absolute collective-count difference.
      allow_unpinned: observed programs with no pinned row pass quietly
        when True (a new workload mid-rollout); False makes them
        incidents (CI semantics — everything dispatched must be pinned).

    Typical wiring — once per run or per re-plan boundary::

        det = BudgetDriftDetector(load_pinned_budgets("AUDIT_r10.json"),
                                  recorder=rec)
        det.observe("mf_tiered_compact", collective_profile(hlo_compact),
                    chunks=rec.counter_value("cold_route.compact_chunks"))
        det.observe("mf_tiered_gathered", collective_profile(hlo_static),
                    chunks=overflow_chunks)
        reports = det.evaluate()

    The live dispatch counters decide WHAT gets checked: each program a
    counter proves was dispatched is compared against its OWN pinned row
    (per-dispatch payloads are exact, so per-program comparison IS the
    measured-traffic check — there is no averaging that could let an
    over-budget program hide behind an under-budget one), and an
    observation with ``chunks=0`` carried no traffic, so it reports its
    ratio but can never fire an incident. ``chunks`` also rides the
    report/incident so responders see how much traffic drifted.
    """

    def __init__(self, pinned: dict, *, recorder=None,
                 byte_rel_tol: float = 0.05, count_tol: int = 0,
                 allow_unpinned: bool = True):
        if byte_rel_tol < 0 or count_tol < 0:
            raise ValueError("byte_rel_tol and count_tol must be >= 0")
        self.pinned = dict(pinned)
        self.recorder = recorder
        self.byte_rel_tol = float(byte_rel_tol)
        self.count_tol = int(count_tol)
        self.allow_unpinned = bool(allow_unpinned)
        self._observed: list[tuple[str, dict, int]] = []

    def observe(self, program: str, profile=None, *, chunks: int = 1,
                budget: dict | None = None) -> None:
        """Record that ``program`` (live profile ``profile``, or an
        already-normalized ``budget`` dict) was dispatched for
        ``chunks`` chunks. ``chunks=0`` observations are kept — their
        report documents the program exists and its ratio — but they
        moved no traffic, so ``evaluate()`` never turns their
        departures into incidents."""
        if (profile is None) == (budget is None):
            raise ValueError("pass exactly one of profile= or budget=")
        b = budget if budget is not None else profile_budget(profile)
        self._observed.append((program, dict(b), max(int(chunks), 0)))

    def evaluate(self, *, emit: bool = True) -> list[DriftReport]:
        """Compare every observation against its pinned row; optionally
        (default) emit ``analysis.budget_drift`` gauges and
        ``budget_drift`` incident events for departures."""
        reports = []
        for program, measured, chunks in self._observed:
            pin = self.pinned.get(program)
            reasons = []
            ratio = None
            if pin is None:
                if not self.allow_unpinned:
                    reasons.append("no pinned budget for observed "
                                   f"program {program!r}")
                pinned_bytes = pinned_count = None
            else:
                pinned_bytes = int(pin["bytes"])
                pinned_count = int(pin["count"])
                if pinned_bytes:
                    ratio = measured["bytes"] / pinned_bytes
                    if not math.isclose(ratio, 1.0,
                                        rel_tol=self.byte_rel_tol):
                        reasons.append(
                            f"collective bytes {measured['bytes']} vs "
                            f"pinned {pinned_bytes} "
                            f"(ratio {ratio:.4f}, tol "
                            f"{self.byte_rel_tol})")
                elif measured["bytes"]:
                    ratio = math.inf
                    reasons.append(
                        f"collective bytes {measured['bytes']} vs "
                        "pinned 0")
                else:
                    ratio = 1.0
                if abs(measured["count"] - pinned_count) > self.count_tol:
                    reasons.append(
                        f"collective count {measured['count']} vs "
                        f"pinned {pinned_count}")
                for kind, pk in (pin.get("per_kind") or {}).items():
                    got = measured["per_kind"].get(
                        kind, {"count": 0, "bytes": 0})
                    if abs(got["count"] - pk["count"]) > self.count_tol:
                        reasons.append(
                            f"{kind}: count {got['count']} vs pinned "
                            f"{pk['count']}")
                for kind in measured["per_kind"]:
                    if kind not in (pin.get("per_kind") or {}):
                        reasons.append(f"unpinned collective kind "
                                       f"{kind!r} appeared")
            if chunks == 0:
                # Zero dispatches moved zero traffic: the report keeps
                # the ratio as evidence, but nothing drifted LIVE.
                reasons = []
            report = DriftReport(
                program=program,
                chunks=chunks,
                pinned_bytes=pinned_bytes,
                measured_bytes=int(measured["bytes"]),
                pinned_count=pinned_count,
                measured_count=int(measured["count"]),
                byte_ratio=(round(ratio, 6)
                            if ratio is not None
                            and math.isfinite(ratio) else ratio),
                ok=not reasons,
                reasons=tuple(reasons),
            )
            reports.append(report)
            if emit:
                self._emit(report)
        return reports

    # -- telemetry --------------------------------------------------------

    def _emit(self, report: DriftReport) -> None:
        rec = self.recorder
        ratio = report.byte_ratio
        gauge = (float(ratio) if ratio is not None
                 and math.isfinite(ratio) else float("nan"))
        if rec is not None:
            rec.set("analysis.budget_drift", gauge,
                    program=report.program)
        else:
            from fps_tpu.obs import events

            events.record_metric("set", "analysis.budget_drift", gauge,
                                 program=report.program)
        if report.ok:
            return
        fields = {k: v for k, v in report.to_json().items()
                  if k != "ok"}
        fields["reasons"] = list(report.reasons)
        if rec is not None:
            rec.event("budget_drift", **fields)
        else:
            from fps_tpu.obs import events

            events.emit("budget_drift", **fields)

    # -- convenience ------------------------------------------------------

    def observe_trainer_chunk(self, trainer, chunk, *, program: str,
                              mode: str = "sync",
                              chunks: int = 1) -> None:
        """Observe the exact program ``trainer.fit_stream`` would
        dispatch for ``chunk`` (lowered via
        ``Trainer.lowered_chunk_text``, profiled via
        ``fps_tpu.analysis.collective_profile``) — the one-call wiring
        for tests and end-of-run checks."""
        from fps_tpu.analysis import collective_profile

        hlo = trainer.lowered_chunk_text(chunk, mode)
        self.observe(program, collective_profile(hlo), chunks=chunks)
