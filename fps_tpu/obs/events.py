"""Process-default recorder: the low-plumbing event path.

The driver takes an explicit recorder, but deep layers (checkpoint
integrity, rollback policies) fire events from places a recorder was
never threaded to — a ``Checkpointer`` is constructed by user code long
before any trainer exists. Rather than plumbing a recorder through every
constructor, those layers emit through the process-default set here:
no-ops when none is installed (the exact zero-cost-off contract the
guard has), so the core stays importable and silent without obs.

Stdlib-only, no fps_tpu imports: ``core/resilience.py`` (which must stay
dependency-light) can call :func:`emit` without a cycle.
"""

from __future__ import annotations

import contextlib
import threading

_lock = threading.Lock()
_default = None


def set_default_recorder(recorder) -> None:
    """Install (or clear, with ``None``) the process-default recorder."""
    global _default
    with _lock:
        _default = recorder


def get_default_recorder():
    return _default


# One warning per dropped metric/event name — background telemetry must
# not spam the log on every chunk.
_warned_metrics: set = set()


def emit(etype: str, **fields) -> None:
    """Fire an event on the process-default recorder, if any.

    Guarded like :func:`record_metric`: background telemetry fired from
    deep layers (checkpoint save, rollback record) must degrade to a
    logged drop when a user-installed recorder misbehaves, never abort
    the training operation that fired it.
    """
    rec = _default
    if rec is None:
        return
    try:
        rec.event(etype, **fields)
    except Exception as e:  # noqa: BLE001 - see docstring
        if etype not in _warned_metrics:
            _warned_metrics.add(etype)
            import logging

            logging.getLogger("fps_tpu.obs").warning(
                "dropping background event %s (%s); the installed "
                "recorder rejected it", etype, e,
            )


def record_metric(kind: str, name: str, value: float, **labels) -> None:
    """Metric sample on the process-default recorder, if any.
    ``kind`` is "inc" / "set" / "observe" (the Recorder method names).

    Unlike a directly-held Recorder (where a schema violation should fail
    at the emission site), the process default may carry a USER registry
    that never declared the framework's leaves — background telemetry
    from deep layers must degrade to a logged drop, not kill training.
    """
    rec = _default
    if rec is None:
        return
    try:
        getattr(rec, kind)(name, value, **labels)
    except (KeyError, TypeError, ValueError) as e:
        if name not in _warned_metrics:
            _warned_metrics.add(name)
            import logging

            logging.getLogger("fps_tpu.obs").warning(
                "dropping background metric %s (%s); the installed "
                "recorder's registry does not accept it", name, e,
            )


@contextlib.contextmanager
def default_recorder(recorder):
    """Scoped install — tests use this to avoid cross-test leakage."""
    prev = _default
    set_default_recorder(recorder)
    try:
        yield recorder
    finally:
        set_default_recorder(prev)
