"""Pluggable telemetry sinks.

Every sink consumes the one record shape the :class:`~fps_tpu.obs.registry.
Recorder` produces (``kind: "metric" | "event"`` dicts) and renders it for
one consumer class:

* :class:`JsonlSink`      — append-only JSONL event log (the machine-
  readable stream ``tools/obs_report.py`` digests);
* :class:`PrometheusSink` — Prometheus text exposition written at flush
  (scrape the file, or serve it from a sidecar; no HTTP server here);
* :class:`MemorySink`     — bounded in-memory ring, for tests and for
  embedding a live tail in a REPL.

Sinks must never throw into the training loop: file-system failures on
``write`` are latched and logged once, then the sink goes quiet (telemetry
must degrade, not take the job down with it).
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import re

_log = logging.getLogger("fps_tpu.obs")


class Sink:
    """Interface: ``write(record)`` per sample/event, ``flush``, ``close``."""

    def write(self, record: dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.flush()


class MemorySink(Sink):
    """Bounded ring of the most recent records (oldest evicted first)."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.records = collections.deque(maxlen=capacity)

    def write(self, record: dict) -> None:
        self.records.append(record)

    def events(self, etype: str | None = None) -> list[dict]:
        return [r for r in self.records if r.get("kind") == "event"
                and (etype is None or r.get("event") == etype)]

    def metrics(self, name: str | None = None) -> list[dict]:
        return [r for r in self.records if r.get("kind") == "metric"
                and (name is None or r.get("name") == name)]


class JsonlSink(Sink):
    """Append-only JSONL file, one record per line.

    ``flush_every`` bounds how many records may sit in the userspace
    buffer — a crash loses at most that many, a `flush()` (the driver
    flushes at chunk boundaries) loses none. Write failures latch the
    sink into a dropping state after one log line.
    """

    def __init__(self, path: str, *, flush_every: int = 64):
        self.path = path
        self.flush_every = max(1, flush_every)
        self._n = 0
        self._dead = False
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def write(self, record: dict) -> None:
        if self._dead:
            return
        try:
            self._f.write(_strict_json_line(record) + "\n")
            self._n += 1
            if self._n % self.flush_every == 0:
                self._f.flush()
        except (OSError, ValueError) as e:
            self._dead = True
            _log.warning("obs sink %s failed (%s); dropping telemetry",
                         self.path, e)

    def flush(self) -> None:
        if not self._dead and not self._f.closed:
            try:
                self._f.flush()
            except OSError:
                self._dead = True

    def close(self) -> None:
        self.flush()
        try:
            self._f.close()
        except OSError:
            pass


def scrub_nonfinite(x):
    """Recursively replace non-finite floats with None (dict/list/tuple
    containers, numpy scalars and arrays degraded first) — the one
    shared spelling of the strict-JSON invariant; ``serve/net.py``
    imports it for the wire, and ``tools/obs_report.py`` (deliberately
    import-free) mirrors it for the digest."""
    if isinstance(x, dict):
        return {k: scrub_nonfinite(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [scrub_nonfinite(v) for v in x]
    if not isinstance(x, (str, bytes)) and callable(
            getattr(x, "tolist", None)):
        return scrub_nonfinite(x.tolist())  # numpy scalar/array first
    if isinstance(x, float) and not math.isfinite(x):
        return None
    return x


def _strict_json_line(record: dict) -> str:
    """One record as STRICT JSON: non-finite floats become null rather
    than the Python-only NaN/Infinity tokens that every other JSON
    parser rejects (the serving watcher legitimately sets a NaN gauge
    when no snapshot survives — the artifact must stay machine-readable
    to jq and non-Python consumers)."""
    try:
        return json.dumps(record, default=_json_default, allow_nan=False)
    except ValueError:
        return json.dumps(scrub_nonfinite(record), default=_json_default,
                          allow_nan=False)


def _json_default(v):
    """Telemetry values arrive as numpy scalars/arrays too — degrade to
    plain Python instead of throwing mid-training."""
    if hasattr(v, "item") and callable(v.item):
        try:
            return v.item()
        except (TypeError, ValueError):
            pass
    if hasattr(v, "tolist"):
        return v.tolist()
    return repr(v)


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


class PrometheusSink(Sink):
    """Prometheus text exposition (format 0.0.4) regenerated at flush.

    Keeps its own aggregates (counter sums, last gauge, histogram
    count/sum — no buckets: the exposition carries ``_count``/``_sum``
    summary series, which is what rate/latency dashboards consume) and
    rewrites ``path`` atomically on ``flush()``/``close()``. Events are
    ignored — Prometheus is a metrics surface.
    """

    def __init__(self, path: str, *, namespace: str = "fps_tpu"):
        self.path = path
        self.namespace = namespace
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, list] = {}  # key -> [count, sum]
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    def write(self, record: dict) -> None:
        if record.get("kind") != "metric":
            return
        key = (record["name"],
               tuple(sorted((record.get("labels") or {}).items())))
        v = float(record["value"])
        mtype = record.get("mtype")
        if mtype == "counter":
            self._counters[key] = self._counters.get(key, 0.0) + v
        elif mtype == "gauge":
            self._gauges[key] = v
        elif mtype == "histogram":
            h = self._hists.setdefault(key, [0, 0.0])
            h[0] += 1
            h[1] += v

    @staticmethod
    def _escape(value) -> str:
        """Label-value escaping per the exposition format (backslash,
        double quote, newline) — a user-chosen table name must not be
        able to invalidate the whole scrape file."""
        return (str(value).replace("\\", r"\\").replace('"', r"\"")
                .replace("\n", r"\n"))

    def _series(self, name: str, labels: tuple, suffix: str = "") -> str:
        base = self.namespace + "_" + _NAME_RE.sub("_", name.replace(".", "_"))
        lbl = ""
        if labels:
            lbl = "{" + ",".join(
                f'{_NAME_RE.sub("_", k)}="{self._escape(v)}"'
                for k, v in labels) + "}"
        return base + suffix + lbl

    def render(self) -> str:
        lines = []
        seen_help: set[str] = set()

        def header(name: str, ptype: str):
            base = self.namespace + "_" + _NAME_RE.sub(
                "_", name.replace(".", "_"))
            if base not in seen_help:
                seen_help.add(base)
                lines.append(f"# TYPE {base} {ptype}")

        for (name, labels), v in sorted(self._counters.items()):
            header(name, "counter")
            lines.append(f"{self._series(name, labels)} {v:g}")
        for (name, labels), v in sorted(self._gauges.items()):
            header(name, "gauge")
            lines.append(f"{self._series(name, labels)} {v:g}")
        for (name, labels), (count, total) in sorted(self._hists.items()):
            header(name, "summary")
            lines.append(f"{self._series(name, labels, '_count')} {count:g}")
            lines.append(f"{self._series(name, labels, '_sum')} {total:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def flush(self) -> None:
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(self.render())
            os.replace(tmp, self.path)
        except OSError as e:
            _log.warning("prometheus sink %s failed: %s", self.path, e)
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                pass
