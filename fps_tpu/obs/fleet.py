"""Fleet telemetry: windowed rollups + SLO burn over N per-host obs dirs.

``tools/obs_report.py`` digests ONE obs directory; a pod run produces N
of them (one per member/host) plus the pod journal. This module tails
them all and folds the per-host event streams into **time-windowed
rollups** of the fleet-level signals the ROADMAP fronts need:

* throughput — examples/s from ``driver.examples`` counter increments;
* tiering hit rate — ``hot_tier.hot_rows / hot_tier.pulled_rows``;
* cold-route certification rate —
  ``cold_route.compact_chunks / (compact + overflow)`` (the
  payload-proportionality health of the data plane, incl. under SSP);
* write→servable freshness — ``serve.write_to_servable_s`` samples;
* restart / fence counts — ``pod_restart`` + ``supervisor_restart``
  events and ``checkpoint.fenced_publishes`` increments.

On top of the rollup, declarative :class:`SLO` objects evaluate each
window and report **burn rate**: the fraction of bad windows divided by
the SLO's error budget (``1 - objective``) — burn > 1 means the
objective is being missed at an unsustainable rate, the standard
multi-window burn-rate alerting form.

Everything here is **post-hoc and host-side**: rollups re-read files the
training loop already wrote, lagged by the sinks' flush cadence (one
chunk of JSONL at most) — they never add work to, let alone block, the
hot path (see the telemetry-lag row in ``docs/STALENESS.md``).

Stdlib-only, zero fps_tpu imports: ``tools/obs_report.py --fleet`` loads
this file by path on jax-free login nodes (the ``tools/supervise.py``
pattern).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os

__all__ = [
    "SLO", "DEFAULT_SLOS", "host_series", "rollup", "evaluate_slos",
    "fleet_digest", "FLEET_SCHEMA_VERSION",
    "TENANTS_DIRNAME", "discover_tenants", "apply_slo_overrides",
    "tenant_fleet_digest",
]

FLEET_SCHEMA_VERSION = 1

# Counter names folded into per-window sums (each JSONL metric record
# carries the INCREMENT, so a window's value is the sum of its samples).
_WINDOW_COUNTERS = (
    "driver.examples",
    "driver.chunks",
    "driver.steps",
    "hot_tier.hot_rows",
    "hot_tier.pulled_rows",
    "cold_route.compact_chunks",
    "cold_route.overflow_chunks",
    "checkpoint.fenced_publishes",
    "checkpoint.saves",
    # Hostile-filesystem degradation (fps_tpu.core.retry + the async
    # writer's degraded mode): skipped publishes spend the storage-
    # staleness budget; degraded read-plane polls count liveness cost.
    "storage.degraded_publishes",
    "storage.poll_errors",
    # Hostile-network survival (fps_tpu.serve.wire / serve.net): shed
    # requests burn the shed-rate SLO; retries/torn frames quantify how
    # hard the wire is fighting back.
    "net.shed_requests",
    "net.retries",
    "net.torn_frames",
    "serve.requests",
)
# Gauge/sample names kept as (t, value) series for per-window max/last.
# serve.fence_step feeds the fleet fence-lag rollup: the fence's last
# published step per window, compared against the newest
# checkpoint_saved step the trainers reported by then.
# serve.reader_heartbeat_age_s feeds the heartbeat-staleness SLO: worst
# beacon age per window across readers.
_WINDOW_SAMPLES = ("serve.write_to_servable_s", "serve.fence_step",
                   "serve.reader_heartbeat_age_s")
# Journal events counted per window.
_WINDOW_EVENTS = ("pod_restart", "supervisor_restart", "budget_drift",
                  "checkpoint_fenced", "checkpoint_degraded",
                  "reader_wedged")


def _read_jsonl(path):
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    return  # torn tail: everything before it is valid
    except OSError:
        return


def host_series(obs_dir: str) -> dict:
    """One host's raw time series from an obs/state directory:
    ``{"counters": {name: [(t, inc), ...]}, "samples": {name: [...]},
    "events": {name: [t, ...]}}`` — the minimal input :func:`rollup`
    windows over. Reads ``events-p*.jsonl`` for metrics and every
    ``journal-*.jsonl`` for events (incident events are deduped on
    content across the two sources, like ``tools/obs_report.py``)."""
    counters = {n: [] for n in _WINDOW_COUNTERS}
    samples = {n: [] for n in _WINDOW_SAMPLES}
    events = {n: [] for n in _WINDOW_EVENTS}
    published = []  # (t, step) from checkpoint_saved — fence-lag ref
    seen_events = set()
    for path in sorted(glob.glob(os.path.join(obs_dir, "events-p*.jsonl"))):
        for rec in _read_jsonl(path):
            kind = rec.get("kind")
            if kind == "metric":
                name = rec.get("name")
                t = rec.get("t")
                raw = rec.get("value")
                v = math.nan if raw is None else float(raw)
                if name in counters and t is not None:
                    counters[name].append((float(t), v))
                elif name in samples and t is not None:
                    samples[name].append((float(t), v))
            elif kind == "event":
                _fold_event(rec, events, seen_events, published)
    for path in sorted(glob.glob(os.path.join(obs_dir,
                                              "journal-*.jsonl"))):
        for rec in _read_jsonl(path):
            if rec.get("kind") == "event":
                _fold_event(rec, events, seen_events, published)
    return {"counters": counters, "samples": samples, "events": events,
            "published": published}


def _fold_event(rec, events, seen, published=None) -> None:
    et = rec.get("event")
    capture_pub = (published is not None and et == "checkpoint_saved"
                   and rec.get("t") is not None
                   and rec.get("step") is not None)
    if et not in events and not capture_pub:
        return
    key = json.dumps(rec, sort_keys=True, default=str)
    if key in seen:
        return
    seen.add(key)
    if rec.get("t") is None:
        return
    if capture_pub:
        try:
            published.append((float(rec["t"]), int(rec["step"])))
        except (TypeError, ValueError):
            pass
    if et in events:
        events[et].append(float(rec["t"]))


def _ratio(num, den, digits=4):
    return round(num / den, digits) if den else None


def _window_stats(series_by_host, t0, t1) -> dict:
    """Fold every host's series into one window's rollup row."""
    c = {n: 0.0 for n in _WINDOW_COUNTERS}
    ev = {n: 0 for n in _WINDOW_EVENTS}
    fresh = []
    fence_lag = None
    hb_age_max = None
    # The fence-lag reference: newest step ANY trainer durably
    # published by the end of this window (fence readers lag it by
    # design; the SLO bounds by how much).
    newest_pub = max((s for series in series_by_host.values()
                      for t, s in series.get("published", ())
                      if t < t1), default=None)
    for series in series_by_host.values():
        for name, pts in series["counters"].items():
            c[name] += sum(v for t, v in pts
                           if t0 <= t < t1 and math.isfinite(v))
        for t, v in series["samples"]["serve.write_to_servable_s"]:
            if t0 <= t < t1 and math.isfinite(v):
                fresh.append(v)
        # serve.fence_step lag vs the newest published step: per host,
        # the LAST fence sample in the window; fold as the worst lag
        # across hosts (one straggling reader burns the SLO).
        fence_last = None
        for t, v in series["samples"]["serve.fence_step"]:
            if t0 <= t < t1 and math.isfinite(v):
                fence_last = v  # samples arrive in time order
        if fence_last is not None and newest_pub is not None:
            lag = max(0.0, float(newest_pub) - float(fence_last))
            fence_lag = lag if fence_lag is None else max(fence_lag, lag)
        # Heartbeat staleness: worst beacon age seen in the window
        # across every reader on every host — one wedged reader burns
        # the SLO.
        for t, v in series["samples"]["serve.reader_heartbeat_age_s"]:
            if t0 <= t < t1 and math.isfinite(v):
                hb_age_max = (v if hb_age_max is None
                              else max(hb_age_max, v))
        for name, ts in series["events"].items():
            ev[name] += sum(1 for t in ts if t0 <= t < t1)
    dt = max(t1 - t0, 1e-9)
    compact = c["cold_route.compact_chunks"]
    overflow = c["cold_route.overflow_chunks"]
    return {
        "t0": round(t0, 3),
        "t1": round(t1, 3),
        "examples": c["driver.examples"],
        "chunks": int(c["driver.chunks"]),
        "examples_per_sec": round(c["driver.examples"] / dt, 1),
        "hot_hit_rate": _ratio(c["hot_tier.hot_rows"],
                               c["hot_tier.pulled_rows"]),
        "cold_route_cert_rate": _ratio(compact, compact + overflow),
        "freshness_s_max": round(max(fresh), 4) if fresh else None,
        "restarts": ev["pod_restart"] + ev["supervisor_restart"],
        # The counter and the journal event fire together; max() keeps a
        # dir holding both sources from double-counting (the
        # obs_report.py rule).
        "fenced_publishes": max(int(c["checkpoint.fenced_publishes"]),
                                ev["checkpoint_fenced"]),
        "budget_drift_incidents": ev["budget_drift"],
        "checkpoint_saves": int(c["checkpoint.saves"]),
        # Hostile-filesystem degradation (same max() dedup rule as the
        # fence counter: event and counter fire together).
        "degraded_publishes": max(
            int(c["storage.degraded_publishes"]),
            ev["checkpoint_degraded"]),
        "storage_poll_errors": int(c["storage.poll_errors"]),
        "fence_lag_steps": (round(fence_lag, 1)
                            if fence_lag is not None else None),
        # Hostile-network survival: shed RATE is sheds over sheds +
        # served (None when the wire moved no traffic in the window —
        # neither good nor bad for the SLO).
        "net_shed_requests": int(c["net.shed_requests"]),
        "net_retries": int(c["net.retries"]),
        "net_torn_frames": int(c["net.torn_frames"]),
        "net_shed_rate": _ratio(
            c["net.shed_requests"],
            c["net.shed_requests"] + c["serve.requests"]),
        "reader_heartbeat_age_s_max": (
            round(hb_age_max, 3) if hb_age_max is not None else None),
        "reader_wedged_incidents": ev["reader_wedged"],
    }


def rollup(dirs, *, window_s: float | None = None,
           num_windows: int = 6) -> dict:
    """Windowed fleet rollup over N obs/state dirs. ``window_s`` fixes
    the window width (default: the observed span divided into
    ``num_windows``). Returns ``{"hosts", "window_s", "windows",
    "totals"}`` — ``totals`` is the single whole-span window."""
    series_by_host = {}
    for d in dirs:
        name = os.path.basename(os.path.normpath(d)) or d
        # Two dirs with one basename (rare) must not silently merge.
        key = name if name not in series_by_host else d
        series_by_host[key] = host_series(d)
    ts = [t
          for s in series_by_host.values()
          for group in ("counters", "samples")
          for pts in s[group].values()
          for t, _ in pts] + [t for s in series_by_host.values()
                              for tl in s["events"].values()
                              for t in tl]
    if not ts:
        return {"hosts": sorted(series_by_host), "window_s": None,
                "windows": [], "totals": None}
    t_min, t_max = min(ts), max(ts)
    span = max(t_max - t_min, 1e-9)
    w = float(window_s) if window_s else span / max(num_windows, 1)
    # Half-open windows need the final edge strictly PAST t_max; a
    # fixed +1e-9 vanishes below float epsilon at unix-epoch magnitudes
    # (~1.8e9), silently dropping the newest sample — nextafter is the
    # smallest representable bump at any magnitude.
    t_end = math.nextafter(t_max, math.inf)
    windows = []
    t0 = t_min
    while t0 < t_max or not windows:
        t1 = t0 + w
        windows.append(_window_stats(
            series_by_host, t0, t1 if t1 < t_max else t_end))
        t0 = t1
    return {
        "hosts": sorted(series_by_host),
        "window_s": round(w, 3),
        "windows": windows,
        "totals": _window_stats(series_by_host, t_min, t_end),
    }


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative service-level objective over rollup windows.

    A window is GOOD when ``field`` compares to ``target`` under ``op``
    (windows where the field is None — no samples — are skipped, they
    are neither good nor bad). ``objective`` is the required good
    fraction; the **burn rate** is ``bad_fraction / (1 - objective)`` —
    burn > 1 means the error budget is being spent faster than the
    objective tolerates."""

    name: str
    field: str
    op: str  # ">=" or "<="
    target: float
    objective: float = 0.9
    description: str = ""

    def __post_init__(self):
        if self.op not in (">=", "<="):
            raise ValueError(f"SLO {self.name!r}: op must be '>=' or "
                             f"'<=', got {self.op!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO {self.name!r}: objective must be in "
                             f"(0, 1), got {self.objective}")

    def good(self, value) -> bool | None:
        if value is None:
            return None
        v = float(value)
        return v >= self.target if self.op == ">=" else v <= self.target


DEFAULT_SLOS = (
    SLO("cold_route_certification", "cold_route_cert_rate", ">=", 0.9,
        objective=0.75,
        description="share of chunks the compacted cold route certified "
                    "(payload-proportional routing healthy)"),
    SLO("write_to_servable_freshness", "freshness_s_max", "<=", 60.0,
        objective=0.9,
        description="worst write->servable lag per window (the serving "
                    "freshness SLO, docs/serving.md)"),
    SLO("restart_quiet", "restarts", "<=", 0.0, objective=0.75,
        description="windows free of coordinated/supervised restarts"),
    SLO("budget_drift_quiet", "budget_drift_incidents", "<=", 0.0,
        objective=0.9,
        description="windows free of measured-vs-certified collective "
                    "budget drift incidents (fps_tpu.obs.drift)"),
    # Hostile-filesystem survival (docs/resilience.md, docs/STALENESS.md
    # storage row): degraded publishes are the storage-STALENESS budget —
    # each one is recency deliberately spent to keep training alive
    # through a brownout, never corruption; sustained burn means the
    # filesystem (not the framework) needs attention.
    SLO("storage_staleness_budget", "degraded_publishes", "<=", 0.0,
        objective=0.75,
        description="windows free of degraded (skipped) checkpoint "
                    "publishes — burn = the shared filesystem is "
                    "costing snapshot recency"),
    # Fleet fence lag vs the newest published step (PR-14 remaining
    # item): the fence trails the trainer by verification + quorum; the
    # SLO bounds how far before the serving plane counts as stale.
    SLO("serve_fence_lag", "fence_lag_steps", "<=", 8.0, objective=0.75,
        description="fleet fence (serve.fence_step) within budget of "
                    "the newest checkpoint_saved step"),
    # Hostile-network survival (docs/resilience.md "Hostile network"):
    # shedding is the wire's staleness-budget twin — lost WORK spent
    # deliberately to bound latency, never lost correctness; sustained
    # burn means capacity (not the framework) needs attention.
    SLO("net_shed_rate", "net_shed_rate", "<=", 0.05, objective=0.75,
        description="share of wire requests shed with BUSY by "
                    "admission control (load lost to keep the serving "
                    "plane bounded)"),
    # A beacon older than the liveness timeout in any window means a
    # reader sat wedged (SIGSTOP, deadlock, partition) — the incident
    # the supervisor must act on, never a silent 0 q/s (BENCH_r14).
    SLO("reader_heartbeat_fresh", "reader_heartbeat_age_s_max", "<=",
        5.0, objective=0.75,
        description="worst fleet-reader liveness-beacon age per window "
                    "within the reader_wedged timeout"),
)


def evaluate_slos(roll: dict, slos=DEFAULT_SLOS) -> dict:
    """Per-SLO verdicts over a :func:`rollup` result: evaluated window
    count, bad windows, bad fraction, burn rate, and ok (burn <= 1)."""
    out = {}
    for slo in slos:
        verdicts = [slo.good(w.get(slo.field)) for w in roll["windows"]]
        evaluated = [v for v in verdicts if v is not None]
        bad = sum(1 for v in evaluated if not v)
        frac = bad / len(evaluated) if evaluated else 0.0
        burn = frac / max(1.0 - slo.objective, 1e-9)
        out[slo.name] = {
            "field": slo.field,
            "op": slo.op,
            "target": slo.target,
            "objective": slo.objective,
            "windows_evaluated": len(evaluated),
            "bad_windows": bad,
            "bad_fraction": round(frac, 4),
            "burn_rate": round(burn, 4),
            "ok": burn <= 1.0,
        }
    return out


def fleet_digest(dirs, *, window_s: float | None = None,
                 num_windows: int = 6, slos=DEFAULT_SLOS,
                 digest_fn=None) -> dict:
    """The ``obs_report --fleet`` payload: rollup + SLO burn (+ each
    host's standard single-dir digest when the caller passes its
    ``render_digest`` as ``digest_fn`` — kept injectable so this module
    stays import-free of the tools)."""
    roll = rollup(dirs, window_s=window_s, num_windows=num_windows)
    out = {
        "schema": FLEET_SCHEMA_VERSION,
        "dirs": [os.path.abspath(d) for d in dirs],
        "rollup": roll,
        "slo": evaluate_slos(roll, slos),
    }
    if digest_fn is not None:
        hosts = {}
        for d in dirs:
            name = os.path.basename(os.path.normpath(d)) or d
            # Same collision rule as rollup(): two dirs sharing one
            # basename must not silently merge into one entry.
            key = name if name not in hosts else d
            try:
                hosts[key] = digest_fn(d)
            except FileNotFoundError:
                hosts[key] = None  # a member dir with no obs files yet
        out["host_digests"] = hosts
    return out


# ---------------------------------------------------------------------------
# Multi-tenant pods (fps_tpu.tenancy): per-tenant rollups + SLO burn.
#
# These constants MIRROR fps_tpu/tenancy/paths.py — this module is
# stdlib-only and loaded by file path on jax-free login nodes, so it
# cannot import the package (tests/test_tenancy.py pins the mirror).
TENANTS_DIRNAME = "tenants"
TENANT_MANIFEST_FILENAME = "tenant.json"
TENANT_OBS_DIRNAME = "obs"
TENANT_STATE_DIRNAME = "state"
# Mirrors fps_tpu/supervise/supervisor.py JOURNAL_FILENAME.
SUPERVISOR_JOURNAL_FILENAME = "journal-supervisor.jsonl"


def discover_tenants(root: str) -> dict:
    """``{name: {"dir", "obs_dir", "state_dir", "manifest"}}`` for every
    ``<root>/tenants/<name>/`` carrying a ``tenant.json`` manifest (the
    :class:`fps_tpu.tenancy.TenantManager` layout). An unreadable or
    torn manifest degrades to ``{}`` — the tenant still reports."""
    out = {}
    base = os.path.join(root, TENANTS_DIRNAME)
    try:
        names = sorted(os.listdir(base))
    except OSError:
        return out
    for name in names:
        tdir = os.path.join(base, name)
        mpath = os.path.join(tdir, TENANT_MANIFEST_FILENAME)
        if not os.path.isfile(mpath):
            continue
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            manifest = {}
        out[name] = {
            "dir": tdir,
            "obs_dir": os.path.join(tdir, TENANT_OBS_DIRNAME),
            "state_dir": os.path.join(tdir, TENANT_STATE_DIRNAME),
            "manifest": manifest if isinstance(manifest, dict) else {},
        }
    return out


def apply_slo_overrides(slos, overrides) -> tuple:
    """Per-tenant SLO overrides (``TenantSpec.slo`` via the tenant.json
    manifest): ``{slo_name: {"target": x, "objective": y}}`` replaces
    just those knobs on the matching default SLO. Unknown SLO names and
    non-dict values are ignored — a manifest written by a newer spec
    must not break an older report."""
    if not overrides or not isinstance(overrides, dict):
        return tuple(slos)
    out = []
    for slo in slos:
        ov = overrides.get(slo.name)
        if isinstance(ov, dict):
            try:
                kw = {k: float(ov[k]) for k in ("target", "objective")
                      if k in ov}
                if kw:
                    slo = dataclasses.replace(slo, **kw)
            except (TypeError, ValueError):
                pass  # malformed override: keep the default knobs
        out.append(slo)
    return tuple(out)


def _load_supervisor():
    """``fps_tpu/supervise/supervisor.py`` for :func:`recovery_times` —
    by file path when the package is not already imported (the same
    login-node rule as ``tools/obs_report.py`` loading THIS file)."""
    import importlib.util
    import sys as _sys

    for name in ("fps_tpu.supervise.supervisor", "_fps_supervisor_fleet"):
        mod = _sys.modules.get(name)
        if mod is not None:
            return mod
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "supervise", "supervisor.py")
    spec = importlib.util.spec_from_file_location(
        "_fps_supervisor_fleet", path)
    mod = importlib.util.module_from_spec(spec)
    _sys.modules[spec.name] = mod  # dataclasses resolve via sys.modules
    spec.loader.exec_module(mod)
    return mod


def tenant_fleet_digest(root: str, *, window_s: float | None = None,
                        num_windows: int = 6, slos=DEFAULT_SLOS) -> dict:
    """Per-tenant rollup + SLO burn over ``<root>/tenants/<name>/``.

    Blast-radius isolation extends to telemetry: each tenant's obs +
    supervisor-state dirs fold into its OWN rollup and its OWN burn
    rates (with manifest SLO overrides applied), so one tenant's
    incidents never burn a neighbor's error budget. The supervisor
    journal's recovery times (attempt kill -> first post-restart
    dispatch) ride along as the tenant's MTTR evidence."""
    out = {"schema": FLEET_SCHEMA_VERSION,
           "root": os.path.abspath(root), "tenants": {}}
    sup = None
    for name, info in discover_tenants(root).items():
        roll = rollup([info["obs_dir"], info["state_dir"]],
                      window_s=window_s, num_windows=num_windows)
        manifest = info["manifest"]
        t_slos = apply_slo_overrides(slos, manifest.get("slo"))
        journal = os.path.join(info["state_dir"],
                               SUPERVISOR_JOURNAL_FILENAME)
        times = []
        if os.path.isfile(journal):
            if sup is None:
                sup = _load_supervisor()
            times = sup.recovery_times(journal)
        try:
            weight = float(manifest.get("weight", 1.0))
        except (TypeError, ValueError):
            weight = 1.0
        out["tenants"][name] = {
            "weight": weight,
            "slo_overrides": sorted(manifest.get("slo") or ())
                             if isinstance(manifest.get("slo"), dict)
                             else [],
            "rollup": roll,
            "slo": evaluate_slos(roll, t_slos),
            "recovery": {
                "count": len(times),
                "times_s": times,
                "mean_s": (round(sum(times) / len(times), 3)
                           if times else None),
                "max_s": round(max(times), 3) if times else None,
            },
        }
    return out
