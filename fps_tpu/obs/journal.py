"""Per-process run journal: the narrative record of one training run.

A JSONL file holding only the run's EVENTS — run start/end (run id,
config digest, process/mesh identity), chunk and epoch boundaries,
checkpoint saves and fallback-restores, rollback/quarantine decisions,
guard escalations, watchdog stalls. Metrics samples stay in the event
log (``JsonlSink``); the journal is the small file a human (or
``tools/obs_report.py``) reads first to understand what a run did.

Multi-host: every process writes its own journal (``journal-p<K>.jsonl``)
and stamps records with ``run_id`` + ``process``; the report tool joins
them. There is no cross-process coordination here — telemetry must not
add collectives.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import uuid

from fps_tpu.obs.sinks import JsonlSink, Sink, _json_default


def new_run_id() -> str:
    """Sortable-by-start-time, collision-free across hosts."""
    return time.strftime("%Y%m%d-%H%M%S") + "-" + uuid.uuid4().hex[:8]


def config_digest(config) -> str:
    """Stable short digest of an arbitrary config mapping/object — the
    journal's answer to "were these two runs the same experiment?".
    Non-JSON values degrade to ``repr`` (callables, dtypes, meshes), so
    the digest is stable for a fixed config but not across refactors that
    change reprs — fine for its job of grouping runs, not proving them."""
    try:
        blob = json.dumps(config, sort_keys=True, default=_json_default)
    except TypeError:
        blob = repr(config)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def process_index() -> int:
    """This process's index in a multi-controller run; 0 when jax is not
    initialized (pure-host tools must not pay a jax import/init)."""
    try:
        import jax

        return jax.process_index()
    except Exception:  # noqa: BLE001 - any backend/init failure => solo
        return 0


class RunJournal(Sink):
    """Sink keeping only ``kind == "event"`` records, plus run_start /
    run_end bracketing. Attach it to a Recorder next to the full JSONL
    sink; both files then share one emission path and one clock."""

    def __init__(self, path: str, *, run_id: str, meta: dict | None = None):
        self.run_id = run_id
        self._inner = JsonlSink(path, flush_every=1)  # journal = durable
        self.path = path
        self._closed = False
        self._inner.write({
            "kind": "event", "t": time.time(), "event": "run_start",
            "run_id": run_id, "pid": os.getpid(), **(meta or {}),
        })

    def write(self, record: dict) -> None:
        if record.get("kind") == "event":
            self._inner.write(record)

    def flush(self) -> None:
        self._inner.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._inner.write({
            "kind": "event", "t": time.time(), "event": "run_end",
            "run_id": self.run_id,
        })
        self._inner.close()
