"""Typed metrics registry + recorder — the front door of the telemetry
subsystem.

The PR-1 health counters ride the training metrics stream as anonymous
pytree leaves: nothing names them, nothing types them, and every consumer
re-derives their meaning from the dict shape. This module replaces that
with a declared schema: every metrics leaf the framework emits is a
:class:`MetricSpec` (name, kind, unit, allowed labels) registered in a
:class:`MetricsRegistry`, and every emission goes through a
:class:`Recorder` that validates against the schema and fans the sample
out to pluggable sinks (:mod:`fps_tpu.obs.sinks`: JSONL event log,
Prometheus text exposition, in-memory ring for tests).

Host-side only, stdlib-only: nothing here is ever traced into a compiled
program, so attaching or detaching a recorder cannot change the XLA
program (asserted by lowered-HLO comparison in ``tests/test_obs.py``).
``recorder=None`` everywhere in the driver means zero calls into this
module — the off state costs nothing.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Iterable, Mapping

METRIC_KINDS = ("counter", "gauge", "histogram")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One named, typed metrics leaf.

    ``labels`` declares the allowed label KEYS (e.g. ``("table",)`` for a
    per-table counter, ``("phase",)`` for the phase timer histogram) —
    recording with an undeclared key raises, so a typo'd label surfaces at
    the emission site instead of silently forking a new series.
    """

    name: str
    kind: str
    unit: str = ""
    labels: tuple[str, ...] = ()
    help: str = ""

    def __post_init__(self):
        if self.kind not in METRIC_KINDS:
            raise ValueError(
                f"metric {self.name!r}: kind {self.kind!r} — expected one "
                f"of {METRIC_KINDS}"
            )
        if not self.name or any(c.isspace() for c in self.name):
            raise ValueError(f"metric name {self.name!r} must be non-empty "
                             "and whitespace-free")
        object.__setattr__(self, "labels", tuple(self.labels))


class MetricsRegistry:
    """Name → :class:`MetricSpec` map; the single source of truth for what
    the framework can emit. Duplicate registration with a different spec
    raises (same spec is idempotent, so library + user code can both
    declare shared leaves)."""

    def __init__(self, specs: Iterable[MetricSpec] = ()):
        self._specs: dict[str, MetricSpec] = {}
        for s in specs:
            self.register(s)

    def register(self, spec: MetricSpec) -> MetricSpec:
        have = self._specs.get(spec.name)
        if have is not None and have != spec:
            raise ValueError(
                f"metric {spec.name!r} already registered as {have}"
            )
        self._specs[spec.name] = spec
        return spec

    def get(self, name: str) -> MetricSpec:
        try:
            return self._specs[name]
        except KeyError:
            raise KeyError(
                f"unregistered metric {name!r} — declare it with "
                "MetricsRegistry.register(MetricSpec(...)) (typed leaves, "
                "not anonymous pytrees)"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    @property
    def specs(self) -> Mapping[str, MetricSpec]:
        return dict(self._specs)


def default_registry() -> MetricsRegistry:
    """A fresh registry pre-declaring every leaf the framework emits."""
    return MetricsRegistry([
        # Driver progress.
        MetricSpec("driver.chunks", "counter", unit="chunks",
                   help="compiled chunks completed (fit_stream)"),
        MetricSpec("driver.epochs", "counter", unit="epochs",
                   help="epochs completed (run_indexed)"),
        MetricSpec("driver.steps", "counter", unit="steps",
                   help="scan steps completed"),
        MetricSpec("driver.examples", "counter", unit="examples",
                   help="examples consumed (sum of the 'n' metrics leaf)"),
        # Phase timers (fps_tpu.obs.timing.PhaseTimer).
        MetricSpec("driver.phase_seconds", "histogram", unit="s",
                   labels=("phase",),
                   help="host wall-clock per phase segment: ingest / place "
                        "/ dispatch / host_sync / checkpoint / callback / "
                        "reconcile / retier / megastep"),
        # Device-resident megastep (fps_tpu.core.megastep;
        # docs/performance.md "Megastep").
        MetricSpec("megastep.windows", "counter", unit="windows",
                   help="in-graph chunk windows executed by megastep "
                        "dispatches (chunks_per_dispatch per call — each "
                        "ends with the flush reconcile + sketch merge the "
                        "per-chunk host loop ran between dispatches)"),
        MetricSpec("megastep.chunks_per_dispatch", "gauge", unit="chunks",
                   help="K of the current megastep program: chunk "
                        "segments fused into one compiled dispatch"),
        MetricSpec("megastep.auto_k", "gauge", unit="chunks",
                   help="K chosen by the auto-K calibration window "
                        "(chunks_per_dispatch='auto'): smallest K whose "
                        "modeled host-serial share h/(h+K*c) clears the "
                        "target, rounded up to the tick cadence"),
        MetricSpec("cold_route.vote_compact_windows", "counter",
                   unit="windows",
                   help="megastep chunk windows whose device-side "
                        "overflow VOTE certified every cold_budget lane "
                        "(the window ran the compacted cold routes; the "
                        "in-graph analog of cold_route.compact_chunks)"),
        MetricSpec("cold_route.vote_overflow_windows", "counter",
                   unit="windows",
                   help="megastep chunk windows whose single AND-ed "
                        "device vote overflowed (or could not certify) "
                        "some cold_budget lane and ran the bit-identical "
                        "static-route branch — unlabeled: the verdict is "
                        "one bit per window, a per-table attribution "
                        "would multiply-count it"),
        # Host pipeline (fps_tpu.core.prefetch).
        MetricSpec("prefetch.chunks", "counter", unit="chunks",
                   help="chunks assembled+placed by the background "
                        "prefetch pipeline"),
        MetricSpec("prefetch.queue_depth", "gauge", unit="chunks",
                   help="placed chunks buffered ahead of the driver "
                        "(sampled at every pipeline put/get)"),
        MetricSpec("prefetch.depth_adjustments", "counter", unit="steps",
                   help="adaptive depth raises: the consumer kept "
                        "draining the buffer empty inside a stall "
                        "window and host memory allowed one more "
                        "buffered chunk"),
        # Two-tier hot storage (TableSpec.hot_tier / TrainerConfig.
        # hot_sync_every; docs/performance.md "Two-tier storage").
        MetricSpec("hot_tier.hot_rows", "counter", unit="rows",
                   labels=("table",),
                   help="pulled rows served by the replicated hot tier "
                        "(local gather, zero collectives)"),
        MetricSpec("hot_tier.pulled_rows", "counter", unit="rows",
                   labels=("table",),
                   help="total live rows pulled from a tiered table "
                        "(hot_rows / pulled_rows = the hit rate)"),
        MetricSpec("hot_tier.pending_delta", "gauge", unit="l2",
                   labels=("table",),
                   help="peak within-call root-sum-square of the hot "
                        "tier's per-device pending (un-reconciled) delta "
                        "buffers — a parameter-plane staleness PROXY: "
                        "the delta a reconcile actually applies is the "
                        "psum, whose norm can exceed this by up to "
                        "sqrt(num_devices) when device deltas align"),
        # Payload-proportional cold routing (TableSpec.cold_budget;
        # docs/performance.md "Payload-proportional routing").
        MetricSpec("cold_route.compact_chunks", "counter", unit="chunks",
                   help="chunks host-certified to fit every cold_budget "
                        "lane and dispatched through the COMPACTED "
                        "cold-route program (O(cold traffic) collective "
                        "payload)"),
        MetricSpec("cold_route.overflow_chunks", "counter", unit="chunks",
                   labels=("table",),
                   help="chunks that overflowed (or could not certify) a "
                        "table's cold_budget lane and fell back to the "
                        "static full-payload cold routes — incremented "
                        "once per overflowing table per chunk"),
        MetricSpec("hot_tier.cold_dropped", "counter", unit="rows",
                   labels=("table",),
                   help="cold rows dropped by the device-side compaction "
                        "lane (the observability net: zero for every "
                        "host-certified chunk by construction)"),
        # Adaptive tiering (fps_tpu.tiering; docs/performance.md
        # "Adaptive tiering"): online hot-set re-ranking + auto-planner.
        MetricSpec("tiering.re_ranks", "counter", unit="re_ranks",
                   labels=("table",),
                   help="hot-set re-ranks applied (replica + slot-map "
                        "swap; never a recompile)"),
        MetricSpec("tiering.churn", "gauge", unit="fraction",
                   labels=("table",),
                   help="last measured churn: |sketched top-H \\ current "
                        "hot set| / H at the most recent check"),
        MetricSpec("tiering.promoted_rows", "counter", unit="rows",
                   labels=("table",),
                   help="ids promoted into the hot set by re-ranks"),
        MetricSpec("tiering.demoted_rows", "counter", unit="rows",
                   labels=("table",),
                   help="ids demoted out of the hot set by re-ranks"),
        MetricSpec("tiering.replans", "counter", unit="replans",
                   labels=("changed",),
                   help="periodic re-planning checks (Retierer."
                        "replan_every): changed=true re-applied a new "
                        "plan (one deliberate recompile), changed=false "
                        "was a strict no-op (zero recompiles)"),
        # Health channel (thresholded by fps_tpu.obs.health.HealthMonitor).
        MetricSpec("health.nonfinite_rows", "counter", unit="rows",
                   labels=("table",),
                   help="push rows dropped/flagged with non-finite values"),
        MetricSpec("health.norm_rows", "counter", unit="rows",
                   labels=("table",),
                   help="push rows over the guard's norm_limit"),
        MetricSpec("health.masked_rows", "counter", unit="rows",
                   labels=("table",),
                   help="push rows masked in guard='mask' mode"),
        MetricSpec("health.poisoned_chunks", "counter", unit="chunks",
                   help="chunks/epochs whose health channel reported poison"),
        # Resilience / persistence events.
        MetricSpec("rollback.quarantined", "counter", unit="chunks",
                   help="chunks/epochs rolled back and quarantined"),
        MetricSpec("rollback.preset_skipped", "counter", unit="chunks",
                   help="chunks/epochs skipped via a supervisor-carried "
                        "quarantine preset (never dispatched)"),
        MetricSpec("checkpoint.saves", "counter", unit="snapshots"),
        MetricSpec("checkpoint.enqueues", "counter", unit="snapshots",
                   help="async snapshots accepted for background write "
                        "(checkpoint.saves marks the durability point)"),
        MetricSpec("checkpoint.save_seconds", "histogram", unit="s"),
        MetricSpec("checkpoint.dump_seconds", "histogram", unit="s",
                   help="what a save costs the TRAINING thread: the "
                        "inline device->host capture, or — on the "
                        "deferred path — just the enqueue of the "
                        "boundary copies (capture itself then rides "
                        "checkpoint.capture_seconds on the writer)"),
        MetricSpec("checkpoint.capture_seconds", "histogram", unit="s",
                   help="device->host snapshot capture time (touched-row "
                        "device_get + CRC prep) wherever it runs — on "
                        "the writer thread under deferred capture, "
                        "inline otherwise; dump_seconds minus this is "
                        "the training thread's residual share"),
        MetricSpec("checkpoint.bytes", "gauge", unit="bytes",
                   help="size of the last written FULL snapshot (delta "
                        "publications ride checkpoint.delta_bytes; the "
                        "two together are the payload-proportionality "
                        "ratio)"),
        MetricSpec("checkpoint.fallbacks", "counter", unit="snapshots",
                   help="corrupt snapshots quarantined by fallback restore"),
        # Delta-snapshot chains (Checkpointer(delta=DeltaPolicy(...))).
        MetricSpec("checkpoint.delta_publishes", "counter",
                   unit="snapshots",
                   help="publications written as row-sparse DELTAS "
                        "against the previous publication (checkpoint."
                        "saves counts fulls and deltas alike)"),
        MetricSpec("checkpoint.delta_bytes", "counter", unit="bytes",
                   help="total bytes published as deltas — against "
                        "checkpoint.bytes' full-snapshot size, the "
                        "payload-proportionality evidence (publish "
                        "bytes ~ touched rows, not table size)"),
        MetricSpec("checkpoint.compactions", "counter", unit="folds",
                   help="LSM-style chain compactions: a delta chain "
                        "folded into a fresh full at its head step "
                        "(atomic-rename + fence-precommit, crash-safe "
                        "at every phase)"),
        MetricSpec("checkpoint.fenced_publishes", "counter",
                   unit="snapshots",
                   help="publishes refused by a pod fence (the writer's "
                        "epoch predates the pod's current attempt — "
                        "fps_tpu.supervise.pod)"),
        MetricSpec("checkpoint.resplits", "counter", unit="restores",
                   help="restores that re-split tables onto a different "
                        "mesh shape than the snapshot's (the elastic "
                        "W±1 path; each is asserted bit-identical)"),
        # Hostile-filesystem survival (fps_tpu.core.retry + degraded-
        # mode storage; docs/resilience.md "Hostile filesystem").
        MetricSpec("storage.retries", "counter", unit="ops",
                   labels=("plane",),
                   help="file operations retried after a transient I/O "
                        "error (bounded deterministic backoff; plane: "
                        "checkpoint / sidecar / ...)"),
        MetricSpec("storage.degraded_publishes", "counter",
                   unit="snapshots",
                   help="checkpoint publishes SKIPPED after the retry "
                        "budget on a transient storage failure — "
                        "training continues on last-good durable state; "
                        "each skip spends recency (the storage-"
                        "staleness SLO), never correctness"),
        MetricSpec("checkpoint.publish_backlog", "gauge",
                   unit="snapshots",
                   help="consecutive degraded (skipped) publishes since "
                        "the last landed one — drains to 0 the moment a "
                        "publish lands, because a landed snapshot fully "
                        "describes its step"),
        MetricSpec("storage.poll_errors", "counter", unit="polls",
                   labels=("plane",),
                   help="read-plane polls degraded by a transient "
                        "filesystem error (plane: watcher / fleet) — "
                        "the reader served last-good state and retried "
                        "next tick, never froze or crashed"),
        MetricSpec("storage.sidecar_skips", "counter", unit="writes",
                   help="tiering sidecar writes skipped after the retry "
                        "budget (advisory state: a resume past that "
                        "boundary cold-starts the tracker, warned "
                        "loudly)"),
        MetricSpec("storage.compaction_aborts", "counter", unit="folds",
                   help="LSM chain compactions aborted by an I/O "
                        "failure mid-fold (ENOSPC and kin): the chain "
                        "stays intact and the fold retries at the next "
                        "publish"),
        # Watchdog.
        MetricSpec("watchdog.stalls", "counter", unit="stalls",
                   help="chunk/epoch dispatches that overran the deadline"),
        # Read-path serving tier (fps_tpu.serve; docs/serving.md).
        MetricSpec("serve.requests", "counter", unit="requests",
                   labels=("op",),
                   help="ReadServer queries answered (op: pull / score / "
                        "topk)"),
        MetricSpec("serve.rows", "counter", unit="rows",
                   help="parameter rows served across all requests"),
        MetricSpec("serve.request_seconds", "histogram", unit="s",
                   labels=("op",),
                   help="per-request service latency (p50/p99 over the "
                        "retained window via ReadServer.latency_s)"),
        MetricSpec("serve.snapshot_step", "gauge", unit="step",
                   help="training step of the snapshot currently served"),
        MetricSpec("serve.snapshot_lag_steps", "gauge", unit="steps",
                   help="newest step the trainer has written minus the "
                        "served step — the freshness SLO in steps (NaN "
                        "when the served step was quarantined and nothing "
                        "survives)"),
        MetricSpec("serve.write_to_servable_s", "gauge", unit="s",
                   help="durability (checkpoint_saved) to servable "
                        "wall-clock lag of the last publish — the "
                        "end-to-end write->servable freshness SLO"),
        MetricSpec("serve.swaps", "counter", unit="swaps",
                   labels=("direction",),
                   help="snapshot hot-swaps published to the ReadServer "
                        "(direction: forward, or backward when the "
                        "trainer quarantined the served snapshot)"),
        MetricSpec("serve.rejected_snapshots", "counter", unit="snapshots",
                   help="snapshot candidates that failed CRC/structural "
                        "verification and were never served"),
        MetricSpec("serve.fence_step", "gauge", unit="step",
                   help="the serving fleet's shared step fence "
                        "(fps_tpu.serve.fleet): the step this reader "
                        "last swapped to under the fence — "
                        "forward-monotone fleet-wide within a fencing "
                        "epoch; backward only on a coordinated "
                        "quarantine rollback (epoch bump)"),
        MetricSpec("serve.reader_heartbeat_age_s", "gauge", unit="s",
                   labels=("reader",),
                   help="age of a fleet reader's newest liveness "
                        "beacon at the last liveness pass "
                        "(fps_tpu.serve.fleet.liveness_check); beyond "
                        "the liveness timeout the reader is classified "
                        "reader_wedged — an incident, never a silent "
                        "0 q/s (BENCH_r14)"),
        MetricSpec("serve.batches", "counter", unit="batches",
                   help="coalesced/multi batches executed by the "
                        "ReadServer (one merged fancy-index gather per "
                        "table per batch; docs/serving.md \"Batched "
                        "reads\")"),
        MetricSpec("serve.batch_size", "histogram", unit="requests",
                   help="requests merged into each coalesced/multi "
                        "batch — the batch-size/latency curve's x-axis "
                        "(bench serve_scale)"),
        MetricSpec("serve.fleet_size", "gauge", unit="readers",
                   help="fleet membership after each autoscaler "
                        "evaluation (fps_tpu.serve.fleet."
                        "ReadAutoscaler)"),
        MetricSpec("serve.autoscale_actions", "counter", unit="actions",
                   labels=("action",),
                   help="autoscaler scale decisions taken (action: "
                        "scale_up / scale_down / replace) — each one "
                        "also journaled as an autoscale_evaluate span "
                        "with its evidence"),
        # Wire plane (fps_tpu.serve.wire / serve.net; docs/resilience.md
        # "Hostile network").
        MetricSpec("net.retries", "counter", unit="requests",
                   labels=("peer_class",),
                   help="wire requests re-sent after a transient "
                        "network failure (classify_net: refused / "
                        "reset / timeout / torn frame), on the bounded "
                        "sha256-jittered backoff schedule"),
        MetricSpec("net.reconnects", "counter", unit="connections",
                   help="client reconnects that re-handshook and "
                        "resumed under the same session id (resends "
                        "dedupe server-side by (session, req_id))"),
        MetricSpec("net.torn_frames", "counter", unit="frames",
                   help="inbound frames rejected by the length/CRC "
                        "gates (short read, bad magic, checksum "
                        "mismatch) — counted and dropped with the "
                        "connection, NEVER decoded"),
        MetricSpec("net.shed_requests", "counter", unit="requests",
                   help="requests shed with a retryable BUSY frame by "
                        "admission control (bounded in-flight queue) — "
                        "the shed-rate SLO burns on this; lost work, "
                        "never lost correctness"),
        MetricSpec("net.deadline_exceeded", "counter", unit="requests",
                   help="requests abandoned on an exhausted deadline "
                        "budget — client side (retry budget ran out "
                        "inside the per-request deadline) or server "
                        "side (dead-on-arrival envelope)"),
        MetricSpec("net.replay_cache_evictions", "counter",
                   unit="responses",
                   help="cached (session, req_id) replay responses "
                        "evicted by the byte-bounded LRU (max_bytes "
                        "cap): an evicted entry's resend is re-executed "
                        "instead of replayed — duplicate work, never a "
                        "duplicate side effect for idempotent reads"),
        MetricSpec("net.bin_responses", "counter", unit="responses",
                   help="responses answered on the zero-copy binary "
                        "framing (CAP_BIN negotiated): table rows ride "
                        "as raw scatter-gather segments straight off "
                        "the snapshot's mapped pages, never "
                        "JSON-materialized"),
        MetricSpec("net.crc_light_frames", "counter", unit="frames",
                   help="large responses sent with a header-only CRC "
                        "trailer (CAP_CRC_LIGHT negotiated AND payload "
                        "over the threshold) on loopback-trusted "
                        "sessions; default sessions keep the "
                        "full-payload CRC"),
        # Shadow serving (fps_tpu.serve.shadow): old-vs-new snapshot
        # scoring gates fleet promotion (docs/STALENESS.md).
        MetricSpec("serve.shadow_promotions", "counter", unit="snapshots",
                   help="snapshot candidates promoted by the shadow "
                        "scorer (score(new) >= score(approved) + "
                        "min_delta) — the gated fleet's fence may now "
                        "advance to them"),
        MetricSpec("serve.shadow_held", "counter", unit="snapshots",
                   help="snapshot candidates HELD by the shadow scorer "
                        "(scored worse than the approved snapshot "
                        "beyond min_delta): the fleet keeps serving the "
                        "old approved step — lost freshness, never "
                        "wrong answers"),
        # Program contract auditor (fps_tpu.analysis; Trainer(audit=...)).
        MetricSpec("analysis.certified_programs", "counter",
                   unit="programs",
                   help="compiled step programs certified clean against "
                        "their ProgramContract at compile time"),
        MetricSpec("analysis.contract_violations", "counter",
                   unit="violations", labels=("rule",),
                   help="static-analysis contract violations (per pass: "
                        "collective_budget / host_transfer / donation / "
                        "dtype_drift / replica_consistency) — each also "
                        "emits an analysis.contract_violation event"),
        # Runtime budget-drift detection (fps_tpu.obs.drift): the live
        # data plane's measured collective traffic vs the budgets pinned
        # in AUDIT_r*.json.
        MetricSpec("analysis.budget_drift", "gauge", unit="ratio",
                   labels=("program",),
                   help="measured/pinned collective payload-byte ratio "
                        "for one observed program (1.0 = on certified "
                        "budget; NaN = unpinned/unbounded); departures "
                        "beyond tolerance also emit a budget_drift "
                        "incident event"),
    ])


class Recorder:
    """Validates samples against a registry and fans them out to sinks.

    One record shape for everything (so a single JSONL stream interleaves
    metrics and events in arrival order):

    * metric sample: ``{"kind": "metric", "t": ..., "name": ...,
      "mtype": "counter"|"gauge"|"histogram", "value": float,
      "labels": {...}}``
    * event: ``{"kind": "event", "t": ..., "event": <type>, **fields}``

    The recorder also keeps in-memory aggregates (counter sums, last
    gauge value, histogram count/sum/min/max) so tests and end-of-run
    digests don't need to re-read a sink. Thread-safe: the watchdog timer
    thread records through the same instance as the training loop.

    ``run_id`` and ``base_labels`` stamp every record — in multi-host runs
    each process opens its own recorder (and sink files), and the report
    tool joins on ``run_id``.
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 sinks: Iterable = (), *, run_id: str | None = None,
                 base_labels: Mapping[str, str] | None = None,
                 time_fn: Callable[[], float] = time.time):
        self.registry = registry or default_registry()
        self.sinks = list(sinks)
        self.run_id = run_id
        self.base = dict(base_labels or {})
        self._time = time_fn
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, dict] = {}
        self.closed = False

    # -- emission ---------------------------------------------------------

    def _key(self, name: str, labels: dict) -> tuple:
        return (name,) + tuple(sorted(labels.items()))

    def _record(self, kind: str, name: str, value: float, labels: dict):
        spec = self.registry.get(name)
        if spec.kind != kind:
            raise TypeError(
                f"metric {name!r} is a {spec.kind}, recorded as a {kind}"
            )
        unknown = set(labels) - set(spec.labels)
        if unknown:
            raise ValueError(
                f"metric {name!r}: undeclared labels {sorted(unknown)} "
                f"(declared: {list(spec.labels)})"
            )
        value = float(value)
        key = self._key(name, labels)
        with self._lock:
            if kind == "counter":
                self._counters[key] = self._counters.get(key, 0.0) + value
            elif kind == "gauge":
                self._gauges[key] = value
            else:
                h = self._hists.setdefault(
                    key, {"count": 0, "sum": 0.0, "min": None, "max": None}
                )
                h["count"] += 1
                h["sum"] += value
                h["min"] = value if h["min"] is None else min(h["min"], value)
                h["max"] = value if h["max"] is None else max(h["max"], value)
            rec = {"kind": "metric", "t": self._time(), "name": name,
                   "mtype": kind, "value": value}
            if self.run_id:
                rec["run_id"] = self.run_id
            if labels or self.base:
                rec["labels"] = {**self.base, **labels}
            for s in self.sinks:
                s.write(rec)

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        """Add to a counter (monotonic; negative increments raise)."""
        if value < 0:
            raise ValueError(f"counter {name!r}: negative increment {value}")
        self._record("counter", name, value, labels)

    def set(self, name: str, value: float, **labels) -> None:
        """Set a gauge to its current value."""
        self._record("gauge", name, value, labels)

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one histogram observation."""
        self._record("histogram", name, value, labels)

    def event(self, etype: str, **fields) -> None:
        """Append a structured event (journal entries ride this)."""
        rec = {"kind": "event", "t": self._time(), "event": etype, **fields}
        if self.run_id:
            rec.setdefault("run_id", self.run_id)
        with self._lock:
            for s in self.sinks:
                s.write(rec)

    # -- aggregates -------------------------------------------------------

    def counter_value(self, name: str, **labels) -> float:
        return self._counters.get(self._key(name, labels), 0.0)

    def snapshot(self) -> dict:
        """Aggregated view: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}`` with ``name{k=v,...}`` flat keys."""
        def flat(key):
            name, *lbls = key
            if not lbls:
                return name
            return name + "{" + ",".join(f"{k}={v}" for k, v in lbls) + "}"

        with self._lock:
            return {
                "counters": {flat(k): v for k, v in self._counters.items()},
                "gauges": {flat(k): v for k, v in self._gauges.items()},
                "histograms": {flat(k): dict(v)
                               for k, v in self._hists.items()},
            }

    def phase_totals(self) -> dict[str, dict]:
        """Per-phase ``{"s": total_seconds, "n": count}`` from the
        ``driver.phase_seconds`` histogram — the bench.py breakdown."""
        out = {}
        with self._lock:
            for key, h in self._hists.items():
                if key[0] != "driver.phase_seconds":
                    continue
                labels = dict(key[1:])
                phase = labels.get("phase", "?")
                out[phase] = {"s": round(h["sum"], 6), "n": h["count"]}
        return out

    # -- lifecycle --------------------------------------------------------

    def flush(self) -> None:
        # Under the lock: every sink WRITE happens under it (via
        # _record/event), so flush — which e.g. iterates PrometheusSink's
        # aggregate dicts to render the exposition — must serialize with
        # concurrent writers (the watchdog timer thread flushes while the
        # training thread records).
        with self._lock:
            for s in self.sinks:
                s.flush()

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            for s in self.sinks:
                s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
