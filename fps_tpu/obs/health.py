"""Health alerting: threshold the PR-1 health channel, watch for stalls.

The resilience layer (``fps_tpu/core/resilience.py``) counts poisoned
push rows onto the metrics stream but nothing acts on the counts — the
ROADMAP's open "health-channel alerting" item. This module closes it:

* :class:`HealthMonitor` — host-side policy the driver consults after
  every chunk/epoch whose metrics were synced: escalate the guard from
  ``observe`` to ``mask`` once the cumulative poisoned-row count crosses
  ``escalate_after_rows``, and abort the run (the driver raises
  :class:`~fps_tpu.core.resilience.PoisonedStreamError`) once
  ``abort_after_chunks`` distinct chunks reported poison. Escalation is
  the production posture: run cheap (observe = byte-identical stream)
  until the stream proves dirty, then pay for masking.
* :class:`StepWatchdog` — arms a deadline around each blocking
  chunk/epoch region; if the region overruns (a hung multi-host peer
  stalls every collective forever — the ROADMAP straggler item), the
  watchdog records the stall, emits a ``stall`` event, and fires the
  user's ``on_stall`` callback from the timer thread (which may page,
  dump stacks, or ``os._exit`` for a supervisor restart — the training
  thread itself is presumed wedged, so a callback is the only lever).

Both are pure host-side policy objects: no jax imports, nothing traced.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time

_log = logging.getLogger("fps_tpu.obs")

# Decisions HealthMonitor.update can return (the driver acts on them).
HEALTH_OK = "ok"
HEALTH_ESCALATE = "escalate"
HEALTH_ABORT = "abort"


@dataclasses.dataclass
class HealthMonitor:
    """Thresholds over the cumulative health-channel totals.

    ``escalate_after_rows``: once this many poisoned rows (nonfinite +
    norm tiers, summed over the run) have been seen, request guard
    escalation observe → mask. ``None`` disables the tier. Fires at most
    once (:attr:`escalated_at` records where).

    ``abort_after_chunks``: once this many distinct chunks/epochs have
    reported poison, request an abort — a stream that keeps producing
    poison is an ingest bug, not a transient. ``None`` disables.

    Requires ``TrainerConfig.guard`` (either mode) — without a guard
    there is no health channel to threshold; the driver validates this.
    """

    escalate_after_rows: int | None = None
    abort_after_chunks: int | None = None
    # Cumulative state (mutated by update()).
    poison_rows: int = 0
    poisoned_chunks: int = 0
    escalated_at: int | None = None
    aborted_at: int | None = None
    # (index, rows) per poisoned chunk — the digest's evidence trail.
    log: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        for name in ("escalate_after_rows", "abort_after_chunks"):
            v = getattr(self, name)
            if v is not None and v < 1:
                raise ValueError(f"{name} must be >= 1 or None, got {v}")

    def update(self, index: int, poison_rows: int) -> str:
        """Fold one chunk/epoch's poisoned-row total; returns the decision
        (``"ok"`` / ``"escalate"`` / ``"abort"``). The driver applies it —
        this object never touches trainer state itself."""
        if poison_rows > 0:
            self.poison_rows += int(poison_rows)
            self.poisoned_chunks += 1
            self.log.append((int(index), int(poison_rows)))
        if (self.abort_after_chunks is not None
                and self.poisoned_chunks >= self.abort_after_chunks):
            self.aborted_at = int(index)
            return HEALTH_ABORT
        if (self.escalate_after_rows is not None
                and self.escalated_at is None
                and self.poison_rows >= self.escalate_after_rows):
            self.escalated_at = int(index)
            return HEALTH_ESCALATE
        return HEALTH_OK


class StepWatchdog:
    """Deadline watchdog over the driver's blocking chunk/epoch regions.

    ``with watchdog.watch("chunk", i):`` arms a one-shot timer; if the
    body has not finished after ``deadline_s`` the timer thread records a
    stall (:attr:`stalls`), emits a ``stall`` event + ``watchdog.stalls``
    counter on the recorder, logs, and calls ``on_stall(info)``. The body
    is NOT interrupted — Python cannot safely preempt a thread blocked in
    a collective; the callback is the escalation point (page, dump
    host stacks, ``os._exit`` under a supervisor). A region that
    eventually completes after flagging emits a ``stall_recovered`` event
    with the real elapsed time, distinguishing a slow straggler from a
    true hang in the digest.

    A callback exception is logged and swallowed: the watchdog must never
    take down a run that was actually healthy.
    """

    def __init__(self, deadline_s: float, on_stall=None, recorder=None):
        if not deadline_s > 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.on_stall = on_stall
        self.recorder = recorder
        self.stalls: list[dict] = []
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def watch(self, what: str, index: int):
        info = {"what": what, "index": int(index),
                "deadline_s": self.deadline_s}
        # The same dict instance is appended to stalls and later stamped
        # with elapsed_s — no stalls[-1] indexing, so a concurrent second
        # watch() can never mis-attribute the recovery.
        entry = dict(info)
        fired = threading.Event()

        def _fire():
            with self._lock:
                self.stalls.append(entry)
            fired.set()  # AFTER the append: the recovery path keys on it
            _log.warning(
                "%s %d exceeded the %.1fs watchdog deadline — stalled "
                "dispatch or hung peer", what, index, self.deadline_s,
            )
            rec = self.recorder
            if rec is not None:
                try:
                    rec.inc("watchdog.stalls")
                    rec.event("stall", **info)
                    rec.flush()  # the process may be about to die; persist
                except Exception:  # noqa: BLE001 - on_stall MUST still run
                    _log.exception("watchdog telemetry failed; continuing "
                                   "to the on_stall escalation")
            if self.on_stall is not None:
                try:
                    self.on_stall(dict(info))
                except Exception:  # noqa: BLE001 - must not kill the run
                    _log.exception("watchdog on_stall callback raised")

        t0 = time.perf_counter()
        timer = threading.Timer(self.deadline_s, _fire)
        timer.daemon = True
        timer.start()
        ok = False
        try:
            yield
            ok = True
        finally:
            timer.cancel()
            # A body finishing right at the deadline can race a _fire
            # already past cancel(): join the timer thread (bounded — the
            # stall path is log + event + callback) so fired reflects
            # reality before we decide whether this was a recovery.
            timer.join(timeout=5.0)
            if fired.is_set():
                elapsed = time.perf_counter() - t0
                entry["elapsed_s"] = round(elapsed, 3)
                # Recovery is claimed only on a CLEAN exit — a region
                # that stalls and then raises died, and the digest must
                # not point the operator away from that.
                if ok and self.recorder is not None:
                    self.recorder.event("stall_recovered", **info,
                                        elapsed_s=round(elapsed, 3))
