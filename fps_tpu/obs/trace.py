"""Causal tracing: one span tree per incident, across every host.

PR 11 made the pod the failure domain, but the telemetry stayed per-host:
a coordinated restart shows up as N disconnected journal fragments with
nothing linking the leader's decision to the attempts it spawned. This
module mints **trace/span ids** and propagates them through the existing
supervised-child env contract, so every layer's journal events carry
causal links:

* the pod leader mints one ``trace_id`` per pod run and a ``span_id``
  per decision (coordinated restart, fence write, lease seizure) — the
  decision's span id rides ``pod_control.json`` to every member;
* each member's attempt becomes a span parented to the decision that
  commanded it (``attempt_start``/``attempt_end`` journal events carry
  ``trace_id``/``span_id``/``parent_id`` and the fencing epoch);
* the training child reads :data:`TRACE_ID_ENV` / :data:`PARENT_SPAN_ENV`
  (set by the supervisor alongside ``FPS_TPU_HEARTBEAT``) and its run
  journal's ``run_start`` links the whole run — chunk phases (the
  :class:`~fps_tpu.obs.timing.PhaseTimer` boundaries riding ``chunk``
  events), checkpoint publishes, and serve-side swaps — under that
  attempt.

``tools/trace_export.py`` renders one or many obs/pod directories into a
single Chrome-trace-event / Perfetto JSON: a ``pod_kill_one_host`` chaos
run becomes ONE causally-linked span tree instead of N fragments.

Tracing is **host-side only**: ids live in env vars and journal lines,
never in anything traced into a compiled program — trace on/off lowers
byte-identical HLO and bit-identical numerics (``tests/test_trace.py``).
Stdlib-only: the supervisor/pod layer mirrors the env names (it is
loaded by file path without the package) and ``tests/test_trace.py``
asserts the mirrors match.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
import uuid

# Env contract — MIRRORED in fps_tpu/supervise/child.py and
# fps_tpu/supervise/supervisor.py (stdlib-only, loadable by file path);
# tests/test_trace.py asserts the three definitions match.
TRACE_ID_ENV = "FPS_TPU_TRACE_ID"
PARENT_SPAN_ENV = "FPS_TPU_PARENT_SPAN"

__all__ = [
    "TRACE_ID_ENV", "PARENT_SPAN_ENV",
    "new_trace_id", "new_span_id",
    "TraceContext", "Tracer",
]


def new_trace_id() -> str:
    """A fresh 128-bit trace id (hex) — one per run attempt / pod run."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """A fresh 64-bit span id (hex)."""
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The causal coordinates a process inherits from its parent.

    ``trace_id`` names the whole incident/run tree; ``parent_id`` is the
    span (an attempt, a decision) this process's own spans hang under.
    """

    trace_id: str | None = None
    parent_id: str | None = None

    @classmethod
    def from_env(cls, environ=None) -> "TraceContext":
        env = os.environ if environ is None else environ
        return cls(trace_id=env.get(TRACE_ID_ENV) or None,
                   parent_id=env.get(PARENT_SPAN_ENV) or None)

    @property
    def active(self) -> bool:
        return self.trace_id is not None

    def child_env(self, parent_id: str | None = None) -> dict:
        """Env-var updates handing this context (re-parented under
        ``parent_id`` when given) to a child process."""
        out = {}
        if self.trace_id:
            out[TRACE_ID_ENV] = self.trace_id
        pid = parent_id or self.parent_id
        if pid:
            out[PARENT_SPAN_ENV] = pid
        return out


class Tracer:
    """Emits ``span`` events through a Recorder (or the process-default
    recorder): the run-side half of the tracing story — the supervisor
    and pod layers write the same record shape into their own journals
    without importing this module.

    The canonical span record (one journal line)::

        {"kind": "event", "event": "span", "span": <name>,
         "trace_id": ..., "span_id": ..., "parent_id": ...,
         "t0": ..., "t1": ..., ...attrs}

    Host-side only: nothing here touches the compiled program.
    """

    def __init__(self, recorder=None, *, trace_id: str | None = None,
                 parent_id: str | None = None, clock=time.time):
        self.recorder = recorder
        self.trace_id = trace_id or new_trace_id()
        self.parent_id = parent_id
        self.clock = clock

    def emit(self, name: str, t0: float, t1: float, *,
             parent_id: str | None = None, span_id: str | None = None,
             **attrs) -> str:
        """Record one finished span; returns its span id (so callers can
        parent further spans under it)."""
        sid = span_id or new_span_id()
        fields = {
            "span": name,
            "trace_id": self.trace_id,
            "span_id": sid,
            "parent_id": parent_id or self.parent_id,
            "t0": float(t0),
            "t1": float(t1),
            **attrs,
        }
        if self.recorder is not None:
            self.recorder.event("span", **fields)
        else:
            from fps_tpu.obs import events

            events.emit("span", **fields)
        return sid

    def instant(self, name: str, **attrs) -> str:
        t = self.clock()
        return self.emit(name, t, t, **attrs)

    @contextlib.contextmanager
    def span(self, name: str, *, parent_id: str | None = None, **attrs):
        """Context manager timing one span; yields the span id so nested
        work can parent under it."""
        sid = new_span_id()
        t0 = self.clock()
        try:
            yield sid
        finally:
            self.emit(name, t0, self.clock(), parent_id=parent_id,
                      span_id=sid, **attrs)
