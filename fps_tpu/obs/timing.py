"""Phase timers, throughput accounting, and device tracing.

The reference has no tracing subsystem — only Flink's built-in operator
metrics (SURVEY.md §5 tracing row). On TPU we get device-level tracing
from ``jax.profiler`` for free; this module packages it plus the two
host-side clocks the chunked driver makes natural:

* :class:`PhaseTimer` — splits each chunk's host wall-clock into named
  segments (``ingest`` / ``place`` / ``dispatch`` / ``host_sync`` /
  ``checkpoint`` / ``callback``), so a BENCH regression is attributable
  to a phase instead of a single opaque number. The compiled program
  fuses pull/compute/push into one dispatch, so those sub-phases are
  visible on the DEVICE timeline instead: the driver wraps them in
  ``jax.named_scope`` (``fps.pull`` / ``fps.compute`` / ``fps.push``),
  which costs nothing outside a profiler trace.
* :class:`Throughput` — per-chunk wall-clock + examples/sec accounting
  for ``Trainer.fit_stream(on_chunk=...)``.
* :func:`trace` — context manager writing a Perfetto/XProf-compatible
  trace of everything (XLA ops, collectives, host callbacks).

(Grew out of ``fps_tpu/utils/profiling.py``, which remains as a compat
shim.)
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

# Phase names the driver emits, in pipeline order. PhaseTimer accepts any
# name (custom loops may add their own); these are the declared ones.
DRIVER_PHASES = (
    "prefetch",    # background pipeline: chunk assembly + placement on
                   # the worker thread (fps_tpu.core.prefetch) — OVERLAPS
                   # the phases below, it is not part of their serial sum
    "ingest",      # pulling the next chunk from the host iterator (with
                   # the pipeline on: waiting on the prefetch buffer)
    "place",       # host->device transfer (host_to_sharded)
    "dispatch",    # the jitted call: enqueue + (first call) compile
    "host_sync",   # blocked fetching metrics back to host
    "checkpoint",  # snapshot save on the training thread
    "callback",    # user on_chunk / on_epoch hooks
    "reconcile",   # two-tier re-split at run entry (hot replica derive)
    "megastep",    # K-chunk device-resident dispatch (enqueue + first-
                   # call compile): the megastep driver's analog of
                   # "dispatch", kept distinct so the A/B's host-serial
                   # attribution can tell the two loop shapes apart
)


class PhaseTimer:
    """Named wall-clock segments, accumulated per chunk and per run.

    Feed it a :class:`~fps_tpu.obs.registry.Recorder` and every closed
    phase lands one ``driver.phase_seconds{phase=...}`` histogram sample;
    the per-chunk dict from :meth:`chunk_summary` rides the journal's
    chunk/epoch events. Dispatch is asynchronous in jax, so ``dispatch``
    measures enqueue (+ compile on the first call) and the device compute
    surfaces in ``host_sync`` wherever the host loop actually blocks —
    honest host-side attribution, not a guess at device internals.
    """

    def __init__(self, recorder=None):
        self.recorder = recorder
        self._chunk: dict[str, float] = {}
        # The prefetch worker thread folds its segments in via add()
        # while the driver thread closes phases and takes summaries.
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Fold an externally-measured segment into the current chunk —
        how the background prefetch worker reports its assemble+place
        time (``prefetch``) without a context manager spanning threads.
        A segment landing exactly at a chunk boundary may attribute to
        either side of it; overlapped phases are inherently concurrent
        with the driver's, so the ambiguity is real, not an artifact."""
        with self._lock:
            self._chunk[name] = self._chunk.get(name, 0.0) + seconds
        if self.recorder is not None:
            self.recorder.observe("driver.phase_seconds", seconds, phase=name)

    def chunk_summary(self, *, reset: bool = True) -> dict[str, float]:
        """Seconds per phase since the last reset (one chunk's breakdown).
        Whole-run totals live where every consumer already reads them:
        ``Recorder.phase_totals()`` over the ``driver.phase_seconds``
        histogram — the timer keeps no duplicate run-level state."""
        with self._lock:
            out = {k: round(v, 6) for k, v in self._chunk.items()}
            if reset:
                self._chunk = {}
        return out


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device+host profile under ``log_dir`` (view with XProf /
    Perfetto). Usable around any training region::

        with obs.trace("/tmp/trace"):
            trainer.run_chunk(...)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Throughput:
    """Callable chunk hook accumulating wall-clock and example counts.

    ``count_key`` names the metrics leaf holding per-step example counts
    (every shipped model emits ``"n"``). The first chunk is recorded
    separately (``first_s``) since it includes compilation.

    Timing origin: :meth:`start` marks the stream start explicitly; when
    it was never called, the first observation measures from CONSTRUCTION
    time. (It used to fall back to "now", which recorded a zero-width
    first chunk and understated compile time — the hook is conventionally
    built immediately before ``fit_stream``, so construction time is the
    honest origin; any setup between the two is attributed to the first
    chunk, which already absorbs one-time costs by design. Call
    ``start()`` right before the run when that setup is expensive, and
    before any *second* stream reusing this hook, or the inter-run gap
    lands in ``steady_s``.)
    """

    def __init__(self, count_key: str = "n"):
        self.count_key = count_key
        self.chunks = 0
        self.first_s: float | None = None
        self._first_examples = 0.0
        self.steady_s = 0.0
        self._steady_examples = 0.0
        self._last: float | None = None
        self._created = time.perf_counter()

    def start(self) -> None:
        """Mark the stream start (see the class docstring for when the
        implicit construction-time origin is not what you want)."""
        self._last = time.perf_counter()

    def __call__(self, step: int, metrics) -> None:
        now = time.perf_counter()
        if self._last is None:
            # No explicit start(): the stream began, as far as this hook
            # can know, when the hook was constructed.
            self._last = self._created
        dt = now - self._last
        self._last = now
        count = (
            float(np.sum(metrics[self.count_key]))
            if self.count_key in metrics
            else 0.0
        )
        if self.first_s is None:
            self.first_s = dt
            self._first_examples = count
        else:
            self.steady_s += dt
            self._steady_examples += count
        self.chunks += 1

    @property
    def examples(self) -> float:
        return self._first_examples + self._steady_examples

    @property
    def examples_per_sec(self) -> float:
        """Steady-state throughput (excludes the compile-laden first chunk)."""
        return self._steady_examples / self.steady_s if self.steady_s else 0.0

    def summary(self) -> dict:
        return {
            "chunks": self.chunks,
            "examples": self.examples,
            "first_chunk_s": round(self.first_s or 0.0, 4),
            "steady_s": round(self.steady_s, 4),
            "examples_per_sec": round(self.examples_per_sec, 1),
        }
