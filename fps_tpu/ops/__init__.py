"""Sparse hot-path ops with pluggable backends (XLA default, Pallas on TPU).

The store's pull/push collectives bottom out in two local ops per shard:
row **gather** (pull answers) and duplicate-combining **scatter-add** (push
folds). Both have an XLA lowering (``jnp.take`` / ``.at[].add``) and a Pallas
TPU kernel (:mod:`fps_tpu.ops.pallas_kernels`); this module picks per call.

Backend selection:

* ``set_backend("xla" | "pallas" | "auto")`` or env ``FPS_TPU_OPS`` at
  import time. Default ``"xla"``.
* ``"auto"``/``"pallas"`` route to Pallas kernels on TPU; off-TPU the
  kernels run in interpreter mode (tests exercise them that way) only when
  the backend is explicitly ``"pallas"``.
* The one-hot-matmul scatter pays ``rows × batch × dim`` MXU FLOPs; for
  tables/batches where that exceeds :data:`SCATTER_FLOP_BUDGET` the XLA
  scatter is used instead even under ``"pallas"``/``"auto"``.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

Array = jax.Array

_BACKEND = os.environ.get("FPS_TPU_OPS", "xla").lower()

# One-hot scatter cost ceiling (MXU flops per call). ~2e10 fl32 flops is
# ~0.2 ms on a v5e chip — beyond that the serialization cost XLA's scatter
# pays is cheaper than the dense indicator matmul.
SCATTER_FLOP_BUDGET = 2e10


def set_backend(name: str) -> None:
    """Select the hot-path backend for subsequently *traced* programs.

    The choice is read at trace time: programs already compiled (e.g. a
    ``Trainer`` that has run a chunk) keep the backend they were traced
    with. ``Trainer`` keys its compile cache on this setting, so new
    trainers — or the same trainer's next fresh trace — pick up the change.
    """
    global _BACKEND
    if name not in ("xla", "pallas", "auto"):
        raise ValueError(f"unknown ops backend {name!r}")
    _BACKEND = name


def get_backend() -> str:
    return _BACKEND


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def _use_pallas() -> tuple[bool, bool]:
    """(use_pallas, interpret) for the current backend setting."""
    if _BACKEND == "xla":
        return False, False
    if _on_tpu():
        return True, False
    # Off-TPU: only the explicit "pallas" setting runs (interpreted);
    # "auto" falls back to XLA for speed.
    return _BACKEND == "pallas", True


def gather_rows(table: Array, ids: Array) -> Array:
    """``table[ids]``; ids outside ``[0, rows)`` yield **zero rows** on every
    backend (the pull path's ``-1`` padding slots read as zeros; real pulls
    are always in range)."""
    use, interpret = _use_pallas()
    R, D = table.shape
    # Pallas gather only wins when the deltas occupy most of the 128-wide
    # lane dim (see measured crossover in pallas_kernels.py); below that the
    # indicator matmul wastes the MXU and XLA's gather is faster.
    if use and D >= 64 and R * ids.shape[0] * D <= SCATTER_FLOP_BUDGET:
        from fps_tpu.ops.pallas_kernels import gather_rows_pallas

        return gather_rows_pallas(table, ids, interpret=interpret)
    in_range = (ids >= 0) & (ids < R)
    vals = jnp.take(table, jnp.where(in_range, ids, 0), axis=0)
    return jnp.where(in_range[:, None], vals, jnp.zeros_like(vals))


def scatter_add(table: Array, ids: Array, deltas: Array) -> Array:
    """``table.at[ids].add(deltas)``; ids outside ``[0, rows)`` are dropped,
    duplicate ids accumulate (the server's additive ``paramUpdate`` fold)."""
    use, interpret = _use_pallas()
    R, D = table.shape
    if use and R * ids.shape[0] * max(D, 1) <= SCATTER_FLOP_BUDGET:
        from fps_tpu.ops.pallas_kernels import scatter_add_pallas

        return scatter_add_pallas(table, ids, deltas, interpret=interpret)
    # XLA path: clamp dropped ids to an out-of-range row and use drop mode.
    safe = jnp.where((ids >= 0) & (ids < R), ids, R)
    masked = jnp.where(((ids >= 0) & (ids < R))[:, None], deltas, 0)
    return table.at[safe].add(masked.astype(table.dtype), mode="drop")
