"""Sparse hot-path ops with pluggable backends (XLA + Pallas TPU kernels).

The store's pull/push collectives bottom out in two local ops per shard:
row **gather** (pull answers) and duplicate-combining **scatter-add** (push
folds). XLA's TPU scatter serializes colliding updates — per-row-transaction
cost that explodes on Zipfian-hot batches (measured on-chip, dedup-safe
fencing: 828us for a 32k-id push with 62% duplicates into a (26744, 11)
table vs ~280us at 0% duplicates). The framework's answer is NuPS-style
**hot/cold splitting** (:func:`scatter_add` with ``hot_rows``): pushes to
the frequency-ranked head rows ride a dense lane-packed one-hot MXU
contraction (:func:`fps_tpu.ops.pallas_kernels.scatter_add_packed_pallas`)
with zero serialization, while the low-duplication tail keeps the XLA
scatter. Correctness never depends on the hotness guess — a mis-ranked
table only wastes MXU work, and that waste is capped: a ``hot_rows`` whose
head contraction would exceed :data:`SCATTER_FLOP_BUDGET` falls back to
the plain XLA scatter.

Backend selection:

* ``set_backend("auto" | "xla" | "pallas")`` or env ``FPS_TPU_OPS`` at
  import time. Default ``"auto"``.
* ``"auto"`` — on TPU, XLA everywhere except the hot/cold split (the only
  Pallas route that beats XLA at realistic duplication on real hardware);
  off TPU, pure XLA.
* ``"xla"`` — pure XLA everywhere (debugging / bit-exact baseline).
* ``"pallas"`` — force the Pallas kernels (one-hot gather/scatter under
  :data:`SCATTER_FLOP_BUDGET`, plus the hot/cold split); off TPU they run
  in interpreter mode so the CPU-mesh test suite exercises them.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

Array = jax.Array

_BACKEND = os.environ.get("FPS_TPU_OPS", "auto").lower()

# One-hot scatter cost ceiling (MXU flops per call) for the FORCED pallas
# backend's full-table kernels. ~2e10 fl32 flops is ~0.2 ms on a v5e chip —
# beyond that the serialization cost XLA's scatter pays is cheaper than the
# dense indicator matmul.
SCATTER_FLOP_BUDGET = 2e10


def packed_crossover_rows(dim: int) -> int:
    """Measured single-chip crossover: the lane-packed MXU scatter beats
    XLA's scatter when the per-shard row count is at or below this (below
    it the whole-shard one-hot contraction is cheaper than the per-row
    -transaction scatter). From ``tools/bench_scatter.py sweep`` on a
    v5 lite chip, B=32768, Zipf(0.8) ids:

    ==== ======================= =======================
    dim  packed wins through R=  packed loses from R=
    ==== ======================= =======================
    10   2048 (667 vs 702 us)    16384 (1222 vs 1092)
    32   4096 (577 vs 663 us)     8192 ( 911 vs  684)
    100  2048 (828 vs 829 us)     4096 (1394 vs 1091)
    ==== ======================= =======================

    Returned thresholds sit at the conservative (clear-win) edge. This is
    the ``TableSpec.hot_ids="auto"`` policy: a large shard axis leaves
    each shard a thin row slice, which is exactly the packed kernel's
    regime — on one shard the shipped tables (26k-1M rows) stay on XLA.
    """
    return 4096 if 17 <= dim <= 48 else 2048


def set_backend(name: str) -> None:
    """Select the hot-path backend for subsequently *traced* programs.

    The choice is read at trace time: programs already compiled (e.g. a
    ``Trainer`` that has run a chunk) keep the backend they were traced
    with. ``Trainer`` keys its compile cache on this setting, so new
    trainers — or the same trainer's next fresh trace — pick up the change.
    """
    global _BACKEND
    if name not in ("xla", "pallas", "auto"):
        raise ValueError(f"unknown ops backend {name!r}")
    _BACKEND = name


def get_backend() -> str:
    return _BACKEND


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def _use_pallas() -> tuple[bool, bool]:
    """(use_pallas, interpret) for the current backend setting."""
    if _BACKEND == "xla":
        return False, False
    if _on_tpu():
        return True, False
    # Off-TPU: only the explicit "pallas" setting runs (interpreted);
    # "auto" falls back to XLA for speed.
    return _BACKEND == "pallas", True


# Scalar-table (D == 1) lane-packed routing. XLA's TPU gather AND scatter
# are per-row-transaction bound (~8 ns/row at B = 2^20, dedup-safe T=256
# measurement), so a dim-1 table pays ~8 ns per SCALAR moved. The dim-1
# kernels pack 128 rows per lane row and build the one-hot + lane
# placement in-kernel (v2: transpose-free, see the kernel docstrings):
# at the PA workload shape (47k rows, 2^20 ids, 95% duplication)
# measured 1.5 (scatter) / 1.6 (gather) ms vs XLA's 7.6 / 8.1 ms per
# call. Kernel cost scales with ceil(R/128) once MAC-bound, so the win
# inverts above the cap below — MEASURED with the v2 kernels at the
# logreg stream shape (B = 426k Zipf(0.9) ids, round 5,
# tools/bench_logreg_routes.py stage b on a v5 lite chip):
#
#   R        dim1 scatter/gather   XLA scatter/gather
#   131k     1.78 / 1.78 ms        3.46 / 3.77 ms   (dim1 ~2x win)
#   262k     2.93 / 3.11 ms        3.67 / 3.89 ms   (dim1 still wins)
#   524k     5.53 / 5.78 ms        3.89 / 4.35 ms   (XLA wins)
#   1M      12.09 / 11.44 ms       6.15 / 5.29 ms   (XLA wins 2x+)
#
# The cap sits at the last measured win (262144) — a THIN (~20%) margin
# verified only at the single-chip logreg stream shape above (B = 426k
# Zipf(0.9) ids, one v5 lite chip); the (131k, 262k] band is unmeasured
# at gathered multi-worker batch sizes, where per-shard R and the W*B
# batch both shift with the mesh. Treat the 131k row band as the
# robust-win region and re-run tools/bench_logreg_routes.py stage b
# before leaning on the upper band at a new shape. The shipped 1M-row
# logreg table stays correctly excluded — its full-table contraction is
# MAC-bound at ~2x XLA's transaction cost. Reads
# and duplicate sums carry the hi+lo bf16 contract (~16 mantissa bits) —
# see scatter_add_packed_pallas — hence bit-exactness is not promised for
# routed shapes, neither across backends (CPU "auto" stays on XLA) nor
# across SHARD COUNTS on TPU: the route predicate sees per-shard R and
# the gathered W*B batch, both of which change with the mesh, so the
# same scalar table can route at one shard count and not another.
# Scope note: the framework's TESTED bit-identity invariants are table
# init across shard counts, checkpoint save/restore across shard and
# worker counts, and same-mesh runs across OS-process layouts — all
# unaffected by this route on CPU and preserved on TPU within a fixed
# mesh + backend. TRAINING bits across different mesh shapes were never
# invariant on any route (fold order follows the gathered batch layout;
# the dense-collective route reassociates differently again). What this
# route adds is same-shape backend sensitivity on TPU, in exchange for
# a ~5x measured win on both sides of every scalar-table transaction;
# force ``set_backend("xla")`` / FPS_TPU_OPS=xla for bit-exact audits
# within one mesh shape.
DIM1_MAX_ROWS = 262_144
DIM1_MIN_BATCH = 8_192

# Small-table threshold for the store's DENSE collective route (replicate
# on read, dense-reduce on write — fps_tpu.core.store.pull/push). The
# gathered route's per-shard work grows with the number of workers (every
# shard processes every worker's ids: O(W * B_local) row transactions per
# step per shard), while the dense route pays O(B_local) transactions plus
# table-sized collectives (all_gather on pull; all_to_all + fixed-order
# sums on push — order-deterministic by design) that ride ICI at line
# rate. At 8 ns/row, a worker pushing 2^20 ids on an
# 8-way mesh saves ~7 * 8.4 ms of serialized scatter per step; a 4 MB
# table costs ~tens of us per collective hop — the trade is lopsided for
# every shipped small table (PA 190 KB, MF items 1.2 MB, logreg 4 MB) and
# wrong for embedding-scale ones (w2v 20 MB+), hence the cap.
DENSE_TABLE_BYTES = 4 << 20


def _route_dim1(R: int, D: int, B: int, dtype=jnp.float32) -> bool:
    if D != 1 or _BACKEND == "xla":
        return False
    # The kernels carry values as bf16 hi+lo: f64 would silently lose 8
    # mantissa bits and integer tables their exact-add semantics.
    dt = jnp.dtype(dtype)
    if dt.itemsize > 4 or not jnp.issubdtype(dt, jnp.floating):
        return False
    if not (_on_tpu() or _BACKEND == "pallas"):
        return False
    return R <= DIM1_MAX_ROWS and B >= DIM1_MIN_BATCH


def _route_head_prefix(R: int, D: int, head_prefix: int, hot_rows: int,
                       dtype) -> bool:
    """Route the guaranteed-head prefix through a head-only dim-1 kernel?

    The dim-1 kernels are STREAM-bound at small row counts (cost ~
    ``rp x B`` — measured round 5, tools/bench_logreg_routes.py), so the
    head-only form saves the row-tile factor on the prefix slice: at the
    PA shape the composite is worth ~15% of the END-TO-END headline
    (measured with the machinery off: 4.53M vs 5.36M examples/s). The
    caller guarantees ``ids[:head_prefix]`` are in ``[0, hot_rows) ∪
    {-1}`` (ingest-side frequency sort — see
    ``fps_tpu.utils.datasets.head_sort_slots``)."""
    if head_prefix < 2048 or hot_rows <= 0 or D != 1:
        return False
    if _BACKEND == "xla" or not (_on_tpu() or _BACKEND == "pallas"):
        return False
    dt = jnp.dtype(dtype)
    if dt.itemsize > 4 or not jnp.issubdtype(dt, jnp.floating):
        return False
    # Head kernel must be meaningfully cheaper than running the prefix
    # through the full-table route it would otherwise take.
    return hot_rows * 4 <= R


def gather_rows(table: Array, ids: Array, *, hot_rows: int = 0,
                head_prefix: int = 0, exact: bool = False) -> Array:
    """``table[ids]``; ids outside ``[0, rows)`` yield **zero rows** on every
    backend (the pull path's ``-1`` padding slots read as zeros; real pulls
    are always in range).

    ``exact=True`` forces the bit-exact XLA gather regardless of backend
    and shape: the dim-1 route reads scalar tables through a hi+lo bf16
    pair (~16 mantissa bits) whenever ``B >= DIM1_MIN_BATCH``, which is a
    deliberate TRAINING concession. This is the per-call escape hatch for
    read-only consumers (an eval pass or audit pulling through the device
    path); the store's :func:`pull` forwards it. The shipped host-side
    read paths (``lookup_host``/``dump_model``) read the table arrays
    directly and are always exact.

    ``head_prefix > 0`` (with ``hot_rows = H``) asserts the STATIC
    guarantee that ``ids[:head_prefix]`` lie in ``[0, H) ∪ {-1}`` — the
    frequency-ranked head a sorted-slot batch layout puts first. The
    prefix then reads through a head-only kernel whose cost scales with
    ``ceil(H/128)`` row tiles instead of ``ceil(R/128)``. Violating the
    guarantee silently reads zeros for the out-of-head ids (the drop
    contract), so callers must only pass prefixes the ingest layer
    actually certified.
    """
    R, D = table.shape
    if not exact and _route_head_prefix(R, D, head_prefix, hot_rows,
                                        table.dtype):
        from fps_tpu.ops.pallas_kernels import gather_rows_dim1_pallas

        head = gather_rows_dim1_pallas(
            table[:hot_rows], ids[:head_prefix], interpret=not _on_tpu()
        )
        tail = gather_rows(table, ids[head_prefix:])
        return jnp.concatenate([head, tail], axis=0)
    if not exact and _route_dim1(R, D, ids.shape[0], table.dtype):
        from fps_tpu.ops.pallas_kernels import gather_rows_dim1_pallas

        return gather_rows_dim1_pallas(table, ids, interpret=not _on_tpu())
    # Forced-pallas only: XLA's gather is not collision-serialized, and
    # dedup-safe on-chip measurement shows it matching or beating the
    # one-hot kernel at the shipped workloads' shapes, so "auto" never
    # routes WIDE gathers to Pallas (the dim-1 route above is measured).
    if not exact and _BACKEND == "pallas" and D >= 64 and (
        R * ids.shape[0] * D <= SCATTER_FLOP_BUDGET
    ):
        from fps_tpu.ops.pallas_kernels import gather_rows_pallas

        return gather_rows_pallas(table, ids, interpret=not _on_tpu())
    in_range = (ids >= 0) & (ids < R)
    vals = jnp.take(table, jnp.where(in_range, ids, 0), axis=0)
    return jnp.where(in_range[:, None], vals, jnp.zeros_like(vals))


def _xla_scatter_add(table: Array, ids: Array, deltas: Array) -> Array:
    """``table.at[ids].add(deltas)`` with drop semantics for ids ∉ [0, R)."""
    R = table.shape[0]
    keep = (ids >= 0) & (ids < R)
    safe = jnp.where(keep, ids, R)
    masked = jnp.where(keep[:, None], deltas, 0)
    return table.at[safe].add(masked.astype(table.dtype), mode="drop")


def scatter_add(
    table: Array, ids: Array, deltas: Array, *, hot_rows: int = 0,
    head_prefix: int = 0
) -> Array:
    """``table.at[ids].add(deltas)``; ids outside ``[0, rows)`` are dropped,
    duplicate ids accumulate (the server's additive ``paramUpdate`` fold).

    ``hot_rows > 0`` marks rows ``[0, hot_rows)`` as write-hot (tables laid
    out with frequency-ranked ids put the Zipfian head there): pushes to
    them are accumulated by a dense lane-packed MXU contraction with zero
    update serialization, and only the (low-duplication) tail pays the XLA
    scatter. The split preserves drop/duplicate semantics for any id
    distribution, but the head contraction carries f32 deltas as a hi+lo
    bf16 pair (~16 of 24 mantissa bits — see
    :func:`fps_tpu.ops.pallas_kernels.scatter_add_packed_pallas`), so
    head-row sums can differ from the XLA scatter in the low mantissa
    bits; SGD-style updates are insensitive to this, bit-exact
    reproducibility across ``hot_rows`` settings is not promised. The
    head contraction is cost-capped by :data:`SCATTER_FLOP_BUDGET`: an
    oversized ``hot_rows`` silently falls back to the plain XLA scatter
    instead of burning unbounded MXU time per push.
    """
    use, interpret = _use_pallas()
    R, D = table.shape

    # Every Pallas scatter variant accumulates in f32 (the packed head path
    # in bf16 hi+lo); a table wider than f32 (f64) must take the XLA
    # scatter, which adds in the table's native dtype.
    if jnp.dtype(table.dtype).itemsize > 4:
        return _xla_scatter_add(table, ids, deltas)

    if _route_head_prefix(R, D, head_prefix, hot_rows, table.dtype):
        # Guaranteed-head prefix (see gather_rows): accumulate the prefix
        # into the head slice via the head-only kernel, then run the tail
        # through the normal routing (WITHOUT the legacy hot_rows masked
        # split — the prefix split supersedes it for this call).
        from fps_tpu.ops.pallas_kernels import scatter_add_dim1_pallas

        head_new = scatter_add_dim1_pallas(
            table[:hot_rows], ids[:head_prefix], deltas[:head_prefix],
            interpret=not _on_tpu(),
        )
        table = jax.lax.dynamic_update_slice_in_dim(table, head_new, 0,
                                                    axis=0)
        return scatter_add(table, ids[head_prefix:], deltas[head_prefix:])

    if _route_dim1(R, D, ids.shape[0], table.dtype):
        from fps_tpu.ops.pallas_kernels import scatter_add_dim1_pallas

        return scatter_add_dim1_pallas(table, ids, deltas,
                                       row_tile=512, batch_tile=8192,
                                       interpret=not _on_tpu())

    if use and hot_rows >= R > 0:
        # Whole-shard packed routing (hot_ids="auto" below the measured
        # crossover): every row is "hot", so there is no tail scatter at
        # all — out-of-range/-1 ids match no one-hot row and drop.
        pack = max(1, 128 // D)
        head_flops = -(-R // pack) * (2 * ids.shape[0]) * 128
        if head_flops > SCATTER_FLOP_BUDGET:
            return _xla_scatter_add(table, ids, deltas)
        from fps_tpu.ops.pallas_kernels import scatter_add_packed_pallas

        return scatter_add_packed_pallas(table, ids, deltas,
                                         interpret=interpret)

    if use and 0 < hot_rows < R:
        pack = max(1, 128 // D)
        head_flops = -(-hot_rows // pack) * (2 * ids.shape[0]) * 128
        if head_flops > SCATTER_FLOP_BUDGET:
            return _xla_scatter_add(table, ids, deltas)
        from fps_tpu.ops.pallas_kernels import scatter_add_packed_pallas

        in_head = (ids >= 0) & (ids < hot_rows)
        head_ids = jnp.where(in_head, ids, -1)
        tail_ids = jnp.where(in_head, R, ids)
        head_upd = scatter_add_packed_pallas(
            jnp.zeros((hot_rows, D), table.dtype),
            head_ids,
            deltas,
            interpret=interpret,
        )
        table = _xla_scatter_add(table, tail_ids, deltas)
        return table.at[:hot_rows].add(head_upd)

    if _BACKEND == "pallas" and use and (
        R * ids.shape[0] * max(D, 1) <= SCATTER_FLOP_BUDGET
    ):
        from fps_tpu.ops.pallas_kernels import scatter_add_pallas

        return scatter_add_pallas(table, ids, deltas, interpret=interpret)
    return _xla_scatter_add(table, ids, deltas)
