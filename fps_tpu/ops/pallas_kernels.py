"""Pallas TPU kernels for the sparse parameter-server hot paths.

SURVEY.md §7 flags the sparse gather / scatter-add paths as the rebuild's
throughput hard part (the reference's per-message ``onPullRecv`` /
``onPushRecv`` handling, expected upstream
``src/main/scala/hu/sztaki/ilab/ps/server/SimplePSLogic.scala``, becomes a
bulk row gather + duplicate-combining scatter-add here). Both kernels use
the same TPU-first idea — turn data-dependent indexing into dense
**indicator (one-hot) matmuls on the MXU**, the systolic array's native
operation, instead of the serialized dynamic-memory ops XLA's gather/scatter
lower to:

* :func:`scatter_add_pallas` — for each (row-tile, batch-tile) grid cell,
  build the ``(row_tile, batch_tile)`` indicator ``ids == row`` and contract
  with the delta block: duplicates accumulate exactly (the reference's
  additive ``paramUpdate`` fold per message), drop sentinels (ids outside
  ``[0, R)``) never match a row and vanish, and there is zero update
  serialization.
* :func:`gather_rows_pallas` — the transpose: ``(batch_tile, row_tile)``
  indicator contracted with the table block accumulates each requested row
  into the output (pull = one-hot matmul route, SURVEY.md §7 step 1).

The FLOP cost of either is ``rows × batch × dim`` (with ``dim`` padded to
the 128-lane width); the dispatcher (``fps_tpu.ops``) only routes here when
that is small enough for the MXU to beat the memory-op path. Contractions
run at ``Precision.HIGHEST`` — the default MXU path rounds operands to bf16,
which visibly loses update mass on heavily-duplicated (Zipfian-hot) rows.

Measured on the attached TPU chip (min over 5×100 calls, f32):

=====================================  ============  =============
shapes (R rows × B ids × D dim)        XLA scatter   Pallas scatter
=====================================  ============  =============
MF      26744 × 16384 × 10             23.8 µs       22.2 µs
word2vec 6272 ×  8192 × 100            12.6 µs       12.4 µs
logreg  32768 ×  8192 × 1              12.2 µs       10.2 µs
=====================================  ============  =============

Gather: Pallas 9.9 µs vs XLA 12.7 µs at D=100; XLA slightly ahead at D=10
(10.4 vs 12.2 µs) where lane padding wastes 92% of the MXU work.

Both kernels run in interpreter mode off-TPU so the CPU-mesh test suite
exercises them bit-for-bit. Tile sizes respect Mosaic's block constraints:
the id row is laid out ``(1, batch_tile)`` with ``batch_tile`` a multiple of
128 (lane dim), and row/batch tiles are multiples of 8 (sublane dim).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _tiles(R: int, B: int, row_tile: int, batch_tile: int) -> tuple[int, int]:
    """Clamp requested tiles to the (padded) problem and Mosaic constraints:
    row tiles are multiples of 8, batch tiles multiples of 128."""
    row_tile = max(8, min(_round_up(row_tile, 8), _round_up(R, 8)))
    batch_tile = max(128, min(_round_up(batch_tile, 128), _round_up(B, 128)))
    return row_tile, batch_tile


# ---------------------------------------------------------------------------
# Scatter-add: table[ids] += deltas (duplicates combine, out-of-range drop)
# ---------------------------------------------------------------------------

def _scatter_kernel(ids_ref, table_ref, deltas_ref, out_ref, *, row_tile):
    i = pl.program_id(0)  # row-tile index (slow)
    j = pl.program_id(1)  # batch-tile index (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = table_ref[:]

    bt = ids_ref.shape[1]
    rows = i * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, bt), dimension=0
    )
    onehot = (ids_ref[:] == rows).astype(jnp.float32)  # (row_tile, bt)
    acc = jnp.dot(
        onehot,
        deltas_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    out_ref[:] += acc.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def scatter_add_pallas(
    table: Array,
    ids: Array,
    deltas: Array,
    *,
    row_tile: int = 256,
    batch_tile: int = 2048,
    interpret: bool = False,
):
    """``table.at[ids].add(deltas)`` with drop semantics for ids ∉ [0, R).

    ``ids (B,)`` int32, ``deltas (B, D)``. Returns the updated ``(R, D)``
    table. Duplicate ids within the batch accumulate additively.
    """
    R, D = table.shape
    B = ids.shape[0]
    row_tile, batch_tile = _tiles(R, B, row_tile, batch_tile)

    pad_b = _round_up(B, batch_tile) - B
    ids2 = jnp.pad(ids.astype(jnp.int32), (0, pad_b), constant_values=-1)
    deltas2 = jnp.pad(deltas, ((0, pad_b), (0, 0)))
    ids2 = ids2.reshape(1, -1)  # 2-D for TPU layout

    grid = (pl.cdiv(R, row_tile), ids2.shape[1] // batch_tile)
    return pl.pallas_call(
        functools.partial(_scatter_kernel, row_tile=row_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, j)),
            pl.BlockSpec((row_tile, D), lambda i, j: (i, 0)),
            pl.BlockSpec((batch_tile, D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, D), table.dtype),
        interpret=interpret,
    )(ids2, table, deltas2)


# ---------------------------------------------------------------------------
# Gather: rows = table[ids] (one-hot matmul route)
# ---------------------------------------------------------------------------

def _gather_kernel(ids_ref, table_ref, out_ref, *, row_tile, num_rows):
    i = pl.program_id(0)  # batch-tile index (slow)
    j = pl.program_id(1)  # row-tile index (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bt = ids_ref.shape[1]
    rows = j * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (bt, row_tile), dimension=1
    )
    ids_col = ids_ref[:].reshape(bt, 1)
    onehot = (ids_col == rows).astype(jnp.float32)  # (bt, row_tile)
    # Boundary row tiles read past the table; those rows carry garbage
    # (NaN in interpret mode) and 0 x NaN would poison the contraction,
    # so zero them explicitly.
    row_ids = j * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, 1), dimension=0
    )
    tb = jnp.where(row_ids < num_rows, table_ref[:].astype(jnp.float32), 0.0)
    acc = jnp.dot(
        onehot,
        tb,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    out_ref[:] += acc.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def gather_rows_pallas(
    table: Array,
    ids: Array,
    *,
    row_tile: int = 512,
    batch_tile: int = 1024,
    interpret: bool = False,
):
    """``table[ids]`` — ``(B,)`` int32 ids into a ``(R, D)`` table.

    Ids outside ``[0, R)`` produce zero rows (the pull path only sends
    in-range ids; padding uses ``-1``).
    """
    R, D = table.shape
    B = ids.shape[0]
    row_tile, batch_tile = _tiles(R, B, row_tile, batch_tile)

    pad_b = _round_up(B, batch_tile) - B
    ids2 = jnp.pad(ids.astype(jnp.int32), (0, pad_b), constant_values=-1)
    ids2 = ids2.reshape(1, -1)

    grid = (ids2.shape[1] // batch_tile, pl.cdiv(R, row_tile))
    out = pl.pallas_call(
        functools.partial(_gather_kernel, row_tile=row_tile, num_rows=R),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, i)),
            pl.BlockSpec((row_tile, D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((batch_tile, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((ids2.shape[1], D), table.dtype),
        interpret=interpret,
    )(ids2, table)
    return out[:B]
