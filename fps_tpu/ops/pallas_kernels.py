"""Pallas TPU kernels for the sparse parameter-server hot paths.

SURVEY.md §7 flags the sparse gather / scatter-add paths as the rebuild's
throughput hard part (the reference's per-message ``onPullRecv`` /
``onPushRecv`` handling, expected upstream
``src/main/scala/hu/sztaki/ilab/ps/server/SimplePSLogic.scala``, becomes a
bulk row gather + duplicate-combining scatter-add here). Both kernels use
the same TPU-first idea — turn data-dependent indexing into dense
**indicator (one-hot) matmuls on the MXU**, the systolic array's native
operation, instead of the serialized dynamic-memory ops XLA's gather/scatter
lower to:

* :func:`scatter_add_pallas` — for each (row-tile, batch-tile) grid cell,
  build the ``(row_tile, batch_tile)`` indicator ``ids == row`` and contract
  with the delta block: duplicates accumulate exactly (the reference's
  additive ``paramUpdate`` fold per message), drop sentinels (ids outside
  ``[0, R)``) never match a row and vanish, and there is zero update
  serialization.
* :func:`gather_rows_pallas` — the transpose: ``(batch_tile, row_tile)``
  indicator contracted with the table block accumulates each requested row
  into the output (pull = one-hot matmul route, SURVEY.md §7 step 1).

A third kernel, :func:`scatter_add_packed_pallas`, packs ``128 // D``
logical small-rank rows per physical lane row so the MXU pass is not mostly
padding, and splits f32 deltas into hi+lo bf16 halves (exact f32
accumulation) instead of paying ``Precision.HIGHEST``.

Measured on the attached TPU chip — **dedup-safe**: each sample is a
256-step scan with a chained table carry, fenced by a host read. (The
tunneled runtime dedupes repeated identical dispatches and
``block_until_ready`` can return early; the round-1 numbers previously in
this table were that artifact — tens-of-us figures that timed dispatch
overhead, not the op — and are superseded.) Per-scatter times at B=32768
ids with realistic popularity skew (p ~ 1/rank^0.8, 62% duplication),
~370us/step dispatch floor subtracted:

==================================  ===========  =================
shape (R rows × D dim)              XLA scatter  packed one-hot
==================================  ===========  =================
MF item   26744 × 11                ~460 µs      ~470 µs
MF user  138496 × 10                ~420 µs      worse (R large)
==================================  ===========  =================

Two further on-chip findings: XLA's scatter cost is ~flat in duplication
(all-unique ids measured *slower*: 517 vs 365 µs at the item shape), and
rows masked to the drop sentinel still pay full cost — so neither
dedup-before-scatter nor hot/cold splitting wins on a single chip, where
XLA's scatter is simply a good primitive at ~12-15 ns/row. The packed
kernel's MXU cost is ``(R/pack) × 2B × 128`` MACs: it wins only when the
per-shard row slice is small — the many-shard regime — hence the
``hot_rows`` routing in :func:`fps_tpu.ops.scatter_add` defaults off and is
worth enabling on large shard axes.

All kernels run in interpreter mode off-TPU so the CPU-mesh test suite
exercises them bit-for-bit. Tile sizes respect Mosaic's block constraints:
the id row is laid out ``(1, batch_tile)`` with ``batch_tile`` a multiple of
128 (lane dim), and row/batch tiles are multiples of 8 (sublane dim).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _tiles(R: int, B: int, row_tile: int, batch_tile: int) -> tuple[int, int]:
    """Clamp requested tiles to the (padded) problem and Mosaic constraints:
    row tiles are multiples of 8, batch tiles multiples of 128."""
    row_tile = max(8, min(_round_up(row_tile, 8), _round_up(R, 8)))
    batch_tile = max(128, min(_round_up(batch_tile, 128), _round_up(B, 128)))
    return row_tile, batch_tile


# ---------------------------------------------------------------------------
# Scatter-add: table[ids] += deltas (duplicates combine, out-of-range drop)
# ---------------------------------------------------------------------------

def _scatter_kernel(ids_ref, table_ref, deltas_ref, out_ref, *, row_tile):
    i = pl.program_id(0)  # row-tile index (slow)
    j = pl.program_id(1)  # batch-tile index (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = table_ref[:]

    bt = ids_ref.shape[1]
    rows = i * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, bt), dimension=0
    )
    onehot = (ids_ref[:] == rows).astype(jnp.float32)  # (row_tile, bt)
    acc = jnp.dot(
        onehot,
        deltas_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    out_ref[:] += acc.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def scatter_add_pallas(
    table: Array,
    ids: Array,
    deltas: Array,
    *,
    row_tile: int = 256,
    batch_tile: int = 2048,
    interpret: bool = False,
):
    """``table.at[ids].add(deltas)`` with drop semantics for ids ∉ [0, R).

    ``ids (B,)`` int32, ``deltas (B, D)``. Returns the updated ``(R, D)``
    table. Duplicate ids within the batch accumulate additively.
    """
    R, D = table.shape
    B = ids.shape[0]
    row_tile, batch_tile = _tiles(R, B, row_tile, batch_tile)

    pad_b = _round_up(B, batch_tile) - B
    ids2 = jnp.pad(ids.astype(jnp.int32), (0, pad_b), constant_values=-1)
    deltas2 = jnp.pad(deltas, ((0, pad_b), (0, 0)))
    ids2 = ids2.reshape(1, -1)  # 2-D for TPU layout

    grid = (pl.cdiv(R, row_tile), ids2.shape[1] // batch_tile)
    return pl.pallas_call(
        functools.partial(_scatter_kernel, row_tile=row_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, j)),
            pl.BlockSpec((row_tile, D), lambda i, j: (i, 0)),
            pl.BlockSpec((batch_tile, D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, D), table.dtype),
        interpret=interpret,
    )(ids2, table, deltas2)


# ---------------------------------------------------------------------------
# Lane-packed scatter-add: the small-rank fast path.
# ---------------------------------------------------------------------------

def _onehot_accum_kernel(ids_ref, deltas_ref, out_ref, *, row_tile):
    """out[r, :] += sum_b [ids[b] == r] * deltas[b, :] — bf16 MXU contract,
    f32 accumulate. The caller is responsible for any lane packing and for
    precision splitting (deltas arrive bf16)."""
    i = pl.program_id(0)  # row tile (slow)
    j = pl.program_id(1)  # batch tile (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bt = ids_ref.shape[1]
    rows = i * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, bt), dimension=0
    )
    onehot = (ids_ref[:] == rows).astype(jnp.bfloat16)  # exact 0/1 in bf16
    out_ref[:] += jnp.dot(
        onehot, deltas_ref[:], preferred_element_type=jnp.float32
    )


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def scatter_add_packed_pallas(
    table: Array,
    ids: Array,
    deltas: Array,
    *,
    row_tile: int = 256,
    batch_tile: int = 4096,
    interpret: bool = False,
):
    """``table.at[ids].add(deltas)`` via a LANE-PACKED one-hot contraction.

    XLA's scatter-add serializes colliding updates — per-row-transaction
    cost that explodes on Zipfian-hot batches. This path instead pays dense
    MXU work with zero serialization:

    * **lane packing** — a plain one-hot scatter wastes the 128-wide lane
      dim on small-rank rows (D=10 uses 8% of every MXU pass). Here
      ``pack = 128 // D`` logical rows share one physical lane row: the
      accumulator is ``(ceil(R/pack), pack*D)``, the one-hot indexes
      ``id // pack``, and each delta is pre-placed (by XLA, outside the
      kernel — cheap vectorized VPU work) into lane block ``id % pack``.
      MXU work drops by the pack factor to ``(R/pack) x B x 128`` MACs.
    * **split-precision deltas** — f32 deltas ride as hi+lo bf16 halves
      (concatenated along the contraction dim with duplicated ids), giving
      ~16 mantissa bits per element with exact f32 MXU accumulation:
      ~8x cheaper than a ``Precision.HIGHEST`` f32 contraction and far
      more update-mass accuracy than single-pass bf16 on hot rows.

    Duplicates accumulate in the f32 accumulator; ids outside ``[0, R)``
    are dropped (negative packed rows never match a tile; overflow rows
    land in padding the final slice discards).
    """
    R, D = table.shape
    B = ids.shape[0]
    pack = max(1, 128 // D)
    rp = -(-R // pack)  # packed rows

    ids = ids.astype(jnp.int32)
    prow = ids // pack  # negative ids floor to -1: never matches
    lane = jnp.where(ids >= 0, ids % pack, 0)
    # Place each delta into its lane block: (B, pack*D).
    if pack > 1:
        onehot_lane = (
            lane[:, None] == jnp.arange(pack, dtype=jnp.int32)[None, :]
        )
        dt = (
            deltas.astype(jnp.float32)[:, None, :]
            * onehot_lane[:, :, None].astype(jnp.float32)
        ).reshape(B, pack * D)
    else:
        dt = deltas.astype(jnp.float32)
    hi, lo = _split_hi_lo(dt)
    # One kernel pass over 2B rows: [hi; lo] with duplicated ids.
    ids_cat = jnp.concatenate([prow, prow])
    d_cat = jnp.concatenate([hi, lo])

    B2 = 2 * B
    row_tile, batch_tile = _tiles(rp, B2, row_tile, batch_tile)
    pad_b = _round_up(B2, batch_tile) - B2
    ids2 = jnp.pad(ids_cat, (0, pad_b), constant_values=-1).reshape(1, -1)
    d2 = jnp.pad(d_cat, ((0, pad_b), (0, 0)))

    grid = (pl.cdiv(rp, row_tile), ids2.shape[1] // batch_tile)
    acc = pl.pallas_call(
        functools.partial(_onehot_accum_kernel, row_tile=row_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, j)),
            pl.BlockSpec((batch_tile, pack * D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, pack * D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rp, pack * D), jnp.float32),
        interpret=interpret,
    )(ids2, d2)
    upd = acc.reshape(rp * pack, D)[:R]
    return table + upd.astype(table.dtype)


# ---------------------------------------------------------------------------
# Dim-1 lane-packed kernels: scalar tables (PA / logreg weight vectors).
#
# For D == 1 the generic packed path's XLA-side lane placement materializes
# a (B, 128) delta matrix in HBM — at the PA workload shape (B = 2^20 ids
# into a 47k-row scalar table) that is ~0.5 GB per step and measured to
# cost as much as the XLA scatter it replaces (~12 vs ~13.5 ms/step).
# These kernels move BOTH the packed-row one-hot and the lane placement
# inside the kernel: HBM traffic is just ids + deltas (8 MB), and the MXU
# pays (R/128) x B x 128 MACs per precision pass. The round-4 v2
# formulation is TRANSPOSE-FREE (see the kernel docstrings): measured
# on-chip at the PA shape, dedup-safe T=256 scan timing
# (tools/bench_scatter.py dim1): scatter 7.6 -> 1.5 ms, gather
# 8.1 -> 1.6 ms per 2^20-id call (the v1 kernels with in-kernel lane
# placement via minor-dim reshapes measured 2.8 ms each).
#
# Precision contract matches scatter_add_packed_pallas: f32 values ride as
# hi+lo bf16 halves (~16 of 24 mantissa bits) with exact f32 MXU
# accumulation; gathered rows and duplicate sums can differ from XLA in
# the low mantissa bits.
# ---------------------------------------------------------------------------

def _split_hi_lo(x: Array) -> tuple[Array, Array]:
    """f32 -> (hi, lo) bf16 with x == hi + lo to ~2^-16 relative.

    Explicit mantissa-truncation split: hi = x's top 16 bits (exactly a
    bf16 value), lo = the remainder (exact in f32, fits bf16 to ~2^-16
    relative). A plain ``x.astype(bf16)`` round-trip is NOT safe here:
    under ``--xla_allow_excess_precision`` XLA may keep the f32 value
    through the downcast-upcast pair, making lo == 0 and silently
    degrading the contraction to single-pass bf16."""
    hi_f32 = jax.lax.bitcast_convert_type(
        jax.lax.bitcast_convert_type(x, jnp.int32) & jnp.int32(-65536),
        jnp.float32,
    )
    return hi_f32.astype(jnp.bfloat16), (x - hi_f32).astype(jnp.bfloat16)


def _scatter_dim1_kernel(ids_ref, deltas_ref, out_ref, *, row_tile):
    """out[(id // 128), (id % 128)] += delta, packed rows x 128 lanes.

    TRANSPOSE-FREE formulation (round-4 v2): the delta multiplies into
    the packed-row one-hot (a native (1, bt)-over-(row_tile, bt)
    broadcast), and the lane one-hot is built TRANSPOSED (128, bt) and
    contracted via dot_general over the shared bt dim — no (bt, 1)
    minor-dim reshapes anywhere. The v1 kernel's in-kernel lane
    placement paid ~4 us/cell in relayouts plus a per-cell floor;
    measured at the PA shape (tools/bench_scatter.py dim1, Zipf(0.9)
    ids) this form is 2.8 -> 1.5 ms/call — uniform ids measure ~1.9 —
    and 0.83 -> ~0.4 ms at the 2048-row head shape.

    Exactness: deltas arrive as f32 containers of exactly-bf16 values
    (the caller's hi/lo split), and one-hot entries are exactly 0/1, so
    ``A = where(match, d, 0)`` downcasts to bf16 losslessly.
    """
    i = pl.program_id(0)  # packed-row tile (slow)
    j = pl.program_id(1)  # batch tile (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bt = ids_ref.shape[1]
    ids = ids_ref[:]  # (1, bt) int32; negative = drop
    # Arithmetic shift keeps negatives negative (never match a row tile).
    prow = jax.lax.shift_right_arithmetic(ids, 7)
    lane = jnp.bitwise_and(ids, 127)
    rows = i * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, bt), dimension=0
    )
    A = jnp.where(prow == rows, deltas_ref[:], 0.0).astype(jnp.bfloat16)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (128, bt), dimension=0)
    Lt = (lane == lanes).astype(jnp.bfloat16)  # (128, bt)
    out_ref[:] += jax.lax.dot_general(
        A, Lt, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def scatter_add_dim1_pallas(
    table: Array,
    ids: Array,
    deltas: Array,
    *,
    row_tile: int = 256,
    batch_tile: int = 8192,
    interpret: bool = False,
):
    """``table.at[ids].add(deltas)`` for a scalar table ``(R, 1)``.

    ``ids (B,)`` int32 (negative/out-of-range dropped), ``deltas (B, 1)``
    f32. hi+lo bf16 precision contract as in
    :func:`scatter_add_packed_pallas`.
    """
    R, D = table.shape
    assert D == 1, "scatter_add_dim1_pallas requires a (R, 1) table"
    B = ids.shape[0]
    rp = -(-R // 128)  # packed rows

    hi, lo = _split_hi_lo(deltas.astype(jnp.float32).reshape(B))
    # Mask ALL out-of-range ids to the -1 drop sentinel (mirrors the gather
    # kernel): ids in [R, rp*128) would otherwise be dropped only by the
    # [:R] truncation and ids >= rp*128 only by Mosaic discarding
    # out-of-bounds block stores — the drop contract must not depend on
    # OOB-store semantics that interpret mode can't exercise.
    ids = jnp.where((ids >= 0) & (ids < R), ids.astype(jnp.int32), -1)
    ids_cat = jnp.concatenate([ids] * 2)
    d_cat = jnp.concatenate([hi, lo]).astype(jnp.float32)

    B2 = 2 * B
    row_tile, batch_tile = _tiles(rp, B2, row_tile, batch_tile)
    pad_b = _round_up(B2, batch_tile) - B2
    ids2 = jnp.pad(ids_cat, (0, pad_b), constant_values=-1).reshape(1, -1)
    d2 = jnp.pad(d_cat, ((0, pad_b),)).reshape(1, -1)

    grid = (pl.cdiv(rp, row_tile), ids2.shape[1] // batch_tile)
    acc = pl.pallas_call(
        functools.partial(_scatter_dim1_kernel, row_tile=row_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, j)),
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((row_tile, 128), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rp, 128), jnp.float32),
        interpret=interpret,
    )(ids2, d2)
    upd = acc.reshape(rp * 128, 1)[:R]
    return table + upd.astype(table.dtype)


def _gather_dim1_kernel(ids_ref, hi_ref, lo_ref, out_ref, *, row_tile):
    """out[b] = table[(id // 128), (id % 128)]; accumulate over row tiles
    (each id matches exactly one packed row).

    TRANSPOSE-FREE formulation (round-4 v2, cf. _scatter_dim1_kernel):
    ``P = W_tile @ laneOneHot^T`` gives ``P[p, b] = W[p, lane_b]``; the
    packed-row match then selects and a column-sum lands the values in
    the native ``(1, bt)`` output layout — no minor-dim reshapes.
    Measured 2.8 -> 1.6 ms per 2^20-id call at the PA shape
    (tools/bench_scatter.py dim1). Garbage in
    the final row tile's block padding stays in its own P rows (the dot
    never mixes rows) and the row mask drops it, so no explicit
    padding-zeroing is needed.
    """
    i = pl.program_id(0)  # batch tile (slow)
    j = pl.program_id(1)  # packed-row tile (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bt = ids_ref.shape[1]
    ids = ids_ref[:]
    prow = jax.lax.shift_right_arithmetic(ids, 7)
    lane = jnp.bitwise_and(ids, 127)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (128, bt), dimension=0)
    Lt = (lane == lanes).astype(jnp.bfloat16)  # (128, bt)
    P = jnp.dot(hi_ref[:], Lt, preferred_element_type=jnp.float32)
    P += jnp.dot(lo_ref[:], Lt, preferred_element_type=jnp.float32)
    rows = j * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, bt), dimension=0
    )
    sel = jnp.where(prow == rows, P, 0.0)  # (row_tile, bt)
    out_ref[:] += jnp.sum(sel, axis=0, keepdims=True)


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def gather_rows_dim1_pallas(
    table: Array,
    ids: Array,
    *,
    row_tile: int = 128,
    batch_tile: int = 8192,
    interpret: bool = False,
):
    """``table[ids]`` for a scalar table ``(R, 1)``; ids outside ``[0, R)``
    produce zero rows. Values carry the hi+lo bf16 precision contract
    (~16 mantissa bits) — callers needing bit-exact reads use the XLA
    gather."""
    R, D = table.shape
    assert D == 1, "gather_rows_dim1_pallas requires a (R, 1) table"
    B = ids.shape[0]
    rp = -(-R // 128)

    packed = jnp.pad(
        table.astype(jnp.float32).reshape(-1), (0, rp * 128 - R)
    ).reshape(rp, 128)
    hi, lo = _split_hi_lo(packed)

    # Mask ALL out-of-range ids to the -1 drop sentinel: ids in [R, rp*128)
    # would lane-select table padding, and larger ids can land a packed row
    # inside the final row tile's BLOCK padding, whose contents are
    # undefined — the zero-row contract must not depend on either.
    ids = jnp.where((ids >= 0) & (ids < R), ids.astype(jnp.int32), -1)
    row_tile, batch_tile = _tiles(rp, B, row_tile, batch_tile)
    pad_b = _round_up(B, batch_tile) - B
    ids2 = jnp.pad(ids, (0, pad_b), constant_values=-1).reshape(1, -1)

    grid = (ids2.shape[1] // batch_tile, pl.cdiv(rp, row_tile))
    out = pl.pallas_call(
        functools.partial(_gather_dim1_kernel, row_tile=row_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, i)),
            pl.BlockSpec((row_tile, 128), lambda i, j: (j, 0)),
            pl.BlockSpec((row_tile, 128), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, batch_tile), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, ids2.shape[1]), jnp.float32),
        interpret=interpret,
    )(ids2, hi, lo)
    return out.reshape(-1)[:B, None].astype(table.dtype)


# ---------------------------------------------------------------------------
# Gather: rows = table[ids] (one-hot matmul route)
# ---------------------------------------------------------------------------

def _gather_kernel(ids_ref, table_ref, out_ref, *, row_tile, num_rows):
    i = pl.program_id(0)  # batch-tile index (slow)
    j = pl.program_id(1)  # row-tile index (fast: out block stays resident)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bt = ids_ref.shape[1]
    rows = j * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (bt, row_tile), dimension=1
    )
    ids_col = ids_ref[:].reshape(bt, 1)
    onehot = (ids_col == rows).astype(jnp.float32)  # (bt, row_tile)
    # Boundary row tiles read past the table; those rows carry garbage
    # (NaN in interpret mode) and 0 x NaN would poison the contraction,
    # so zero them explicitly.
    row_ids = j * row_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, 1), dimension=0
    )
    tb = jnp.where(row_ids < num_rows, table_ref[:].astype(jnp.float32), 0.0)
    acc = jnp.dot(
        onehot,
        tb,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    out_ref[:] += acc.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("row_tile", "batch_tile", "interpret")
)
def gather_rows_pallas(
    table: Array,
    ids: Array,
    *,
    row_tile: int = 512,
    batch_tile: int = 1024,
    interpret: bool = False,
):
    """``table[ids]`` — ``(B,)`` int32 ids into a ``(R, D)`` table.

    Ids outside ``[0, R)`` produce zero rows (the pull path only sends
    in-range ids; padding uses ``-1``).
    """
    R, D = table.shape
    B = ids.shape[0]
    row_tile, batch_tile = _tiles(R, B, row_tile, batch_tile)

    pad_b = _round_up(B, batch_tile) - B
    ids2 = jnp.pad(ids.astype(jnp.int32), (0, pad_b), constant_values=-1)
    ids2 = ids2.reshape(1, -1)

    grid = (ids2.shape[1] // batch_tile, pl.cdiv(R, row_tile))
    out = pl.pallas_call(
        functools.partial(_gather_kernel, row_tile=row_tile, num_rows=R),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, batch_tile), lambda i, j: (0, i)),
            pl.BlockSpec((row_tile, D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((batch_tile, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((ids2.shape[1], D), table.dtype),
        interpret=interpret,
    )(ids2, table)
    return out[:B]
