"""Generic pull/push probe workload: lower a plan's data-plane program
without a real model.

``tools/plan.py`` promises the plan's *measured* collective-byte budget
(via ``fps_tpu.analysis.collective_profile``), which needs an actual
lowered program per planned table set — but arbitrary user tables don't
map onto any shipped model. :class:`ProbeLogic` is the minimal
WorkerLogic whose data plane is exactly the store's: pull ``B`` ids per
table, push same-shaped deltas back (a fixed scale of the pulled rows —
enough to keep the push route live through DCE), emit one scalar. The
lowered per-chunk program therefore carries precisely the collectives
the plan's routing implies (gathered/dense pulls and pushes, the tier's
reconcile psum, the tracker's sketch merge) and nothing else.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from fps_tpu.core.api import StepOutput, WorkerLogic


class ProbeLogic(WorkerLogic):
    """Pull/push probe over every table of a store.

    The batch carries one ``{name}_ids`` column per table; ``step``
    pushes ``-0.001 * pulled`` (a tiny decay — value is irrelevant, it
    only has to depend on the pulled rows so no route folds away).
    """

    def __init__(self, table_names):
        self.table_names = tuple(sorted(table_names))

    def pull_ids(self, batch):
        return {name: batch[f"{name}_ids"] for name in self.table_names}

    def step(self, batch, pulled, local_state, key):
        pushes = {
            name: (batch[f"{name}_ids"], -0.001 * pulled[name])
            for name in self.table_names
        }
        n = jnp.sum(
            (batch[f"{self.table_names[0]}_ids"] >= 0).astype(jnp.float32))
        return StepOutput(pushes=pushes, local_state=local_state,
                          out={"n": n})


def probe_chunk(table_specs, *, num_workers: int, local_batch: int = 32,
                steps_per_chunk: int = 4, seed: int = 0) -> dict:
    """One host chunk of uniform-random ids per table, shaped
    ``(T, B_global)`` for the sync driver."""
    rng = np.random.default_rng(seed)
    B = num_workers * local_batch
    return {
        f"{name}_ids": rng.integers(
            0, spec.num_ids, (steps_per_chunk, B)).astype(np.int32)
        for name, spec in sorted(table_specs.items())
    }


def lowered_plan_text(mesh, specs, plans, *, hot_sync_every: int,
                      retierer=None, local_batch: int = 32,
                      steps_per_chunk: int = 4) -> str:
    """Build a probe trainer with ``plans`` applied over ``specs`` and
    return the StableHLO text of the exact per-chunk program it would
    dispatch — what ``tools/plan.py`` feeds to ``collective_profile``.

    ``retierer``: attach one to lower the ADAPTIVE (mapped + tracked)
    variant of the program instead of the static tier.
    """
    import dataclasses

    from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
    from fps_tpu.core.store import ParamStore

    planned = {}
    for name, spec in sorted(specs.items()):
        plan = plans.get(name)
        if plan is not None:
            spec = dataclasses.replace(
                spec, hot_tier=plan.hot_tier,
                dense_collectives=plan.dense)
        planned[name] = spec
    store = ParamStore(mesh, planned)
    trainer = Trainer(
        mesh, store, ProbeLogic(planned),
        config=TrainerConfig(hot_sync_every=hot_sync_every),
    )
    trainer.retierer = retierer
    chunk = probe_chunk(planned, num_workers=num_workers_of(mesh),
                        local_batch=local_batch,
                        steps_per_chunk=steps_per_chunk)
    return trainer.lowered_chunk_text(chunk, "sync")
