"""In-graph tier ticks: the megastep's boundary contract.

The host :class:`~fps_tpu.tiering.retier.Retierer` runs the adaptive-
tiering boundary between compiled calls — fold the device count-min
windows into a decayed sketch, re-rank the hot head, re-split the
replica. The megastep driver (:mod:`fps_tpu.core.megastep`) has no host
boundary to run it on: K chunks execute inside ONE compiled program, so
the tick itself must trace.

:class:`MegastepTick` is that contract. It subclasses ``Retierer`` so
the trainer's resolution machinery (``manages`` / ``track_specs`` /
``hot_ids_for`` / ``_table_cm`` / sidecar persistence) applies
unchanged — the mapped tier, the device-side sketch updates, and the
compile-cache keys are all the host tier's — but the boundary work runs
in-graph with bit-matching arithmetic:

* the decayed fold is :func:`fps_tpu.sketch.dcm_fold_traced` (exact
  power-of-two halving + IEEE f32 add — identical to the host fold);
* the ranking is :func:`device_top_ids` — the same (count desc, id asc)
  TOTAL order as :func:`fps_tpu.tiering.retier.top_ids`, so both sides
  select the identical head for identical estimates (tested);
* the re-split re-derives the replica from the canonical table via
  :func:`fps_tpu.core.store.replica_from_shard`, valid because every
  segment ends with a flush reconcile.

The decayed state and fold counter round-trip between dispatches as
device arrays (:meth:`tick_ops` in, ``aux["tick"]`` out); host mirrors
(:attr:`state` / :attr:`tick` / :attr:`hot_ids`) sync lazily at
checkpoint boundaries so the hot loop never blocks on them, and the
inherited sidecar machinery persists them for bit-identical supervised
resume.
"""

from __future__ import annotations

import numpy as np

from fps_tpu import sketch as sklib
from fps_tpu.core.store import ids_key, sketch_key
from fps_tpu.tiering.retier import Retierer


def device_top_ids(est, H: int):
    """Traced analog of :func:`fps_tpu.tiering.retier.top_ids`: the
    deterministic top-H ids of an estimate vector by (count desc, id
    asc). Both implementations realize the same TOTAL order (ids are
    unique), so the selected head is identical whichever side ranks —
    the property that keeps in-graph and host re-rank decisions
    interchangeable."""
    import jax.numpy as jnp

    n = est.shape[0]
    order = jnp.lexsort((jnp.arange(n, dtype=jnp.int32),
                         -est.astype(jnp.float32)))
    return order[:H].astype(jnp.int32)


class MegastepTick(Retierer):
    """Boundary-tick spec for ``Trainer.run_megastep``.

    Args mirror :class:`~fps_tpu.tiering.retier.Retierer` where shared:

      tables: table names to manage (default: every store spec).
      spec: the decayed count-min config (per-table hash seeds derive
        from the table name, like the host tracker).
      check_every: tick cadence in CHUNK SEGMENTS inside the megastep —
        must divide ``chunks_per_dispatch`` so every tick lands on a
        static in-graph boundary.
      churn_threshold: re-rank when ``|top-H \\ current| / H`` exceeds
        this; ``< 0`` re-ranks on every tick (forced-cadence mode).
      state_dir / keep: sidecar persistence beside the checkpoints
        (inherited) — written at megastep checkpoint boundaries.

    Auto-planning is deliberately unsupported: the planner's recompile
    has no boundary inside one compiled program (``run_megastep``
    rejects ``auto_tier`` too).
    """

    def __init__(self, tables=None, *,
                 spec: sklib.DecayedCountMinSpec | None = None,
                 check_every: int = 1,
                 churn_threshold: float = 0.25,
                 state_dir: str | None = None,
                 keep: int = 3):
        super().__init__(tables, spec=spec, check_every=check_every,
                         churn_threshold=churn_threshold,
                         state_dir=state_dir, keep=keep)

    # -- dispatch plumbing (consumed by fps_tpu.core.megastep) ------------

    def tick_ops(self, trainer) -> dict:
        """First-dispatch operands: the host-mirror decayed states (or
        fresh zeros) plus the fold counter. Later dispatches feed the
        previous dispatch's device-resident ``aux["tick"]`` back in
        directly — no per-dispatch host round trip."""
        dcm = {}
        for name in sorted(trainer._track_specs()):
            st = self.state.get(name)
            if st is None or st.shape != (self.spec.depth,
                                          self.spec.width):
                st = sklib.dcm_init(self.spec)
            dcm[name] = np.asarray(st, np.float32)
        return {"dcm": dcm, "tick": np.int32(self.tick)}

    def absorb(self, trainer, tick_dev, tables) -> None:
        """Sync the host mirrors from device state (blocking reads —
        called only at checkpoint boundaries / end of run): decayed
        sketches, fold counter, and the hot id sets the program
        currently carries (``::hotids`` — rank order, like a host
        re-rank would have left them)."""
        for name in sorted(tick_dev["dcm"]):
            self.state[name] = np.asarray(tick_dev["dcm"][name])
        self.tick = int(tick_dev["tick"])
        self.checks = self.tick
        for name in sorted(trainer._mapped_tables()):
            k = ids_key(name)
            if k in tables:
                self.hot_ids[name] = np.asarray(
                    tables[k]).astype(np.int64)

    def save_boundary(self, step: int, tables) -> None:
        """Sidecar write at a megastep checkpoint boundary: the pending
        (merged, un-folded) windows still live in the tables dict's
        ``::sketch`` entries, so persist them alongside the mirrors —
        a resume re-seeds them via ``_attach_hot`` exactly like the
        host tracker's restore path."""
        windows = {}
        for name in sorted(self.state):
            k = sketch_key(name)
            if k in tables:
                windows[name] = np.asarray(tables[k])
        self._save_sidecar(step, windows)
