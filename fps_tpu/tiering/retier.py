"""Online hot-set re-ranking: the host half of the adaptive tier.

The driver tracks pulled-id frequencies device-side (a count-min window
per tracked table, accumulated inside the compiled step and psum-merged
across the mesh — ``fps_tpu.sketch``); this module owns everything that
happens to those windows at chunk/epoch boundaries:

* **fold** — every ``check_every`` boundaries the device windows are
  read, folded into a host-side *decayed* count-min
  (:class:`fps_tpu.sketch.DecayedCountMinSpec`, halve-on-schedule so a
  drifting stream forgets its stale head), and reset;
* **re-rank + re-split** — per mapped-tier table, the sketched top-H is
  compared against the current hot id set; when churn exceeds the
  threshold the hot set is replaced: the replica is re-derived from the
  CANONICAL table (valid at any boundary — the flush reconcile already
  ran), and the slot-map / gid arrays are swapped. All three are DATA
  (same shapes), so a re-rank NEVER recompiles — the compile cache is
  keyed on H only;
* **auto-plan** (:func:`fps_tpu.tiering.planner.plan_tables`) — with
  ``TrainerConfig.auto_tier`` the first ``warmup_checks`` folds run
  untiered-but-tracked; the planner then derives per-table ``hot_tier``
  / ``hot_sync_every`` / dense-route from the sketched densities and
  the trainer re-resolves (one deliberate recompile — re-ranks after it
  stay compile-free);
* **sidecar persistence** (``state_dir``) — tracker state (decayed
  sketches, pending windows, hot id sets, fold tick) is written beside
  the checkpoints at every boundary via atomic rename, so a supervised
  restart restores the EXACT tracker the straight run had at that step
  and replays bit-identically (the ``retier_kill`` chaos scenario).
  Checkpoints themselves stay canonical — one table per spec, byte-
  compatible across re-ranks — because re-ranks never touch canonical
  rows.

Thread-safety note: a Retierer is driven only by the trainer's host
loop (one call per boundary, same thread) — no locking needed.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Mapping

import numpy as np

from fps_tpu import sketch as sklib
from fps_tpu.core.store import (
    FOLD_KEY_SUFFIX,
    hot_key,
    hot_slot_map,
    ids_key,
    is_aux_key,
    map_key,
    sketch_key,
    _stable_hash,
)
from fps_tpu.tiering.planner import (
    TableDensity,
    global_sync_every,
    plan_tables,
)

_log = logging.getLogger("fps_tpu.tiering")

SIDECAR_FMT = "tiering-{:08d}.npz"


def sidecar_path(state_dir: str, step: int) -> str:
    return os.path.join(state_dir, SIDECAR_FMT.format(step))


def top_ids(est: np.ndarray, H: int) -> np.ndarray:
    """Deterministic top-H ids of an estimate vector: by count desc,
    id asc on ties — IDENTICAL to a full ``lexsort`` ranking, but via
    ``argpartition`` + a sort of only the candidates (``O(n + H log H)``
    instead of ``O(n log n)``: a 20M-id table's per-check ranking must
    not full-sort inside the training loop's retier phase). All ties at
    the H-th value are included before the final cut so the tie-break
    never depends on partition order."""
    n = len(est)
    if H >= n:
        cand = np.arange(n)
    else:
        part = np.argpartition(-est, H - 1)[:H]
        thresh = est[part].min()
        cand = np.flatnonzero(est >= thresh)
    order = cand[np.lexsort((cand, -est[cand]))]
    return order[:H].astype(np.int64)


class Retierer:
    """Boundary-driven hot-set manager for one trainer.

    Attach with ``trainer.retierer = Retierer(...)`` BEFORE the first
    compiled call (tracking/mapped-tier resolution is part of the
    compile key, like the guard), or set ``TrainerConfig.auto_tier``
    and let the driver attach :meth:`auto_for` at run entry.

    Args:
      tables: table names to track/manage (default: every store spec).
      spec: the decayed count-min config shared by every tracked table
        (per-table hash seeds are derived from the table name).
      check_every: fold/re-rank cadence in chunk/epoch boundaries —
        the re-rank-cadence staleness knob (docs/STALENESS.md).
      churn_threshold: re-rank when ``|top-H \\ current| / H`` exceeds
        this; ``< 0`` re-ranks on every check (deterministic-cadence
        mode, used by the chaos scenario).
      auto_plan: run :func:`plan_tables` after ``warmup_checks`` folds
        and apply it (spec/config mutation + one recompile).
      warmup_checks: folds of evidence required before planning.
      replan_every: periodic RE-planning cadence, in folds (checks):
        after the initial plan, every ``replan_every``-th fold re-runs
        :func:`plan_tables` against the current decayed densities. A
        plan whose knobs (``TierPlan.knobs``: hot_tier / hot_sync_every
        / dense / cold_budget) are unchanged is a strict no-op — zero
        recompiles, specs/config untouched; a changed plan re-applies
        with exactly one deliberate recompile (tested via build-count
        asserts). 0 (default): the plan lands once per run, as before.
      state_dir: write the per-boundary sidecar here (``keep`` newest
        retained); None disables persistence.
      batch_rows_hint: pulled rows per step fed to the planner's
        reconcile cost model (the tracker cannot observe step counts).
      plan_kwargs: extra :func:`plan_tables` keyword overrides
        (replica_budget_bytes, coverage_target, ...).
    """

    def __init__(self, tables=None, *,
                 spec: sklib.DecayedCountMinSpec | None = None,
                 check_every: int = 4,
                 churn_threshold: float = 0.25,
                 auto_plan: bool = False,
                 warmup_checks: int = 1,
                 replan_every: int = 0,
                 state_dir: str | None = None,
                 keep: int = 3,
                 batch_rows_hint: int = 1024,
                 plan_kwargs: Mapping | None = None):
        if check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        if replan_every < 0:
            raise ValueError(
                f"replan_every must be >= 0, got {replan_every}")
        self.tables = None if tables is None else frozenset(tables)
        self.spec = spec or sklib.DecayedCountMinSpec(depth=4, width=2048)
        self.check_every = check_every
        self.churn_threshold = churn_threshold
        self.auto_plan = auto_plan
        self.warmup_checks = warmup_checks
        self.replan_every = replan_every
        self.state_dir = state_dir
        self.keep = keep
        self.batch_rows_hint = batch_rows_hint
        self.plan_kwargs = dict(plan_kwargs or {})
        # -- mutable tracker state (sidecar-persisted) --
        self.state: dict[str, np.ndarray] = {}   # decayed host sketches
        self.hot_ids: dict[str, np.ndarray] = {}
        self.tick = 0          # fold count (the decay schedule's clock)
        self.planned = False
        self.plans = None
        # Pending device windows seeded into ``::sketch`` entries by
        # _attach_hot after a restore (consumed lazily, kept until
        # overwritten so idempotent attaches agree).
        self._restored_windows: dict[str, np.ndarray] = {}
        # -- run stats (not persisted; recorder carries the durable copy)
        self.re_ranks = 0
        self.checks = 0
        self.last_churn: dict[str, float] = {}

    @classmethod
    def auto_for(cls, trainer) -> "Retierer":
        """The ``TrainerConfig.auto_tier`` default: track every table,
        plan after one warmup fold, re-rank at the default cadence."""
        return cls(auto_plan=True)

    # -- resolution interface (consumed by the driver) --------------------

    def manages(self, name: str) -> bool:
        return self.tables is None or name in self.tables

    def _table_cm(self, name: str) -> sklib.CountMinSpec:
        """The one hashing spec for ``name`` — used by BOTH the device-
        side window updates and the host-side queries (a seed mismatch
        between the two would silently estimate garbage; pinned by
        tests/test_tiering.py). Seeds derive from the table name so
        tables hash independently but every process (and every restart)
        agrees."""
        return sklib.CountMinSpec(
            depth=self.spec.depth, width=self.spec.width,
            seed=(self.spec.seed + _stable_hash(name)) % (2 ** 31))

    def track_specs(self, specs: Mapping) -> dict[str, sklib.CountMinSpec]:
        """{table: CountMinSpec} for every MANAGED table — the candidate
        set; the driver's ``_track_specs`` intersects it with the
        resolved tier (re-rankable partial heads only, except during an
        auto-plan warmup where the planner needs every density), so a
        fully-replicated, untiered, or resolution-disengaged table
        carries no tracker ops in its steady-state program."""
        out = {}
        for name in sorted(specs):
            if not self.manages(name):
                continue
            out[name] = self._table_cm(name)
        return out

    def hot_ids_for(self, name: str, H: int) -> np.ndarray:
        """Current hot id set of ``name`` at head size ``H`` (rank
        order, hottest first). Defaults to the static head ``[0, H)`` —
        the frequency-ranked-ids convention — until a re-rank or plan
        replaces it; a stored set of the wrong size (H changed by a
        re-plan) resets to the default rather than guessing."""
        cur = self.hot_ids.get(name)
        if cur is None or len(cur) != H:
            cur = np.arange(H, dtype=np.int64)
            self.hot_ids[name] = cur
        return cur

    def device_window(self, name: str) -> np.ndarray | None:
        """Restored pending window to seed ``name``'s ``::sketch`` entry
        with (None = start from zeros)."""
        return self._restored_windows.get(name)

    def snapshot(self) -> dict:
        """Copy of the mutable tracker state, paired with the driver's
        pre-chunk ``last_good`` table copies: under ``health_lag=1`` a
        chunk's quarantine restores tables captured BEFORE the previous
        boundary's fold/re-rank ran, so the tracker must roll back with
        them or hot_ids/tick desynchronize from the ``::hotids`` the
        program actually carries (the fold is not lost — the restored
        ``::sketch`` window still holds the unfolded traffic)."""
        return {
            "state": {k: v.copy() for k, v in self.state.items()},
            "hot_ids": {k: v.copy() for k, v in self.hot_ids.items()},
            "tick": self.tick,
            "planned": self.planned,
            "plans": self.plans,
            "restored_windows": dict(self._restored_windows),
            "re_ranks": self.re_ranks,
            "checks": self.checks,
        }

    def restore_snapshot(self, snap: Mapping) -> None:
        """Inverse of :meth:`snapshot` (quarantine rollback)."""
        self.state = {k: v.copy() for k, v in snap["state"].items()}
        self.hot_ids = {k: v.copy() for k, v in snap["hot_ids"].items()}
        self.tick = snap["tick"]
        self.planned = snap["planned"]
        self.plans = snap["plans"]
        self._restored_windows = dict(snap["restored_windows"])
        self.re_ranks = snap["re_ranks"]
        self.checks = snap["checks"]

    def on_run_entry(self, trainer) -> None:
        """Run-entry hook (called by the drivers before the tier
        resolution is first consulted): re-apply a restored plan's
        spec/config mutations so a supervised restart resolves the SAME
        tiered program the interrupted run was dispatching. Idempotent;
        a no-op until a plan exists."""
        if self.planned and self.plans:
            self._apply_plans_to(trainer)

    # -- the boundary hook -------------------------------------------------

    def on_boundary(self, trainer, tables: dict, index: int, *,
                    recorder=None) -> dict:
        """Fold/re-rank step after chunk/epoch ``index`` was adjudicated
        clean. Mutates and returns the run's tables dict (aux entries
        only — canonical tables are never touched here)."""
        check = (index + 1) % self.check_every == 0
        if not check and self.state_dir is None:
            return tables
        track = trainer._track_specs()
        windows: dict[str, np.ndarray] = {}
        for name in sorted(track):
            k = sketch_key(name)
            if k in tables:
                windows[name] = np.asarray(tables[k])
        if check:
            self.checks += 1
            for name in sorted(windows):
                st = self.state.get(name)
                if st is None:
                    st = sklib.dcm_init(self.spec)
                self.state[name] = sklib.dcm_fold(
                    self.spec, st, windows[name], self.tick)
                tables[sketch_key(name)] = self._put_replicated(
                    trainer, np.zeros_like(windows[name]))
                windows[name] = np.zeros_like(windows[name])
                # The restored seed window (if any) is folded now: a
                # later aux re-derivation must start from zeros, not
                # re-seed (and double-count) the same traffic.
                self._restored_windows.pop(name, None)
            self.tick += 1
            if (self.auto_plan and not self.planned
                    and self.tick >= self.warmup_checks):
                tables = self._apply_plan(trainer, tables, recorder)
            elif (self.auto_plan and self.planned and self.replan_every
                    and self.tick % self.replan_every == 0):
                tables = self._replan(trainer, tables, recorder)
            tables = self._maybe_rerank(trainer, tables, recorder)
        if self.state_dir is not None:
            self._save_sidecar(index + 1, windows)
        return tables

    # -- re-rank + re-split ------------------------------------------------

    def _estimated_counts(self, name: str, num_ids: int) -> np.ndarray | None:
        st = self.state.get(name)
        if st is None or float(st.sum()) <= 0:
            return None
        probe = np.arange(num_ids, dtype=np.int32)
        # Query with the TABLE's hashing spec (the decayed spec only
        # schedules the halvings) — the window sketches were built with
        # it device-side.
        return np.asarray(sklib.cm_query(self._table_cm(name),
                                         np.asarray(st, np.float32),
                                         probe))

    def _maybe_rerank(self, trainer, tables: dict, recorder) -> dict:
        mapped = trainer._mapped_tables()
        for name in sorted(mapped):
            H = mapped[name]
            spec = trainer.store.specs[name]
            est = self._estimated_counts(name, spec.num_ids)
            if est is None:
                continue
            # Deterministic ranking: by estimated count desc, id asc.
            cand = top_ids(est, H)
            cur = set(self.hot_ids_for(name, H).tolist())
            promoted = [g for g in cand.tolist() if g not in cur]
            churn = len(promoted) / H
            self.last_churn[name] = churn
            if recorder is not None:
                recorder.set("tiering.churn", churn, table=name)
            if churn <= self.churn_threshold or not promoted:
                continue
            demoted = sorted(cur - set(cand.tolist()))
            self.hot_ids[name] = cand
            # Re-split: replica from the CANONICAL table (boundary
            # invariant — the flush reconcile ran), maps as fresh
            # replicated data. Same shapes as before: no recompile.
            tables[hot_key(name)] = trainer.store.rows_replica(
                name, cand, tables[name])
            tables[ids_key(name)] = self._put_replicated(
                trainer, cand.astype(np.int32))
            tables[map_key(name)] = self._put_replicated(
                trainer, hot_slot_map(spec.num_ids, cand))
            self.re_ranks += 1
            _log.info("tiering: re-ranked %r at check %d (churn %.3f, "
                      "%d promoted / %d demoted of H=%d)", name,
                      self.checks, churn, len(promoted), len(demoted), H)
            if recorder is not None:
                recorder.inc("tiering.re_ranks", table=name)
                recorder.inc("tiering.promoted_rows", len(promoted),
                             table=name)
                recorder.inc("tiering.demoted_rows", len(demoted),
                             table=name)
                recorder.event("tiering_rerank", table=name,
                               churn=round(churn, 4),
                               promoted=len(promoted),
                               demoted=len(demoted), head=H)
        return tables

    # -- auto-plan ----------------------------------------------------------

    def _compute_plans(self, trainer):
        """Run the planner against the current decayed densities.
        Returns ``(plans, est_by_name)`` without mutating anything —
        shared by the initial plan and periodic re-planning."""
        from fps_tpu import ops

        store = trainer.store
        densities = []
        est_by_name = {}
        for name in sorted(store.specs):
            if not self.manages(name):
                continue
            spec = store.specs[name]
            est = self._estimated_counts(name, spec.num_ids)
            if est is not None:
                est_by_name[name] = est
            else:
                est = np.zeros(spec.num_ids)
            densities.append(TableDensity(
                name, spec.num_ids, spec.dim, est,
                itemsize=np.dtype(spec.dtype).itemsize))
        kwargs = dict(
            batch_rows_per_step=self.batch_rows_hint,
            dense_table_bytes=ops.DENSE_TABLE_BYTES,
            num_shards=trainer.num_shards,
            num_workers=trainer.num_workers,
        )
        kwargs.update(self.plan_kwargs)
        return plan_tables(densities, **kwargs), est_by_name

    def _install_plans(self, trainer, tables, plans, est_by_name,
                       recorder, *, what: str) -> dict:
        """Adopt ``plans``: seed partial-head rankings, mutate
        specs/config, strip + re-derive the aux entries (the ONE
        deliberate recompile; re-ranks after it swap data only)."""
        store = trainer.store
        for name in sorted(plans):
            plan = plans[name]
            spec = store.specs[name]
            est = est_by_name.get(name)
            if 0 < plan.hot_tier < spec.num_ids and est is not None:
                self.hot_ids[name] = top_ids(est, plan.hot_tier)
        self.planned = True
        self.plans = plans
        E = self._apply_plans_to(trainer)
        _log.info("tiering: %s applied at check %d — %s, "
                  "hot_sync_every=%d", what, self.checks,
                  {n: (p.hot_tier, p.hot_sync_every, p.dense,
                       p.cold_budget)
                   for n, p in sorted(plans.items())}, E)
        if recorder is not None:
            recorder.event(
                "tiering_plan", hot_sync_every=E, what=what,
                plan={n: p.to_json() for n, p in sorted(plans.items())})
        # Strip the DERIVABLE aux entries (replicas, slot maps, sketches)
        # so _attach_hot re-derives them under the new resolution — but
        # KEEP ::fold optimizer state: it is the one aux kind that is not
        # a projection of the canonical table (driver._attach_hot
        # validates its shape against the new resolution and drops it
        # only if genuinely stale; silently zeroing a live Adagrad/Adam
        # accumulator on a re-plan would change step sizes mid-run).
        tables = {k: v for k, v in tables.items()
                  if not is_aux_key(k) or k.endswith(FOLD_KEY_SUFFIX)}
        return trainer._attach_hot(tables)

    def _apply_plan(self, trainer, tables: dict, recorder) -> dict:
        plans, est_by_name = self._compute_plans(trainer)
        return self._install_plans(trainer, tables, plans, est_by_name,
                                   recorder, what="plan")

    def _replan(self, trainer, tables: dict, recorder) -> dict:
        """Periodic RE-planning (``replan_every``): recompute the plan
        from the current decayed densities; unchanged knobs are a strict
        no-op (zero recompiles — specs, config, and aux entries all
        untouched), changed knobs re-apply with one deliberate
        recompile."""
        plans, est_by_name = self._compute_plans(trainer)
        old = self.plans or {}
        unchanged = (set(plans) == set(old) and all(
            plans[n].knobs() == old[n].knobs() for n in plans))
        if recorder is not None:
            recorder.inc("tiering.replans",
                         changed="false" if unchanged else "true")
        if unchanged:
            # Refresh the evidence (coverage/reason) for the sidecar,
            # but leave specs/config/aux alone — the compile key cannot
            # move.
            self.plans = plans
            return tables
        _log.info("tiering: re-plan at check %d changed the knobs — "
                  "re-applying", self.checks)
        return self._install_plans(trainer, tables, plans, est_by_name,
                                   recorder, what="replan")

    def _apply_plans_to(self, trainer) -> int:
        """Mutate the trainer's specs/config to match ``self.plans``
        (idempotent — replaying the same plan is a no-op on the compile
        key). Returns the applied global hot_sync_every."""
        store = trainer.store
        for name in sorted(self.plans):
            plan = self.plans[name]
            spec = store.specs.get(name)
            if spec is None:
                continue
            store.specs[name] = dataclasses.replace(
                spec, hot_tier=plan.hot_tier,
                dense_collectives=plan.dense,
                cold_budget=getattr(plan, "cold_budget", 0))
        E = global_sync_every(self.plans)
        trainer.config = dataclasses.replace(
            trainer.config, hot_sync_every=E)
        return E

    # -- sidecar persistence -------------------------------------------------

    def _put_replicated(self, trainer, arr: np.ndarray):
        import jax

        return jax.device_put(np.asarray(arr), trainer._replicated)

    def _save_sidecar(self, step: int, windows: dict) -> None:
        """Write the boundary sidecar — ONE inline attempt, then the
        retry/backoff budget runs on a background retrier thread so
        its sleeps never land on the training thread; persistent
        transient failure DEGRADES to a skip: the sidecar is advisory
        (a missing one only cold-starts the tracker on resume, warned
        loudly by :meth:`restore`), so a storage brownout at a
        boundary must not crash — or throttle — training over it.
        ``storage.sidecar_skips`` counts the lost durability."""
        from fps_tpu.core import retry as _retry

        os.makedirs(self.state_dir, exist_ok=True)
        path = sidecar_path(self.state_dir, step)
        arrays = {"meta": np.frombuffer(json.dumps({
            "version": 1, "tick": self.tick, "step": step,
            "planned": self.planned,
            "plans": ({n: p.to_json() for n, p in sorted(
                self.plans.items())} if self.plans else None),
        }).encode(), dtype=np.uint8)}
        for name in sorted(self.state):
            arrays[f"state::{name}"] = self.state[name]
        for name in sorted(self.hot_ids):
            arrays[f"hot::{name}"] = self.hot_ids[name]
        for name in sorted(windows):
            arrays[f"window::{name}"] = windows[name]
        try:
            self._write_sidecar_file(path, arrays)
        except OSError as e:
            if _retry.classify_error(e) != "retryable":
                raise
            self._obs_metric("inc", "storage.retries", 1,
                             plane="sidecar")
            self._sidecar_retry_bg(step, path, arrays)
            return
        self._sweep_sidecars()

    def _sidecar_retry_bg(self, step: int, path: str,
                          arrays: dict) -> None:
        """Hand a transiently-failed sidecar write to the background
        retrier (lazily spawned, latest-wins slot): the remaining
        retry budget and its backoff sleeps run there. A pending older
        sidecar displaced by a newer boundary counts as a skip — only
        the newest sidecar matters for resume."""
        import threading

        lock = self.__dict__.setdefault("_sc_lock", threading.Lock())
        with lock:
            prev = self.__dict__.get("_sc_pending")
            if prev is not None:
                _log.warning("tiering: sidecar for step %d displaced by "
                             "step %d before its background retry ran — "
                             "skipped", prev[0], step)
                self._obs_metric("inc", "storage.sidecar_skips", 1)
            self._sc_pending = (step, path, arrays)
            t = self.__dict__.get("_sc_thread")
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._sidecar_retry_loop,
                                     name="sidecar-retrier", daemon=True)
                self._sc_thread = t
                t.start()

    def _sidecar_retry_loop(self) -> None:
        from fps_tpu.core import retry as _retry

        while True:
            with self._sc_lock:
                item = self.__dict__.pop("_sc_pending", None)
            if item is None:
                return
            step, path, arrays = item
            try:
                _retry.call_with_retry(
                    lambda: self._write_sidecar_file(path, arrays),
                    policy=dataclasses.replace(
                        _retry.DEFAULT_PUBLISH_RETRY, seed=path),
                    op="sidecar",
                    on_retry=lambda a, e, d: self._obs_metric(
                        "inc", "storage.retries", 1, plane="sidecar"))
            except OSError as e:
                _log.warning("tiering: sidecar write for step %d "
                             "DEGRADED (skipped after retries): %r — a "
                             "resume past this boundary cold-starts the "
                             "tracker", step, e)
                self._obs_metric("inc", "storage.sidecar_skips", 1)
                continue
            try:
                self._sweep_sidecars()
            except OSError:
                pass

    def sidecar_flush(self, timeout: float | None = None) -> None:
        """Block until any background sidecar retry has drained —
        test/shutdown seam; training never calls this on the hot
        path."""
        t = self.__dict__.get("_sc_thread")
        if t is not None and t.is_alive():
            t.join(timeout)

    @staticmethod
    def _obs_metric(kind: str, name: str, value, **labels) -> None:
        from fps_tpu.obs import events

        events.record_metric(kind, name, value, **labels)

    @staticmethod
    def _write_sidecar_file(path: str, arrays: dict) -> None:
        from fps_tpu.core import retry as _retry

        _retry.fault_check("write", path)
        tmp = path + ".tmp.npz"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                _retry.fault_check("fsync", path)
                os.fsync(f.fileno())
            _retry.fault_check("replace", path)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    def _sweep_sidecars(self) -> None:
        """Retention must track RESTORABILITY, not recency: a resume
        restores the tracker sidecar matching ``latest_valid_step``,
        which under ``checkpoint_every > 1`` is older than the last few
        boundaries — so any sidecar whose step still has a published
        snapshot beside it (``state_dir`` is the checkpoint dir in
        supervised runs) survives the sweep, plus the newest ``keep``
        regardless. Without co-located snapshots (a bare state_dir) the
        newest-``keep`` fallback applies; co-locate with the
        checkpoints when bit-identical resume matters."""
        from fps_tpu.core import snapshot_format as fmt

        ckpt_steps = set()
        for f in os.listdir(self.state_dir):
            m = fmt.SNAPSHOT_RE.fullmatch(f)
            if m:
                ckpt_steps.add(int(m.group(1)))
        kept = sorted(
            f for f in os.listdir(self.state_dir)
            if f.startswith("tiering-") and f.endswith(".npz")
            and not f.endswith(".tmp.npz"))
        for f in kept[:-self.keep] if self.keep else kept:
            try:
                step = int(f[len("tiering-"):-len(".npz")])
            except ValueError:
                continue
            if step in ckpt_steps:
                continue
            try:
                os.remove(os.path.join(self.state_dir, f))
            except OSError:
                pass

    def restore(self, step: int) -> bool:
        """Load the sidecar written at boundary ``step`` (the checkpoint
        step a supervised restart resumes from). Returns True on an
        exact match; False (cold tracker, warns) when the sidecar is
        missing — training stays correct either way, only the re-rank
        decisions restart from scratch."""
        if self.state_dir is None:
            return False
        path = sidecar_path(self.state_dir, step)
        if not os.path.exists(path):
            if step:
                _log.warning(
                    "tiering: no sidecar for step %d under %s — tracker "
                    "restarts cold (re-rank decisions may differ from "
                    "the uninterrupted run)", step, self.state_dir)
            return False
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            self.tick = int(meta["tick"])
            self.planned = bool(meta.get("planned", False))
            from fps_tpu.tiering.planner import TierPlan

            self.plans = ({n: TierPlan(**p) for n, p in
                           meta["plans"].items()}
                          if meta.get("plans") else None)
            self.state = {}
            self.hot_ids = {}
            self._restored_windows = {}
            for k in z.files:
                if k.startswith("state::"):
                    self.state[k[len("state::"):]] = z[k].copy()
                elif k.startswith("hot::"):
                    self.hot_ids[k[len("hot::"):]] = z[k].copy()
                elif k.startswith("window::"):
                    self._restored_windows[k[len("window::"):]] = (
                        z[k].copy())
        return True
