"""Auto-tiering planner: derive the per-table storage knobs from
observed (sketched) id densities instead of hand-tuning them.

Parallax (arxiv.org/pdf/1808.02621) shows the replicate-vs-shard
decision and the sync cadence should come from *measured sparsity*;
NuPS (arxiv.org/pdf/2104.00501) adds the hot/cold management policy.
This module is the decision function: given per-table estimated id
frequencies (from the online tracker's decayed count-min, or any other
density estimate), :func:`plan_tables` chooses for every table

* ``hot_tier`` — the replicated head size ``H``: the full table when it
  fits the replica budget (the NuPS small-hot-table regime — statically
  elides the collective pull/push routes), else the smallest head
  covering ``coverage_target`` of estimated traffic (clamped to the
  budget), else 0 when the distribution is too flat for a head to pay;
* ``hot_sync_every`` — the reconcile cadence ``E``: smallest window
  whose amortized reconcile traffic (``H*dim*itemsize/E`` bytes/step,
  ``+1`` count column under a "mean" fold) stays below
  ``reconcile_frac`` of the estimated per-step hot-row pull traffic it
  replaces (clamped to ``[2, max_sync_every]`` — 1 is the exact mode,
  i.e. "tier off");
* ``dense`` — the replicate-on-read/dense-reduce collective route for
  small tables (``TableSpec.dense_collectives``), decided against the
  same byte threshold the trainer's "auto" resolution uses.

numpy-only on purpose: the planner runs in jax-free tools
(``tools/plan.py``) and on login nodes. The predicted collective-byte
budget of a plan is NOT computed here — ``tools/plan.py`` lowers a
probe program with the plan applied and measures it with
``fps_tpu.analysis.collective_profile`` (a measured program, not a
model).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Default replica budget per table: how much memory each device may
# spend on one table's hot replica. Deliberately generous relative to
# the embedding-table scales the shipped workloads use — operators with
# tight HBM override it per plan call.
DEFAULT_REPLICA_BUDGET_BYTES = 64 << 20
# A head must cover at least this fraction of estimated traffic to be
# worth its reconcile + replica cost; flatter distributions stay
# untiered (the gathered route is already payload-balanced for them).
MIN_HEAD_COVERAGE = 0.5


@dataclasses.dataclass(frozen=True)
class TableDensity:
    """What the planner needs to know about one table: geometry plus the
    estimated per-id pull frequencies (any non-negative array of length
    ``num_ids``; the online tracker supplies decayed count-min
    estimates, tools/plan.py can synthesize Zipf profiles)."""

    name: str
    num_ids: int
    dim: int
    counts: np.ndarray
    itemsize: int = 4

    def __post_init__(self):
        c = np.asarray(self.counts, np.float64)
        if c.shape != (self.num_ids,):
            raise ValueError(
                f"table {self.name!r}: counts shape {c.shape} != "
                f"({self.num_ids},)")
        if c.size and c.min() < 0:
            raise ValueError(f"table {self.name!r}: negative counts")
        object.__setattr__(self, "counts", c)


@dataclasses.dataclass(frozen=True)
class TierPlan:
    """One table's planned knobs (``reason`` is the human-readable
    audit trail ``tools/plan.py`` prints per row). ``cold_budget``
    (PR 10) is the payload-proportional cold-route lane width — 0 keeps
    the static cold routes (pre-PR-10 sidecars deserialize with the
    default)."""

    hot_tier: int
    hot_sync_every: int
    dense: bool
    coverage: float  # estimated traffic fraction the head serves
    reason: str
    cold_budget: int = 0

    def knobs(self) -> tuple:
        """The compile-affecting knob tuple — what periodic re-planning
        compares to decide whether a fresh plan is a no-op (same knobs →
        zero recompiles) or a real change (one deliberate recompile).
        ``coverage``/``reason`` are evidence, not knobs: estimates drift
        every fold and must not force spurious recompiles."""
        return (self.hot_tier, self.hot_sync_every, self.dense,
                self.cold_budget)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def head_coverage(counts: np.ndarray, H: int) -> float:
    """Estimated traffic fraction served by the TOP-H ids (by count)."""
    total = float(counts.sum())
    if total <= 0 or H <= 0:
        return 0.0
    top = np.sort(counts)[::-1][:H]
    return float(top.sum() / total)


def choose_sync_every(
    H: int,
    dim: int,
    itemsize: int,
    coverage: float,
    *,
    batch_rows_per_step: int,
    mean_combine: bool = False,
    reconcile_frac: float = 0.25,
    max_sync_every: int = 8,
) -> int:
    """Smallest reconcile window E whose amortized traffic stays under
    ``reconcile_frac`` of the per-step hot traffic it replaces.

    Cost model (docs/performance.md "Adaptive tiering"): a window moves
    one ``(H, dim (+1 if mean))`` psum, i.e. ``H*(dim+mean)*itemsize/E``
    bytes/step amortized; the hot rows it absorbs would otherwise ride
    per-step collectives carrying about ``coverage * B * dim * itemsize``
    bytes/step. E is the smallest integer making
    ``reconcile/step <= reconcile_frac * absorbed/step``, clamped to
    ``[2, max_sync_every]`` — the bound is the parameter-plane staleness
    the operator accepts (docs/STALENESS.md).
    """
    reconcile_bytes = H * (dim + (1 if mean_combine else 0)) * itemsize
    absorbed = coverage * batch_rows_per_step * dim * itemsize
    if absorbed <= 0:
        return max_sync_every
    e = int(np.ceil(reconcile_bytes / (reconcile_frac * absorbed)))
    return int(np.clip(e, 2, max_sync_every))


def choose_cold_budget(
    coverage: float,
    batch_rows_per_step: int,
    *,
    num_workers: int = 1,
    margin: float = 4.0,
    min_budget: int = 8,
) -> int:
    """Per-worker cold-lane width for a partial head: the expected cold
    rows per worker per step (``(1 - coverage) · B / W``) times a safety
    ``margin`` (batch-to-batch variance — an undersized lane makes every
    chunk overflow back to the static route), rounded up to a multiple
    of 8 lanes. Returns 0 (static routes) when the margined lane would
    not be meaningfully narrower than the batch itself — the compacted
    route pays a pack/unpack per step, so it must buy a real payload
    reduction."""
    per_worker = max(batch_rows_per_step // max(num_workers, 1), 1)
    expect = (1.0 - coverage) * per_worker
    budget = int(np.ceil(max(expect * margin, min_budget) / 8.0) * 8)
    if budget * 2 >= per_worker:
        return 0
    return budget


def plan_tables(
    densities: list[TableDensity] | dict[str, TableDensity],
    *,
    batch_rows_per_step: int,
    replica_budget_bytes: int = DEFAULT_REPLICA_BUDGET_BYTES,
    dense_table_bytes: int = 1 << 20,
    coverage_target: float = 0.9,
    min_head_coverage: float = MIN_HEAD_COVERAGE,
    reconcile_frac: float = 0.25,
    max_sync_every: int = 8,
    mean_combine: bool = False,
    num_shards: int = 8,
    num_workers: int = 1,
    cold_budget_margin: float = 4.0,
) -> dict[str, TierPlan]:
    """Choose ``(hot_tier, hot_sync_every, dense)`` per table from its
    estimated density — the three knobs ``TableSpec``/``TrainerConfig``
    otherwise make users hand-tune.

    ``batch_rows_per_step``: pulled rows per step per table across all
    workers (the traffic unit the cost model amortizes against).
    ``num_shards`` informs only the reason strings (the single-shard
    no-op case is resolved by the trainer, not here).

    The driver's reconcile cadence is global (``TrainerConfig.
    hot_sync_every``); per-table recommendations are returned anyway and
    the applier takes the MIN over tiered tables — the tightest
    staleness bound any table asked for (see ``apply_plan``).
    """
    if isinstance(densities, dict):
        densities = list(densities.values())
    plans: dict[str, TierPlan] = {}
    for d in densities:
        table_bytes = d.num_ids * d.dim * d.itemsize
        dense = table_bytes <= dense_table_bytes
        total = float(d.counts.sum())
        if total <= 0:
            plans[d.name] = TierPlan(
                0, 1, dense, 0.0,
                "no observed traffic: untiered until the tracker has "
                "evidence")
            continue
        if table_bytes <= replica_budget_bytes:
            cov = 1.0
            H = d.num_ids
            E = choose_sync_every(
                H, d.dim, d.itemsize, cov,
                batch_rows_per_step=batch_rows_per_step,
                mean_combine=mean_combine,
                reconcile_frac=reconcile_frac,
                max_sync_every=max_sync_every)
            plans[d.name] = TierPlan(
                H, E, dense, cov,
                f"full replication ({table_bytes}B <= "
                f"{replica_budget_bytes}B budget): collective pull/push "
                "statically elided")
            continue
        order = np.sort(d.counts)[::-1]
        cum = np.cumsum(order) / total
        H_cov = int(np.searchsorted(cum, coverage_target) + 1)
        budget_rows = max(replica_budget_bytes // (d.dim * d.itemsize), 1)
        H = int(min(H_cov, budget_rows, d.num_ids))
        cov = float(cum[H - 1])
        if cov < min_head_coverage:
            plans[d.name] = TierPlan(
                0, 1, dense, cov,
                f"flat distribution: top-{H} covers only {cov:.2f} < "
                f"{min_head_coverage} — a head would not pay its "
                "reconcile")
            continue
        E = choose_sync_every(
            H, d.dim, d.itemsize, cov,
            batch_rows_per_step=batch_rows_per_step,
            mean_combine=mean_combine,
            reconcile_frac=reconcile_frac,
            max_sync_every=max_sync_every)
        # Partial head: the cold routes stay — make their payload track
        # the measured cold traffic (docs/performance.md
        # "Payload-proportional routing"). Dense tables skip it (their
        # collectives are table-sized regardless of the lane).
        C = 0 if dense else choose_cold_budget(
            cov, batch_rows_per_step, num_workers=num_workers,
            margin=cold_budget_margin)
        plans[d.name] = TierPlan(
            H, E, dense, cov,
            f"partial head: top-{H} covers {cov:.2f} of estimated "
            f"traffic (target {coverage_target}, budget "
            f"{budget_rows} rows, {num_shards} shards"
            + (f"; compacted cold lane {C}/worker" if C else "")
            + ")",
            cold_budget=C)
    return plans


def tenant_demand_bytes(
    densities: list[TableDensity] | dict[str, TableDensity],
    *,
    replica_budget_bytes: int = DEFAULT_REPLICA_BUDGET_BYTES,
    coverage_target: float = 0.9,
    min_head_coverage: float = MIN_HEAD_COVERAGE,
) -> int:
    """Replica bytes one tenant would CONSUME given a solo budget of
    ``replica_budget_bytes`` — the demand figure the multi-tenant
    arbitration splits. Mirrors :func:`plan_tables`' branch structure
    exactly (full replication / coverage-target head / flat-untiered),
    so ``granted >= demand`` guarantees the arbitrated plan is
    bit-identical to the solo plan."""
    if isinstance(densities, dict):
        densities = list(densities.values())
    demand = 0
    for d in densities:
        table_bytes = d.num_ids * d.dim * d.itemsize
        total = float(d.counts.sum())
        if total <= 0:
            continue
        if table_bytes <= replica_budget_bytes:
            demand += table_bytes
            continue
        order = np.sort(d.counts)[::-1]
        cum = np.cumsum(order) / total
        H_cov = int(np.searchsorted(cum, coverage_target) + 1)
        budget_rows = max(replica_budget_bytes // (d.dim * d.itemsize), 1)
        H = int(min(H_cov, budget_rows, d.num_ids))
        if float(cum[H - 1]) < min_head_coverage:
            continue
        demand += H * d.dim * d.itemsize
    return demand


def arbitrate_replica_budget(
    demands: dict[str, int],
    total_budget: int,
    *,
    weights: dict[str, float] | None = None,
) -> dict[str, int]:
    """Split ONE replica budget across tenants by weighted water-filling.

    ``demands`` maps tenant → bytes it would consume solo
    (:func:`tenant_demand_bytes`); ``weights`` maps tenant → arbitration
    weight (default 1.0 each; must be > 0).

    The blast-radius contract, stated as arithmetic:

    * a tenant demanding no more than its weighted fair share is granted
      its FULL demand — no neighbor, however hungry, can dilute it;
    * surplus left by under-demanders is redistributed among the
      still-hungry by weight (work-conserving);
    * a tenant's overflow (demand above its final share) is simply not
      granted — the shortfall degrades only that tenant's coverage.

    Returns ``{tenant: granted_bytes}`` with ``sum(granted) <=
    total_budget`` and ``granted[t] <= demands[t]`` for every tenant.
    """
    if total_budget < 0:
        raise ValueError(f"total_budget must be >= 0, got {total_budget}")
    weights = dict(weights or {})
    for name in demands:
        w = weights.setdefault(name, 1.0)
        if not (isinstance(w, (int, float)) and w > 0):
            raise ValueError(f"tenant {name!r}: weight must be > 0, "
                             f"got {w!r}")
    granted = {name: 0 for name in demands}
    active = {name for name, dem in demands.items() if dem > 0}
    remaining = int(total_budget)
    # Water-filling: repeatedly satisfy every tenant whose demand fits
    # its weighted share of what is left, then re-divide the surplus
    # among the rest. Terminates: each round either fully satisfies at
    # least one tenant or performs the final pro-rata split.
    while active and remaining > 0:
        wsum = sum(weights[n] for n in active)
        shares = {n: remaining * weights[n] / wsum for n in active}
        sated = [n for n in active if demands[n] <= shares[n]]
        if not sated:
            # Everyone overflows: final split, largest-remainder so the
            # full budget is handed out deterministically (sorted name
            # order breaks ties).
            floor = {n: int(shares[n]) for n in active}
            left = remaining - sum(floor.values())
            by_frac = sorted(active,
                             key=lambda n: (-(shares[n] - floor[n]), n))
            for n in by_frac[:left]:
                floor[n] += 1
            for n in active:
                granted[n] = min(floor[n], demands[n])
            break
        for n in sated:
            granted[n] = demands[n]
            remaining -= demands[n]
            active.remove(n)
    return granted


def plan_tenants(
    tenant_densities: dict[str, list[TableDensity] | dict[str, TableDensity]],
    *,
    batch_rows_per_step: int,
    weights: dict[str, float] | None = None,
    total_replica_budget_bytes: int = DEFAULT_REPLICA_BUDGET_BYTES,
    coverage_target: float = 0.9,
    min_head_coverage: float = MIN_HEAD_COVERAGE,
    **plan_kwargs,
) -> dict[str, dict]:
    """Plan every tenant's tables under ONE shared replica budget.

    Each tenant's demand (:func:`tenant_demand_bytes`, measured against
    the full shared budget — i.e. what it would consume running solo)
    is arbitrated by :func:`arbitrate_replica_budget`; the grant becomes
    that tenant's ``replica_budget_bytes`` for a normal
    :func:`plan_tables` call. Isolation property (tested): a tenant
    whose demand fits its weighted share gets a plan whose
    :meth:`TierPlan.knobs` are identical to its solo plan's (``reason``
    strings cite the differing budgets; knobs are what lower); a noisy
    neighbor's overflow degrades only the noisy neighbor's coverage.

    Returns ``{tenant: {"demand": bytes, "granted": bytes,
    "plans": {table: TierPlan}}}``.
    """
    demands = {
        name: tenant_demand_bytes(
            dens, replica_budget_bytes=total_replica_budget_bytes,
            coverage_target=coverage_target,
            min_head_coverage=min_head_coverage)
        for name, dens in tenant_densities.items()}
    granted = arbitrate_replica_budget(
        demands, total_replica_budget_bytes, weights=weights)
    out: dict[str, dict] = {}
    for name, dens in tenant_densities.items():
        out[name] = {
            "demand": demands[name],
            "granted": granted[name],
            "plans": plan_tables(
                dens, batch_rows_per_step=batch_rows_per_step,
                replica_budget_bytes=granted[name],
                coverage_target=coverage_target,
                min_head_coverage=min_head_coverage,
                **plan_kwargs),
        }
    return out


def global_sync_every(plans: dict[str, TierPlan]) -> int:
    """The driver's single reconcile cadence from per-table plans: the
    MIN over tiered tables (tightest staleness bound requested); 1 (the
    exact mode / tier off) when nothing tiers."""
    es = [p.hot_sync_every for p in plans.values() if p.hot_tier > 0]
    return min(es) if es else 1
