"""fps_tpu.tiering — adaptive tiering: online hot-set re-ranking and
the auto-tiering planner.

PR 5's hot tier is a *static* frequency-ranked head fixed at table-spec
time; any workload whose hot set drifts decays back to cold-route
collectives. This subsystem manages the tier *online*, in the NuPS
(arxiv.org/pdf/2104.00501) mold, with the knobs *derived* from observed
sparsity in the Parallax (arxiv.org/pdf/1808.02621) spirit:

* **tracking** — a count-min window per table, updated device-side
  inside the compiled step from the batch's pulled ids and psum-merged
  across the mesh (:mod:`fps_tpu.sketch`); folded host-side into a
  halve-on-schedule DECAYED count-min so drift forgets the stale head;
* **re-rank + re-split** (:class:`Retierer`) — at chunk boundaries the
  sketched top-H replaces the hot id set by swapping the replica and
  its slot-map/gid arrays (replicated DATA, fixed shapes): re-ranks
  never recompile, and the flush-reconcile invariant keeps checkpoints
  canonical and byte-compatible across them;
* **planning** (:func:`plan_tables`) — per-table ``hot_tier`` /
  ``hot_sync_every`` / dense-route derived from sketched densities,
  replacing three hand-tuned knobs (``TrainerConfig.auto_tier``,
  ``tools/plan.py``).

See docs/performance.md "Adaptive tiering" and docs/STALENESS.md (the
re-rank cadence is a staleness knob on the tier-membership plane).
"""

from fps_tpu.tiering.planner import (
    TableDensity,
    TierPlan,
    choose_sync_every,
    global_sync_every,
    head_coverage,
    plan_tables,
)
from fps_tpu.tiering.probe import ProbeLogic, lowered_plan_text, probe_chunk
from fps_tpu.tiering.retier import Retierer, sidecar_path
from fps_tpu.tiering.tick import MegastepTick, device_top_ids

__all__ = [
    "TableDensity", "TierPlan", "plan_tables", "choose_sync_every",
    "global_sync_every", "head_coverage",
    "Retierer", "sidecar_path",
    "MegastepTick", "device_top_ids",
    "ProbeLogic", "probe_chunk", "lowered_plan_text",
]
