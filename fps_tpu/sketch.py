"""Streaming sketches: count-min, tug-of-war (count-sketch), Bloom filter.

The upstream project family this reference forks carries a ``sketch`` module
for estimating word co-occurrence statistics from a stream without storing
the full matrix (SURVEY.md §2 #10 — flagged [conf: L]; bloom-filter and
tug-of-war sketches for co-occurrence similarity). Rebuilt here TPU-first:

* every sketch is **plain additive array state** — update is a masked
  scatter-add, so a sketch can live device-side inside a compiled step, and
  **merging across workers is just a sum** (``lax.psum`` over the mesh axes,
  or ``+`` on host). This mirrors how the reference merges per-operator
  sketches by reduction.
* hashing is vectorized multiply-shift on uint32 (overflow wraps by design),
  with per-row constants derived deterministically from the spec seed — the
  same reproducible-under-resharding contract as the store's per-id
  initializers.

API is functional (spec + pure init/update/query), matching the framework's
WorkerLogic style; a sketch used inside a worker is just more local state.

Estimates (standard guarantees):

* count-min: ``query >= true``; overestimate ≤ ``2N/width`` w.p. ``1-2^-depth``.
* tug-of-war inner product: unbiased, variance ``O(F2(a)F2(b)/width)``,
  median over ``depth`` rows tightens the tail — this is the co-occurrence
  similarity estimator.
* Bloom: no false negatives; false-positive rate ``(1-e^{-kn/m})^k``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_PRIME = np.uint32(2654435761)  # Knuth multiplicative constant


@functools.lru_cache(maxsize=None)
def _hash_constants(seed: int, depth: int) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    a = (rng.integers(1, 2**31, depth, dtype=np.int64) * 2 + 1).astype(np.uint32)
    b = rng.integers(0, 2**31, depth, dtype=np.int64).astype(np.uint32)
    return a, b


def _mix(h: Array) -> Array:
    """murmur3-style 32-bit finalizer: diffuses the weak low/high bits of the
    multiply so the full 2^32 range is usable for any width."""
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _bucket(ids: Array, a: Array, b: Array, width: int) -> Array:
    """(depth, B) bucket index per hash row."""
    x = ids.astype(jnp.uint32)[None, :] * _PRIME
    h = _mix(a[:, None] * x + b[:, None])
    # uint32 % width over the fully-mixed hash: modulo bias <= width/2^32.
    return (h % np.uint32(width)).astype(jnp.int32)


def _sign(ids: Array, a: Array, b: Array) -> Array:
    """(depth, B) ±1 four-ish-wise-independent sign per hash row."""
    x = ids.astype(jnp.uint32)[None, :] * _PRIME
    h = _mix(a[:, None] * x + b[:, None])
    return (1 - 2 * ((h >> np.uint32(31)).astype(jnp.int32))).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Count-min sketch (point frequency estimates, biased up).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CountMinSpec:
    depth: int = 4
    width: int = 1024
    seed: int = 0

    def constants(self):
        a, b = _hash_constants(self.seed, self.depth)
        return jnp.asarray(a), jnp.asarray(b)


def cm_init(spec: CountMinSpec) -> Array:
    return jnp.zeros((spec.depth, spec.width), jnp.float32)


def cm_update(spec: CountMinSpec, sketch: Array, ids: Array,
              values: Array | None = None) -> Array:
    """Add ``values`` (default 1.0) at ``ids``; ids < 0 are dropped."""
    a, b = spec.constants()
    cols = _bucket(ids, a, b, spec.width)  # (depth, B)
    v = jnp.ones(ids.shape, jnp.float32) if values is None else values
    v = jnp.where(ids >= 0, v.astype(jnp.float32), 0.0)
    rows = jnp.broadcast_to(
        jnp.arange(spec.depth, dtype=jnp.int32)[:, None], cols.shape
    )
    flat = rows.reshape(-1) * spec.width + cols.reshape(-1)
    updated = sketch.reshape(-1).at[flat].add(
        jnp.broadcast_to(v[None, :], cols.shape).reshape(-1)
    )
    return updated.reshape(spec.depth, spec.width)


def cm_query(spec: CountMinSpec, sketch: Array, ids: Array) -> Array:
    """(B,) frequency estimates: min over depth rows."""
    a, b = spec.constants()
    cols = _bucket(ids, a, b, spec.width)
    vals = jnp.take_along_axis(sketch, cols, axis=1)  # (depth, B)
    return jnp.min(vals, axis=0)


# ---------------------------------------------------------------------------
# Decayed count-min (halve-on-schedule): drifting streams forget stale
# hot sets. The decayed state is a running fold over fixed-size WINDOW
# sketches (plain :func:`cm_update` accumulations): every
# ``half_every``-th fold first halves the whole state, then adds the new
# window — deterministic (the schedule is a tick counter, never
# wall-clock), exact in float (halving is a power-of-two scale), and
# LINEAR, so decay commutes with the psum/``+`` merge contract: folding
# the merged windows of two substreams equals merging their separately
# folded states (tested in tests/test_sketch.py). The effective weight
# of a window folded ``k`` halvings ago is ``2^-k`` — an exponential
# forget schedule with half-life ``half_every`` folds.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DecayedCountMinSpec:
    depth: int = 4
    width: int = 1024
    seed: int = 0
    # Halve the whole state every `half_every` folds (ticks). The decayed
    # count of an id is therefore a window-weighted sum with weights
    # 1, 1, ..., 1/2, 1/2, ... stepping down per half_every-fold block.
    half_every: int = 8

    def __post_init__(self):
        if self.half_every < 1:
            raise ValueError(
                f"half_every must be >= 1, got {self.half_every}")

    def cm(self) -> CountMinSpec:
        """The plain count-min spec sharing this spec's hashing — window
        sketches are built with it (``cm_init``/``cm_update``), so the
        decayed state and its windows index identical buckets."""
        return CountMinSpec(self.depth, self.width, self.seed)


def dcm_init(spec: DecayedCountMinSpec):
    """Fresh decayed state (works as numpy on host or jnp on device)."""
    return np.zeros((spec.depth, spec.width), np.float32)


def dcm_fold(spec: DecayedCountMinSpec, state, window, tick: int):
    """Fold one window sketch into the decayed state at fold index
    ``tick`` (0-based, monotone): halve first when the schedule says so,
    then add — the newest window always enters at full weight. Pure
    arithmetic: numpy in, numpy out (host tracker) or jnp in, jnp out.
    """
    if tick < 0:
        raise ValueError(f"tick must be >= 0, got {tick}")
    if tick > 0 and tick % spec.half_every == 0:
        state = state * 0.5
    return state + window


def dcm_fold_traced(spec: DecayedCountMinSpec, state, window, tick):
    """Traced :func:`dcm_fold` for in-graph ticks (``tick`` is a traced
    int32 scalar, so the halve-on-schedule branch becomes a ``where``).
    Bit-identical to the host fold: halving is an exact power-of-two
    scale and the add is the same IEEE f32 sum, whichever side runs it
    (tested in tests/test_megastep.py)."""
    halve = (tick > 0) & (tick % spec.half_every == 0)
    state = jnp.where(halve, state * 0.5, state)
    return state + window


def dcm_query(spec: DecayedCountMinSpec, state, ids) -> Array:
    """(B,) decayed frequency estimates (min over depth rows); same
    upward-bias guarantee as :func:`cm_query`, on the decayed counts."""
    return cm_query(spec.cm(), jnp.asarray(state), ids)


# ---------------------------------------------------------------------------
# Tug-of-war / count-sketch (unbiased inner products & frequencies).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TugOfWarSpec:
    depth: int = 5
    width: int = 1024
    seed: int = 0

    def constants(self):
        a1, b1 = _hash_constants(self.seed * 2 + 1, self.depth)
        a2, b2 = _hash_constants(self.seed * 2 + 2, self.depth)
        return (jnp.asarray(a1), jnp.asarray(b1),
                jnp.asarray(a2), jnp.asarray(b2))


def tow_init(spec: TugOfWarSpec) -> Array:
    return jnp.zeros((spec.depth, spec.width), jnp.float32)


def tow_update(spec: TugOfWarSpec, sketch: Array, ids: Array,
               values: Array | None = None) -> Array:
    """Add ``values·sign(id)`` into each row's bucket; ids < 0 dropped."""
    a1, b1, a2, b2 = spec.constants()
    cols = _bucket(ids, a1, b1, spec.width)
    signs = _sign(ids, a2, b2)
    v = jnp.ones(ids.shape, jnp.float32) if values is None else values
    v = jnp.where(ids >= 0, v.astype(jnp.float32), 0.0)
    rows = jnp.broadcast_to(
        jnp.arange(spec.depth, dtype=jnp.int32)[:, None], cols.shape
    )
    flat = rows.reshape(-1) * spec.width + cols.reshape(-1)
    updated = sketch.reshape(-1).at[flat].add((signs * v[None, :]).reshape(-1))
    return updated.reshape(spec.depth, spec.width)


def tow_update_rows(spec: TugOfWarSpec, stack: Array, rows: Array,
                    ids: Array, values: Array | None = None) -> Array:
    """Batched multi-sketch update: add each id's tug-of-war contribution
    into ``stack[rows[b]]`` of a ``(P, depth, width)`` sketch stack in ONE
    scatter (vs one full-width scatter per stack row). Items with
    ``rows < 0`` or ``ids < 0`` are dropped."""
    P = stack.shape[0]
    a1, b1, a2, b2 = spec.constants()
    cols = _bucket(ids, a1, b1, spec.width)  # (depth, B)
    signs = _sign(ids, a2, b2)  # (depth, B)
    v = jnp.ones(ids.shape, jnp.float32) if values is None else values
    v = jnp.where((ids >= 0) & (rows >= 0), v.astype(jnp.float32), 0.0)
    r = jnp.clip(rows, 0, P - 1).astype(jnp.int32)  # dropped rows add 0.0
    d = jnp.arange(spec.depth, dtype=jnp.int32)[:, None]
    flat = (r[None, :] * spec.depth + d) * spec.width + cols  # (depth, B)
    updated = stack.reshape(-1).at[flat.reshape(-1)].add(
        (signs * v[None, :]).reshape(-1)
    )
    return updated.reshape(stack.shape)


def tow_inner(s1: Array, s2: Array) -> Array:
    """Unbiased estimate of the inner product of the two sketched frequency
    vectors — the co-occurrence-similarity estimator (median over rows)."""
    return jnp.median(jnp.sum(s1 * s2, axis=1))


def tow_query(spec: TugOfWarSpec, sketch: Array, ids: Array) -> Array:
    """(B,) unbiased point-frequency estimates (median over rows)."""
    a1, b1, a2, b2 = spec.constants()
    cols = _bucket(ids, a1, b1, spec.width)
    signs = _sign(ids, a2, b2)
    vals = jnp.take_along_axis(sketch, cols, axis=1) * signs
    return jnp.median(vals, axis=0)


# ---------------------------------------------------------------------------
# Bloom filter (set membership, no false negatives).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BloomSpec:
    num_hashes: int = 4
    num_bits: int = 8192
    seed: int = 0

    def constants(self):
        a, b = _hash_constants(self.seed + 17, self.num_hashes)
        return jnp.asarray(a), jnp.asarray(b)


def bloom_init(spec: BloomSpec) -> Array:
    return jnp.zeros((spec.num_bits,), jnp.uint8)


def bloom_add(spec: BloomSpec, bits: Array, ids: Array) -> Array:
    a, b = spec.constants()
    pos = _bucket(ids, a, b, spec.num_bits).reshape(-1)
    live = jnp.broadcast_to((ids >= 0)[None, :], (spec.num_hashes,) + ids.shape)
    pos = jnp.where(live.reshape(-1), pos, spec.num_bits)  # dropped
    return bits.at[pos].max(jnp.uint8(1), mode="drop")


def bloom_contains(spec: BloomSpec, bits: Array, ids: Array) -> Array:
    """(B,) bool — True may be a false positive; False is definite."""
    a, b = spec.constants()
    pos = _bucket(ids, a, b, spec.num_bits)  # (k, B)
    return jnp.all(jnp.take(bits, pos, axis=0) > 0, axis=0)


def merge(*sketches: Array) -> Array:
    """Merge sketches built over disjoint substreams (any of the three kinds
    — they are all additive; for Bloom this is saturating max)."""
    out = sketches[0]
    for s in sketches[1:]:
        out = jnp.maximum(out, s) if out.dtype == jnp.uint8 else out + s
    return out
