from fps_tpu.parallel.mesh import make_ps_mesh, DATA_AXIS, SHARD_AXIS

__all__ = ["make_ps_mesh", "DATA_AXIS", "SHARD_AXIS"]
