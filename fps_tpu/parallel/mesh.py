"""Device-mesh construction for the parameter-server layout.

The reference runs ``workerParallelism`` worker subtasks and ``psParallelism``
server subtasks as separate Flink operators connected by a network shuffle
(``FlinkParameterServer.transform``, expected upstream path
``src/main/scala/hu/sztaki/ilab/ps/FlinkParameterServer.scala``).

On TPU we use an SPMD layout instead: every chip is *both* a worker and a
parameter shard. The mesh has two named axes:

* ``data``  — pure data parallelism: parameter tables are **replicated** along
  it, the example stream is split across it.
* ``shard`` — the parameter-server axis: tables are **row-sharded** along it
  (the analog of ``psParallelism``), and the example stream is split across it
  too (workers = all devices).

So ``workerParallelism == data * shard`` and ``psParallelism == shard``.
A plain single-axis PS is ``data=1``.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

# Canonical axis names used throughout the framework.
DATA_AXIS = "data"
SHARD_AXIS = "shard"


def make_ps_mesh(
    num_shards: int | None = None,
    num_data: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build a ``(data, shard)`` mesh over the available devices.

    Args:
      num_shards: size of the parameter-shard axis (the reference's
        ``psParallelism``). Defaults to ``len(devices) // num_data``.
      num_data: size of the replicated data-parallel axis.
      devices: optional explicit device list (defaults to ``jax.devices()``).

    Returns:
      A ``jax.sharding.Mesh`` with axes ``('data', 'shard')``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if num_shards is None:
        if n % num_data != 0:
            raise ValueError(f"{n} devices not divisible by num_data={num_data}")
        num_shards = n // num_data
    if num_data * num_shards != n:
        raise ValueError(
            f"mesh {num_data}x{num_shards} does not cover {n} devices"
        )
    import numpy as np

    dev_grid = np.asarray(devices).reshape(num_data, num_shards)
    return Mesh(dev_grid, (DATA_AXIS, SHARD_AXIS))


def default_mesh_shape(n_devices: int) -> tuple[int, int]:
    """Factor ``n_devices`` into a (data, shard) shape.

    Prefers a square-ish split with shard >= data so that parameter sharding
    (the scarce resource: HBM) gets the larger axis.
    """
    best = (1, n_devices)
    d = int(math.isqrt(n_devices))
    while d >= 1:
        if n_devices % d == 0 and n_devices // d >= d:
            best = (d, n_devices // d)
            break
        d -= 1
    return best
