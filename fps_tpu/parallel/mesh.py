"""Device-mesh construction for the parameter-server layout.

The reference runs ``workerParallelism`` worker subtasks and ``psParallelism``
server subtasks as separate Flink operators connected by a network shuffle
(``FlinkParameterServer.transform``, expected upstream path
``src/main/scala/hu/sztaki/ilab/ps/FlinkParameterServer.scala``).

On TPU we use an SPMD layout instead: every chip is *both* a worker and a
parameter shard. The mesh has two named axes:

* ``data``  — pure data parallelism: parameter tables are **replicated** along
  it, the example stream is split across it.
* ``shard`` — the parameter-server axis: tables are **row-sharded** along it
  (the analog of ``psParallelism``), and the example stream is split across it
  too (workers = all devices).

So ``workerParallelism == data * shard`` and ``psParallelism == shard``.
A plain single-axis PS is ``data=1``.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis names used throughout the framework.
DATA_AXIS = "data"
SHARD_AXIS = "shard"


def make_ps_mesh(
    num_shards: int | None = None,
    num_data: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build a ``(data, shard)`` mesh over the available devices.

    Args:
      num_shards: size of the parameter-shard axis (the reference's
        ``psParallelism``). Defaults to ``len(devices) // num_data``.
      num_data: size of the replicated data-parallel axis.
      devices: optional explicit device list (defaults to ``jax.devices()``).

    Returns:
      A ``jax.sharding.Mesh`` with axes ``('data', 'shard')``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if num_shards is None:
        if n % num_data != 0:
            raise ValueError(f"{n} devices not divisible by num_data={num_data}")
        num_shards = n // num_data
    if num_data * num_shards != n:
        raise ValueError(
            f"mesh {num_data}x{num_shards} does not cover {n} devices"
        )
    dev_grid = np.asarray(devices).reshape(num_data, num_shards)
    return Mesh(dev_grid, (DATA_AXIS, SHARD_AXIS))


def default_mesh_shape(n_devices: int) -> tuple[int, int]:
    """Factor ``n_devices`` into a (data, shard) shape.

    Prefers a square-ish split with shard >= data so that parameter sharding
    (the scarce resource: HBM) gets the larger axis.
    """
    best = (1, n_devices)
    d = int(math.isqrt(n_devices))
    while d >= 1:
        if n_devices % d == 0 and n_devices // d >= d:
            best = (d, n_devices // d)
            break
        d -= 1
    return best


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     local_device_ids=None) -> None:
    """Initialize multi-host JAX (call once per process, before any jax use).

    Thin wrapper over ``jax.distributed.initialize``: on TPU pods the
    arguments are auto-detected from the environment; on CPU/GPU fleets pass
    the coordinator address and process topology explicitly. After this,
    ``jax.devices()`` spans every host and :func:`make_ps_mesh` builds a
    global mesh — the framework's collectives then ride ICI within a slice
    and DCN across hosts, replacing the reference's Netty/Akka fabric for
    the multi-node case.
    """
    # CPU fleets (and the multi-process test harness): cross-process
    # collectives need the gloo transport; without it the CPU backend
    # refuses multiprocess computations outright. Best-effort — the knob
    # moved/disappeared across jax versions, and TPU ignores it.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def host_to_replicated(x, mesh: Mesh):
    """Place a host array replicated over ``mesh``, multi-controller safe.

    Single-process: a plain ``device_put``. Multi-process (mesh spans
    non-addressable devices): every process supplies its identical local
    copy via ``make_array_from_process_local_data``.
    """
    sh = NamedSharding(mesh, PartitionSpec())
    if sh.is_fully_addressable:
        return jax.device_put(x, sh)
    return jax.make_array_from_process_local_data(sh, np.asarray(x))


def host_to_sharded(x, sharding: NamedSharding):
    """Place a GLOBAL host array onto a (possibly multi-process) sharding.

    Single-process: a plain ``device_put``. Multi-process: every process
    passes the identical full array and
    ``make_array_from_process_local_data`` slices out each process's
    addressable portion (the documented ``global_shape == data.shape``
    mode, which requires the data to be identical across hosts — exactly
    the host-ingest contract: every process runs the same deterministic
    chunk iterator).
    """
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_process_local_data(sharding, x, x.shape)


_KEY_PUT_CACHE: dict = {}


def key_to_replicated(key, mesh: Mesh):
    """Place a PRNG key replicated over ``mesh``, multi-controller safe.

    Key arrays have an extended dtype numpy can't hold, so the key *data*
    (identical in every process) rides through a jitted re-wrap with
    replicated output sharding.
    """
    sh = NamedSharding(mesh, PartitionSpec())
    if sh.is_fully_addressable:
        return jax.device_put(key, sh)
    fn = _KEY_PUT_CACHE.get(mesh)
    if fn is None:
        fn = _KEY_PUT_CACHE[mesh] = jax.jit(
            jax.random.wrap_key_data, out_shardings=sh
        )
    return fn(np.asarray(jax.random.key_data(key)))


_REPLICATE_CACHE: dict = {}


def replicate_to_mesh(x, mesh: Mesh):
    """Replicate a (possibly sharded) device array over ``mesh`` through a
    per-mesh cached jitted identity.

    NOTE: in multi-controller runs this is a COLLECTIVE — every process of
    the mesh must call it (a lone process blocks forever waiting for the
    others' shards). Host-read helpers built on it (``ParamStore``'s
    ``lookup_host``/``dump_model``) inherit that contract.
    """
    fn = _REPLICATE_CACHE.get(mesh)
    if fn is None:
        fn = _REPLICATE_CACHE[mesh] = jax.jit(
            lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec())
        )
    return fn(x)
