"""Pod-level coordination: one failure domain for a multi-host run.

The per-host :class:`~fps_tpu.supervise.supervisor.RunSupervisor` gave a
SINGLE host deadline-abort, retry, and quarantine — but a multi-host run
supervised that way dies by N uncoordinated wall-clocks and can restart
from N *different* ``latest_valid_step``s. This module makes the POD the
failure domain, the way the paper's reference runtime (Flink's
coordinated checkpoint/restart) and Parameter Box's multi-node appliance
framing assume:

* **Leader election over the shared checkpoint filesystem** — a lease
  file (:class:`Lease`) renewed by heartbeat and written only by
  atomic rename; any member can seize an expired lease, and every
  seizure (and every coordinated restart) bumps a monotone **fencing
  epoch** so a deposed leader's decisions — and its orphaned child's
  checkpoint publishes (``fps_tpu.core.checkpoint`` refuses to publish
  behind a fence) — can never leak into the new attempt.
* **Pod-wide deadline abort** — any member's wedge/crash/disappearance
  becomes ONE leader decision; every member then runs the same
  SIGTERM → grace → SIGKILL escalation against its own child. No more
  N independent stall timers.
* **Coordinated restart** — the leader computes the COMMON restart
  point (min over plan members' newest verified snapshots, verified
  with stdlib ``zipfile`` CRCs so this process never imports numpy or
  jax) and commands every member to resume from it, with the new epoch
  stamped into the control record, the fences, and
  ``supervisor_state.json``.
* **Pod-consistent quarantine** — crash evidence from every member
  folds into one pod-level quarantine list (size-capped, oldest-first
  eviction), broadcast to every child through the supervised-child env
  contract, so no host re-dispatches a chunk another host proved
  poisonous.
* **Elastic membership** — a member whose failures exhaust its budget
  is EVICTED: the leader re-plans the run at W−1 hosts and the
  survivors restart from the canonical checkpoint (legal because
  snapshots are mesh-shape independent — the flush-reconcile invariant;
  ``Trainer.restore_checkpoint`` re-splits and asserts it). A returning
  member is re-admitted at the next boundary: the leader syncs it the
  newest canonical snapshot (shared filesystem copy) and restarts the
  pod at W.

Stdlib-only by the same contract as the supervisor: this module must run
on a login node / pod coordinator VM with zero jax (``tools/supervise.py``
loads it by file path). All cross-member state lives in the pod
directory:

```
pod_dir/
  pod_lease.json        leader lease (atomic-rename, fencing epoch)
  pod_control.json      current leader command (epoch-ordered)
  pod_state.json        pod-level persisted state (quarantine, plan, ...)
  journal-pod.jsonl     pod decision journal (tools/obs_report.py folds it)
  members/<host>.json   per-member status beacons
  <host>/               member state dir == that member's child ckpt dir
```

See ``docs/resilience.md`` for the pod failure-model table.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import time
import zipfile

# Sibling modules (child.py: the env/fence contract; supervisor.py: the
# RunSupervisor base). In package context they are ALREADY in
# sys.modules (fps_tpu.supervise.__init__ imports child before pod), so
# we reuse them for class identity; loaded by file path
# (tools/supervise.py) we path-load them the same way — NEVER a package
# import, which would drag fps_tpu.__init__ (and with it jax) into a
# process whose whole contract is staying a few-MB pure-python agent.
import sys as _sys


def _load_sibling(name: str, *parts: str):
    import importlib.util as _ilu

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), *parts, name + ".py")
    spec = _ilu.spec_from_file_location("_fps_pod_" + name, path)
    mod = _ilu.module_from_spec(spec)
    _sys.modules[spec.name] = mod  # pre-registered for 3.10 dataclasses
    spec.loader.exec_module(mod)
    return mod


_child = (_sys.modules.get("fps_tpu.supervise.child")
          or _load_sibling("child"))
_sup = (_sys.modules.get("fps_tpu.supervise.supervisor")
        or _load_sibling("supervisor"))
# fps_tpu/core/retry.py is stdlib-only by the same contract as this
# module: in package context it is already in sys.modules (checkpoint
# imports it before supervise loads); by file path it loads the same
# way the siblings do — never a package import, which would drag jax.
_retry = (_sys.modules.get("fps_tpu.core.retry")
          or _load_sibling("retry", os.pardir, "core"))

LEASE_FILENAME = "pod_lease.json"
CONTROL_FILENAME = "pod_control.json"
POD_STATE_FILENAME = "pod_state.json"
POD_JOURNAL_FILENAME = "journal-pod.jsonl"
MEMBERS_DIRNAME = "members"

POD_STATE_SCHEMA_VERSION = 1

# Snapshot filename contract — MIRRORED from fps_tpu/core/snapshot_format
# (which needs numpy; this module must stay stdlib-only).
# tests/test_pod.py asserts the two patterns match.
SNAPSHOT_RE = re.compile(r"ckpt_(\d{12})\.npz")


def _atomic_write_json(path: str, obj: dict) -> None:
    # The hostile-filesystem seam (fps_tpu.core.retry.fault_check): the
    # deterministic injector may fail or slow this write — lease
    # renewals, control records, fences, member beacons all cross here.
    # All three sub-ops are exposed, matching the sibling write seams
    # (_atomic_savez, serve/fleet, the retier sidecar), so a schedule
    # written in the documented ('write'|'fsync'|'replace') vocabulary
    # targets the pod plane too instead of matching nothing.
    _retry.fault_check("write", path)
    _retry.fault_check("fsync", path)
    _retry.fault_check("replace", path)
    _sup._atomic_write_json(path, obj)


# Full-content CRC verification is capped: the scan runs inside the
# member's single-threaded poll loop (which also renews the lease), and
# reading a multi-GB snapshot end-to-end there could stall renewal past
# the lease TTL — a spurious seizure on every large publish. Beyond the
# cap only the zip structure (central directory) is checked, which still
# catches truncation/torn publishes; the child's restore runs the full
# per-array ``meta::crc`` pass either way and falls back on mismatch.
FULL_VERIFY_MAX_BYTES = 64 * 1024 * 1024


def latest_valid_snapshot_step(directory: str, _cache: dict | None = None
                               ) -> int | None:
    """Newest snapshot step under ``directory`` whose zip passes the
    stdlib verification the coordinator can afford: full member CRC-32s
    (``zipfile.testzip``, covering truncation AND bit rot) up to
    :data:`FULL_VERIFY_MAX_BYTES`, structural central-directory checks
    beyond. ``_cache`` (optional ``{path: (mtime_ns, size, ok)}``) skips
    re-reading files already verified at the same identity."""
    best = None
    try:
        _retry.fault_check("listdir", directory)
        names = os.listdir(directory)
    except OSError:
        return None
    for f in sorted(names, reverse=True):
        m = SNAPSHOT_RE.fullmatch(f)
        if not m:
            continue
        step = int(m.group(1))
        if best is not None and step <= best:
            continue
        path = os.path.join(directory, f)
        try:
            st = os.stat(path)
            ident = (st.st_mtime_ns, st.st_size)
        except OSError:
            continue
        if _cache is not None and _cache.get(path, (None,))[:2] == ident:
            ok = _cache[path][2]
        else:
            try:
                with zipfile.ZipFile(path) as z:
                    if st.st_size <= FULL_VERIFY_MAX_BYTES:
                        ok = z.testzip() is None
                    else:
                        ok = bool(z.namelist())  # structure only
            except (OSError, zipfile.BadZipFile):
                ok = False
            if _cache is not None:
                _cache[path] = (*ident, ok)
        if ok:
            best = step
    return best


class Lease:
    """Leader lease over a shared filesystem, with a fencing epoch.

    The lease file holds ``{host, nonce, epoch, t, ttl}`` and is only
    ever written by atomic rename. The holder renews by rewriting with a
    fresh ``t``; anyone observing ``now - t > ttl`` may SEIZE by writing
    itself in with ``epoch + 1``. Because rename is last-writer-wins,
    acquisition is two-phase across ticks: :meth:`tick` writes a claim,
    and only the claimant that still reads itself back on the NEXT tick
    holds the lease — racing claimants settle on the single rename
    winner. The epoch is the pod's fencing token: it only ever grows
    (seizure and every coordinated restart bump it), so a deposed
    holder's stale decisions are ordered out by every consumer.
    """

    def __init__(self, path: str, host: str, ttl_s: float,
                 clock=time.time):
        self.path = path
        self.host = host
        self.ttl_s = float(ttl_s)
        self.clock = clock
        # Uniqueness, not secrecy: distinguishes two agents that (mis)use
        # one host name, and a restarted agent from its previous life.
        self.nonce = f"{os.getpid()}-{int(clock() * 1e6)}"
        self._claimed = False
        # Highest epoch ever OBSERVED: a lease record below it is a
        # deposed holder's resumed stale rename (frozen mid-renewal,
        # woke after a seizure) — treated as expired and re-seized above
        # the max, so the fencing epoch stays monotone for every
        # observer even across that race.
        self._max_epoch = 0
        # Slow-lease step-down state (hostile-filesystem survival): a
        # holder whose renewal cannot LAND before TTL/2 relinquishes —
        # ``_lapsed`` stops further renewals so the record expires on
        # schedule and a follower seizes with a monotone epoch bump,
        # instead of a slow filesystem silently carrying a leader past
        # its own TTL. ``stepdowns``/``renew_failures`` are evidence
        # counters for the slow_lease_near_ttl chaos scenario.
        self._lapsed = False
        self.stepdowns = 0
        self.renew_failures = 0
        # Consecutive slow renewals (landed, but slower than TTL/2).
        # ONE is tolerated — an isolated fsync latency spike on a
        # loaded box must not depose a healthy leader and churn the
        # pod through seizures; two in a row mean the filesystem is
        # persistently slow and holding on risks blowing the TTL.
        self._slow_strikes = 0

    def read(self) -> dict | None:
        try:
            with open(self.path, encoding="utf-8") as f:
                rec = json.load(f)
            return rec if isinstance(rec, dict) else None
        except (OSError, json.JSONDecodeError):
            return None

    def _is_mine(self, rec: dict | None) -> bool:
        return bool(rec) and rec.get("host") == self.host \
            and rec.get("nonce") == self.nonce

    def expired(self, rec: dict | None) -> bool:
        if not rec:
            return True
        try:
            return self.clock() - float(rec["t"]) > float(
                rec.get("ttl", self.ttl_s))
        except (KeyError, TypeError, ValueError):
            return True

    def _write(self, epoch: int) -> None:
        _atomic_write_json(self.path, {
            "host": self.host, "nonce": self.nonce, "epoch": int(epoch),
            "t": self.clock(), "ttl": self.ttl_s,
        })

    def tick(self) -> tuple[bool, dict | None, bool]:
        """One election step. Returns ``(held, lease_record, seized)``
        where ``seized`` is True on the tick a claim is CONFIRMED (the
        caller journals the takeover)."""
        rec = self.read()
        try:
            rec_epoch = int(rec["epoch"]) if rec else 0
        except (KeyError, TypeError, ValueError):
            rec_epoch = 0
        regressed = rec is not None and rec_epoch < self._max_epoch
        self._max_epoch = max(self._max_epoch, rec_epoch)
        mine = self._is_mine(rec)
        if mine and not regressed and not self._lapsed:
            confirmed = self._claimed
            self._claimed = False
            renew_failed = False
            if self.clock() - float(rec["t"]) > self.ttl_s / 3.0:
                t0 = self.clock()
                try:
                    self._write(rec_epoch)
                    write_s = self.clock() - t0
                    rec = self.read() or rec
                except OSError:
                    # Renewal write failed (ENOSPC/EIO brownout): the
                    # on-disk record keeps its old t; the step-down
                    # check below decides whether we can carry on.
                    renew_failed = True
                    self.renew_failures += 1
                else:
                    if write_s > self.ttl_s / 2.0:
                        self._slow_strikes += 1
                    else:
                        self._slow_strikes = 0
            # Slow-lease step-down: relinquish when renewals cannot
            # LAND within TTL/2 — two CONSECUTIVE slow writes (the
            # WRITE's own measured duration, so a scheduler hiccup
            # between ticks never deposes a leader, and one isolated
            # fsync spike is tolerated), a failing write stream whose
            # record has aged past TTL/2, or a record that is no
            # longer ours. Followers seize only after the full TTL, so
            # a stepping-down leader is always out before any
            # successor exists — clean handover, never two writers.
            # The record is then left to expire (no further renewals:
            # a slow-landing renewal stream must not extend a hold we
            # gave up).
            try:
                age = self.clock() - float((rec or {}).get("t", 0) or 0)
            except (TypeError, ValueError):
                age = float("inf")
            if (not self._is_mine(rec)
                    or self._slow_strikes >= 2
                    or (renew_failed and age > self.ttl_s / 2.0)):
                self._lapsed = True
                self._slow_strikes = 0
                self.stepdowns += 1
                return False, rec, False
            return True, rec, confirmed
        self._claimed = False
        if (mine and self._lapsed and not regressed
                and not self.expired(rec)):
            # Our own relinquished record, still unexpired: wait it out
            # like any other observer (re-entry only via the ordinary
            # expired-seize path, with its epoch bump).
            return False, rec, False
        if regressed or self.expired(rec):
            # Seize strictly ABOVE everything ever observed — a
            # regressed record's writer may believe it leads at its old
            # epoch, and only a higher epoch orders it out.
            epoch = max(rec_epoch, self._max_epoch) + 1
            try:
                self._write(epoch)
            except OSError:
                return False, rec, False  # brownout: retry next tick
            self._max_epoch = epoch
            self._claimed = True  # confirm (or lose) next tick
            self._lapsed = False
            self._slow_strikes = 0
        return False, rec, False

    def advance_epoch(self, epoch: int) -> None:
        """Holder-only: rewrite the lease at a new (higher) epoch — the
        coordinated-restart fencing bump."""
        self._max_epoch = max(self._max_epoch, int(epoch))
        self._write(int(epoch))


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """Pod policy knobs, on top of a per-member ``SupervisorConfig``.

    ``pod_size`` members form the pod (the leader waits for all of them
    to register before the first launch). ``lease_ttl_s`` bounds how long
    a dead leader blocks the pod; ``member_timeout_s`` is how stale a
    member's status beacon may go before the leader treats that HOST as
    unreachable (its agent can no longer kill its child — the restart
    that follows is what the fencing epoch protects). ``max_restarts``
    is the pod-wide coordinated-restart budget. With ``elastic`` on, a
    member whose consecutive failures reach ``evict_after`` is evicted
    (the pod re-plans at W−1) and may be re-admitted up to
    ``readmit_budget`` times once it reports ready again
    (``rejoin_delay_s`` after eviction)."""

    pod_size: int = 1
    elastic: bool = False
    lease_ttl_s: float = 5.0
    member_timeout_s: float = 10.0
    max_restarts: int = 8
    evict_after: int = 2
    readmit_budget: int = 2
    rejoin_delay_s: float = 0.5
    startup_deadline_s: float = 600.0
    member: object | None = None  # SupervisorConfig (None: defaults)

    def __post_init__(self):
        if self.pod_size < 1:
            raise ValueError(f"pod_size must be >= 1, got {self.pod_size}")
        if self.lease_ttl_s <= 0 or self.member_timeout_s <= 0:
            raise ValueError("lease_ttl_s and member_timeout_s must be > 0")
        if self.evict_after < 1:
            raise ValueError(
                f"evict_after must be >= 1, got {self.evict_after}")


class PodMember(_sup.RunSupervisor):
    """One host's pod agent: member duties always, leader duties while
    holding the lease.

    Layered on :class:`RunSupervisor` for the per-host mechanics (child
    spawn into its own process group, heartbeat tailing with schema
    hardening, the SIGTERM → grace → SIGKILL escalation, jittered
    backoff, journaling) — but every RESTART/ABORT/QUARANTINE decision
    is the pod leader's, consumed through ``pod_control.json``. The
    member's own ``state_dir`` (``pod_dir/<host>``) doubles as its
    child's checkpoint dir, so fences and snapshot scans need no extra
    configuration.
    """

    def __init__(self, cmd: list[str], *, pod_dir: str, host: str,
                 config: PodConfig | None = None,
                 watch: tuple[str, ...] = (), env: dict | None = None,
                 cwd: str | None = None):
        self.pod_config = config or PodConfig()
        member_cfg = self.pod_config.member or _sup.SupervisorConfig()
        if not host or "/" in host or host != host.strip():
            raise ValueError(f"invalid pod host name {host!r}")
        super().__init__(cmd, state_dir=os.path.join(pod_dir, host),
                         config=member_cfg, watch=watch, env=env, cwd=cwd,
                         host=host)
        self.pod_dir = pod_dir
        self.ckpt_dir = self.state_dir  # convention: snapshots land here
        self.members_dir = os.path.join(pod_dir, MEMBERS_DIRNAME)
        os.makedirs(self.members_dir, exist_ok=True)
        self.member_path = os.path.join(self.members_dir, f"{host}.json")
        self.control_path = os.path.join(pod_dir, CONTROL_FILENAME)
        self.pod_state_path = os.path.join(pod_dir, POD_STATE_FILENAME)
        self.pod_journal_path = os.path.join(pod_dir, POD_JOURNAL_FILENAME)
        self.lease = Lease(os.path.join(pod_dir, LEASE_FILENAME), host,
                           self.pod_config.lease_ttl_s)
        self.is_leader = False
        self.leader_terms = 0
        self.pod_state: dict | None = None  # loaded on leadership
        self._snap_cache: dict = {}
        # Child/attempt trackers (the non-blocking analog of
        # RunSupervisor._run_attempt's loop locals).
        self._child = None
        self._attempt = -1
        self._status = "idle"  # idle|running|done|failed|evicted|ready
        self._status_kind = None  # crash|stall|None
        self._rc = None
        self._executed_epoch = 0
        self._pod_ctx: dict | None = None  # current control's env values
        self._spawn_at: float | None = None
        self._ready_at: float | None = None
        self._t0 = None
        self._hb_mtime = None
        self._watch_fp = ()
        self._last_signal = None
        self._deadline_s = None
        self._respawns = 0
        # Transient shared-filesystem failures this agent degraded
        # through (failed beacons/journal lines, retried leader ticks).
        self.io_errors = 0

    # -- journaling --------------------------------------------------------

    def _pod_event(self, etype: str, **fields) -> None:
        """Pod-journal append (O_APPEND single line: safe under the brief
        dual-writer window a lease handover allows). Every record carries
        the pod trace id (when known) so ``tools/trace_export.py`` can
        hang the whole pod narrative under one tree."""
        trace = (self.pod_state or {}).get("trace_id") or self.trace_id
        rec = {"kind": "event", "t": time.time(), "event": etype,
               "host": self.host, "trace_id": trace, **fields}
        try:
            with open(self.pod_journal_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            # Journal evidence is best-effort under a storage brownout:
            # losing a line must never take the coordinator down with it.
            self.io_errors += 1

    # -- pod state (leader-persisted) --------------------------------------

    def _load_pod_state(self) -> dict:
        try:
            with open(self.pod_state_path, encoding="utf-8") as f:
                st = json.load(f)
        except (OSError, json.JSONDecodeError):
            st = {}
        found = int(st.get("schema", POD_STATE_SCHEMA_VERSION))
        if found > POD_STATE_SCHEMA_VERSION:
            raise ValueError(
                f"{self.pod_state_path} has schema v{found}, this "
                f"coordinator understands <= v{POD_STATE_SCHEMA_VERSION}")
        st["schema"] = POD_STATE_SCHEMA_VERSION
        st.setdefault("epoch", 0)
        st.setdefault("roster", [])
        st.setdefault("plan", [])
        st.setdefault("restarts", 0)
        st.setdefault("readmissions", 0)
        st.setdefault("attempts", [])
        st.setdefault("quarantined", [])
        st.setdefault("evicted", [])
        st.setdefault("failures", {})
        st.setdefault("readmits", {})
        st.setdefault("crash_streaks", {})
        st.setdefault("handled", {})
        st.setdefault("last_control", None)
        # Causal tracing (fps_tpu/obs/trace.py): one trace per pod run,
        # minted by the first leader; the root span is the pod_start and
        # every decision span hangs under it. Persisted so a seizing
        # leader continues the SAME trace instead of forking a new one.
        st.setdefault("trace_id", None)
        st.setdefault("root_span", None)
        return st

    def _save_pod_state(self) -> None:
        _atomic_write_json(self.pod_state_path, self.pod_state)

    # -- member beacon -----------------------------------------------------

    def _write_member(self) -> None:
        _atomic_write_json(self.member_path, {
            "schema": 1,
            "host": self.host,
            "pid": os.getpid(),
            "child_pid": self._child.pid if self._child is not None
            else None,
            "t": time.time(),
            "epoch": self._executed_epoch,
            "status": self._status,
            "kind": self._status_kind,
            "attempt": self._attempt,
            "rc": self._rc,
            "last_index": getattr(self, "_last_index", None),
            "last_phase": getattr(self, "_last_phase", None),
            "latest_step": latest_valid_snapshot_step(
                self.ckpt_dir, self._snap_cache),
            "leader": self.is_leader,
        })

    def _read_members(self) -> dict[str, dict]:
        out = {}
        try:
            names = os.listdir(self.members_dir)
        except OSError:
            return out
        for f in names:
            if not f.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.members_dir, f),
                          encoding="utf-8") as fh:
                    rec = json.load(fh)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(rec, dict) and rec.get("host"):
                out[rec["host"]] = rec
        return out

    # -- child control (member side) ---------------------------------------

    def _child_cmd(self) -> list[str]:
        """``{host}`` in any argv element expands to this member's host
        name — one command template serves every member of the pod."""
        return [a.replace("{host}", self.host) for a in self.cmd]

    def _child_env(self, attempt: int) -> dict:
        env = super()._child_env(attempt)
        # Quarantine broadcast: the child reads its carried quarantine
        # set from STATE_ENV — pointed at the POD state file, so a chunk
        # any member proved poisonous is skipped by every member.
        env[_sup.STATE_ENV] = self.pod_state_path
        ctx = self._pod_ctx or {}
        env[_child.POD_HOST_ENV] = self.host
        env[_child.POD_EPOCH_ENV] = str(ctx.get("epoch", 0))
        env[_child.POD_WORLD_ENV] = str(ctx.get("world", 0))
        env[_child.POD_STEP_ENV] = str(ctx.get("step", 0))
        return env

    def _spawn_child(self, now: float) -> None:
        self._attempt += 1
        try:
            os.remove(self.heartbeat_path)  # stale beats must not count
        except OSError:
            pass
        log_path = os.path.join(self.state_dir,
                                f"attempt-{self._attempt}.log")
        self._attempt_span = _sup._mint_id()
        self._child = self._spawn(self._attempt, log_path)
        self._status, self._status_kind, self._rc = "running", None, None
        self._last_index = None
        self._last_phase = None
        self._t0 = now
        self._hb_mtime, self._watch_fp = None, self._watch_fingerprint()
        self._last_signal = now
        cfg = self.config
        self._deadline_s = (cfg.startup_grace_s
                            if cfg.startup_grace_s is not None
                            else cfg.stall_timeout_s)
        self._event("attempt_start", attempt=self._attempt,
                    pid=self._child.pid, cmd=self._child_cmd(),
                    pod_epoch=(self._pod_ctx or {}).get("epoch"),
                    trace_id=self.trace_id, span_id=self._attempt_span,
                    parent_id=(self._pod_ctx or {}).get("span")
                    or self.run_span,
                    quarantined=(self._pod_ctx or {}).get("quarantined",
                                                          []))

    def _finish_attempt(self, record_extra: dict) -> None:
        record = {
            "attempt": self._attempt,
            "rc": self._rc,
            "last_index": getattr(self, "_last_index", None),
            "last_phase": getattr(self, "_last_phase", None),
            "pod_epoch": (self._pod_ctx or {}).get("epoch"),
            "runtime_s": round(time.time() - (self._t0 or time.time()), 3),
            **record_extra,
        }
        self.state["attempts"].append(record)
        self._save_state()
        self._event("attempt_end", trace_id=self.trace_id,
                    span_id=self._attempt_span,
                    parent_id=(self._pod_ctx or {}).get("span")
                    or self.run_span,
                    **record)

    def _babysit(self, now: float) -> None:
        """Non-blocking slice of RunSupervisor._run_attempt: liveness off
        the (hardened) heartbeat + watched files; exits recorded; stalls
        REPORTED (status=failed/stall) rather than locally aborted — the
        abort is the leader's pod-wide decision."""
        if self._child is None or self._status != "running":
            return
        rc = self._child.poll()
        new_mtime, idx, phase = self._read_heartbeat()
        new_fp = self._watch_fingerprint()
        if new_mtime != self._hb_mtime or new_fp != self._watch_fp:
            if idx is not None:
                self._last_index = idx
            if new_mtime != self._hb_mtime:
                self._last_phase = phase
            self._hb_mtime, self._watch_fp = new_mtime, new_fp
            self._last_signal = now
            self._deadline_s = self.config.stall_timeout_s
        if rc is not None:
            self._rc = rc
            self._child = None
            self._status = "done" if rc == 0 else "failed"
            self._status_kind = None if rc == 0 else "crash"
            self._finish_attempt({"aborted": None})
            return
        if now - self._last_signal > self._deadline_s:
            # Report the wedge; keep the child for the coordinated abort.
            self._status = "failed"
            self._status_kind = "stall"
            stall_kind = self._stall_kind(
                getattr(self, "_last_phase", None))
            self._event("member_stall_detected", attempt=self._attempt,
                        stall_kind=stall_kind,
                        last_index=getattr(self, "_last_index", None))
            self._finish_attempt({"aborted": "stall",
                                  "stall_kind": stall_kind})

    def _abort_child(self, reason: str) -> None:
        if self._child is not None:
            self._rc = self._abort(self._child, reason, self._attempt)
            self._child = None
        self._status_kind = None

    # -- control consumption (member side) ---------------------------------

    def _consume_control(self, now: float) -> str | None:
        """Execute a control record newer than the last one executed.
        Returns a terminal action (``shutdown``/``give_up``) or None."""
        ctl = self._read_control()
        if not ctl or int(ctl.get("epoch", 0)) <= self._executed_epoch:
            return None
        self._executed_epoch = int(ctl["epoch"])
        action = ctl.get("action")
        # The coordinated SIGTERM -> grace -> SIGKILL: every member kills
        # its OWN child at the leader's single decision.
        self._abort_child(f"pod_{action}")
        if action in ("shutdown", "give_up"):
            return action
        members = list(ctl.get("members", ()))
        # Join the pod's trace: attempts spawned for this control parent
        # under the leader's decision span.
        if ctl.get("trace_id"):
            self.trace_id = ctl["trace_id"]
        self._pod_ctx = {
            "epoch": self._executed_epoch,
            "world": int(ctl.get("world", len(members))),
            "step": int(ctl.get("step", 0)),
            "quarantined": list(ctl.get("quarantined", ())),
            "span": ctl.get("span_id"),
        }
        if self.host in members:
            if self._respawns == 0:
                self._spawn_at = now  # first launch: no backoff
            else:
                # Jittered, state_dir-seeded backoff: pod members fan out
                # over [base, base*(1+jitter)] instead of stampeding the
                # shared filesystem in lockstep after a pod abort.
                self._spawn_at = now + self.backoff_s(
                    min(self._respawns - 1, 16))
            self._respawns += 1
            self._status, self._status_kind = "restarting", None
        else:
            self._status, self._status_kind = "evicted", None
            self._spawn_at = None
            self._ready_at = now + self.pod_config.rejoin_delay_s
        return None

    def _read_control(self) -> dict | None:
        try:
            with open(self.control_path, encoding="utf-8") as f:
                rec = json.load(f)
            return rec if isinstance(rec, dict) else None
        except (OSError, json.JSONDecodeError):
            return None

    # -- leader duties ------------------------------------------------------

    def _leader_tick(self, now: float) -> None:
        st = self.pod_state
        cfg = self.pod_config
        reports = self._read_members()
        epoch = int(st["epoch"])

        # Control self-healing: a deposed leader's stale rename can
        # clobber pod_control.json after ours; rewrite until the file
        # matches the current decision.
        last = st.get("last_control")
        if last is not None:
            ctl = self._read_control()
            if ctl != last and int(last.get("epoch", 0)) >= int(
                    (ctl or {}).get("epoch", 0)):
                _atomic_write_json(self.control_path, last)

        # Roster formation: wait for pod_size registrations.
        if not st["plan"]:
            if len(reports) >= cfg.pod_size:
                roster = sorted(reports)[: cfg.pod_size]
                st["roster"] = list(roster)
                st["plan"] = list(roster)
                if not st.get("trace_id"):
                    st["trace_id"] = _sup._mint_id(128)
                    st["root_span"] = _sup._mint_id()
                self.trace_id = st["trace_id"]
                self._pod_event("pod_start", roster=roster,
                                pod_size=cfg.pod_size,
                                elastic=cfg.elastic,
                                span_id=st["root_span"])
                self._decide_restart(now, reason="start", failed=[],
                                     spend_budget=False)
            return

    # fall through to steady-state decisions
        plan = list(st["plan"])

        # Reset per-member failure evidence on success.
        for h in plan:
            r = reports.get(h)
            if r and int(r.get("epoch", -1)) == epoch \
                    and r.get("status") == "done":
                st["failures"].pop(h, None)
                st["crash_streaks"].pop(h, None)

        # Completion: every plan member done at the current epoch.
        if plan and all(
            (r := reports.get(h)) is not None
            and int(r.get("epoch", -1)) == epoch
            and r.get("status") == "done"
            for h in plan
        ):
            self._decide_terminal(now, "shutdown", reason="complete")
            return

        # Failure sweep. Reachable failures count once per (epoch,
        # attempt); CONTINUED unreachability re-fires every
        # member_timeout_s — a permanently dead host must keep accruing
        # failures so an elastic pod reaches its eviction budget (one
        # frozen incident would stick it at 1 forever), while the pacing
        # keeps a brief partition from burning the restart budget in a
        # single poll tick.
        failures = []

        def _fresh_incident(h, incident, refire_after=None):
            prev = st["handled"].get(h)
            if not isinstance(prev, dict):  # absent (or a pre-fix string)
                prev = None
            if prev is not None and prev.get("incident") == incident and (
                    refire_after is None
                    or now - float(prev.get("t", 0)) < refire_after):
                return False
            st["handled"][h] = {"incident": incident, "t": now}
            return True

        for h in plan:
            r = reports.get(h)
            if r is None or now - float(r.get("t", 0)) > cfg.member_timeout_s:
                if _fresh_incident(h, f"stale:{(r or {}).get('t', 0)}",
                                   refire_after=cfg.member_timeout_s):
                    failures.append({"host": h, "kind": "unreachable",
                                     "last_index": (r or {}).get(
                                         "last_index")})
                continue
            if int(r.get("epoch", -1)) == epoch \
                    and r.get("status") == "failed":
                if _fresh_incident(h, f"e{epoch}:a{r.get('attempt')}"):
                    failures.append({"host": h,
                                     "kind": r.get("kind") or "crash",
                                     "last_index": r.get("last_index")})

        if failures:
            self._handle_failures(now, failures)
            return

        # Readmission: an evicted member reporting ready again rejoins at
        # the next boundary — which this decision IS.
        if cfg.elastic and st["evicted"]:
            for h in list(st["evicted"]):
                r = reports.get(h)
                if (r and r.get("status") == "ready"
                        and now - float(r.get("t", 0)) <= cfg.member_timeout_s
                        and int(st["readmits"].get(h, 0)) < cfg.readmit_budget):
                    self._readmit(now, h)
                    return

    def _handle_failures(self, now: float, failures: list[dict]) -> None:
        st = self.pod_state
        cfg = self.pod_config
        epoch = int(st["epoch"])
        for f in failures:
            self._pod_event("member_failed", failed_host=f["host"],
                            fail_kind=f["kind"],
                            last_index=f.get("last_index"), epoch=epoch)
            st["failures"][f["host"]] = int(
                st["failures"].get(f["host"], 0)) + 1
            # Pod-consistent quarantine: crash evidence only (stalls and
            # disappearances are environmental — same rule as the
            # single-host supervisor), consecutive same-index.
            if f["kind"] == "crash" and f.get("last_index") is not None:
                k = int(f["last_index"])
                streak = st["crash_streaks"].get(f["host"])
                if streak and int(streak.get("index", -1)) == k:
                    streak["count"] = int(streak["count"]) + 1
                else:
                    streak = {"index": k, "count": 1}
                st["crash_streaks"][f["host"]] = streak
                if (streak["count"] >= self.config.quarantine_after
                        and k not in st["quarantined"]):
                    st["quarantined"].append(k)
                    if len(st["quarantined"]) > _sup.QUARANTINE_CAP:
                        evicted = st["quarantined"][:-_sup.QUARANTINE_CAP]
                        st["quarantined"] = st["quarantined"][
                            -_sup.QUARANTINE_CAP:]
                        self._pod_event("pod_quarantine_evicted",
                                        evicted=evicted)
                    self._pod_event("pod_quarantine", index=k,
                                    evidence_host=f["host"],
                                    count=streak["count"])
        # Elastic eviction: failures past the per-member budget re-plan
        # the pod at W-1.
        if cfg.elastic:
            for f in failures:
                h = f["host"]
                if (h in st["plan"]
                        and int(st["failures"].get(h, 0)) >= cfg.evict_after):
                    st["plan"].remove(h)
                    if h not in st["evicted"]:
                        st["evicted"].append(h)
                    self._pod_event("member_evicted", evicted_host=h,
                                    failures=int(st["failures"][h]),
                                    world=len(st["plan"]))
        if not st["plan"]:
            self._decide_terminal(now, "give_up", reason="no_members_left")
            return
        if int(st["restarts"]) >= cfg.max_restarts:
            self._decide_terminal(now, "give_up",
                                  reason="retry_budget_exhausted")
            return
        st["restarts"] = int(st["restarts"]) + 1
        self._decide_restart(
            now, reason="failure",
            failed=[f["host"] for f in failures], spend_budget=True)

    def _common_step(self) -> int:
        """The pod-wide restart point: min over plan members' newest
        VERIFIED snapshots (0 when any member has none) — all hosts
        resume from one step, never from N different ones."""
        steps = []
        for h in self.pod_state["plan"]:
            s = latest_valid_snapshot_step(
                os.path.join(self.pod_dir, h), self._snap_cache)
            steps.append(0 if s is None else int(s))
        return min(steps) if steps else 0

    def _fence_all(self, epoch: int, step: int,
                   parent_id: str | None = None) -> None:
        """Drop the fencing epoch into EVERY roster member's checkpoint
        dir (evicted and unreachable hosts included — their orphaned
        children are exactly the writers the fence must stop). A fence
        only ever RISES: a deposed leader resuming mid-decision must not
        be able to lower the bar back to its own stale epoch."""
        for h in self.pod_state["roster"]:
            d = os.path.join(self.pod_dir, h)
            have = _child.read_fence(d) or {}
            try:
                floor = int(have.get("min_epoch", 0))
            except (TypeError, ValueError):
                floor = 0
            _child.write_fence(d, max(int(epoch), floor), step)
        self._pod_event("fence_written", min_epoch=epoch, step=step,
                        span_id=_sup._mint_id(), parent_id=parent_id,
                        hosts=list(self.pod_state["roster"]))

    def _still_leader(self) -> bool:
        """Re-verify the lease immediately before a decision lands: a
        member SIGSTOPped while leading resumes exactly where it froze,
        and this check shrinks the stale-decision window from a whole
        poll tick to a few syscalls (the fencing epoch and control
        healing cover the residual race — see docs/resilience.md)."""
        if self.lease._is_mine(self.lease.read()):
            return True
        self._pod_event("decision_abandoned", reason="lease_lost")
        self.is_leader = False
        self.pod_state = None
        return False

    def _decide_restart(self, now: float, *, reason: str,
                        failed: list[str], spend_budget: bool) -> None:
        if not self._still_leader():
            return
        st = self.pod_state
        new_epoch = int(st["epoch"]) + 1
        st["epoch"] = new_epoch
        self.lease.advance_epoch(new_epoch)
        step = self._common_step()
        # The decision's span id: rides the control record to every
        # member, whose attempt spans parent under it — ONE coordinated
        # restart = one span tree across all hosts in the exported trace.
        decision_span = _sup._mint_id()
        # Fences BEFORE the control record: by the time any member (or
        # straggler child) can see the new attempt, stale publishes are
        # already refused.
        self._fence_all(new_epoch, step, parent_id=decision_span)
        control = {
            "schema": 1,
            "action": "run",
            "epoch": new_epoch,
            "step": step,
            "members": list(st["plan"]),
            "world": len(st["plan"]),
            "quarantined": list(st["quarantined"]),
            "reason": reason,
            "trace_id": st.get("trace_id"),
            "span_id": decision_span,
            "t": time.time(),
        }
        st["attempts"].append({
            "epoch": new_epoch, "reason": reason, "failed": failed,
            "step": step, "world": len(st["plan"]), "t": time.time(),
        })
        st["last_control"] = control
        self._save_pod_state()
        _atomic_write_json(self.control_path, control)
        self._pod_event("pod_restart" if spend_budget else "pod_launch",
                        epoch=new_epoch, step=step,
                        world=len(st["plan"]), members=list(st["plan"]),
                        failed=failed, reason=reason,
                        restarts=int(st["restarts"]),
                        span_id=decision_span,
                        parent_id=st.get("root_span"),
                        quarantined=list(st["quarantined"]))

    def _readmit(self, now: float, host: str) -> None:
        """Scale back UP: sync the returning member the newest canonical
        snapshot (shared-filesystem copy from this leader's own dir — the
        elastic re-split source) and restart the pod at W+1. A FAILED
        sync defers the readmission (retried next tick, paced by the
        member timeout): admitting an unsynced member would drag the
        common restart step — and the whole pod — back to its stale
        frontier."""
        st = self.pod_state
        synced = self._sync_member(host)
        if synced is None and self._common_step() > 0:
            if now - getattr(self, "_last_readmit_defer", 0.0) \
                    > self.pod_config.member_timeout_s:
                self._last_readmit_defer = now
                self._pod_event("readmit_deferred", deferred_host=host,
                                reason="sync_failed")
            return
        st["evicted"].remove(host)
        st["plan"] = sorted(set(st["plan"]) | {host})
        st["failures"][host] = 0
        st["crash_streaks"].pop(host, None)
        st["readmits"][host] = int(st["readmits"].get(host, 0)) + 1
        st["readmissions"] = int(st["readmissions"]) + 1
        self._pod_event("member_readmitted", readmitted_host=host,
                        synced_step=synced, world=len(st["plan"]))
        self._decide_restart(now, reason="readmit", failed=[],
                             spend_budget=False)

    def _sync_member(self, host: str) -> int | None:
        """Copy a canonical snapshot into ``host``'s dir (tmp + atomic
        rename), so the returning member restores the pod's canonical
        state instead of rolling the whole pod back to its own stale
        trail. Source: the PLAN member at the pod's common frontier (the
        one whose newest verified snapshot is the pod minimum) — after
        the copy, the commanded common step exists in every member's dir,
        the leader's own (possibly evicted-stale) dir included."""
        src_host, src_step = None, None
        for h in self.pod_state["plan"]:
            s = latest_valid_snapshot_step(
                os.path.join(self.pod_dir, h), self._snap_cache)
            if s is not None and (src_step is None or s < src_step):
                src_host, src_step = h, s
        if src_step is None:
            return None
        name = f"ckpt_{src_step:012d}.npz"
        src = os.path.join(self.pod_dir, src_host, name)
        dst_dir = os.path.join(self.pod_dir, host)
        os.makedirs(dst_dir, exist_ok=True)
        dst = os.path.join(dst_dir, name)
        tmp = dst + ".sync.tmp"
        try:
            shutil.copyfile(src, tmp)
            os.replace(tmp, dst)
        except OSError:
            return None
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        self._pod_event("member_synced", synced_host=host, step=src_step)
        return src_step

    def _decide_terminal(self, now: float, action: str, *,
                         reason: str) -> None:
        if not self._still_leader():
            return
        st = self.pod_state
        new_epoch = int(st["epoch"]) + 1
        st["epoch"] = new_epoch
        self.lease.advance_epoch(new_epoch)
        if action == "give_up":
            # Terminal fence: nothing may publish after the pod gives up.
            self._fence_all(new_epoch, self._common_step())
        control = {"schema": 1, "action": action, "epoch": new_epoch,
                   "reason": reason, "t": time.time()}
        st["last_control"] = control
        self._save_pod_state()
        _atomic_write_json(self.control_path, control)
        self._pod_event(f"pod_{action}", epoch=new_epoch, reason=reason,
                        restarts=int(st["restarts"]),
                        span_id=_sup._mint_id(),
                        parent_id=st.get("root_span"),
                        quarantined=list(st["quarantined"]),
                        evicted=list(st["evicted"]))

    # -- the member loop ----------------------------------------------------

    def run(self) -> dict:
        """Run this member to pod completion (or give-up). Returns the
        member digest; ``success`` is True only when the POD shut down
        cleanly (every plan member finished)."""
        cfg = self.pod_config
        t0 = time.time()
        wall = self.config.wall_deadline_s
        deadline = t0 + wall if wall is not None else None
        startup_deadline = t0 + cfg.startup_deadline_s
        # Same span contract as RunSupervisor's supervisor_start: the
        # member's own run span must EXIST in the journal, or attempts
        # that fall back to it (no control span yet) dangle in the
        # exported tree.
        self._event("pod_member_start", pod_dir=self.pod_dir,
                    pod_size=cfg.pod_size, elastic=cfg.elastic,
                    trace_id=self.trace_id, span_id=self.run_span,
                    parent_id=self.trace_parent)
        try:
            self._write_member()
        except OSError:
            self.io_errors += 1
        terminal = None
        try:
            while terminal is None:
                now = time.time()
                held, lease_rec, seized = self.lease.tick()
                if held and not self.is_leader:
                    self.leader_terms += 1
                    self.pod_state = self._load_pod_state()
                    # Leadership (initial or seized) syncs the pod epoch
                    # to the lease's fencing epoch.
                    lease_epoch = int((lease_rec or {}).get("epoch", 0))
                    self.pod_state["epoch"] = max(
                        int(self.pod_state["epoch"]), lease_epoch)
                    self._save_pod_state()
                    # A seizing leader continues the pod's ONE trace.
                    if self.pod_state.get("trace_id"):
                        self.trace_id = self.pod_state["trace_id"]
                    # epoch 1 is the pod's very first acquisition; any
                    # higher claimed epoch means a previous holder was
                    # deposed — that is a seizure.
                    self._pod_event(
                        "lease_seized" if seized and lease_epoch > 1
                        else "lease_acquired",
                        epoch=int(self.pod_state["epoch"]),
                        span_id=_sup._mint_id(),
                        parent_id=self.pod_state.get("root_span"),
                        term=self.leader_terms)
                elif not held and self.is_leader:
                    self._pod_event("lease_lost",
                                    holder=(lease_rec or {}).get("host"))
                    self.pod_state = None
                self.is_leader = held

                if self.is_leader:
                    try:
                        if deadline is not None and now >= deadline:
                            self._decide_terminal(now, "give_up",
                                                  reason="wall_deadline")
                        elif (not self.pod_state["plan"]
                              and now >= startup_deadline):
                            self._decide_terminal(
                                now, "give_up",
                                reason="startup_deadline")
                        else:
                            self._leader_tick(now)
                    except OSError as e:
                        # Transient shared-filesystem failure mid-
                        # decision: every leader write is either
                        # idempotent (fences, beacons) or self-healing
                        # (pod_control rewrites from last_control each
                        # tick), so the safe move is to log, count, and
                        # retry the whole tick — never to crash the
                        # agent and orphan its child.
                        self.io_errors += 1
                        self._pod_event("leader_io_error",
                                        error=repr(e))

                terminal = self._consume_control(now)
                if terminal is None:
                    if (self._status == "restarting"
                            and self._spawn_at is not None
                            and now >= self._spawn_at):
                        self._spawn_at = None
                        self._spawn_child(now)
                    self._babysit(now)
                    if (self._status == "evicted"
                            and self._ready_at is not None
                            and now >= self._ready_at):
                        self._status = "ready"
                try:
                    self._write_member()
                except OSError:
                    # A failed beacon is one stale liveness sample — the
                    # leader's pacing tolerates it; retried next tick.
                    self.io_errors += 1
                if terminal is None:
                    # Non-leader failsafe: a member must not outlive the
                    # pod wall deadline even if no leader ever emerges.
                    if deadline is not None and now >= deadline + max(
                            cfg.lease_ttl_s * 4, 10.0):
                        terminal = "give_up"
                        break
                    time.sleep(self.config.poll_interval_s)
        finally:
            self._abort_child("pod_member_exit")
            try:
                self._write_member()
            except OSError:
                pass  # exiting anyway; the beacon just goes stale
        success = terminal == "shutdown"
        pod = self._load_pod_state()
        digest = {
            "success": success,
            "host": self.host,
            "action": terminal,
            "attempts": self._attempt + 1,
            "leader_terms": self.leader_terms,
            "epoch": int(pod.get("epoch", 0)),
            "pod": {
                "restarts": int(pod.get("restarts", 0)),
                "readmissions": int(pod.get("readmissions", 0)),
                "quarantined": list(pod.get("quarantined", ())),
                "evicted": list(pod.get("evicted", ())),
                "plan": list(pod.get("plan", ())),
                "world": len(pod.get("plan", ())),
            },
            "heartbeat_rejected": int(
                self.state.get("heartbeat_rejected", 0)),
            "wall_s": round(time.time() - t0, 3),
            "state_path": self.state_path,
            "pod_state_path": self.pod_state_path,
        }
        self._event("pod_member_end", trace_id=self.trace_id,
                    span_id=self.run_span, **{
                        k: v for k, v in digest.items()
                        if k not in ("state_path", "pod_state_path")})
        return digest
