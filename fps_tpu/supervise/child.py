"""Child-process side of a supervised run: heartbeat + carried quarantine.

A training process launched by :class:`fps_tpu.supervise.RunSupervisor`
finds its contract in environment variables:

* :data:`HEARTBEAT_ENV` — path of the heartbeat file this process should
  touch on every progress boundary (the supervisor's liveness signal; a
  stalled heartbeat is what triggers deadline-abort);
* :data:`STATE_ENV` — path of the supervisor's persisted state file,
  holding among other things the chunk/epoch indices quarantined by
  PREVIOUS attempts (:func:`quarantined_from_env` feeds them into
  ``RollbackPolicy(preset=...)`` so a deterministic poison batch cannot
  crash-loop the run);
* :data:`ATTEMPT_ENV` — zero-based attempt number, for logging.

Everything here is stdlib-only and import-safe without jax: the same file
is loaded by path from ``tools/supervise.py`` (which must never drag a
TPU runtime into the supervisor process) and imported normally by
training children (which already run jax).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

HEARTBEAT_ENV = "FPS_TPU_HEARTBEAT"
STATE_ENV = "FPS_TPU_SUPERVISOR_STATE"
ATTEMPT_ENV = "FPS_TPU_ATTEMPT"

# Pod membership contract (fps_tpu/supervise/pod.py sets these when the
# child runs under a pod coordinator; all absent in plain supervised runs):
# the member host name this child belongs to, the fencing epoch of the pod
# attempt that spawned it, the pod world size (live member count), and the
# pod-commanded common restart step.
POD_HOST_ENV = "FPS_TPU_POD_HOST"
POD_EPOCH_ENV = "FPS_TPU_POD_EPOCH"
POD_WORLD_ENV = "FPS_TPU_POD_WORLD"
POD_STEP_ENV = "FPS_TPU_POD_STEP"

# Causal-tracing contract (fps_tpu/obs/trace.py is the canonical doc;
# mirrored here because this module must stay loadable without the
# package): the trace id of the run/pod this child belongs to, and the
# span id of the supervisor ATTEMPT that spawned it — the child's run
# journal (obs.open_run) links its own spans under that parent, so one
# exported trace connects leader decision -> member attempt -> chunk.
TRACE_ID_ENV = "FPS_TPU_TRACE_ID"
PARENT_SPAN_ENV = "FPS_TPU_PARENT_SPAN"


def trace_from_env() -> dict:
    """The tracing contract from the environment: ``{"trace_id",
    "parent_id"}`` with Nones when untraced."""
    return {
        "trace_id": os.environ.get(TRACE_ID_ENV) or None,
        "parent_id": os.environ.get(PARENT_SPAN_ENV) or None,
    }

# Heartbeat schema version, written into every beat. The supervisor
# rejects beats wearing an unknown version (or a foreign ``host``) loudly
# instead of silently misparsing them — the cross-host beat-file
# collision a shared pod directory makes possible.
HEARTBEAT_VERSION = 2

# Fence file a pod leader drops into a member's CHECKPOINT dir before
# commanding a new attempt: a writer whose own epoch is below
# ``min_epoch`` must refuse to publish (fps_tpu.core.checkpoint checks it
# before every atomic rename). Lives here — not in the checkpoint layer —
# because both sides of the pod contract (the stdlib-only coordinator and
# the jax-laden child) must share one definition, and this module is the
# one both can load.
FENCE_FILENAME = "pod_fence.json"


class StaleEpochError(RuntimeError):
    """A checkpoint publish was refused because the writer's fencing
    epoch predates the pod fence — the writer belongs to an attempt the
    pod has already aborted and restarted past."""


def read_fence(directory: str) -> dict | None:
    """The pod fence in ``directory`` (``{"min_epoch": E, "step": S}``),
    or None when the dir is unfenced / the fence is torn (an unreadable
    fence must not brick an unsupervised run)."""
    try:
        with open(os.path.join(directory, FENCE_FILENAME),
                  encoding="utf-8") as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def write_fence(directory: str, min_epoch: int, step: int) -> None:
    """Atomically publish the fence (tmp + rename, same dir)."""
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".fence.tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump({"min_epoch": int(min_epoch), "step": int(step)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(directory, FENCE_FILENAME))
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def fence_allows(directory: str, epoch: int | None) -> tuple[bool, int]:
    """Whether a writer with fencing epoch ``epoch`` may publish into
    ``directory``. Returns ``(allowed, min_epoch)``. An unfenced dir
    allows everyone; a fenced dir refuses writers with no epoch at all
    (``epoch=None``) — a writer that predates the pod contract must not
    publish into a pod-managed dir."""
    fence = read_fence(directory)
    if fence is None:
        return True, 0
    try:
        min_epoch = int(fence.get("min_epoch", 0))
    except (TypeError, ValueError):
        return True, 0
    if epoch is None:
        return False, min_epoch
    return int(epoch) >= min_epoch, min_epoch


def pod_env() -> dict:
    """The pod contract from the environment: ``{"host", "epoch",
    "world", "step"}`` with Nones when unsupervised/un-podded."""

    def _int(name):
        v = os.environ.get(name)
        try:
            return int(v) if v not in (None, "") else None
        except ValueError:
            return None

    return {
        "host": os.environ.get(POD_HOST_ENV) or None,
        "epoch": _int(POD_EPOCH_ENV),
        "world": _int(POD_WORLD_ENV),
        "step": _int(POD_STEP_ENV),
    }


class Heartbeat:
    """Progress beacon: one small JSON object, atomically replaced.

    The supervisor keys liveness off the file's mtime and reads ``index``
    to localize where an attempt died (two consecutive deaths at the same
    index quarantine it). Atomic replace (tmp + rename in the same
    directory) so the supervisor never reads a torn beat.

    Extra keyword fields ride the beat verbatim; the drivers use
    ``phase`` (``prefetch`` / ``ingest`` / ``dispatch``) to beat at
    SUB-chunk boundaries, so the supervisor's attempt records name the
    sub-phase a death between chunk boundaries happened in
    (``last_phase`` in ``supervisor_state.json``).
    """

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._dir = d

    def beat(self, index: int | None = None, **fields) -> None:
        rec = {
            "version": HEARTBEAT_VERSION,
            "t": time.time(),
            "pid": os.getpid(),
            # The pod member this beat belongs to (None outside pods):
            # in a shared pod dir a misrouted heartbeat path would
            # otherwise let host A's beats keep host B's supervisor
            # believing its child is alive.
            "host": os.environ.get(POD_HOST_ENV) or None,
            "index": index,
        }
        rec.update(fields)
        fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".hb.tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def on_chunk(self, inner=None):
        """An ``on_chunk``/``on_epoch`` callback that beats and then
        forwards to ``inner`` when given. Beats ``i + 1`` — the index
        about to be attempted — so a death inside the NEXT chunk
        attributes to that chunk on every attempt (the supervisor's
        quarantine keys on the last indexed beat; see
        :class:`HeartbeatSink`)."""

        def cb(i, metrics):
            self.beat(index=int(i) + 1)
            if inner is not None:
                inner(i, metrics)

        return cb


class HeartbeatSink:
    """obs sink adapter: beats on run_start / chunk / epoch events.

    Duck-typed against :class:`fps_tpu.obs.sinks.Sink` (write/flush/close)
    so this module stays importable without the obs package. Attach it to
    the run's Recorder and every chunk/epoch journal event doubles as a
    liveness signal — no per-example callback wiring needed.

    The beat carries the index ABOUT TO BE ATTEMPTED (chunk event ``i``
    → beat ``i + 1``), not the one just finished: the supervisor
    quarantines the index a crashing child was last working on, and a
    crash MID-chunk ``i`` must attribute to ``i``, which only the
    beat-before-work convention gives (the last indexed beat before the
    death names the doomed chunk on every attempt). Children that resume
    mid-stream and want exact attribution beat directly at chunk start
    (the ``Heartbeat.on_chunk`` / supervised_demo pattern).
    """

    def __init__(self, heartbeat: Heartbeat):
        self.heartbeat = heartbeat

    def write(self, record: dict) -> None:
        if record.get("kind") != "event":
            return
        if record.get("event") in ("run_start", "chunk", "epoch"):
            idx = record.get("index")
            self.heartbeat.beat(index=None if idx is None else int(idx) + 1)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def from_env() -> Heartbeat | None:
    """The supervisor-provided heartbeat, or None when unsupervised."""
    path = os.environ.get(HEARTBEAT_ENV)
    return Heartbeat(path) if path else None


def attempt_from_env() -> int:
    try:
        return int(os.environ.get(ATTEMPT_ENV, "0"))
    except ValueError:
        return 0


def read_state(path: str) -> dict:
    """The supervisor's persisted state ({} when absent/unreadable — a
    child must start rather than crash on a torn state file)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def quarantined_from_env() -> frozenset[int]:
    """Chunk/epoch indices quarantined by previous attempts (empty when
    unsupervised) — feed into ``RollbackPolicy(preset=...)``."""
    path = os.environ.get(STATE_ENV)
    if not path:
        return frozenset()
    state = read_state(path)
    try:
        return frozenset(int(i) for i in state.get("quarantined", ()))
    except (TypeError, ValueError):
        return frozenset()
