"""External run supervisor: deadline-abort, retry/backoff, quarantine.

The ROADMAP straggler item PR 2 left open: ``StepWatchdog`` can *flag* a
stalled chunk, but Python cannot preempt a thread wedged in a collective
— the training process itself has no lever left. Deadline-ABORT therefore
lives one level up, in a separate OS process that:

* launches the training run as a child (its own session/process group);
* tails a **heartbeat file** (:mod:`fps_tpu.supervise.child`) and/or the
  run's obs journal files as the liveness signal;
* on a stalled signal or an exhausted wall-clock budget, aborts the child
  with **SIGTERM → (grace) → SIGKILL** against the whole process group —
  SIGKILL is the only signal a group wedged in a collective (or SIGSTOP'd
  outright) cannot ignore;
* restarts the child with **exponential backoff** under a bounded retry
  budget — the child finds ``latest_valid_step`` in its checkpoint dir
  and resumes (the framework's existing kill-resume contract);
* **quarantines deterministic poison**: when consecutive attempts die at
  the same progress index, that index is recorded in a state file
  persisted next to the checkpoint dir and exported to the next attempt
  (``RollbackPolicy(preset=...)`` skips it), so a poison batch that
  crashes the worker cannot crash-loop the run.

Stdlib-only by contract: the supervisor must run on a login node (or
wrap a TPU job) without importing jax — ``tools/supervise.py`` loads this
module by file path for exactly that reason. Every decision is journaled
(JSONL, one fsync'd line per event) so ``tools/obs_report.py`` can fold
the supervisor's narrative into the run digest.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import hashlib
import json
import os
import signal
import subprocess
import tempfile
import time

STATE_FILENAME = "supervisor_state.json"
HEARTBEAT_FILENAME = "heartbeat.json"
JOURNAL_FILENAME = "journal-supervisor.jsonl"

# Child env contract — MIRRORED in fps_tpu/supervise/child.py (which the
# training child imports), because this module must stay loadable by file
# path with zero fps_tpu imports (a package import would drag jax into
# the supervisor process). tests/test_supervise.py asserts they match.
HEARTBEAT_ENV = "FPS_TPU_HEARTBEAT"
STATE_ENV = "FPS_TPU_SUPERVISOR_STATE"
ATTEMPT_ENV = "FPS_TPU_ATTEMPT"

# Causal-tracing contract — mirrored from fps_tpu/obs/trace.py /
# fps_tpu/supervise/child.py (same loadable-by-path reason as above;
# tests/test_trace.py asserts the mirrors match). The supervisor stamps
# trace_id/span_id/parent_id on its attempt_start/attempt_end journal
# events and hands each child its attempt's span id, so the child's run
# journal links under the attempt and tools/trace_export.py can render
# the whole supervised run as one span tree.
TRACE_ID_ENV = "FPS_TPU_TRACE_ID"
PARENT_SPAN_ENV = "FPS_TPU_PARENT_SPAN"


def _mint_id(bits: int = 64) -> str:
    """A fresh random hex id (uuid4-backed, stdlib-only)."""
    import uuid

    return uuid.uuid4().hex[: bits // 4]

# Heartbeat schema this supervisor understands — mirrored from child.py
# (same loadable-by-path reason as the env contract above). Beats wearing
# any other version are rejected loudly, never misparsed.
HEARTBEAT_VERSION = 2

# supervisor_state.json schema. Version-less files are the v1 layout
# (every field this loader defaults); a FUTURE version means a newer
# supervisor owns this state dir and silently reinterpreting its file
# could un-quarantine poison — refuse loudly instead.
STATE_SCHEMA_VERSION = 2

# The quarantine list is append-only evidence; a long pod run hitting a
# drifting poison source could otherwise grow it without bound (and the
# state file with it, rewritten every attempt). Oldest entries evict
# first — they describe chunks the run has long replayed past.
QUARANTINE_CAP = 256


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Deadline/retry policy knobs.

    ``stall_timeout_s`` is the liveness deadline BETWEEN progress signals
    (heartbeat mtime change or watched-file growth); ``startup_grace_s``
    replaces it for the FIRST signal of each attempt, because a cold
    start pays interpreter + jax import + XLA compile before the first
    chunk can possibly beat (None: use ``stall_timeout_s``).
    ``wall_deadline_s`` bounds the whole supervised run across attempts
    and backoffs (None: unbounded). ``max_restarts`` is the retry budget
    — the first launch is free, every relaunch spends one.
    ``quarantine_after`` consecutive failures at the same progress index
    quarantine that index (persisted, exported to later attempts).
    """

    stall_timeout_s: float = 120.0
    startup_grace_s: float | None = None
    wall_deadline_s: float | None = None
    max_restarts: int = 3
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    # Bounded jitter fraction applied by RunSupervisor.backoff_s on top
    # of the exponential schedule: each backoff lands in
    # [base, base * (1 + jitter)], deterministically derived from the
    # supervisor's state_dir — N hosts of a pod restarting after a
    # coordinated abort then hit the shared filesystem desynchronized
    # instead of in lockstep, while any ONE host's schedule stays exactly
    # reproducible across reruns.
    backoff_jitter: float = 0.25
    term_grace_s: float = 5.0
    poll_interval_s: float = 0.25
    quarantine_after: int = 2

    def __post_init__(self):
        if not self.stall_timeout_s > 0:
            raise ValueError(
                f"stall_timeout_s must be > 0, got {self.stall_timeout_s}")
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {self.max_restarts}")
        if self.quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got {self.quarantine_after}")
        if not 0 <= self.backoff_jitter <= 1:
            raise ValueError(
                f"backoff_jitter must be in [0, 1], got {self.backoff_jitter}")

    def backoff_s(self, restart: int) -> float:
        """Deterministic exponential backoff before relaunch ``restart``
        (0-based): base * factor**restart, capped. Jitter-free — the
        per-host jittered schedule is :meth:`RunSupervisor.backoff_s`."""
        return min(self.backoff_max_s,
                   self.backoff_base_s * self.backoff_factor ** restart)


def _atomic_write_json(path: str, obj: dict) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(obj, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


class RunSupervisor:
    """Supervise one training command to completion or exhaustion.

    Args:
      cmd: argv of the training child — identical every attempt; the
        child derives per-attempt behavior from its checkpoint dir
        (``latest_valid_step`` resume) and the exported env contract
        (:mod:`fps_tpu.supervise.child`).
      state_dir: where the supervisor persists its state file, heartbeat,
        journal, and per-attempt child logs — conventionally the
        checkpoint dir itself or a sibling, so quarantine decisions live
        (and survive) next to the snapshots they protect.
      config: the :class:`SupervisorConfig` policy.
      watch: extra glob patterns whose matched files' growth also counts
        as liveness (point one at ``<obs-dir>/journal-p*.jsonl`` and the
        run journal's per-boundary flushes become the signal, heartbeat
        or no heartbeat).
      env: extra environment for the child (merged over os.environ; the
        heartbeat/state/attempt contract vars are always set on top).
      cwd: child working directory.
    """

    def __init__(self, cmd: list[str], *, state_dir: str,
                 config: SupervisorConfig | None = None,
                 watch: tuple[str, ...] = (),
                 env: dict | None = None, cwd: str | None = None,
                 host: str | None = None):
        self.cmd = list(cmd)
        self.config = config or SupervisorConfig()
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self.state_path = os.path.join(state_dir, STATE_FILENAME)
        self.heartbeat_path = os.path.join(state_dir, HEARTBEAT_FILENAME)
        self.journal_path = os.path.join(state_dir, JOURNAL_FILENAME)
        self.watch = tuple(watch)
        self.env = dict(env or {})
        self.cwd = cwd
        # Pod member identity: when set, only beats carrying this host
        # (or none) count — a beat from another pod member's child that
        # lands in this file by misconfiguration is rejected loudly.
        self.host = host
        # (mtime) of beats already reported bad — one loud event per
        # distinct rejected beat, not one per poll.
        self._rejected_beats: set = set()
        # Causal tracing: inherit the trace from the environment (a pod
        # member re-inherits the pod's trace via the control record) or
        # mint a fresh one; the supervisor's own run is a span under the
        # inherited parent, and every attempt is a span under that.
        self.trace_id = os.environ.get(TRACE_ID_ENV) or _mint_id(128)
        self.trace_parent = os.environ.get(PARENT_SPAN_ENV) or None
        self.run_span = _mint_id()
        self._attempt_span = None  # minted per attempt, pre-spawn
        self.state = self._load_state()

    def backoff_s(self, restart: int) -> float:
        """The per-host jittered backoff schedule: the config's
        exponential base stretched by a bounded factor in
        ``[1, 1 + backoff_jitter]`` derived deterministically from
        ``(state_dir, restart)``. Same state_dir ⇒ the exact same
        schedule on every rerun (replayable chaos tests); different
        state_dirs (= different pod members) ⇒ desynchronized restarts
        after a pod-wide abort."""
        base = self.config.backoff_s(restart)
        if not self.config.backoff_jitter:
            return base
        seed = f"{os.path.abspath(self.state_dir)}:{restart}".encode()
        u = int.from_bytes(hashlib.sha256(seed).digest()[:8], "big")
        u /= float(1 << 64)  # [0, 1)
        return base * (1.0 + self.config.backoff_jitter * u)

    # -- persisted state ---------------------------------------------------

    def _load_state(self) -> dict:
        try:
            with open(self.state_path, encoding="utf-8") as f:
                state = json.load(f)
        except (OSError, json.JSONDecodeError):
            state = {}
        # Migration guard: version-less files are the v1 layout, loadable
        # by defaulting every newer field (below). A FUTURE schema means a
        # newer supervisor owns this dir — reinterpreting its fields here
        # could silently drop quarantine evidence, so refuse loudly.
        found = int(state.get("schema", 1))
        if found > STATE_SCHEMA_VERSION:
            raise ValueError(
                f"{self.state_path} has schema v{found}, this supervisor "
                f"understands <= v{STATE_SCHEMA_VERSION} — refusing to "
                "reinterpret a newer supervisor's state"
            )
        state["schema"] = STATE_SCHEMA_VERSION
        state.setdefault("restarts", 0)
        state.setdefault("quarantined", [])
        state.setdefault("attempts", [])
        state.setdefault("heartbeat_rejected", 0)
        return state

    def _save_state(self) -> None:
        _atomic_write_json(self.state_path, self.state)

    def _cap_quarantine(self) -> None:
        """Bound the quarantine list at :data:`QUARANTINE_CAP`, evicting
        OLDEST-first (append order): ancient entries describe chunks the
        run replayed past long ago, while the newest entries are the ones
        protecting the current resume window."""
        q = self.state["quarantined"]
        if len(q) <= QUARANTINE_CAP:
            return
        evicted, self.state["quarantined"] = q[:-QUARANTINE_CAP], q[-QUARANTINE_CAP:]
        self._event("quarantine_evicted", evicted=evicted,
                    cap=QUARANTINE_CAP)

    def _event(self, etype: str, **fields) -> None:
        rec = {"kind": "event", "t": time.time(), "event": etype, **fields}
        with open(self.journal_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    # -- liveness ----------------------------------------------------------

    def _read_heartbeat(self):
        """(mtime, index, phase) of the heartbeat file, or three Nones.

        ``phase`` is the optional sub-chunk boundary the child last
        crossed (the drivers beat ``prefetch``/``ingest``/``dispatch``
        between chunk boundaries) — it sharpens where an attempt died
        without changing the index-keyed quarantine logic.

        Schema hardening: a beat wearing an unknown ``version``, or a
        ``host`` other than this supervisor's (a cross-host collision in
        a shared pod dir), is REJECTED — one loud ``heartbeat_rejected``
        journal event + persisted counter per distinct beat — and never
        counts as liveness or progress."""
        try:
            mtime = os.path.getmtime(self.heartbeat_path)
            with open(self.heartbeat_path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None, None, None
        reason = None
        version = rec.get("version") if isinstance(rec, dict) else None
        if not isinstance(rec, dict) or version != HEARTBEAT_VERSION:
            reason = f"unknown heartbeat version {version!r}"
        elif self.host is not None and rec.get("host") not in (None,
                                                               self.host):
            reason = (f"beat from host {rec.get('host')!r}, "
                      f"this supervisor is {self.host!r}")
        if reason is not None:
            if mtime not in self._rejected_beats:
                if len(self._rejected_beats) > 512:
                    self._rejected_beats.clear()  # bound the dedupe memory
                self._rejected_beats.add(mtime)
                self.state["heartbeat_rejected"] = (
                    int(self.state.get("heartbeat_rejected", 0)) + 1)
                self._save_state()
                self._event("heartbeat_rejected", reason=reason,
                            path=self.heartbeat_path,
                            beat={k: rec.get(k) for k in
                                  ("version", "host", "index", "pid")}
                            if isinstance(rec, dict) else None)
            return None, None, None
        return mtime, rec.get("index"), rec.get("phase")

    def _watch_fingerprint(self):
        """Size+mtime fingerprint over the watched globs — any change in
        the run's journal/event files counts as life."""
        fp = []
        for pattern in self.watch:
            for path in sorted(_glob.glob(pattern)):
                try:
                    st = os.stat(path)
                    fp.append((path, st.st_size, st.st_mtime))
                except OSError:
                    continue
        return tuple(fp)

    # -- child control -----------------------------------------------------

    def _child_env(self, attempt: int) -> dict:
        """Environment for one attempt — subclass hook (the pod member
        adds the pod membership contract on top)."""
        env = dict(os.environ)
        env.update(self.env)
        env[HEARTBEAT_ENV] = self.heartbeat_path
        env[STATE_ENV] = self.state_path
        env[ATTEMPT_ENV] = str(attempt)
        # The child's spans parent under THIS attempt's span.
        env[TRACE_ID_ENV] = self.trace_id
        env[PARENT_SPAN_ENV] = self._attempt_span or self.run_span
        return env

    def _child_cmd(self) -> list[str]:
        """argv for one attempt — subclass hook (the pod member
        substitutes its host name into path templates)."""
        return list(self.cmd)

    def _spawn(self, attempt: int, log_path: str) -> subprocess.Popen:
        logf = open(log_path, "ab")
        try:
            # Own session => own process group: the TERM/KILL escalation
            # reaches every thread/grandchild, not just the leader.
            return subprocess.Popen(
                self._child_cmd(), env=self._child_env(attempt),
                cwd=self.cwd, stdout=logf,
                stderr=subprocess.STDOUT, start_new_session=True,
            )
        finally:
            logf.close()  # the child holds its own fd now

    def _signal_group(self, proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def _abort(self, proc: subprocess.Popen, reason: str,
               attempt: int, stall_kind: str | None = None) -> int:
        """TERM → grace → KILL escalation against the child's group.

        SIGTERM gives a healthy-but-slow child its atexit/flush; a child
        wedged in a collective — or SIGSTOP'd, which *queues* SIGTERM
        until continued — only dies to the SIGKILL. ``stall_kind``
        classifies a stall abort (see :meth:`_stall_kind`) and rides the
        journal event so ``tools/obs_report.py`` can count source stalls
        separately. Returns the reaped returncode."""
        fields = {}
        if stall_kind is not None:
            fields["stall_kind"] = stall_kind
        self._event("deadline_abort", attempt=attempt, reason=reason,
                    pid=proc.pid, term_grace_s=self.config.term_grace_s,
                    **fields)
        self._signal_group(proc, signal.SIGTERM)
        deadline = time.monotonic() + self.config.term_grace_s
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(min(0.05, self.config.poll_interval_s))
        if proc.poll() is None:
            self._signal_group(proc, signal.SIGKILL)
        proc.wait()
        return proc.returncode

    @staticmethod
    def _stall_kind(last_phase) -> str:
        """Classify a stall abort by the child's last sub-phase beat.

        A heartbeat frozen in the ``prefetch`` phase means the driver
        was healthy and WAITING on the ingest source (the prefetch
        worker / chunk iterator) when progress stopped — a wedged
        SOURCE, not a wedged driver. Keeping the two apart matters for
        response: a source stall points at the data pipeline (filesystem,
        generator, upstream service) while a driver stall points at the
        device/collective path. Neither kind quarantines (stalls are
        environmental, never poison evidence — see
        :meth:`_maybe_quarantine`)."""
        return "source_stall" if last_phase == "prefetch" else "driver_stall"

    # -- one attempt -------------------------------------------------------

    def _run_attempt(self, attempt: int, run_deadline: float | None) -> dict:
        """Launch + babysit one attempt. Returns the attempt record:
        ``{"attempt", "rc", "aborted": None|"stall"|"wall_deadline",
        "last_index", "runtime_s", "log"}``."""
        cfg = self.config
        log_path = os.path.join(self.state_dir, f"attempt-{attempt}.log")
        # A stale heartbeat from the previous attempt must not count as
        # this attempt's first signal.
        try:
            os.remove(self.heartbeat_path)
        except OSError:
            pass
        t0 = time.monotonic()
        self._attempt_span = _mint_id()
        proc = self._spawn(attempt, log_path)
        self._event("attempt_start", attempt=attempt, pid=proc.pid,
                    cmd=self.cmd,
                    trace_id=self.trace_id, span_id=self._attempt_span,
                    parent_id=self.run_span,
                    quarantined=list(self.state["quarantined"]))
        last_signal = t0
        deadline_s = (cfg.startup_grace_s if cfg.startup_grace_s is not None
                      else cfg.stall_timeout_s)
        hb_mtime, last_index, last_phase = self._read_heartbeat()
        watch_fp = self._watch_fingerprint()
        aborted = None
        stall_kind = None
        first_signal_seen = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            now = time.monotonic()
            new_mtime, idx, phase = self._read_heartbeat()
            new_fp = self._watch_fingerprint()
            if new_mtime != hb_mtime or new_fp != watch_fp:
                # Index on ANY fresh signal (it is monotone, and coarse
                # filesystem mtimes can hide a fresh beat behind an
                # unchanged mtime); phase only on a definitely-fresh
                # beat, taken verbatim, None included — a boundary beat
                # with no phase field must CLEAR a stale sub-phase, or a
                # later death in e.g. a chunk callback would be
                # attributed to the previous beat's 'dispatch'.
                if idx is not None:
                    last_index = idx
                if new_mtime != hb_mtime:
                    last_phase = phase
                hb_mtime, watch_fp = new_mtime, new_fp
                last_signal = now
                deadline_s = cfg.stall_timeout_s  # startup grace spent
                if not first_signal_seen:
                    # The attempt's FIRST liveness signal — the moment a
                    # restarted child demonstrably dispatches again.
                    # recovery_times() pairs this with the previous
                    # attempt_end to measure time_to_recovered_s
                    # (kill -> first post-restart dispatch), the MTTR
                    # figure the chaos sweep digests record.
                    first_signal_seen = True
                    self._event("attempt_first_signal", attempt=attempt,
                                t_rel_s=round(now - t0, 3),
                                trace_id=self.trace_id,
                                span_id=self._attempt_span,
                                parent_id=self.run_span)
            if run_deadline is not None and now >= run_deadline:
                rc = self._abort(proc, "wall_deadline", attempt)
                aborted = "wall_deadline"
                break
            if now - last_signal > deadline_s:
                stall_kind = self._stall_kind(last_phase)
                rc = self._abort(proc, "stall", attempt,
                                 stall_kind=stall_kind)
                aborted = "stall"
                break
            time.sleep(cfg.poll_interval_s)
        # Catch a final beat that landed between the last poll and exit.
        final_mtime, idx, phase = self._read_heartbeat()
        if idx is not None:
            last_index = idx
        if final_mtime != hb_mtime:
            last_phase = phase  # fresh beat: take its phase, None included
        record = {
            "attempt": attempt,
            "rc": rc,
            "aborted": aborted,
            "last_index": last_index,
            # The sub-chunk boundary the child last crossed (prefetch /
            # ingest / dispatch) — a death BETWEEN chunk boundaries now
            # attributes to the right sub-phase in the persisted state.
            "last_phase": last_phase,
            # Stall aborts only: "source_stall" when the last beat was
            # the driver WAITING ON THE SOURCE (prefetch phase) — the
            # wedged-ingest incident the ROADMAP item called out as
            # indistinguishable from a wedged driver; "driver_stall"
            # otherwise.
            "stall_kind": stall_kind,
            "runtime_s": round(time.monotonic() - t0, 3),
            "log": log_path,
        }
        self._event("attempt_end", trace_id=self.trace_id,
                    span_id=self._attempt_span, parent_id=self.run_span,
                    **record)
        return record

    # -- the supervision loop ----------------------------------------------

    def run(self) -> dict:
        """Supervise to completion. Returns the digest dict (also what
        ``tools/supervise.py`` prints): success, attempts, restarts,
        deadline aborts, quarantined indices, give-up reason."""
        cfg = self.config
        t0 = time.monotonic()
        run_deadline = (t0 + cfg.wall_deadline_s
                        if cfg.wall_deadline_s is not None else None)
        self._event("supervisor_start", cmd=self.cmd,
                    state_path=self.state_path,
                    trace_id=self.trace_id, span_id=self.run_span,
                    parent_id=self.trace_parent,
                    config=dataclasses.asdict(cfg))
        attempt = len(self.state["attempts"])
        restarts_this_run = 0
        reason = None
        success = False
        while True:
            record = self._run_attempt(attempt, run_deadline)
            self.state["attempts"].append(record)
            self._save_state()
            if record["rc"] == 0 and record["aborted"] is None:
                # rc alone is not success: a SIGTERM-trapping child may
                # exit 0 from its graceful-shutdown handler after a
                # deadline abort — that run is still incomplete.
                success = True
                break
            self._maybe_quarantine(record)
            if record["aborted"] == "wall_deadline":
                reason = "wall_deadline"
                break
            if restarts_this_run >= cfg.max_restarts:
                reason = "retry_budget_exhausted"
                self._event("supervisor_give_up", attempts=attempt + 1,
                            restarts=restarts_this_run, reason=reason)
                break
            backoff = self.backoff_s(restarts_this_run)
            if run_deadline is not None and (
                    time.monotonic() + backoff >= run_deadline):
                reason = "wall_deadline"
                break
            self._event("supervisor_restart", attempt=attempt + 1,
                        backoff_s=backoff,
                        restarts=restarts_this_run + 1)
            time.sleep(backoff)
            restarts_this_run += 1
            self.state["restarts"] = int(self.state["restarts"]) + 1
            self._save_state()
            attempt += 1
        attempts = self.state["attempts"]
        digest = {
            "success": success,
            "reason": reason,
            "attempts": len(attempts),
            "restarts": int(self.state["restarts"]),
            "deadline_aborts": sum(
                1 for a in attempts if a.get("aborted") == "stall"),
            "source_stalls": sum(
                1 for a in attempts
                if a.get("stall_kind") == "source_stall"),
            "wall_deadline_hit": any(
                a.get("aborted") == "wall_deadline" for a in attempts),
            "quarantined": list(self.state["quarantined"]),
            "heartbeat_rejected": int(
                self.state.get("heartbeat_rejected", 0)),
            "last_index": attempts[-1].get("last_index") if attempts else None,
            "wall_s": round(time.monotonic() - t0, 3),
            # Restart-to-first-signal seconds per recovery (the chaos
            # digest's restart-cost evidence: with a persistent
            # JAX compilation cache the retrace disappears from this
            # number; without one every restart pays it again).
            "restart_to_first_signal_s": [
                round(v, 3)
                for v in recovery_times(self.journal_path)],
            "state_path": self.state_path,
            "journal_path": self.journal_path,
        }
        self._event("supervised_run_end", trace_id=self.trace_id,
                    span_id=self.run_span, **{
                        k: v for k, v in digest.items()
                        if k != "journal_path"})
        return digest

    def _maybe_quarantine(self, record: dict) -> None:
        """Two (``quarantine_after``) consecutive CRASH failures at the
        same progress index mark that index poisoned: persist it and
        export it to the next attempt (the child's RollbackPolicy preset
        skips it). Index None (died before any beat) never quarantines —
        there is nothing addressable to skip. Deadline-ABORTED attempts
        are not evidence either: a shared-filesystem hiccup stalling the
        same chunk twice is environmental, and quarantining it would
        silently drop healthy training data (the failure model scopes
        quarantine to deterministic poison that CRASHES the worker)."""
        idx = record.get("last_index")
        if idx is None or record.get("aborted") is not None:
            return
        # Only the CONSECUTIVE trailing crash failures count: the
        # persisted attempt history spans supervisor invocations, and two
        # transient deaths at the same index with a fully successful run
        # between them are coincidence, not determinism — a success
        # resets the evidence (an interleaved stall-abort neither counts
        # nor resets).
        tail = []
        for a in reversed(self.state["attempts"]):
            if a.get("rc") == 0 and a.get("aborted") is None:
                break
            if a.get("aborted") is None:
                tail.append(a)
        tail = tail[:self.config.quarantine_after]
        if (len(tail) >= self.config.quarantine_after
                and all(a.get("last_index") == idx for a in tail)
                and idx not in self.state["quarantined"]):
            self.state["quarantined"].append(int(idx))
            self._cap_quarantine()
            self._save_state()
            self._event("chunk_quarantined", index=int(idx),
                        after_attempts=len(tail),
                        phase=record.get("last_phase"))


def recovery_times(journal_path: str) -> list[float]:
    """``time_to_recovered_s`` per restart from one supervisor journal:
    for every attempt k+1 that produced a first liveness signal, the
    wall-clock seconds from attempt k's ``attempt_end`` (the kill /
    crash) to attempt k+1's ``attempt_first_signal`` (the first
    post-restart dispatch). The list is one entry per RECOVERED restart
    — an attempt that died before signaling contributes nothing (its
    successor's recovery measures from the newest prior end anyway).

    Stdlib-only and journal-only: the chaos sweep and ``obs_report``
    both call this against ``journal-supervisor.jsonl`` after the fact.
    """
    ends: dict[int, float] = {}
    firsts: dict[int, float] = {}
    try:
        with open(journal_path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write
                if rec.get("kind") != "event":
                    continue
                ev, attempt = rec.get("event"), rec.get("attempt")
                if attempt is None or "t" not in rec:
                    continue
                if ev == "attempt_end":
                    ends[int(attempt)] = float(rec["t"])
                elif ev == "attempt_first_signal":
                    firsts.setdefault(int(attempt), float(rec["t"]))
    except OSError:
        return []
    out = []
    for attempt, t_first in sorted(firsts.items()):
        prior = [t for a, t in ends.items()
                 if a < attempt and t <= t_first]
        if prior:
            out.append(round(t_first - max(prior), 3))
    return out
