"""fps_tpu.supervise — external run supervision (deadline-abort layer).

Two halves of one contract:

* :mod:`fps_tpu.supervise.supervisor` — the parent side:
  :class:`RunSupervisor` launches a training command, tails its heartbeat
  / obs journal for liveness, deadline-aborts a wedged child (SIGTERM →
  SIGKILL against the process group), restarts with exponential backoff
  under a retry budget, and quarantines deterministically-poisoned
  chunk/epoch indices across restarts (persisted next to the checkpoint
  dir). Stdlib-only; ``tools/supervise.py`` is its CLI.
* :mod:`fps_tpu.supervise.child` — the child side: :class:`Heartbeat`
  (+ :class:`HeartbeatSink` for the obs Recorder) and the env-var
  contract through which a supervised process finds its heartbeat path,
  attempt number, and carried quarantine set.

See ``docs/resilience.md`` for the failure model this closes.
"""

from fps_tpu.supervise.child import (
    ATTEMPT_ENV,
    HEARTBEAT_ENV,
    STATE_ENV,
    Heartbeat,
    HeartbeatSink,
    attempt_from_env,
    from_env,
    quarantined_from_env,
)
from fps_tpu.supervise.supervisor import (
    RunSupervisor,
    SupervisorConfig,
)

__all__ = [
    "RunSupervisor",
    "SupervisorConfig",
    "Heartbeat",
    "HeartbeatSink",
    "from_env",
    "attempt_from_env",
    "quarantined_from_env",
    "HEARTBEAT_ENV",
    "STATE_ENV",
    "ATTEMPT_ENV",
]
