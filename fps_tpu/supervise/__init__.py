"""fps_tpu.supervise — external run supervision (deadline-abort layer).

Two halves of one contract:

* :mod:`fps_tpu.supervise.supervisor` — the parent side:
  :class:`RunSupervisor` launches a training command, tails its heartbeat
  / obs journal for liveness, deadline-aborts a wedged child (SIGTERM →
  SIGKILL against the process group), restarts with exponential backoff
  under a retry budget, and quarantines deterministically-poisoned
  chunk/epoch indices across restarts (persisted next to the checkpoint
  dir). Stdlib-only; ``tools/supervise.py`` is its CLI.
* :mod:`fps_tpu.supervise.child` — the child side: :class:`Heartbeat`
  (+ :class:`HeartbeatSink` for the obs Recorder) and the env-var
  contract through which a supervised process finds its heartbeat path,
  attempt number, and carried quarantine set.

See ``docs/resilience.md`` for the failure model this closes.
"""

from fps_tpu.supervise.child import (
    ATTEMPT_ENV,
    FENCE_FILENAME,
    HEARTBEAT_ENV,
    HEARTBEAT_VERSION,
    POD_EPOCH_ENV,
    POD_HOST_ENV,
    POD_STEP_ENV,
    POD_WORLD_ENV,
    STATE_ENV,
    Heartbeat,
    HeartbeatSink,
    StaleEpochError,
    attempt_from_env,
    fence_allows,
    from_env,
    pod_env,
    quarantined_from_env,
    read_fence,
    write_fence,
)
from fps_tpu.supervise.supervisor import (
    RunSupervisor,
    SupervisorConfig,
)

# pod.py resolves its siblings through sys.modules (it must also load by
# bare file path with zero fps_tpu imports), so child and supervisor are
# imported above it here — the package then shares ONE class identity.
from fps_tpu.supervise.pod import (
    Lease,
    PodConfig,
    PodMember,
)

__all__ = [
    "RunSupervisor",
    "SupervisorConfig",
    "PodConfig",
    "PodMember",
    "Lease",
    "Heartbeat",
    "HeartbeatSink",
    "StaleEpochError",
    "from_env",
    "attempt_from_env",
    "quarantined_from_env",
    "pod_env",
    "read_fence",
    "write_fence",
    "fence_allows",
    "HEARTBEAT_ENV",
    "HEARTBEAT_VERSION",
    "STATE_ENV",
    "ATTEMPT_ENV",
    "POD_HOST_ENV",
    "POD_EPOCH_ENV",
    "POD_WORLD_ENV",
    "POD_STEP_ENV",
    "FENCE_FILENAME",
]
