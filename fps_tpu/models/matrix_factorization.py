"""Online matrix factorization (SGD) — the reference's flagship algorithm.

Reference behavior being rebuilt (SURVEY.md §2 #8 / §3.3; expected upstream
``src/main/scala/hu/sztaki/ilab/ps/matrix/factorization/PSOnlineMatrixFactorization.scala``):

* rating stream ``(userId, itemId, score)`` (MovieLens-style);
* **item factor vectors are the PS parameters** — pulled/pushed by item id,
  hash-sharded across servers;
* **user factor vectors live in worker-local state** — the stream is
  partitioned by user so each worker owns its users' vectors outright;
* per rating: pull ``q_i`` → SGD step on ``(p_u, q_i)`` with learning rate
  and L2 regularization → ``p_u`` updated locally, ``Δq_i`` pushed;
* factors initialized by a per-id seeded uniform in a configured range so
  initialization is reproducible across shards;
* worker emits the prediction/error on the ``WOut`` channel.

TPU design: a batch of ratings per worker per step; one collective ``pull``
of the batch's item vectors; dense vectorized SGD on the (B, rank) blocks
(VPU work — rank is small); local scatter-add into the user block; collective
scatter-add ``push`` of item deltas. Duplicate users/items within a batch
accumulate additively into the same row — Hogwild-flavored, exactly the
update interleaving the asynchronous reference produces.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from fps_tpu.core.api import StepOutput, WorkerLogic
from fps_tpu.core.store import (
    ParamStore,
    TableSpec,
    make_table_values,
    pull_local,
    ranged_uniform_init,
    rows_per_shard,
)

Array = jax.Array

ITEM_TABLE = "item_factors"


@dataclasses.dataclass
class MFConfig:
    num_users: int
    num_items: int
    rank: int = 10
    learning_rate: float = 0.05
    reg: float = 0.01
    init_min: float = -0.1
    init_max: float = 0.1
    # Item ids [0, hot_items) are treated as write-hot (NuPS-style hot/cold
    # push splitting, see fps_tpu.ops.scatter_add), moving the Zipf head's
    # pushes onto the MXU when item ids are popularity-ranked. Exact for
    # any id order. Default 0: dedup-safe on-chip measurement shows XLA's
    # scatter cost is ~flat in duplication on a single chip, so the split
    # only pays off when the per-shard table slice is small (large shard
    # axis) — enable it there.
    hot_items: int = 0
    # Negative sampling of unrated items (the reference MF's optional knob,
    # SURVEY.md §2 #8): each rating additionally samples this many random
    # items, treated as pseudo-ratings of ``negative_target`` with weight
    # ``negative_weight`` in the same SGD step. Sharpens ranking on
    # implicit/positive-only feedback; 0 disables. Sampling is uniform over
    # items — with realistic catalog sizes the collision probability with
    # the user's true positives is negligible, matching the reference's
    # "sample unrated" intent without a per-user seen-set.
    negative_samples: int = 0
    negative_target: float = 0.0
    negative_weight: float = 1.0
    dtype: object = jnp.float32


class MatrixFactorizationWorker(WorkerLogic):
    """Worker logic: local user factors, pulled item factors, SGD updates."""

    def __init__(self, config: MFConfig, num_workers: int):
        self.cfg = config
        self.num_workers = num_workers

    # local_state = the worker-sharded user factor table (owner-major cyclic
    # over num_workers, like a PS table but never communicated).
    def init_local_state(self, key: Array, num_workers: int):
        return make_table_values(
            key,
            self.cfg.num_users,
            self.cfg.rank,
            num_workers,
            ranged_uniform_init(
                self.cfg.init_min, self.cfg.init_max, self.cfg.rank, self.cfg.dtype
            ),
            self.cfg.dtype,
        )

    def export_local_state(self, local_state):
        """User factors in LOGICAL user order (padding stripped) — the same
        worker-count-independent convention the store's tables use, so a
        checkpoint taken at one worker count restores at any other."""
        from fps_tpu.models.recommendation import mf_user_vectors

        return mf_user_vectors(
            np.asarray(local_state), self.num_workers,
            np.arange(self.cfg.num_users),
        )

    def import_local_state(self, leaves, num_workers):
        (logical,) = leaves
        nu, rank = self.cfg.num_users, self.cfg.rank
        if logical.shape != (nu, rank):
            raise ValueError(
                f"checkpointed user factors shape {logical.shape} != "
                f"({nu}, {rank})"
            )
        rps = -(-nu // num_workers)
        table = np.zeros((rps * num_workers, rank), logical.dtype)
        u = np.arange(nu)
        table[(u % num_workers) * rps + u // num_workers] = logical
        return table

    def prepare(self, batch, key):
        n = self.cfg.negative_samples
        if not n:
            return batch
        B = batch["item"].shape[0]
        negs = jax.random.randint(
            key, (B, n), 0, self.cfg.num_items, jnp.int32
        )
        # Single source of truth for the [positive, negatives] column
        # layout: pull_ids and step both consume this (B, 1+n) matrix, so
        # their orderings cannot drift apart.
        all_items = jnp.concatenate(
            [batch["item"].astype(jnp.int32)[:, None], negs], axis=1
        )
        return dict(batch, all_items=all_items)

    def pull_ids(self, batch) -> Mapping[str, Array]:
        if self.cfg.negative_samples:
            return {ITEM_TABLE: batch["all_items"].reshape(-1)}
        return {ITEM_TABLE: batch["item"].astype(jnp.int32)}

    def pulled_ids_host(self, chunk):
        """Cold-route certification stream (``TableSpec.cold_budget``):
        the raw item column covers every id the step pulls AND pushes —
        pushes mask padding to ``-1``, so certifying on the pull stream
        is conservative. With negative sampling the ids are synthesized
        on device in :meth:`prepare`, so chunks are not certifiable."""
        if self.cfg.negative_samples:
            return None
        return {ITEM_TABLE: chunk["item"]}

    def pulled_ids_traced(self, batch):
        """Device-side certification stream (the megastep's in-graph
        overflow vote): same contract as :meth:`pulled_ids_host`, from
        one worker's raw traced batch. Negative sampling synthesizes
        ids in :meth:`prepare`, so those configs stay uncertifiable."""
        if self.cfg.negative_samples:
            return None
        return {ITEM_TABLE: batch["item"].astype(jnp.int32)}

    def touched_local_rows(self, batch):
        """Ids-aware local-guard refinement: :meth:`step` scatters only
        into the batch's own users' LOCAL rows (``u // num_workers`` —
        ingest routes ``u % W == me``), so the guard's row screening can
        be restricted to exactly those; padding examples (weight 0) touch
        no row. One entry: the user-factor table is the only leaf."""
        u = batch["user"].astype(jnp.int32)
        live = batch["weight"].astype(jnp.float32) > 0
        return (jnp.where(live, u // self.num_workers, -1),)

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        n = cfg.negative_samples
        user_factors = local_state
        u = batch["user"].astype(jnp.int32)
        w = batch["weight"].astype(cfg.dtype)
        r = batch["rating"].astype(cfg.dtype)
        B = u.shape[0]
        if n:
            # Column 0 is the real rating; columns 1.. are sampled unrated
            # items with target negative_target and weight negative_weight
            # (layout defined once by prepare()'s all_items).
            q = pulled[ITEM_TABLE].reshape(B, 1 + n, -1)
            items = batch["all_items"]  # (B, 1+n)
            targets = jnp.concatenate(
                [r[:, None],
                 jnp.full((B, n), cfg.negative_target, cfg.dtype)], axis=1)
            wts = jnp.concatenate(
                [w[:, None],
                 w[:, None] * jnp.full((B, n), cfg.negative_weight,
                                       cfg.dtype)], axis=1)
        else:
            q = pulled[ITEM_TABLE][:, None, :]  # (B, 1, rank)
            items = batch["item"].astype(jnp.int32)[:, None]
            targets = r[:, None]
            wts = w[:, None]

        uidx = u // self.num_workers  # local row (ingest routes u % W == me)
        p = pull_local(user_factors, u, num_shards=self.num_workers)

        pred = jnp.einsum("bd,bkd->bk", p, q)  # (B, 1+n)
        err = (targets - pred) * wts
        lr = cfg.learning_rate
        # Reference SGDUpdater: d_p = lr*(err*q - reg*p), d_q = lr*(err*p - reg*q).
        dp = lr * (jnp.einsum("bk,bkd->bd", err, q)
                   - cfg.reg * w[:, None] * p)
        dq = lr * (err[:, :, None] * p[:, None, :]
                   - cfg.reg * wts[:, :, None] * q)

        user_factors = user_factors.at[uidx].add(dp.astype(cfg.dtype))

        out = {
            # Quality metrics track the REAL ratings only (column 0), so
            # the train-RMSE line is comparable with negatives on or off.
            "se": jnp.sum(err[:, 0] * err[:, 0]).astype(jnp.float32),
            "n": jnp.sum(w).astype(jnp.float32),
        }
        # Padding rows push id -1 so the store drops them outright.
        push_ids = jnp.where(wts > 0, items, -1)
        pushes = {ITEM_TABLE: (push_ids.reshape(-1),
                               dq.reshape(B * (1 + n), -1))}
        return StepOutput(pushes=pushes, local_state=user_factors, out=out)


def make_store(mesh, cfg: MFConfig) -> ParamStore:
    spec = TableSpec(
        name=ITEM_TABLE,
        num_ids=cfg.num_items,
        dim=cfg.rank,
        init_fn=ranged_uniform_init(cfg.init_min, cfg.init_max, cfg.rank, cfg.dtype),
        dtype=cfg.dtype,
        hot_ids=min(cfg.hot_items, cfg.num_items),
    )
    return ParamStore(mesh, [spec])


def online_mf(mesh, cfg: MFConfig, *, sync_every: int | None = None,
              push_delay: int = 0, donate: bool = True,
              max_steps_per_call: int | None = None,
              combine="sum", guard=None):
    """Construct (trainer, store) for online MF — the analog of
    ``PSOnlineMatrixFactorization.psOnlineMF(...)``.

    ``combine``: how duplicate item ids within one batch merge — ``"sum"``
    (the reference's per-message fold; faithful, but at very large batches
    Zipfian-hot items receive hundreds of summed steps per batch and SGD
    diverges) or ``"mean"`` (one averaged step per touched item per batch,
    the analog of the reference's combining senders — stable at any batch
    size).

    ``guard``: push-delta health guard (``TrainerConfig.guard``) —
    ``"mask"`` drops poison updates in-step, ``"observe"`` only counts."""
    from fps_tpu.core.api import ServerLogic
    from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of

    store = make_store(mesh, cfg)
    worker = MatrixFactorizationWorker(cfg, num_workers_of(mesh))
    trainer = Trainer(
        mesh, store, worker,
        server_logic=ServerLogic(combine=combine),
        config=TrainerConfig(sync_every=sync_every, push_delay=push_delay,
                             donate=donate,
                             max_steps_per_call=max_steps_per_call,
                             guard=guard),
    )
    return trainer, store


def predict_host(
    store: ParamStore,
    user_factors_global: np.ndarray,
    num_workers: int,
    users: np.ndarray,
    items: np.ndarray,
) -> np.ndarray:
    """Host-side predictions from the live tables (for eval/RMSE)."""
    rps = rows_per_shard_global(user_factors_global, num_workers)
    phys = (users % num_workers) * rps + users // num_workers
    p = np.asarray(user_factors_global)[phys]
    q = store.lookup_host(ITEM_TABLE, items)
    return np.sum(p * q, axis=-1)


def rows_per_shard_global(table: np.ndarray, num_shards: int) -> int:
    return table.shape[0] // num_shards


def rmse(pred: np.ndarray, truth: np.ndarray) -> float:
    return float(np.sqrt(np.mean((pred - truth) ** 2)))
