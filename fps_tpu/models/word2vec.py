"""word2vec skip-gram with negative sampling (SGNS) on the PS.

Reference behavior being rebuilt (SURVEY.md §2 #10; confirmed to exist by
BASELINE.json "word2vec SGNS (text8)"; exact upstream package unverified —
the survey flags its location as [conf: L]):

* two embedding matrices (input/center and output/context), sharded by
  word id across the servers;
* worker slides a window over the token stream, pulls the center vector,
  the context vector, and K negative-sample vectors, computes the SGNS
  gradient, pushes deltas to both tables;
* negatives drawn from the unigram distribution raised to 3/4; frequent
  words subsampled away (Mikolov et al. 2013); workload: text8.

TPU design
----------
* Skip-gram **pair generation and subsampling are host-side streaming**
  (ingest), producing static-shape (center, context) batches.
* **Negative sampling is on-device** in ``WorkerLogic.prepare``: Vose
  alias-method tables over unigram^0.75 (built once on host) — O(1) per
  draw, two gathers + a compare, fully inside the compiled step
  (``searchsorted`` over the CDF measured ~100x slower on TPU).
* One pull on the input table (centers) and one on the output table
  (contexts ++ negatives, flattened) per step; one push each. The sigmoid/
  gradient math is dense (B, 1+K, dim) VPU work.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from fps_tpu.core.api import StepOutput, WorkerLogic
from fps_tpu.core.store import ParamStore, TableSpec, ranged_uniform_init
from fps_tpu.parallel.mesh import host_to_replicated, key_to_replicated

Array = jax.Array

IN_TABLE = "in_embeddings"
OUT_TABLE = "out_embeddings"


def _keep_probs(cfg: W2VConfig, unigram_counts: np.ndarray) -> np.ndarray:
    """Per-token keep probability min(1, sqrt(t/f)) — word2vec's frequent-word
    subsampling; ones when ``cfg.subsample_t`` is None. Single source of
    truth for the host ingest and device-plan paths."""
    counts = np.asarray(unigram_counts, np.float64)
    freq = counts / max(1.0, counts.sum())
    if cfg.subsample_t is None:
        return np.ones_like(freq)
    return np.minimum(1.0, np.sqrt(cfg.subsample_t / np.maximum(freq, 1e-12)))


def _build_alias(p: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Vose alias tables for a discrete distribution ``p`` (sums to 1).

    Returns (prob, alias): draw ``j ~ U{0..V-1}``, ``u ~ U[0,1)``; the
    sample is ``j`` if ``u < prob[j]`` else ``alias[j]``.
    """
    V = len(p)
    prob = np.zeros(V)
    alias = np.zeros(V, np.int64)
    scaled = np.asarray(p, np.float64) * V
    small = [i for i in range(V) if scaled[i] < 1.0]
    large = [i for i in range(V) if scaled[i] >= 1.0]
    while small and large:
        s = small.pop()
        l = large.pop()
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] = scaled[l] - (1.0 - scaled[s])
        (small if scaled[l] < 1.0 else large).append(l)
    for i in large + small:
        prob[i] = 1.0
    return prob, alias


@dataclasses.dataclass
class W2VConfig:
    vocab_size: int
    dim: int = 100
    window: int = 5  # dynamic window: actual half-width ~ U{1..window}
    negatives: int = 5
    learning_rate: float = 0.025
    subsample_t: float | None = 1e-4  # None disables frequent-word subsampling
    neg_power: float = 0.75
    # Word ids [0, hot_words) are write-hot (NuPS-style hot/cold push split,
    # fps_tpu.ops.scatter_add); vocabulary ids are frequency-ranked by every
    # loader (most_common order), so the Zipf head sits exactly there.
    # "auto" routes the WHOLE shard slice through the packed MXU scatter
    # when the mesh leaves it thinner than the measured crossover
    # (fps_tpu.ops.packed_crossover_rows) — the many-shard regime; w2v's
    # mean-combine push always takes the gathered route, so this is the
    # shipped family where "auto" actually fires (vocab 50k on 32+ shards,
    # or proportionally smaller vocabs — see dryrun_multichip).
    # Default 0 — see MFConfig.hot_items for when enabling it pays.
    hot_words: int | str = 0
    # Block-mode only (Word2VecBlockWorker): positions share one set of K
    # negatives per group of this many tokens. Default 1 = per-POSITION
    # negatives (shared only across a position's ~2*window instances) —
    # already the full transaction win, and quality tracks the pair worker.
    # G>1 shrinks OUT-row traffic further but measurably stalls SGNS: the
    # store's per-id mean-combine weights rows equally, so collapsing many
    # negative instances into few group rows starves hot ids of negative
    # pressure and a common embedding component grows unchecked.
    neg_group_size: int = 1
    dtype: object = jnp.float32


class _AliasNegativeSampler:
    """Shared negative-drawing mixin: Vose alias tables over
    ``unigram^neg_power`` — O(1) per draw on device, two gathers and a
    compare (searchsorted over the CDF measured ~27ms per 200k draws on
    TPU; the alias sampler is ~100x cheaper)."""

    def _init_alias(self, cfg: W2VConfig, unigram_counts: np.ndarray):
        p = np.asarray(unigram_counts, np.float64) ** cfg.neg_power
        p /= p.sum()
        prob, alias = _build_alias(p)
        self._alias_prob = jnp.asarray(prob, jnp.float32)
        self._alias_idx = jnp.asarray(alias, jnp.int32)

    def _draw_negatives(self, key: Array, shape: tuple[int, ...]) -> Array:
        k1, k2 = jax.random.split(key)
        j = jax.random.randint(k1, shape, 0, self.cfg.vocab_size, jnp.int32)
        u = jax.random.uniform(k2, shape)
        return jnp.where(u < jnp.take(self._alias_prob, j),
                         j, jnp.take(self._alias_idx, j))


class Word2VecWorker(WorkerLogic, _AliasNegativeSampler):
    """SGNS worker. Batch columns: ``center (B,)``, ``context (B,)``,
    ``weight (B,)``. ``prepare`` adds ``negatives (B, K)``."""

    def __init__(self, cfg: W2VConfig, unigram_counts: np.ndarray):
        self.cfg = cfg
        self._init_alias(cfg, unigram_counts)

    def prepare(self, batch, key):
        B = batch["center"].shape[0]
        negs = self._draw_negatives(key, (B, self.cfg.negatives))
        return dict(batch, negatives=negs)

    def pull_ids(self, batch) -> Mapping[str, Array]:
        ctx_and_neg = jnp.concatenate(
            [batch["context"].astype(jnp.int32)[:, None], batch["negatives"]],
            axis=1,
        )  # (B, 1+K)
        return {
            IN_TABLE: batch["center"].astype(jnp.int32),
            OUT_TABLE: ctx_and_neg.reshape(-1),
        }

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        B = batch["center"].shape[0]
        K = cfg.negatives
        w = batch["weight"].astype(cfg.dtype)  # (B,)

        v = pulled[IN_TABLE]  # (B, dim) center vectors
        u = pulled[OUT_TABLE].reshape(B, 1 + K, -1)  # ctx ++ negs

        # labels: slot 0 positive, rest negative.
        logits = jnp.einsum("bd,bkd->bk", v, u)  # (B, 1+K)
        labels = jnp.zeros((B, 1 + K), cfg.dtype).at[:, 0].set(1.0)
        sig = jax.nn.sigmoid(logits)
        # dL/dlogit for L = -log σ(x_pos) - Σ log σ(-x_neg):
        g = (sig - labels) * w[:, None]  # (B, 1+K)

        lr = cfg.learning_rate
        dv = -lr * jnp.einsum("bk,bkd->bd", g, u)  # (B, dim)
        du = -lr * g[:, :, None] * v[:, None, :]  # (B, 1+K, dim)

        # SGNS loss (for monitoring): -logσ(pos) - Σ logσ(-neg).
        loss = -(
            jax.nn.log_sigmoid(logits[:, 0])
            + jnp.sum(jax.nn.log_sigmoid(-logits[:, 1:]), axis=1)
        )

        center_ids = jnp.where(w > 0, batch["center"].astype(jnp.int32), -1)
        ctx_and_neg = jnp.concatenate(
            [batch["context"].astype(jnp.int32)[:, None], batch["negatives"]],
            axis=1,
        )
        out_ids = jnp.where(w[:, None] > 0, ctx_and_neg, -1)

        out = {
            "loss": jnp.sum(loss * w).astype(jnp.float32),
            "n": jnp.sum(w).astype(jnp.float32),
        }
        pushes = {
            IN_TABLE: (center_ids, dv),
            OUT_TABLE: (out_ids.reshape(-1), du.reshape(B * (1 + K), -1)),
        }
        return StepOutput(pushes=pushes, local_state=local_state, out=out)


class Word2VecBlockWorker(WorkerLogic, _AliasNegativeSampler):
    """SGNS at token-BLOCK granularity — the transaction-minimal fast path.

    The pair-level worker pulls/pushes one OUT-table row per (pair, slot):
    ``2·window·L`` pairs × ``(1+K)`` rows ≈ 250k row transactions per
    2048-token block — and on TPU sparse row ops are per-transaction bound
    (~12ns/row), so transactions, not FLOPs, set the word2vec ceiling.

    Because pair generation is fused on device (Word2VecDevicePlan), the
    worker can instead receive the raw token block and exploit that every
    pair's endpoints are block positions:

    * pull each block position's IN and OUT row ONCE (``L+W`` rows each);
      pairs are assembled by static slices of those rows (dense VPU work);
    * per-position gradients accumulate across the ``2W`` orientations by
      static slice-adds, and each table takes ONE push of ``L+W`` rows;
    * negatives are shared per group of ``neg_group_size`` positions (each
      center instance weighted by its exact pair count), adding only
      ``K·ceil((L+W)/G)`` OUT rows. Default G=1: one negative set per
      position, shared across its ~2·window instances — see
      ``W2VConfig.neg_group_size`` for why larger groups stall SGNS under
      the store's mean-combine.

    Per block at G=1: ~(4 + 2K)(L+W) transactions vs ~2·2W·L·(1+K) for the
    pair worker — ~10x fewer at the default geometry. The SGNS gradient is
    exact for the stated sampling scheme; only the negative-sampling
    coupling (instance-shared draws) differs from the per-pair reference.

    Batch columns (from ``Word2VecDevicePlan(mode="block")``):
    ``block (L+W,)`` int32 tokens, ``half (L,)`` int32 per-position dynamic
    half-windows, ``valid_len ()`` int32 count of in-stream positions.
    """

    def __init__(self, cfg: W2VConfig, unigram_counts: np.ndarray,
                 block_len: int):
        if cfg.neg_group_size <= 0:
            raise ValueError("neg_group_size must be positive in block mode")
        self.cfg = cfg
        self.block_len = block_len
        self.num_groups = -(-(block_len + cfg.window) // cfg.neg_group_size)
        self._init_alias(cfg, unigram_counts)

    def prepare(self, batch, key):
        negs = self._draw_negatives(
            key, (self.num_groups, self.cfg.negatives)
        )
        return dict(batch, negatives=negs)

    def pull_ids(self, batch) -> Mapping[str, Array]:
        block = batch["block"].astype(jnp.int32)
        return {
            IN_TABLE: block,
            OUT_TABLE: jnp.concatenate(
                [block, batch["negatives"].reshape(-1)]
            ),
        }

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        L, W, K, G = (self.block_len, cfg.window, cfg.negatives,
                      cfg.neg_group_size)
        LW = L + W
        lr = cfg.learning_rate

        half = batch["half"].astype(jnp.int32)  # (L,)
        vlen = batch["valid_len"].astype(jnp.int32)  # ()
        v = pulled[IN_TABLE]  # (LW, dim) center rows
        uo = pulled[OUT_TABLE][:LW]  # (LW, dim) context rows
        negs_u = pulled[OUT_TABLE][LW:].reshape(self.num_groups, K, -1)

        dv = jnp.zeros_like(v)
        du = jnp.zeros_like(uo)
        inst = jnp.zeros((LW,), cfg.dtype)  # center-instance counts
        pos = jnp.arange(L, dtype=jnp.int32)
        loss = jnp.float32(0.0)
        npairs = jnp.float32(0.0)

        for d in range(1, W + 1):
            c, x = v[:L], v[d : L + d]
            uc, ux = uo[:L], uo[d : L + d]
            wk = ((half >= d) & (pos + d < vlen)).astype(cfg.dtype)  # (L,)
            # Both orientations of each ordered adjacency (i, i+d), exactly
            # like the pair path: centers i and i+d, contexts swapped.
            l1 = jnp.sum(c * ux, axis=-1)  # center=i, context=i+d
            l2 = jnp.sum(x * uc, axis=-1)  # center=i+d, context=i
            g1 = (jax.nn.sigmoid(l1) - 1.0) * wk
            g2 = (jax.nn.sigmoid(l2) - 1.0) * wk
            dv = dv.at[:L].add(-lr * g1[:, None] * ux)
            du = du.at[d : L + d].add(-lr * g1[:, None] * c)
            dv = dv.at[d : L + d].add(-lr * g2[:, None] * uc)
            du = du.at[:L].add(-lr * g2[:, None] * x)
            inst = inst.at[:L].add(wk)
            inst = inst.at[d : L + d].add(wk)
            loss += jnp.sum(
                -(jax.nn.log_sigmoid(l1) + jax.nn.log_sigmoid(l2)) * wk
            )
            npairs += 2.0 * jnp.sum(wk)

        # Group-shared negatives: every pair whose center sits in group g
        # scores the same K rows, so per (position, negative) the gradient
        # is the single-pair gradient times the position's instance count.
        pad = self.num_groups * G - LW
        vp = jnp.pad(v, ((0, pad), (0, 0))).reshape(self.num_groups, G, -1)
        instp = jnp.pad(inst, (0, pad)).reshape(self.num_groups, G)
        ln = jnp.einsum("gid,gkd->gik", vp, negs_u)  # (NG, G, K)
        sn = jax.nn.sigmoid(ln) * instp[:, :, None]
        dv_neg = -lr * jnp.einsum("gik,gkd->gid", sn, negs_u)
        du_neg = -lr * jnp.einsum("gik,gid->gkd", sn, vp)
        dv = dv + dv_neg.reshape(-1, v.shape[-1])[:LW]
        loss += jnp.sum(-jax.nn.log_sigmoid(-ln) * instp[:, :, None])

        # Normalize to per-INSTANCE means so block mode takes the same
        # effective step sizes as the pair worker under the store's per-id
        # mean combine: each position's delta above is a SUM over its
        # ~2·window center/context instances (and each negative's over its
        # whole group's instances) — unnormalized, that multiplies the
        # learning rate by the instance count and SGNS plateaus.
        ginst = instp.sum(axis=1)  # (NG,) total instances per group
        inv = 1.0 / jnp.maximum(inst, 1.0)
        dv = dv * inv[:, None]
        du = du * inv[:, None]
        du_neg = du_neg / jnp.maximum(ginst, 1.0)[:, None, None]

        # One push row per block position; zero-instance rows drop (-1) so
        # the mean-combine denominator counts only real contributors.
        block = batch["block"].astype(jnp.int32)
        row_ids = jnp.where(inst > 0, block, -1)
        neg_ids = jnp.where(
            ginst[:, None] > 0, batch["negatives"], -1
        ).reshape(-1)

        out = {
            "loss": loss.astype(jnp.float32),
            "n": npairs.astype(jnp.float32),
        }
        pushes = {
            IN_TABLE: (row_ids, dv),
            OUT_TABLE: (
                jnp.concatenate([row_ids, neg_ids]),
                jnp.concatenate([du, du_neg.reshape(-1, v.shape[-1])]),
            ),
        }
        return StepOutput(pushes=pushes, local_state=local_state, out=out)


def make_store(mesh, cfg: W2VConfig) -> ParamStore:
    half = 0.5 / cfg.dim
    hot = cfg.hot_words
    if isinstance(hot, str):
        if hot != "auto":
            # Same altitude contract as driver._resolve_hot_rows: a typo'd
            # literal must not surface as a TypeError inside min().
            raise ValueError(
                f"hot_words={hot!r} — expected an int or the literal 'auto'"
            )
    else:
        hot = min(hot, cfg.vocab_size)
    in_spec = TableSpec(
        name=IN_TABLE,
        num_ids=cfg.vocab_size,
        dim=cfg.dim,
        init_fn=ranged_uniform_init(-half, half, cfg.dim, cfg.dtype),
        dtype=cfg.dtype,
        hot_ids=hot,
    )
    # word2vec initializes the output matrix to zeros.
    out_spec = TableSpec(
        name=OUT_TABLE, num_ids=cfg.vocab_size, dim=cfg.dim, dtype=cfg.dtype,
        hot_ids=hot,
    ).zeros_init()
    return ParamStore(mesh, [in_spec, out_spec])


def _make_trainer(mesh, cfg: W2VConfig, worker, *, sync_every, donate,
                  max_steps_per_call, push_delay=0, step_tap=None,
                  guard=None):
    from fps_tpu.core.api import MEAN_COMBINE
    from fps_tpu.core.driver import Trainer, TrainerConfig

    if push_delay >= 16:
        import warnings

        # Measured guardrail (docs/STALENESS.md finding #5): the staleness
        # sweep holds SGNS partner recovery at 0.675-0.700 through s=64
        # STALE READS at full lr, but the delayed-WRITE diagonal with the
        # lr-downscale recipe collapses it — 0.125 at s=d=16, 0.050 at
        # s=d=64 (chance 0.017). The mechanism is under-training, not
        # divergence: the downscale that stabilizes MF's bilinear objective
        # leaves the non-convex SGNS objective barely moving.
        downscaled = cfg.learning_rate < W2VConfig.learning_rate
        warnings.warn(
            f"word2vec with push_delay={push_delay}"
            + (f" and downscaled learning_rate={cfg.learning_rate} "
               f"(< default {W2VConfig.learning_rate})" if downscaled
               else "")
            + ": the measured staleness sweep (docs/STALENESS.md finding "
            "#5) collapsed SGNS quality in this regime (partner recovery "
            "0.70 -> 0.125 at delay 16 with the lr-downscale recipe). "
            "Prefer bounding READS (sync_every) at full lr and keeping "
            "push_delay small or zero.",
            UserWarning, stacklevel=3,
        )

    store = make_store(mesh, cfg)
    # Per-id mean combine: with Zipfian word frequencies a hot id appears
    # many times per batch; summing those deltas diverges, averaging gives
    # each touched row one stable step per batch (NuPS-style skew handling).
    trainer = Trainer(
        mesh, store, worker, server_logic=MEAN_COMBINE,
        config=TrainerConfig(sync_every=sync_every, donate=donate,
                             max_steps_per_call=max_steps_per_call,
                             push_delay=push_delay, step_tap=step_tap,
                             guard=guard),
    )
    return trainer, store


def word2vec(mesh, cfg: W2VConfig, unigram_counts: np.ndarray, *,
             sync_every: int | None = None, donate: bool = True,
             max_steps_per_call: int | None = None, push_delay: int = 0,
             step_tap=None, guard=None):
    """(trainer, store) — the analog of the reference's word2vec transform.
    ``sync_every``/``push_delay`` select SSP staleness brackets exactly as
    in :func:`fps_tpu.models.matrix_factorization.online_mf`."""
    return _make_trainer(
        mesh, cfg, Word2VecWorker(cfg, unigram_counts),
        sync_every=sync_every, donate=donate,
        max_steps_per_call=max_steps_per_call, push_delay=push_delay,
        step_tap=step_tap, guard=guard,
    )


def word2vec_block(mesh, cfg: W2VConfig, unigram_counts: np.ndarray,
                   block_len: int, *, sync_every: int | None = None,
                   donate: bool = True,
                   max_steps_per_call: int | None = None,
                   push_delay: int = 0, step_tap=None, guard=None):
    """(trainer, store) with the block-granularity worker — pair with a
    ``Word2VecDevicePlan(..., block_len=block_len, mode="block")``. Same
    tables, same SGNS objective; ~10x fewer sparse row transactions per
    step at the default geometry (see :class:`Word2VecBlockWorker`).
    ``step_tap`` taps (e.g. :func:`cooccurrence_sketch_tap`) see the raw
    block batch and can reconstruct its exact pair stream id-only via
    :func:`block_pair_stream`."""
    return _make_trainer(
        mesh, cfg, Word2VecBlockWorker(cfg, unigram_counts, block_len),
        sync_every=sync_every, donate=donate,
        max_steps_per_call=max_steps_per_call, push_delay=push_delay,
        step_tap=step_tap, guard=guard,
    )


# ---------------------------------------------------------------------------
# Host-side streaming skip-gram pair generation (the ingest source).
# ---------------------------------------------------------------------------

def skipgram_chunks(
    tokens: np.ndarray,
    unigram_counts: np.ndarray,
    cfg: W2VConfig,
    *,
    num_workers: int,
    local_batch: int,
    steps_per_chunk: int,
    sync_every: int | None = None,
    seed: int = 0,
    segment_tokens: int = 1 << 20,
    use_native: bool | None = None,
) -> Iterator[dict]:
    """Stream ``(center, context, weight)`` chunks over one pass of ``tokens``.

    Works segment-by-segment so the full pair list (≈ 2·window·N) never
    materializes. Applies frequent-word subsampling (prob. 1 - sqrt(t/f))
    and a dynamic window (per-position half-width uniform in 1..window),
    both matching word2vec's reference implementation.

    ``use_native`` selects the C++ pair generator (``fps_tpu.native``):
    ``None`` (default) uses it when available, ``True`` requires it,
    ``False`` forces the numpy path. Both paths implement the same sampling
    scheme; streams differ only in RNG draws.
    """
    from fps_tpu import native

    if use_native is None:
        use_native = native.available()
    elif use_native and not native.available():
        raise RuntimeError("use_native=True but fps_tpu.native is unavailable")
    rng = np.random.default_rng(seed)
    n = len(tokens)
    if n and int(np.max(tokens)) >= len(unigram_counts):
        raise ValueError(
            f"token id {int(np.max(tokens))} >= vocab "
            f"{len(unigram_counts)} (unigram_counts too small)"
        )
    keep_p = _keep_probs(cfg, unigram_counts)

    B = num_workers * local_batch
    stride = steps_per_chunk * B
    if sync_every is not None and steps_per_chunk % sync_every:
        raise ValueError("steps_per_chunk must be a multiple of sync_every")

    buf_c: list[np.ndarray] = []
    buf_x: list[np.ndarray] = []
    buffered = 0
    native_kp = (
        keep_p.astype(np.float32) if cfg.subsample_t is not None else None
    )

    def emit(c, x, wgt):
        chunk = {
            "center": c.reshape(steps_per_chunk, B),
            "context": x.reshape(steps_per_chunk, B),
            "weight": wgt.reshape(steps_per_chunk, B).astype(np.float32),
        }
        if sync_every is not None:
            chunk = {
                k: v.reshape(-1, sync_every, B) for k, v in chunk.items()
            }
        return chunk

    # Segments are disjoint: cross-boundary pairs (at most window per
    # ~million-token segment) are dropped rather than double-counted.
    for si, start in enumerate(range(0, n, segment_tokens)):
        seg = tokens[start : start + segment_tokens]
        if use_native:
            pair = native.skipgram_pairs(
                seg, cfg.window, seed=(seed << 20) ^ si, keep_p=native_kp
            )
            if pair is None:  # native failure mid-stream (e.g. OOM)
                raise RuntimeError(
                    "native skipgram_pairs failed mid-stream; rerun with "
                    "use_native=False"
                )
            c, x = pair
            if len(c):
                buf_c.append(c)
                buf_x.append(x)
                buffered += len(c)
        else:
            # subsample frequent words (drop positions entirely, like word2vec).
            keep = rng.random(len(seg)) < keep_p[seg]
            seg = seg[keep]
            if len(seg) < 2:
                continue
            m = len(seg)
            half = rng.integers(1, cfg.window + 1, m)  # dynamic window
            for d in range(1, cfg.window + 1):
                ok = (half >= d)[: m - d]
                c = seg[: m - d][ok]
                x = seg[d:][ok]
                # both directions: (center, context) and (context, center)
                buf_c.append(np.concatenate([c, x]))
                buf_x.append(np.concatenate([x, c]))
                buffered += 2 * len(c)

        while buffered >= stride:
            cs = np.concatenate(buf_c)
            xs = np.concatenate(buf_x)
            take_c, rest_c = cs[:stride], cs[stride:]
            take_x, rest_x = xs[:stride], xs[stride:]
            buf_c, buf_x = [rest_c], [rest_x]
            buffered = len(rest_c)
            yield emit(take_c, take_x, np.ones(stride))

    if buffered:
        cs = np.concatenate(buf_c)[:stride]
        xs = np.concatenate(buf_x)[:stride]
        pad = stride - len(cs)
        wgt = np.concatenate([np.ones(len(cs)), np.zeros(pad)])
        cs = np.concatenate([cs, np.zeros(pad, cs.dtype)])
        xs = np.concatenate([xs, np.zeros(pad, xs.dtype)])
        yield emit(cs, xs, wgt)


def nearest_neighbors(store: ParamStore, word_ids: np.ndarray, k: int = 5,
                      center: bool = True):
    """Host-side cosine nearest neighbors in the input embedding table.

    ``center=True`` removes the common mean vector first — SGNS embeddings
    are strongly anisotropic (a large shared component; cf. "All-but-the-Top",
    Mu et al. 2018), and raw cosine is dominated by it.
    """
    ids = np.arange(store.specs[IN_TABLE].num_ids)
    emb = store.lookup_host(IN_TABLE, ids)
    if center:
        emb = emb - emb.mean(axis=0)
    emb = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True), 1e-9)
    q = emb[word_ids]
    sims = q @ emb.T
    order = np.argsort(-sims, axis=1)
    return order[:, 1 : k + 1], np.take_along_axis(sims, order, 1)[:, 1 : k + 1]


# ---------------------------------------------------------------------------
# Streaming co-occurrence similarity via tug-of-war sketches (step_tap).
#
# The reference family's sketch module estimated word co-occurrence
# similarity from the pair stream without storing the |V|x|V| matrix
# (SURVEY.md §2 #10, [conf: L]). Here the estimator RIDES THE TRAINING
# LOOP: a ``step_tap`` sketches each probe word's context distribution
# from the very batches the SGNS worker trains on — no second pass over
# the corpus, no extra host<->device traffic beyond the (P, depth, width)
# delta that joins the metrics stream.
# ---------------------------------------------------------------------------

def _sketch_pair_stream(spec, probe, center, ctx, w):
    """Route each (center, context, weight) pair to its probe row and add
    its tug-of-war contribution — one O(B*P) compare plus ONE scatter into
    the flattened ``(P, depth, width)`` stack (not a full-width scatter
    per probe)."""
    from fps_tpu.sketch import tow_update_rows

    P = int(probe.shape[0])
    eq = center[:, None] == probe[None, :]  # (B, P)
    row = jnp.where(eq.any(axis=1), jnp.argmax(eq, axis=1), -1)
    stack = jnp.zeros((P, spec.depth, spec.width), jnp.float32)
    return tow_update_rows(spec, stack, row, ctx, w)


def block_pair_stream(batch):
    """Reconstruct the exact (center, context, weight) pair stream of one
    BLOCK-worker batch from its raw columns — the same pairs
    :class:`Word2VecBlockWorker.step` trains on without materializing.

    The block batch carries ``block (L+W,)``, ``half (L,)`` and
    ``valid_len ()``; the worker's pair semantics are, for every offset
    ``d in [1, W]`` and position ``i < L``: weight
    ``(half[i] >= d) & (i + d < valid_len)`` on BOTH orientations of the
    adjacency ``(i, i+d)``. ``W`` is inferred from the static shapes
    (``len(block) - len(half)``). Returns ``(center, ctx, w)`` arrays of
    length ``2*W*L`` — ids only, so a probe tap costs O(W·L·P) compares
    per step, no embedding traffic.
    """
    block = batch["block"].astype(jnp.int32)  # (L+W,)
    half = batch["half"].astype(jnp.int32)  # (L,)
    vlen = batch["valid_len"].astype(jnp.int32)  # ()
    L = half.shape[0]
    W = block.shape[0] - L
    pos = jnp.arange(L, dtype=jnp.int32)
    centers, ctxs, ws = [], [], []
    for d in range(1, W + 1):
        wk = ((half >= d) & (pos + d < vlen)).astype(jnp.float32)
        lo, hi = block[:L], block[d : L + d]
        centers += [lo, hi]
        ctxs += [hi, lo]
        ws += [wk, wk]
    return (jnp.concatenate(centers), jnp.concatenate(ctxs),
            jnp.concatenate(ws))


def cooccurrence_sketch_tap(spec, probe_ids):
    """``step_tap`` emitting per-step tug-of-war sketch DELTAS of each probe
    word's context-frequency vector.

    For every training batch the tap sketches ``{context: weight}`` of the
    pairs whose center is ``probe_ids[p]`` into row ``p`` of a
    ``(P, depth, width)`` stack. Sketches are additive, so the stream
    sketch is just the sum of the emitted deltas over steps AND workers —
    exactly :func:`accumulate_sketch_taps`. Pad pairs carry weight 0 and
    vanish from the estimate.

    Works with BOTH worker schemas: the PAIR batch (``center``/``context``/
    ``weight`` columns — :func:`skipgram_chunks` and the pair-mode
    :class:`Word2VecDevicePlan`) is sketched directly, and a BLOCK batch
    (``block``/``half``/``valid_len``) has its exact pair stream
    reconstructed id-only on the fly (:func:`block_pair_stream`) — so the
    estimator also rides the fused fast path that delivers the w2v
    headline, at ~2·window·L extra int32 compares per step.
    """
    probe = jnp.asarray(probe_ids, jnp.int32)  # (P,)

    def tap(tables, batch, local_state, t):
        del tables, local_state, t
        if "block" in batch:
            center, ctx, w = block_pair_stream(batch)
        else:
            center = batch["center"].astype(jnp.int32)
            ctx = batch["context"].astype(jnp.int32)
            w = batch["weight"].astype(jnp.float32)
        return _sketch_pair_stream(spec, probe, center, ctx, w)

    return tap


def accumulate_sketch_taps(metrics) -> np.ndarray:
    """Sum the ``tap`` channel of ``fit_stream``/``run_indexed`` metrics
    into the stream's (P, depth, width) co-occurrence sketch stack."""
    total = None
    for m in metrics:
        # (steps, W, P, depth, width) -> (P, depth, width)
        part = np.asarray(m["tap"]).sum(axis=(0, 1))
        total = part if total is None else total + part
    if total is None:
        raise ValueError("no metrics chunks — nothing was trained")
    return total


def sketch_similarity(sketches: np.ndarray) -> np.ndarray:
    """(P, P) unbiased co-occurrence inner-product estimates among the
    probe words (median-of-rows tug-of-war estimator, all on host — one
    einsum, not P^2 device dispatches)."""
    s = np.asarray(sketches)
    return np.median(np.einsum("pdw,qdw->pqd", s, s), axis=-1)


# ---------------------------------------------------------------------------
# Device-resident SGNS epochs: pair generation fused into the compiled loop.
# ---------------------------------------------------------------------------

class Word2VecDevicePlan:
    """Epoch plan generating skip-gram pairs ON DEVICE for ``run_indexed``.

    The host streaming path (:func:`skipgram_chunks`) materializes and
    uploads every (center, context) chunk — dominated by the host→device
    link on a TPU VM. Here the raw token stream is uploaded once; each
    epoch then runs as ONE compiled program that:

    1. **subsamples + compacts** the stream on device (uniform-vs-keep_p
       mask → cumsum → scatter), exactly word2vec's semantics where
       dropped tokens vanish from the stream *before* windows apply;
    2. **generates pairs inside the training scan**: worker ``w``'s step
       ``t`` takes a block of ``block_len`` compacted tokens, draws a
       dynamic half-window ``U{1..window}`` per center, and emits the
       ``2 * window * block_len`` candidate pairs (both orientations per
       ordered adjacency, like the host path) with validity weights;
    3. trains the usual SGNS step (negatives drawn in ``prepare``).

    The per-epoch kept-token count is random on device, so the epoch is
    sized from its host-computable expectation ``sum(keep_p[tokens])``
    plus a generous slack; the overflow probability is negligible and any
    overflow tokens are dropped (one-pass streaming semantics).
    """

    def __init__(self, dataset_tokens: np.ndarray, unigram_counts: np.ndarray,
                 cfg: W2VConfig, mesh, *, num_workers: int,
                 block_len: int = 8192, seed: int = 0,
                 sync_every: int | None = None, mode: str = "pairs"):
        from jax.sharding import NamedSharding, PartitionSpec as P

        if mode not in ("pairs", "block"):
            raise ValueError(f"unknown mode {mode!r}")
        self.cfg = cfg
        self.mode = mode
        self.num_workers = num_workers
        self.block_len = block_len
        self.local_batch = 2 * cfg.window * block_len  # pairs per step
        self.seed = seed
        self.sync_every = sync_every
        self.num_tokens = int(len(dataset_tokens))

        replicated = NamedSharding(mesh, P())
        self._tokens = host_to_replicated(
            np.asarray(dataset_tokens, np.int32), mesh
        )
        keep_p = _keep_probs(cfg, unigram_counts)
        self._keep_p = host_to_replicated(keep_p.astype(np.float32), mesh)

        expected_kept = float(keep_p[np.asarray(dataset_tokens)].sum())
        bound = int(expected_kept + 8.0 * np.sqrt(expected_kept + 1.0) + 1024)
        bound = min(bound, self.num_tokens)
        per_worker = -(-bound // (block_len * num_workers))
        steps = max(1, per_worker)
        if sync_every:
            steps = -(-steps // sync_every) * sync_every
        self.steps_per_epoch = steps
        # Compacted buffer: every block slice (+ window lookahead) in range.
        self._buf_len = steps * block_len * num_workers + cfg.window

        W = cfg.window
        buf_len = self._buf_len

        def compact(key_data):
            key = jax.random.wrap_key_data(key_data)
            toks = self._tokens
            keep = (jax.random.uniform(key, toks.shape)
                    < jnp.take(self._keep_p, toks))
            dest = jnp.cumsum(keep.astype(jnp.int32)) - 1
            kept = dest[-1] + 1
            dest = jnp.where(keep, jnp.minimum(dest, buf_len - 1), buf_len)
            compacted = jnp.zeros((buf_len + 1,), jnp.int32)
            compacted = compacted.at[dest].set(toks, mode="drop")
            return compacted[:buf_len], jnp.minimum(kept, buf_len)

        # Takes raw key data (plain numpy, implicitly replicated) and pins
        # replicated outputs so the path works under multi-controller JAX.
        self._compact_jit = jax.jit(
            compact, out_shardings=(replicated, replicated)
        )
        self._mesh = mesh

    def epoch_args(self, epoch: int):
        ekey = jax.random.fold_in(jax.random.key(self.seed), epoch)
        ck, wk = jax.random.split(ekey)
        # _compact_jit pins replicated outputs, so the (tokens,)-sized
        # buffer is placed once and never re-broadcast by the dispatches.
        compacted, kept = self._compact_jit(
            np.asarray(jax.random.key_data(ck))
        )
        return {
            "compacted": compacted,
            "kept": kept,
            "wkey": key_to_replicated(wk, self._mesh),
        }

    def local_batch_at(self, args, w, t):
        """Worker ``w``'s step-``t`` batch: skip-gram ``(center, context,
        weight)`` pairs in ``"pairs"`` mode, or the raw ``(block, half,
        valid_len)`` columns for :class:`Word2VecBlockWorker` in ``"block"``
        mode (same block slice, same half-window draws — only the
        granularity handed to the worker differs)."""
        L, W = self.block_len, self.cfg.window
        base = (t * self.num_workers + w) * L
        block = jax.lax.dynamic_slice(args["compacted"], (base,), (L + W,))
        key = jax.random.fold_in(args["wkey"], t * self.num_workers + w)
        half = jax.random.randint(key, (L,), 1, W + 1, dtype=jnp.int32)
        if self.mode == "block":
            return {
                "block": block,
                "half": half,
                "valid_len": jnp.clip(args["kept"] - base, 0, L + W),
            }
        pos = jnp.arange(L, dtype=jnp.int32)

        centers, contexts, valids = [], [], []
        for d in range(1, W + 1):
            c = block[:L]
            x = jax.lax.dynamic_slice(block, (d,), (L,))
            ok = (half >= d) & (base + pos + d < args["kept"])
            # both orientations of each ordered adjacency, like word2vec
            centers += [c, x]
            contexts += [x, c]
            valids += [ok, ok]
        return {
            "center": jnp.concatenate(centers),
            "context": jnp.concatenate(contexts),
            "weight": jnp.concatenate(valids).astype(jnp.float32),
        }
