"""Implicit-feedback iALS (Hu, Koren, Volinsky 2008) — sharded ALS driver.

BASELINE.json names "Implicit-feedback iALS (MovieLens-20M)" as a required
workload; SURVEY.md §6/§7 flag it as an *extension* (likely absent from the
reference) needing a different driver from the streaming PS loop: per-epoch
sharded normal-equation solves instead of per-record SGD.

Model: observed interaction (u, i, r) has confidence ``c = 1 + alpha*r`` and
preference 1; unobserved pairs have confidence 1 and preference 0. Each
half-epoch fixes one side and solves, per id on the other side,

    (G + alpha * sum_i r_ui * y_i y_i^T + reg*I) x_u = sum_i (1+alpha*r_ui) y_i

with ``G = Y^T Y`` the Gramian over *all* items (the classic trick that makes
the "all unobserved pairs" term tractable).

TPU-native decomposition (everything static-shape, jit-compiled once):

1. **Gramian** — each shard computes ``local_block^T @ local_block`` on its
   ``(rps, k)`` rows (MXU matmul) and ``psum``s over the shard axis.
   Padding rows are zeroed first via an on-device validity mask.
2. **Accumulate** — stream interaction chunks through a scan: collective
   :func:`fps_tpu.core.store.pull` of the fixed side's rows, form per-example
   ``alpha*r * y y^T`` (k*k) and ``(1+alpha*r) * y`` (k) blocks, collective
   :func:`~fps_tpu.core.store.push` into sharded accumulator tables keyed by
   the solved side's id. iALS thus *reuses the PS fabric*: the normal
   equations are just another sharded table being pushed to.
3. **Solve** — each shard solves its own ``(rps, k, k)`` batched SPD systems
   locally (``jnp.linalg.solve``; k is small so the batched LU is cheap
   next to the accumulate pass), no communication.

The user and item factor tables share the owner-major-cyclic layout of
:mod:`fps_tpu.core.store`, so accumulators align row-for-row with the factor
table being solved and the solve phase is purely local.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu.core.store import (
    ParamStore,
    TableSpec,
    phys_to_id,
    pull,
    push,
    ranged_uniform_init,
    rows_per_shard,
)
from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS

Array = jax.Array

USER_TABLE = "user_factors"
ITEM_TABLE = "item_factors"


@dataclasses.dataclass
class IALSConfig:
    num_users: int
    num_items: int
    rank: int = 16
    alpha: float = 40.0
    reg: float = 0.1
    init_scale: float = 0.01
    dtype: object = jnp.float32


class IALSSolver:
    """Alternating sharded normal-equation solver for implicit feedback.

    Usage::

        solver = IALSSolver(mesh, IALSConfig(nu, ni, rank=16))
        solver.init(jax.random.key(0))
        for _ in range(epochs):
            solver.epoch(lambda: interaction_chunks(...))
        users, items = solver.factors()
    """

    def __init__(self, mesh, cfg: IALSConfig):
        self.mesh = mesh
        self.cfg = cfg
        self.num_shards = mesh.shape[SHARD_AXIS]
        self.num_data = mesh.shape.get(DATA_AXIS, 1)
        # Workers = ALL devices: the interaction stream splits over both
        # mesh axes; pushes gather across the data axis (like the Trainer's)
        # so the replicated accumulators fold every worker's contributions
        # exactly once.
        self.num_workers = self.num_data * self.num_shards
        init = ranged_uniform_init(-cfg.init_scale, cfg.init_scale, cfg.rank,
                                   cfg.dtype)
        self.store = ParamStore(
            mesh,
            [
                TableSpec(USER_TABLE, cfg.num_users, cfg.rank, init, cfg.dtype),
                TableSpec(ITEM_TABLE, cfg.num_items, cfg.rank, init, cfg.dtype),
            ],
        )
        self._sharding = self.store.sharding
        self._replicated = NamedSharding(mesh, P())
        self._compiled_gram = {}
        self._compiled_acc = {}
        self._compiled_solve = {}
        self._compiled_zeros = {}
        # Overlapped host pipeline depth for half_epoch's chunk stream
        # (fps_tpu.core.prefetch): chunk assembly + placement run this
        # many chunks ahead on a worker thread. 0 = synchronous; the
        # accumulate order (and so the solve) is identical either way.
        self.prefetch = 0

    # -- state --------------------------------------------------------------

    def init(self, key: Array) -> dict[str, Array]:
        return self.store.init(key)

    def factors(self) -> tuple[np.ndarray, np.ndarray]:
        return (
            self.store.dump_model(USER_TABLE)[1],
            self.store.dump_model(ITEM_TABLE)[1],
        )

    # -- device-side pieces ---------------------------------------------------

    def _valid_mask(self, num_ids: int, rps: int):
        """(rps,) bool per shard: physical row is a real id (not padding)."""

        def local(_):
            me = lax.axis_index(SHARD_AXIS)
            phys = me * rps + jnp.arange(rps, dtype=jnp.int32)
            ids = phys_to_id(phys, self.num_shards, rps)
            return ids < num_ids

        return local

    def _gram_fn(self, num_ids: int, rps: int):
        """jit: sharded table -> replicated (k, k) Gramian (padding excluded)."""

        def device_fn(table):
            valid = self._valid_mask(num_ids, rps)(None)
            rows = jnp.where(valid[:, None], table, 0.0)
            g = rows.T @ rows
            return lax.psum(g, SHARD_AXIS)

        def run(table):
            return jax.shard_map(
                device_fn,
                mesh=self.mesh,
                in_specs=(P(SHARD_AXIS, None),),
                out_specs=P(),
                check_vma=False,
            )(table)

        return jax.jit(run)

    def _accumulate_fn(self):
        """jit: stream one chunk of interactions into (A, b) accumulators.

        Chunk leaves are (T, B) with B split over ALL devices (the data AND
        shard axes): ``solve_ids``, ``fixed_ids``, ``rating``, ``weight``.
        With a data axis, pushes gather across it so the replicated
        accumulators fold every worker's contributions exactly once.
        """
        cfg = self.cfg
        k = cfg.rank

        def device_fn(fixed_table, A, b, chunk):
            def body(carry, xs):
                A, b = carry
                solve_ids = xs["solve_ids"].astype(jnp.int32)
                fixed_ids = xs["fixed_ids"].astype(jnp.int32)
                r = xs["rating"].astype(cfg.dtype)
                w = xs["weight"].astype(cfg.dtype)

                y = pull(fixed_table, fixed_ids, num_shards=self.num_shards)
                cr = cfg.alpha * r * w  # confidence minus 1, masked
                outer = (cr[:, None, None] * y[:, :, None] * y[:, None, :])
                vec = ((1.0 + cfg.alpha * r) * w)[:, None] * y

                ids = jnp.where(w > 0, solve_ids, -1)
                data_axis = DATA_AXIS if self.num_data > 1 else None
                A = push(A, ids, outer.reshape(-1, k * k),
                         num_shards=self.num_shards, data_axis=data_axis)
                b = push(b, ids, vec,
                         num_shards=self.num_shards, data_axis=data_axis)
                return (A, b), None

            (A, b), _ = lax.scan(body, (A, b), chunk)
            return A, b

        def run(fixed_table, A, b, chunk):
            return jax.shard_map(
                device_fn,
                mesh=self.mesh,
                in_specs=(
                    P(SHARD_AXIS, None),
                    P(SHARD_AXIS, None),
                    P(SHARD_AXIS, None),
                    jax.tree.map(
                        lambda _: P(None, (DATA_AXIS, SHARD_AXIS)), chunk
                    ),
                ),
                out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)),
                check_vma=False,
            )(fixed_table, A, b, chunk)

        return jax.jit(run, donate_argnums=(1, 2))

    def _solve_fn(self, num_ids: int, rps: int):
        """jit: (gram, A, b) -> solved factor table (local batched Cholesky)."""
        cfg = self.cfg
        k = cfg.rank

        def device_fn(gram, A, b):
            lhs = gram[None] + A.reshape(-1, k, k)
            lhs = lhs + cfg.reg * jnp.eye(k, dtype=cfg.dtype)[None]
            # Batched SPD solve; jnp.linalg handles the (rps, k, k) batch.
            x = jnp.linalg.solve(lhs, b[:, :, None])[:, :, 0]
            valid = self._valid_mask(num_ids, rps)(None)
            return jnp.where(valid[:, None], x, 0.0).astype(cfg.dtype)

        def run(gram, A, b):
            return jax.shard_map(
                device_fn,
                mesh=self.mesh,
                in_specs=(P(), P(SHARD_AXIS, None), P(SHARD_AXIS, None)),
                out_specs=P(SHARD_AXIS, None),
                check_vma=False,
            )(gram, A, b)

        return jax.jit(run)

    # -- half-epoch ----------------------------------------------------------

    def _zeros_acc(self, rows: int, dim: int) -> Array:
        fn = self._compiled_zeros.get((rows, dim))
        if fn is None:
            fn = self._compiled_zeros[(rows, dim)] = jax.jit(
                lambda: jnp.zeros((rows, dim), self.cfg.dtype),
                out_shardings=self._sharding,
            )
        return fn()

    def half_epoch(self, solve: str, chunks: Iterable[dict]) -> None:
        """One ALS half-step: solve ``"user"`` or ``"item"`` factors.

        ``chunks`` yield dicts with (T, B) arrays ``user``, ``item``,
        ``rating``, ``weight`` (as produced by
        :func:`fps_tpu.core.ingest.epoch_chunks`; B must be divisible by
        ``num_workers`` = data * shard, the full device count).
        """
        cfg = self.cfg
        if solve == "user":
            solve_name, fixed_name = USER_TABLE, ITEM_TABLE
            solve_col, fixed_col = "user", "item"
            solve_n, fixed_n = cfg.num_users, cfg.num_items
        elif solve == "item":
            solve_name, fixed_name = ITEM_TABLE, USER_TABLE
            solve_col, fixed_col = "item", "user"
            solve_n, fixed_n = cfg.num_items, cfg.num_users
        else:
            raise ValueError(f"solve must be 'user' or 'item', got {solve!r}")

        solve_rps = rows_per_shard(solve_n, self.num_shards)
        fixed_rps = rows_per_shard(fixed_n, self.num_shards)
        k = cfg.rank

        if fixed_name not in self._compiled_gram:
            self._compiled_gram[fixed_name] = self._gram_fn(fixed_n, fixed_rps)
        gram = self._compiled_gram[fixed_name](self.store.tables[fixed_name])

        A = self._zeros_acc(solve_rps * self.num_shards, k * k)
        b = self._zeros_acc(solve_rps * self.num_shards, k)

        acc = self._compiled_acc.get(solve)
        if acc is None:
            acc = self._compiled_acc[solve] = self._accumulate_fn()
        sharding = NamedSharding(self.mesh, P(None, (DATA_AXIS, SHARD_AXIS)))

        def to_dev(x):
            # Device-resident chunks (fps_tpu.core.device_ingest) reshard
            # on device; host chunks upload. Either way no host round trip
            # for data already on the mesh.
            if not isinstance(x, jax.Array):
                x = jnp.asarray(np.asarray(x))
            return jax.device_put(x, sharding)

        def place(chunk):
            return {
                "solve_ids": to_dev(chunk[solve_col]),
                "fixed_ids": to_dev(chunk[fixed_col]),
                "rating": to_dev(chunk["rating"]),
                "weight": to_dev(chunk["weight"]),
            }

        it, pf = chunks, None
        if self.prefetch:
            from fps_tpu.core.prefetch import ChunkPrefetcher

            it = pf = ChunkPrefetcher(chunks, place, depth=self.prefetch)
        try:
            for item in it:
                # Prefetched items arrive pre-placed (PlacedChunk).
                dev_chunk = item.batches if pf is not None else place(item)
                A, b = acc(self.store.tables[fixed_name], A, b, dev_chunk)
        finally:
            if pf is not None:
                pf.close()

        if solve_name not in self._compiled_solve:
            self._compiled_solve[solve_name] = self._solve_fn(solve_n, solve_rps)
        self.store.tables[solve_name] = self._compiled_solve[solve_name](
            gram, A, b
        )

    def epoch(self, make_chunks) -> None:
        """One full ALS epoch. ``make_chunks()`` returns a fresh chunk
        iterator (it is consumed twice: once per half-epoch)."""
        self.half_epoch("user", make_chunks())
        self.half_epoch("item", make_chunks())

    # -- evaluation ----------------------------------------------------------

    def weighted_loss(self, users: np.ndarray, items: np.ndarray,
                      ratings: np.ndarray, sample_unobserved: int = 0,
                      seed: int = 0) -> float:
        """Host-side iALS objective estimate: the observed confidence-weighted
        term ``sum c*(1 - x·y)^2`` plus the exact regularizer
        ``reg*(sum ||x_u||^2 + sum ||y_i||^2)`` (+ optionally a Monte-Carlo
        estimate of the unobserved ``(0 - x·y)^2`` term: the sampled mean
        scaled by ``num_users * num_items``; pairs are drawn uniformly with
        replacement, so observed pairs can be sampled too, biasing the
        estimate up by O(nnz / (U·I)) — negligible for sparse data)."""
        cfg = self.cfg
        U, V = self.factors()
        xy = np.sum(U[users] * V[items], axis=-1)
        c = 1.0 + cfg.alpha * ratings
        loss = float(np.sum(c * (1.0 - xy) ** 2))
        loss += cfg.reg * float(np.sum(U * U) + np.sum(V * V))
        if sample_unobserved:
            rng = np.random.default_rng(seed)
            su = rng.integers(0, cfg.num_users, sample_unobserved)
            si = rng.integers(0, cfg.num_items, sample_unobserved)
            mean_sq = float(np.mean(np.sum(U[su] * V[si], axis=-1) ** 2))
            loss += mean_sq * cfg.num_users * cfg.num_items
        return loss


def interaction_chunks(
    data: dict,
    *,
    num_workers: int,
    local_batch: int,
    steps_per_chunk: int,
    seed: int | None = 0,
) -> Iterator[dict]:
    """Fixed-shape (T, B) interaction chunks for the accumulate pass.

    Thin wrapper over :func:`fps_tpu.core.ingest.epoch_chunks` with
    round-robin placement (iALS has no worker-local state to route for).
    ``num_workers`` is ALL mesh devices (``IALSSolver.num_workers``) — the
    stream splits over the data AND shard axes.
    """
    from fps_tpu.core.ingest import epoch_chunks

    return epoch_chunks(
        data,
        num_workers=num_workers,
        local_batch=local_batch,
        steps_per_chunk=steps_per_chunk,
        seed=seed,
    )


def recall_at_k(
    solver: IALSSolver,
    heldout_user: np.ndarray,
    heldout_item: np.ndarray,
    *,
    k: int = 10,
    exclude: tuple[np.ndarray, np.ndarray] | None = None,
) -> float:
    """Fraction of held-out (user, item) pairs ranked in the user's top-k.

    ``exclude`` = (train_users, train_items) pairs masked out of the ranking
    (standard leave-out evaluation).
    """
    U, V = solver.factors()
    scores = U[heldout_user] @ V.T  # (H, num_items)
    if exclude is not None:
        tu, ti = exclude
        # One groupby of train items per user, then mask each evaluated
        # user's train items — but never the held-out item itself (it may
        # also occur in train when interactions repeat).
        order = np.argsort(tu, kind="stable")
        tu_s, ti_s = np.asarray(tu)[order], np.asarray(ti)[order]
        starts = np.searchsorted(tu_s, np.arange(solver.cfg.num_users))
        ends = np.searchsorted(tu_s, np.arange(solver.cfg.num_users), "right")
        for row, u in enumerate(heldout_user):
            held = scores[row, heldout_item[row]]
            scores[row, ti_s[starts[u]:ends[u]]] = -np.inf
            scores[row, heldout_item[row]] = held
    ranks = np.argsort(-scores, axis=1)[:, :k]
    return float(np.mean(np.any(ranks == heldout_item[:, None], axis=1)))
