"""Sparse logistic regression with bounded-staleness SGD (Criteo CTR style).

This is the "async bounded-staleness SGD, multi-worker data-parallel"
workload named in BASELINE.json's configs. The reference framework runs any
such model through the same WorkerLogic/ServerLogic machinery; here it is
the canonical exerciser of the **SSP driver** (``sync_every=s``): workers
read weights from a snapshot up to ``s`` steps stale, compute sigmoid-loss
gradients over hashed sparse features, and push per-feature deltas that land
in the authoritative sharded table every step.

Batch columns: ``feat_ids (B, nnz)``, ``feat_vals (B, nnz)``,
``label (B,)`` in {0, 1}, ``weight (B,)``.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from fps_tpu.core.api import StepOutput, WorkerLogic
from fps_tpu.core.store import ParamStore, TableSpec

Array = jax.Array

WEIGHT_TABLE = "weights"


@dataclasses.dataclass
class LogRegConfig:
    num_features: int
    learning_rate: float = 0.1
    l2: float = 0.0
    batch_average: bool = True  # average grads over the local batch
    dtype: object = jnp.float32


class LogisticRegressionWorker(WorkerLogic):
    def __init__(self, cfg: LogRegConfig):
        self.cfg = cfg

    def pull_ids(self, batch) -> Mapping[str, Array]:
        return {WEIGHT_TABLE: batch["feat_ids"].astype(jnp.int32).reshape(-1)}

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        B, nnz = batch["feat_ids"].shape
        x = batch["feat_vals"].astype(cfg.dtype)
        y = batch["label"].astype(cfg.dtype)  # {0,1}
        w = batch["weight"].astype(cfg.dtype)

        wrows = pulled[WEIGHT_TABLE].reshape(B, nnz)
        logit = jnp.sum(wrows * x, axis=-1)
        p = jax.nn.sigmoid(logit)
        g = (p - y) * w  # dL/dlogit, zeroed for padding

        n_real = jnp.maximum(jnp.sum(w), 1.0)
        scale = cfg.learning_rate / (n_real if cfg.batch_average else 1.0)
        deltas = -scale * (g[:, None] * x + cfg.l2 * wrows * w[:, None])

        active = (x != 0.0) & (w[:, None] > 0)
        push_ids = jnp.where(active, batch["feat_ids"].astype(jnp.int32), -1)

        # log loss, clipped for monitoring stability.
        eps = 1e-7
        ll = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
        mistakes = jnp.sum(w * ((p > 0.5) != (y > 0.5)))
        out = {
            "logloss": jnp.sum(ll * w).astype(jnp.float32),
            "mistakes": mistakes.astype(jnp.float32),
            "n": jnp.sum(w).astype(jnp.float32),
        }
        pushes = {WEIGHT_TABLE: (push_ids.reshape(-1), deltas.reshape(-1, 1))}
        return StepOutput(pushes=pushes, local_state=local_state, out=out)


def make_store(mesh, cfg: LogRegConfig) -> ParamStore:
    spec = TableSpec(
        name=WEIGHT_TABLE, num_ids=cfg.num_features, dim=1, dtype=cfg.dtype
    ).zeros_init()
    return ParamStore(mesh, [spec])


def logistic_regression(mesh, cfg: LogRegConfig, *,
                        sync_every: int | None = None, donate: bool = True,
                        max_steps_per_call: int | None = None):
    """(trainer, store); pass ``sync_every=s`` for SSP bounded staleness."""
    from fps_tpu.core.driver import Trainer, TrainerConfig

    store = make_store(mesh, cfg)
    trainer = Trainer(
        mesh, store, LogisticRegressionWorker(cfg),
        config=TrainerConfig(sync_every=sync_every, donate=donate,
                             max_steps_per_call=max_steps_per_call),
    )
    return trainer, store


def predict_proba_host(store: ParamStore, feat_ids: np.ndarray,
                       feat_vals: np.ndarray) -> np.ndarray:
    rows = store.lookup_host(WEIGHT_TABLE, feat_ids.reshape(-1))
    B, nnz = feat_ids.shape
    logit = np.sum(rows.reshape(B, nnz) * feat_vals, axis=-1)
    return 1.0 / (1.0 + np.exp(-logit))
