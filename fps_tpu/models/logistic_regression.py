"""Sparse logistic regression with bounded-staleness SGD (Criteo CTR style).

This is the "async bounded-staleness SGD, multi-worker data-parallel"
workload named in BASELINE.json's configs. The reference framework runs any
such model through the same WorkerLogic/ServerLogic machinery; here it is
the canonical exerciser of the **SSP driver** (``sync_every=s``): workers
read weights from a snapshot up to ``s`` steps stale, compute sigmoid-loss
gradients over hashed sparse features, and push per-feature deltas that land
in the authoritative sharded table every step.

Batch columns: ``feat_ids (B, nnz)``, ``feat_vals (B, nnz)``,
``label (B,)`` in {0, 1}, ``weight (B,)``.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from fps_tpu.core.api import ServerLogic, StepOutput, WorkerLogic
from fps_tpu.core.store import ParamStore, TableSpec

Array = jax.Array

WEIGHT_TABLE = "weights"


@dataclasses.dataclass
class LogRegConfig:
    num_features: int
    learning_rate: float = 0.1
    l2: float = 0.0
    batch_average: bool = True  # average grads over the local batch
    # "sgd": worker pushes lr-scaled deltas, server fold is additive (the
    # reference's SimplePSLogic semantics). "adagrad": worker pushes raw
    # [grad, grad^2] pairs and the server fold keeps a per-coordinate
    # accumulator IN the sharded table (column 1) — per-coordinate adaptive
    # rates tame Zipfian-hot features with no framework changes, showing
    # the ServerLogic fold is general enough to host optimizer state.
    optimizer: str = "sgd"
    adagrad_eps: float = 1e-6
    # Feature ids [0, hot_features) are write-hot (NuPS-style hot/cold push
    # split, fps_tpu.ops.scatter_add); effective with frequency-ranked ids
    # and a small per-shard table slice. Default 0 — see MFConfig.hot_items.
    hot_features: int = 0
    # FIXED-SLOT dense head: the first ``dense_features`` batch slots carry
    # feature id j at slot j in EVERY example (value 0 = inactive), the
    # Criteo loader's layout for the 13 numeric columns. The worker then
    # pulls those weights once per step (d rows, not B*d gathered rows)
    # and pushes ONE batch-combined delta per column — cutting the sparse
    # scatter from B*nnz to B*(nnz-d) rows. Semantically identical to
    # dense_features=0 under the additive server fold (the per-id sums are
    # just pre-combined on the worker; equal up to f32 reassociation).
    dense_features: int = 0
    dtype: object = jnp.float32

    def __post_init__(self):
        if self.optimizer not in ("sgd", "adagrad"):
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if not 0 <= self.dense_features <= self.num_features:
            raise ValueError(
                f"dense_features={self.dense_features} out of range"
            )

    @property
    def table_width(self) -> int:
        """Columns per feature row: weight (+ AdaGrad accumulator)."""
        return 2 if self.optimizer == "adagrad" else 1


class LogisticRegressionWorker(WorkerLogic):
    def __init__(self, cfg: LogRegConfig):
        self.cfg = cfg

    def pull_ids(self, batch) -> Mapping[str, Array]:
        d = self.cfg.dense_features
        if not d:
            return {
                WEIGHT_TABLE: batch["feat_ids"].astype(jnp.int32).reshape(-1)
            }
        # Dense head: one static d-row pull (fixed-slot contract: slot j
        # carries id j for j < d) + the sparse tail per example.
        tail = batch["feat_ids"][:, d:].astype(jnp.int32).reshape(-1)
        return {
            WEIGHT_TABLE: jnp.concatenate(
                [jnp.arange(d, dtype=jnp.int32), tail]
            )
        }

    def pulled_ids_host(self, chunk):
        """Host certification/traffic stream (cold-route certifier +
        the delta-snapshot touched-rows tracker): the raw feature-id
        column covers every id the step pulls AND pushes. Multi-id
        contract shape: ``(T, B, nnz)`` flattens to ``(T, B*nnz)`` —
        worker-major blocks survive the flatten. A dense head pulls its
        ``d`` leading ids every step OUTSIDE the batch columns, which
        the per-position stream cannot express: those configs stay
        host-uncertifiable (None), like negative-sampling MF."""
        if self.cfg.dense_features:
            return None
        import numpy as np

        ids = np.asarray(chunk["feat_ids"])
        if ids.ndim >= 2:
            # (..., B, nnz) -> (..., B*nnz): worker-major blocks survive.
            ids = ids.reshape(*ids.shape[:-2], -1)
        return {WEIGHT_TABLE: ids}

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        d = cfg.dense_features
        B, nnz = batch["feat_ids"].shape
        x = batch["feat_vals"].astype(cfg.dtype)
        y = batch["label"].astype(cfg.dtype)  # {0,1}
        w = batch["weight"].astype(cfg.dtype)

        width = cfg.table_width
        if d:
            flat = pulled[WEIGHT_TABLE]
            head = jnp.broadcast_to(flat[:d, 0][None], (B, d))
            tail = flat[d:].reshape(B, nnz - d, width)[:, :, 0]
            wrows = jnp.concatenate([head, tail], axis=1)
        else:
            wrows = pulled[WEIGHT_TABLE].reshape(B, nnz, width)[:, :, 0]
        logit = jnp.sum(wrows * x, axis=-1)
        p = jax.nn.sigmoid(logit)
        g = (p - y) * w  # dL/dlogit, zeroed for padding

        n_real = jnp.maximum(jnp.sum(w), 1.0)
        norm = n_real if cfg.batch_average else 1.0
        grads = (g[:, None] * x + cfg.l2 * wrows * w[:, None]) / norm
        if cfg.optimizer == "adagrad":
            # raw gradient + its square; lr is applied by the server fold.
            deltas = jnp.stack([grads, grads * grads], axis=-1)
        else:
            deltas = (-cfg.learning_rate * grads)[:, :, None]

        active = (x != 0.0) & (w[:, None] > 0)
        if d:
            # Head: batch-combine on the worker (the per-id sum the server
            # fold would compute anyway) -> d pushed rows, not B*d.
            head_deltas = jnp.sum(
                jnp.where(active[:, :d, None], deltas[:, :d, :], 0.0),
                axis=0,
            )
            tail_ids = jnp.where(
                active[:, d:], batch["feat_ids"][:, d:].astype(jnp.int32), -1
            )
            push_ids = jnp.concatenate(
                [jnp.arange(d, dtype=jnp.int32), tail_ids.reshape(-1)]
            )
            push_deltas = jnp.concatenate(
                [head_deltas.astype(cfg.dtype),
                 deltas[:, d:, :].reshape(-1, width)]
            )
        else:
            push_ids = jnp.where(
                active, batch["feat_ids"].astype(jnp.int32), -1
            ).reshape(-1)
            push_deltas = deltas.reshape(-1, width)

        # log loss, clipped for monitoring stability.
        eps = 1e-7
        ll = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
        mistakes = jnp.sum(w * ((p > 0.5) != (y > 0.5)))
        out = {
            "logloss": jnp.sum(ll * w).astype(jnp.float32),
            "mistakes": mistakes.astype(jnp.float32),
            "n": jnp.sum(w).astype(jnp.float32),
        }
        pushes = {WEIGHT_TABLE: (push_ids, push_deltas)}
        return StepOutput(pushes=pushes, local_state=local_state, out=out)


def make_store(mesh, cfg: LogRegConfig) -> ParamStore:
    spec = TableSpec(
        name=WEIGHT_TABLE, num_ids=cfg.num_features, dim=cfg.table_width,
        dtype=cfg.dtype, hot_ids=min(cfg.hot_features, cfg.num_features),
    ).zeros_init()
    return ParamStore(mesh, [spec])


def adagrad_fold(lr: float, eps: float):
    """Server fold holding AdaGrad state in the table: column 0 = weight,
    column 1 = squared-gradient accumulator. The combined push delta is
    [sum g, sum g^2] per touched id."""

    def apply_fn(rows, delta):
        wcol, acc = rows[:, 0], rows[:, 1]
        gsum, g2sum = delta[:, 0], delta[:, 1]
        acc_new = acc + g2sum
        w_new = wcol - lr * gsum / (jnp.sqrt(acc_new) + eps)
        return jnp.stack([w_new, acc_new], axis=-1)

    return apply_fn


def logistic_regression(mesh, cfg: LogRegConfig, *,
                        sync_every: int | None = None, push_delay: int = 0,
                        donate: bool = True,
                        max_steps_per_call: int | None = None,
                        guard=None):
    """(trainer, store); pass ``sync_every=s`` for SSP bounded staleness.

    ``guard``: push-delta health guard (``TrainerConfig.guard``) —
    ``"mask"`` drops poison updates in-step, ``"observe"`` only counts."""
    from fps_tpu.core.driver import Trainer, TrainerConfig

    store = make_store(mesh, cfg)
    server_logic = (
        ServerLogic(apply_fn=adagrad_fold(cfg.learning_rate, cfg.adagrad_eps))
        if cfg.optimizer == "adagrad"
        else ServerLogic()
    )
    trainer = Trainer(
        mesh, store, LogisticRegressionWorker(cfg),
        server_logic=server_logic,
        config=TrainerConfig(sync_every=sync_every, push_delay=push_delay,
                             donate=donate,
                             max_steps_per_call=max_steps_per_call,
                             guard=guard),
    )
    return trainer, store


def predict_proba_host(store: ParamStore, feat_ids: np.ndarray,
                       feat_vals: np.ndarray) -> np.ndarray:
    rows = store.lookup_host(WEIGHT_TABLE, feat_ids.reshape(-1))
    B, nnz = feat_ids.shape
    weights = rows[:, 0]  # column 0 is the weight for every optimizer
    logit = np.sum(weights.reshape(B, nnz) * feat_vals, axis=-1)
    return 1.0 / (1.0 + np.exp(-logit))
