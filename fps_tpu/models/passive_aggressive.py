"""Passive-aggressive classifier (binary + multiclass) on the PS.

Reference behavior being rebuilt (SURVEY.md §2 #9 / §3.4; expected upstream
``src/main/scala/hu/sztaki/ilab/ps/passive/aggressive/`` with
``PassiveAggressiveParameterServer.transformBinary`` / ``transformMulticlass``
and the closed-form algorithms in its ``algorithm/`` subpackage):

* model = weight vector (binary) or per-class weight vectors (multiclass),
  sharded by **feature id** across the servers;
* one sparse example fans out to one pull per nonzero feature; the reference
  buffers the example until all pull answers arrive, computes the margin and
  the PA/PA-I/PA-II closed-form step size, then pushes per-feature deltas;
* workloads: RCV1 binary classification.

TPU design: the pull-fanout-and-reassembly bookkeeping disappears — a batch
of examples pulls the *union* of its feature rows in one collective gather
(``(B*nnz,)`` flattened ids), computes all margins/taus dense on the VPU,
and pushes all per-feature deltas in one scatter-add. Within a batch,
updates are computed against the same pulled snapshot (mini-batch PA) —
the same interleaving the asynchronous reference produces when many
workers share the servers.

Closed-form step sizes (Crammer et al. 2006), hinge loss l = max(0, 1 - y·m):

* PA    : tau = l / ||x||^2
* PA-I  : tau = min(C, l / ||x||^2)
* PA-II : tau = l / (||x||^2 + 1/(2C))
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from fps_tpu.core.api import StepOutput, WorkerLogic
from fps_tpu.core.store import ParamStore, TableSpec

Array = jax.Array

WEIGHT_TABLE = "weights"


@dataclasses.dataclass
class PAConfig:
    """``batch_average=True`` (default) scales each example's PA step by
    1/local_batch — each worker applies the *average* of its batch's
    closed-form steps, and concurrent workers' pushes sum (exactly the
    reference's async semantics with W workers pushing interleaved
    single-example steps). Raw summing within a worker's batch diverges
    for the uncapped variants (PA, PA-II) once the batch is large.
    ``batch_average=False`` restores raw summing (safe for PA-I with
    small C or tiny batches)."""

    num_features: int
    num_classes: int = 2  # 2 => binary (single weight vector)
    variant: str = "PA-I"  # "PA" | "PA-I" | "PA-II"
    C: float = 1.0
    batch_average: bool = True
    # Feature ids [0, hot_features) are write-hot (NuPS-style hot/cold push
    # split, fps_tpu.ops.scatter_add); effective with frequency-ranked ids
    # and a small per-shard table slice. Default 0 — see MFConfig.hot_items.
    hot_features: int = 0
    # Head-prefix routing (single-device meshes): set together with
    # ``hot_features = H`` after laying the dataset out with
    # ``fps_tpu.utils.datasets.head_sort_slots(data, H)`` — the returned
    # ``q`` is the number of leading slot COLUMNS guaranteed to carry ids
    # in [0, H). The worker then flattens ids nnz-major so those q*B
    # leading entries ride head-only kernels with ceil(H/128) row tiles
    # instead of ceil(num_features/128) (``fps_tpu.ops.gather_rows``
    # ``head_prefix``) — measured at ~15% of the END-TO-END PA headline
    # (BASELINE.md round-5 section; widening q further is refuted there).
    # Purely a routing hint: results are identical (to the dim-1 kernels'
    # documented hi+lo precision) with it on or off.
    head_prefix_cols: int = 0
    dtype: object = jnp.float32

    @property
    def table_dim(self) -> int:
        return 1 if self.num_classes == 2 else self.num_classes


def _tau(variant: str, C: float, loss: Array, x2: Array) -> Array:
    x2 = jnp.maximum(x2, 1e-12)
    if variant == "PA":
        return loss / x2
    if variant == "PA-I":
        return jnp.minimum(C, loss / x2)
    if variant == "PA-II":
        return loss / (x2 + 1.0 / (2.0 * C))
    raise ValueError(f"unknown PA variant {variant!r}")


class PassiveAggressiveWorker(WorkerLogic):
    """Binary PA: batch of sparse examples, one gather, one scatter-add.

    Batch columns: ``feat_ids (B, nnz)`` int32 (pad slots may hold any id as
    long as ``feat_vals`` is 0 there), ``feat_vals (B, nnz)``, ``label (B,)``
    in {-1, +1}, ``weight (B,)``.
    """

    def __init__(self, cfg: PAConfig):
        if cfg.num_classes != 2:
            raise ValueError("use MulticlassPassiveAggressiveWorker")
        self.cfg = cfg

    def _flatten(self, a: Array) -> Array:
        """(B, nnz[, ...]) -> (B*nnz[, ...]): nnz-major when head-prefix
        routing is on (so the head-sorted leading COLUMNS become the
        leading flat entries), row-major otherwise."""
        if self.cfg.head_prefix_cols:
            a = jnp.swapaxes(a, 0, 1)
        return a.reshape((-1,) + a.shape[2:])

    def pull_ids(self, batch) -> Mapping[str, Array]:
        return {WEIGHT_TABLE: self._flatten(
            batch["feat_ids"].astype(jnp.int32))}

    def head_prefix(self, batch) -> Mapping[str, int]:
        q = self.cfg.head_prefix_cols
        if not q:
            return {}
        B, nnz = batch["feat_ids"].shape
        return {WEIGHT_TABLE: min(q, nnz) * B}

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        B, nnz = batch["feat_ids"].shape
        x = batch["feat_vals"].astype(cfg.dtype)  # (B, nnz)
        y = batch["label"].astype(cfg.dtype)  # (B,)
        w = batch["weight"].astype(cfg.dtype)  # (B,)

        if cfg.head_prefix_cols:  # nnz-major pull order (see _flatten)
            wrows = pulled[WEIGHT_TABLE].reshape(nnz, B).T
        else:
            wrows = pulled[WEIGHT_TABLE].reshape(B, nnz)
        margin = jnp.sum(wrows * x, axis=-1)
        loss = jnp.maximum(0.0, 1.0 - y * margin)
        x2 = jnp.sum(x * x, axis=-1)
        tau = _tau(cfg.variant, cfg.C, loss, x2) * w  # zero for padding
        if cfg.batch_average:
            tau = tau / jnp.maximum(jnp.sum(w), 1.0)

        # Per-feature delta: tau * y * x_f; dropped slots push id -1.
        deltas = (tau * y)[:, None] * x  # (B, nnz)
        active = (x != 0.0) & (w[:, None] > 0)
        push_ids = jnp.where(active, batch["feat_ids"].astype(jnp.int32), -1)

        mistakes = jnp.sum(w * (jnp.sign(margin) != y))
        out = {
            "mistakes": mistakes.astype(jnp.float32),
            "loss": jnp.sum(loss * w).astype(jnp.float32),
            "n": jnp.sum(w).astype(jnp.float32),
        }
        pushes = {
            WEIGHT_TABLE: (self._flatten(push_ids),
                           self._flatten(deltas)[:, None])
        }
        return StepOutput(pushes=pushes, local_state=local_state, out=out)


class MulticlassPassiveAggressiveWorker(WorkerLogic):
    """Multiclass PA: per-class weight columns, max-margin violation update.

    For true class r and highest-scoring wrong class s:
    l = max(0, 1 - (score_r - score_s)), tau per variant with ||x||^2
    doubled (the update touches two class columns), push +tau·x to column r
    and -tau·x to column s. Mirrors the reference's multiclass algorithm
    shape (expected upstream ``.../passive/aggressive/algorithm/``).
    """

    def __init__(self, cfg: PAConfig):
        if cfg.num_classes < 3:
            raise ValueError("use PassiveAggressiveWorker for binary")
        if cfg.head_prefix_cols:
            # Head-prefix routing targets scalar tables (dim-1 kernels);
            # the multiclass table is (NF, num_classes). Fail loudly
            # rather than silently ignoring the knob.
            raise ValueError(
                "head_prefix_cols is binary-only (the multiclass table "
                "is not dim-1; no head-only kernel route exists for it)"
            )
        self.cfg = cfg

    def pull_ids(self, batch) -> Mapping[str, Array]:
        return {WEIGHT_TABLE: batch["feat_ids"].astype(jnp.int32).reshape(-1)}

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        cfg = self.cfg
        B, nnz = batch["feat_ids"].shape
        C = cfg.num_classes
        x = batch["feat_vals"].astype(cfg.dtype)  # (B, nnz)
        r = batch["label"].astype(jnp.int32)  # (B,) class index
        w = batch["weight"].astype(cfg.dtype)

        wrows = pulled[WEIGHT_TABLE].reshape(B, nnz, C)
        scores = jnp.einsum("bn,bnc->bc", x, wrows)  # (B, C)
        r_onehot = jax.nn.one_hot(r, C, dtype=cfg.dtype)
        score_r = jnp.sum(scores * r_onehot, axis=-1)
        masked = jnp.where(r_onehot > 0, -jnp.inf, scores)
        s = jnp.argmax(masked, axis=-1)
        score_s = jnp.max(masked, axis=-1)

        loss = jnp.maximum(0.0, 1.0 - (score_r - score_s))
        x2 = 2.0 * jnp.sum(x * x, axis=-1)
        tau = _tau(cfg.variant, cfg.C, loss, x2) * w
        if cfg.batch_average:
            tau = tau / jnp.maximum(jnp.sum(w), 1.0)

        s_onehot = jax.nn.one_hot(s, C, dtype=cfg.dtype)
        class_dir = r_onehot - s_onehot  # (B, C)
        # delta[b, f, c] = tau_b * x_bf * class_dir_bc
        deltas = tau[:, None, None] * x[:, :, None] * class_dir[:, None, :]

        active = (x != 0.0) & (w[:, None] > 0)
        push_ids = jnp.where(active, batch["feat_ids"].astype(jnp.int32), -1)

        pred = jnp.argmax(scores, axis=-1)
        mistakes = jnp.sum(w * (pred != r))
        out = {
            "mistakes": mistakes.astype(jnp.float32),
            "loss": jnp.sum(loss * w).astype(jnp.float32),
            "n": jnp.sum(w).astype(jnp.float32),
        }
        pushes = {WEIGHT_TABLE: (push_ids.reshape(-1), deltas.reshape(-1, C))}
        return StepOutput(pushes=pushes, local_state=local_state, out=out)


def make_store(mesh, cfg: PAConfig) -> ParamStore:
    spec = TableSpec(
        name=WEIGHT_TABLE,
        num_ids=cfg.num_features,
        dim=cfg.table_dim,
        dtype=cfg.dtype,
        hot_ids=min(cfg.hot_features, cfg.num_features),
    ).zeros_init()  # reference: paramInit = 0.0 per feature
    return ParamStore(mesh, [spec])


def passive_aggressive(mesh, cfg: PAConfig, *, sync_every: int | None = None,
                       donate: bool = True,
                       max_steps_per_call: int | None = None,
                       guard=None):
    """(trainer, store) — the analog of
    ``PassiveAggressiveParameterServer.transformBinary/transformMulticlass``.

    ``guard``: push-delta health guard (``TrainerConfig.guard``) —
    ``"mask"`` drops poison updates in-step, ``"observe"`` only counts."""
    from fps_tpu.core.driver import Trainer, TrainerConfig

    store = make_store(mesh, cfg)
    worker = (
        PassiveAggressiveWorker(cfg)
        if cfg.num_classes == 2
        else MulticlassPassiveAggressiveWorker(cfg)
    )
    trainer = Trainer(
        mesh, store, worker,
        config=TrainerConfig(sync_every=sync_every, donate=donate,
                             max_steps_per_call=max_steps_per_call,
                             guard=guard),
    )
    return trainer, store


def predict_host(store: ParamStore, feat_ids: np.ndarray,
                 feat_vals: np.ndarray, num_classes: int = 2) -> np.ndarray:
    """Host-side predictions from the live table (binary: {-1,+1};
    multiclass: class index)."""
    rows = store.lookup_host(WEIGHT_TABLE, feat_ids.reshape(-1))
    B, nnz = feat_ids.shape
    rows = rows.reshape(B, nnz, -1)
    scores = np.einsum("bn,bnc->bc", feat_vals, rows)
    if num_classes == 2:
        return np.where(scores[:, 0] > 0, 1.0, -1.0)
    return np.argmax(scores, axis=-1)
