from fps_tpu.models.ials import IALSConfig, IALSSolver
from fps_tpu.models.logistic_regression import (
    LogisticRegressionWorker,
    logistic_regression,
)
from fps_tpu.models.matrix_factorization import MatrixFactorizationWorker, online_mf
from fps_tpu.models.passive_aggressive import (
    MulticlassPassiveAggressiveWorker,
    PassiveAggressiveWorker,
    passive_aggressive,
)
from fps_tpu.models.word2vec import Word2VecWorker, word2vec

__all__ = [
    "IALSConfig",
    "IALSSolver",
    "LogisticRegressionWorker",
    "logistic_regression",
    "MatrixFactorizationWorker",
    "online_mf",
    "MulticlassPassiveAggressiveWorker",
    "PassiveAggressiveWorker",
    "passive_aggressive",
    "Word2VecWorker",
    "word2vec",
]
