from fps_tpu.models.matrix_factorization import MatrixFactorizationWorker, online_mf

__all__ = ["MatrixFactorizationWorker", "online_mf"]
