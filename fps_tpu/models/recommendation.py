"""Top-K recommendation over a sharded factor table.

Reference behavior being rebuilt (SURVEY.md §2 #8): the reference's online-MF
package ships a top-K variant (upstream ``PSOnlineMatrixFactorizationAndTopK``,
expected under ``src/main/scala/hu/sztaki/ilab/ps/matrix/factorization/``)
that, alongside training, emits the current top-K items for a user by scoring
the user's factor vector against the item factors held on the servers.

TPU-native design — instead of the reference's per-rating pull-everything
scoring on one worker, ranking is a sharded dense score + distributed top-k
merge, all on-device:

* each shard scores the queries against **its own rows only**:
  ``(B, dim) @ (rps, dim)^T`` — one MXU matmul per shard, no table movement;
* each shard takes a **local top-(k+E)** of its partial scores
  (``E`` = exclusion capacity, so exclusions can never eat into the true
  top-k);
* the ``S*(k+E)`` candidates per query are ``all_gather``-ed over ICI
  (tiny: candidates only, never the table) and merged with a final top-k.

Exclusion (mask the user's already-rated items — the reference's top-K
worker keeps exactly such a seen-set) is per-query: pass ``exclude`` ids,
``-1`` for unused slots.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu.core.store import ParamStore, phys_to_id
from fps_tpu.parallel.mesh import SHARD_AXIS

Array = jax.Array

NEG_INF = jnp.float32(-3.0e38)


def build_topk_fn(store: ParamStore, table: str, k: int,
                  exclude_capacity: int = 0):
    """Compile ``(tables, queries, exclude) -> (ids, scores)`` top-k ranking.

    Args:
      store: the :class:`ParamStore` holding ``table`` (its mesh is used).
      table: name of the ``(num_ids, dim)`` factor table to rank over.
      k: results per query.
      exclude_capacity: max exclusion ids per query (0 disables the
        ``exclude`` argument's effect; slots of ``-1`` are ignored).

    Returns:
      A jitted function ``fn(tables, queries, exclude)``:
        * ``queries`` — ``(B, dim)`` float query vectors (user factors),
        * ``exclude`` — ``(B, exclude_capacity)`` int32 ids to mask
          (pass an all ``-1`` array when unused),
      returning ``(ids (B, k) int32, scores (B, k))``, best first.
    """
    mesh = store.mesh
    spec = store.specs[table]
    num_shards = store.num_shards
    cand = k + exclude_capacity
    table_specs = {name: P(SHARD_AXIS, None) for name in store.specs}

    def device_fn(tables, queries, exclude):
        local = tables[table]  # (rps, dim) this shard's block
        top_s, top_ids = _score_and_local_topk(
            local, queries, num_shards=num_shards, num_ids=spec.num_ids,
            n=cand,
        )  # (B, n_local)

        # Merge: gather every shard's candidates (concat along axis 1).
        all_s = lax.all_gather(top_s, SHARD_AXIS, axis=1, tiled=True)
        all_i = lax.all_gather(top_ids, SHARD_AXIS, axis=1, tiled=True)

        if exclude_capacity:
            hit = jnp.any(
                all_i[:, :, None] == exclude[:, None, :], axis=-1
            )  # (B, S*n_local)
            all_s = jnp.where(hit, NEG_INF, all_s)

        return _merge_topk(all_s, all_i, k)

    shmapped = jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(table_specs, P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(shmapped)


def recommend_topk(
    store: ParamStore,
    table: str,
    queries: np.ndarray,
    k: int,
    *,
    exclude: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """One-shot host API: rank ``table`` rows for ``queries``.

    ``exclude`` is an optional ``(B, E)`` int array of ids to mask per query
    (``-1`` = unused slot). Returns ``(ids, scores)`` as numpy arrays.

    The online analog of streaming top-K emission: call this between chunks
    (the tables passed are the live sharded arrays — no copies are made).
    """
    B = len(queries)
    E = 0 if exclude is None else int(np.asarray(exclude).shape[1])
    # Memoize the compiled program on the store (repeated streaming calls
    # between training chunks must not re-trace/re-compile).
    cache = store.__dict__.setdefault("_topk_fns", {})
    cache_key = (table, k, E)
    fn = cache.get(cache_key)
    if fn is None:
        fn = cache[cache_key] = build_topk_fn(store, table, k, exclude_capacity=E)
    replicated = NamedSharding(store.mesh, P())
    q = jax.device_put(jnp.asarray(queries), replicated)
    ex = jax.device_put(
        jnp.asarray(
            exclude if exclude is not None else np.full((B, 1), -1), jnp.int32
        ),
        replicated,
    )
    ids, scores = fn(store.tables, q, ex)
    return np.asarray(ids), np.asarray(scores)


def _score_and_local_topk(local, queries, *, num_shards, num_ids, n):
    """Shared per-shard scoring block: score ``queries`` against this
    shard's rows (MXU matmul), mask padding rows, and take the local
    top-``n`` with logical ids. Used by both the replicated-query ranking
    (:func:`build_topk_fn`) and the per-worker tap path, so masking /
    id-translation fixes cannot drift between them."""
    rps = local.shape[0]
    me = lax.axis_index(SHARD_AXIS)
    phys = me * rps + jnp.arange(rps, dtype=jnp.int32)
    ids = phys_to_id(phys, num_shards, rps)

    scores = queries.astype(jnp.float32) @ local.astype(jnp.float32).T
    scores = jnp.where((ids < num_ids)[None, :], scores, NEG_INF)

    n_local = min(n, rps)
    top_s, top_i = lax.top_k(scores, n_local)
    return top_s, jnp.take(ids, top_i)


def _merge_topk(scores, ids, k):
    """Final cross-shard merge: top-``k`` of the ``(B, S*n_local)`` candidate
    pool. On a small table (rows_per_shard < k/S) the pool can undershoot
    ``k``, and ``lax.top_k(x, k)`` with ``k > x.shape[-1]`` fails at trace
    time with an opaque XLA error — clamp, then pad back out to the (B, k)
    contract with -1 ids / NEG_INF scores (the same "no candidate" sentinels
    the off-cadence tap path emits). Shared by :func:`build_topk_fn` and
    :func:`_topk_local_queries` so the clamp cannot drift between them."""
    k_eff = min(k, scores.shape[1])
    out_s, out_j = lax.top_k(scores, k_eff)
    out_i = jnp.take_along_axis(ids, out_j, axis=1)
    if k_eff < k:
        pad = ((0, 0), (0, k - k_eff))
        out_s = jnp.pad(out_s, pad, constant_values=NEG_INF)
        out_i = jnp.pad(out_i, pad, constant_values=-1)
    return out_i.astype(jnp.int32), out_s


def _topk_local_queries(local, queries, *, num_shards, num_ids, k):
    """Device-side top-k for PER-WORKER queries (inside shard_map).

    Unlike :func:`build_topk_fn` (replicated queries), every worker here
    ranks its OWN ``(q, dim)`` queries: queries are all-gathered across the
    shard axis so each shard scores its rows against everyone's queries,
    local candidates are exchanged, and each worker merges the slice
    belonging to its queries. Candidate traffic only — the table never
    moves.
    """
    me = lax.axis_index(SHARD_AXIS)
    q = queries.shape[0]
    q_all = lax.all_gather(queries, SHARD_AXIS, tiled=True)  # (S*q, dim)
    top_s, top_ids = _score_and_local_topk(
        local, q_all, num_shards=num_shards, num_ids=num_ids, n=k
    )  # (S*q, n_local)
    n_local = top_s.shape[1]

    all_s = lax.all_gather(top_s, SHARD_AXIS)  # (S, S*q, n_local)
    all_i = lax.all_gather(top_ids, SHARD_AXIS)
    mine_s = lax.dynamic_slice_in_dim(all_s, me * q, q, axis=1)  # (S, q, n)
    mine_i = lax.dynamic_slice_in_dim(all_i, me * q, q, axis=1)
    mine_s = mine_s.transpose(1, 0, 2).reshape(q, -1)  # (q, S*n_local)
    mine_i = mine_i.transpose(1, 0, 2).reshape(q, -1)

    return _merge_topk(mine_s, mine_i, k)


def make_online_topk_tap(store: ParamStore, table: str, k: int, *,
                         every: int, query_fn):
    """Build a ``TrainerConfig.step_tap`` emitting top-K INSIDE the loop.

    The reference's ``...AndTopK`` jobs emit the current top-K items for
    the users being trained, interleaved with training on the output
    stream. This tap reproduces that shape: every ``every`` steps each
    worker ranks ``query_fn``'s queries against the live sharded table and
    the results ride the metrics stream (leaves ``(T, W, q, k)`` after the
    driver's per-worker gather); off-cadence steps emit ``-1`` ids and
    ``NEG_INF`` scores and skip the ranking work entirely (``lax.cond``).

    ``query_fn(batch, local_state) -> (query_ids (q,) int32,
    queries (q, dim))`` — e.g. the first q users of the worker's current
    batch with their local factor rows (:func:`mf_topk_query_fn`).
    """
    num_shards = store.num_shards
    num_ids = store.specs[table].num_ids

    def tap(tables, batch, local_state, t):
        qids, queries = query_fn(batch, local_state)
        q = queries.shape[0]

        def emit(_):
            return _topk_local_queries(
                tables[table], queries,
                num_shards=num_shards, num_ids=num_ids, k=k,
            )

        def skip(_):
            return (jnp.full((q, k), -1, jnp.int32),
                    jnp.full((q, k), NEG_INF))

        on = (t % every) == 0
        ids, scores = lax.cond(on, emit, skip, None)
        return {
            "topk_query": jnp.where(on, qids.astype(jnp.int32), -1),
            "topk_ids": ids,
            "topk_scores": scores,
        }

    return tap


def mf_topk_query_fn(num_workers: int, num_queries: int):
    """Query fn for MF: the first ``num_queries`` users of the worker's
    batch, with their worker-local factor rows (no communication).

    Padding rows (``weight == 0``) emit query id ``-1``: a padded slot's
    user id belongs to ANOTHER worker's routing domain, so its local
    factor-row lookup would silently rank with a different user's vector
    — consumers must skip ``-1`` queries (their ranking rows are
    meaningless)."""
    from fps_tpu.core.store import pull_local

    def query_fn(batch, local_state):
        users = batch["user"][:num_queries].astype(jnp.int32)
        valid = batch["weight"][:num_queries] > 0
        qids = jnp.where(valid, users, -1)
        qvecs = pull_local(local_state, users, num_shards=num_workers)
        return qids, qvecs

    return query_fn


def mf_user_vectors(
    user_factors_global: np.ndarray, num_workers: int, users: np.ndarray
) -> np.ndarray:
    """Extract user factor rows from MF's worker-sharded local state.

    MF keeps user vectors worker-local in owner-major cyclic layout
    (``fps_tpu.models.matrix_factorization``); this resolves logical user
    ids to their physical rows for use as top-k ``queries``.
    """
    table = np.asarray(user_factors_global)
    rps = table.shape[0] // num_workers
    users = np.asarray(users)
    return table[(users % num_workers) * rps + users // num_workers]
