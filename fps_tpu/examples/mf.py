"""Online matrix factorization entrypoint (MovieLens-style ratings).

The analog of the reference's MF example job (upstream a ``main`` next to
``PSOnlineMatrixFactorization``, SURVEY.md §3.3): parse CLI args, build the
pipeline, train, emit metrics and the final model. ``--topk K`` additionally
prints top-K recommendations for a few users — the reference's
``...AndTopK`` variant.
"""

from __future__ import annotations

import jax
import numpy as np

from fps_tpu.examples.common import (
    apply_host_pipeline,
    apply_hot_tier,
    attach_obs,
    base_parser,
    make_guard,
    emit,
    finish,
    make_chunks,
    make_rollback,
    make_watchdog,
    make_mesh,
    maybe_checkpointer,
    maybe_profile,
    maybe_serve,
    maybe_warm_start,
)


def main(argv=None) -> int:
    ap = base_parser("Online MF (SGD) on the TPU parameter server")
    ap.add_argument("--scale", default="100k", choices=["100k", "1m", "20m"],
                    help="synthetic size when no --input is given")
    ap.add_argument("--rank", type=int, default=10)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("--reg", type=float, default=0.01)
    ap.add_argument("--topk", type=int, default=0,
                    help="after training, print top-K items for sample users")
    ap.add_argument("--topk-every", type=int, default=0,
                    help="emit top-K (per worker, for users being trained) "
                         "every N steps FROM INSIDE the compiled loop — the "
                         "reference's streaming ...AndTopK shape; requires "
                         "--topk")
    ap.add_argument("--negative-samples", type=int, default=0,
                    help="sample this many unrated items per rating as "
                         "weighted pseudo-negatives (implicit feedback)")
    ap.add_argument("--negative-weight", type=float, default=0.5)
    args = ap.parse_args(argv)
    if args.topk_every and not args.topk:
        raise SystemExit("--topk-every requires --topk")

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import (
        MFConfig,
        online_mf,
        predict_host,
        rmse,
    )
    from fps_tpu.utils.datasets import load_movielens, train_test_split

    data, nu, ni = load_movielens(args.input, args.scale)
    train, test = train_test_split(data, test_frac=0.1, seed=args.seed + 1)
    mesh = make_mesh(args)
    W = num_workers_of(mesh)
    emit({"event": "start", "workload": "mf", "num_users": nu, "num_items": ni,
          "num_ratings": len(data["user"]), "mesh": dict(mesh.shape)})

    cfg = MFConfig(num_users=nu, num_items=ni, rank=args.rank,
                   learning_rate=args.learning_rate, reg=args.reg,
                   negative_samples=args.negative_samples,
                   negative_weight=args.negative_weight)
    trainer, store = online_mf(mesh, cfg, sync_every=args.sync_every,
                               guard=make_guard(args))
    if args.topk_every:
        import dataclasses

        from fps_tpu.models.recommendation import (
            make_online_topk_tap,
            mf_topk_query_fn,
        )

        trainer.config = dataclasses.replace(
            trainer.config,
            step_tap=make_online_topk_tap(
                store, "item_factors", args.topk, every=args.topk_every,
                query_fn=mf_topk_query_fn(W, num_queries=2),
            ),
        )
    apply_hot_tier(args, trainer)
    apply_host_pipeline(args, trainer)
    rec = attach_obs(args, trainer, workload="mf")
    tables, local_state = trainer.init_state(jax.random.key(args.seed))
    maybe_warm_start(args, store, None)

    chunks = make_chunks(args, mesh, train, route_key="user")

    def report(i, m):
        se, n = np.sum(m["se"]), max(1.0, np.sum(m["n"]))
        emit({"event": "chunk", "i": i, "train_rmse": float(np.sqrt(se / n)),
              "examples": float(n)})
        if "tap" in m:
            # Streaming AndTopK records: one event per emission step.
            users = np.asarray(m["tap"]["topk_query"])  # (T, W, q)
            items = np.asarray(m["tap"]["topk_ids"])  # (T, W, q, k)
            for t in np.flatnonzero((users >= 0).any(axis=(1, 2))):
                emit({"event": "topk_online", "chunk": i, "step": int(t),
                      "users": users[t].reshape(-1),
                      "items": items[t].reshape(users[t].size, -1)})

    with maybe_profile(args), maybe_serve(args, rec):
        tables, local_state, _ = trainer.fit_stream(
            tables, local_state, chunks, jax.random.key(args.seed),
            checkpointer=maybe_checkpointer(args),
            checkpoint_every=args.checkpoint_every,
            on_chunk=report,
            rollback=make_rollback(args),
            watchdog=make_watchdog(args, rec),
        )

    uf = np.asarray(local_state)
    pred = predict_host(store, uf, W, test["user"], test["item"])
    emit({"event": "done", "test_rmse": rmse(pred, test["rating"])})

    if args.topk:
        from fps_tpu.models.recommendation import mf_user_vectors, recommend_topk

        users = np.unique(test["user"])[:8]
        q = mf_user_vectors(uf, W, users)
        ids, scores = recommend_topk(store, "item_factors", q, args.topk)
        for u, row_i, row_s in zip(users, ids, scores):
            emit({"event": "topk", "user": int(u), "items": row_i,
                  "scores": np.round(row_s, 4)})

    finish(args, store, recorder=rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
