"""Shared CLI plumbing for the example entrypoints."""

from __future__ import annotations

import argparse
import contextlib
import json
import sys

import numpy as np


def base_parser(description: str) -> argparse.ArgumentParser:
    """Common flags: mesh shape (the reference's workerParallelism /
    psParallelism pair), batching, execution mode, persistence."""
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--input", default=None, help="dataset path (default: synthetic)")
    ap.add_argument("--num-shards", type=int, default=None,
                    help="parameter-shard axis size (reference: psParallelism); "
                         "default: all devices")
    ap.add_argument("--num-data", type=int, default=1,
                    help="replicated data-parallel axis size")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--local-batch", type=int, default=256,
                    help="examples per worker per step")
    ap.add_argument("--steps-per-chunk", type=int, default=16,
                    help="microbatch steps per compiled call")
    ap.add_argument("--sync-every", type=int, default=None,
                    help="SSP staleness bound s (default: fully synchronous)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--export", default=None, help="write final model to this .npz")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="snapshot every N chunks (requires --checkpoint-dir)")
    ap.add_argument("--checkpoint-async", action="store_true",
                    help="double-buffered background checkpoint writer "
                         "(fps_tpu.core.checkpoint.AsyncCheckpointer): "
                         "save() returns before serialize+fsync; the "
                         "driver's end-of-run flush is the durability "
                         "barrier")
    ap.add_argument("--warm-start", default=None,
                    help="initialize tables from a saved model .npz "
                         "(reference: transformWithModelLoad)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="write a jax.profiler device trace of the training "
                         "region under DIR (view with XProf/Perfetto)")
    ap.add_argument("--ingest", default="device", choices=["device", "host"],
                    help="'device' keeps the dataset resident on the mesh "
                         "and builds chunks with on-device gathers (fast "
                         "path on TPU VMs); 'host' regenerates and uploads "
                         "every chunk (the unbounded-stream shape)")
    ap.add_argument("--prefetch", type=int, default=0, metavar="N",
                    help="overlapped host pipeline depth "
                         "(fps_tpu.core.prefetch): chunk assembly and "
                         "host->device placement run up to N chunks "
                         "ahead on a background thread, so the device "
                         "never idles on host ingest; 0 = synchronous "
                         "host loop. Numerics are bit-identical either "
                         "way; 2 is the recommended depth")
    ap.add_argument("--hot-tier", type=int, default=0, metavar="H",
                    help="two-tier parameter storage "
                         "(fps_tpu.core.store.TableSpec.hot_tier): "
                         "replicate the leading H ids of every PS table "
                         "across the shard axis — hot reads become "
                         "collective-free local gathers, hot pushes "
                         "accumulate locally and reconcile by one psum "
                         "every --hot-sync-every steps. Ids must be "
                         "frequency-ranked (hottest first; the shipped "
                         "loaders are). Engages on multi-device meshes "
                         "with --hot-sync-every > 1 and an additive/mean "
                         "server fold; otherwise the exact untiered "
                         "program runs")
    ap.add_argument("--hot-sync-every", type=int, default=1, metavar="E",
                    help="hot-tier reconcile cadence in steps "
                         "(TrainerConfig.hot_sync_every): the SSP "
                         "staleness bound applied to the parameter "
                         "plane. 1 (default) = exact mode, bit-identical "
                         "to the untiered path")
    ap.add_argument("--cold-budget", type=int, default=0, metavar="C",
                    help="payload-proportional cold routing "
                         "(TableSpec.cold_budget): with a PARTIAL hot "
                         "head, compact each batch's cold ids into a "
                         "C-wide per-worker lane so the cold collective "
                         "routes carry O(cold traffic) payload instead "
                         "of O(batch). Host-certified per chunk (like "
                         "head_prefix); overflowing chunks fall back to "
                         "the static routes bit-identically with a "
                         "cold_route.overflow_chunks counter. Requires "
                         "--hot-tier with H < num_ids on a non-dense "
                         "route; 0 = static cold routes")
    ap.add_argument("--hot-fold", default=None,
                    choices=["adagrad", "adam"],
                    help="stateful hot-tier server optimizer "
                         "(ServerLogic.hot_fold): per-row Adagrad/Adam "
                         "state sharded over the replica axis by the "
                         "sharded reconcile (reduce-scatter -> apply "
                         "the owned 1/S slice -> all-gather). Requires "
                         "a FULLY-replicated hot tier (--hot-tier >= "
                         "num_ids) with --hot-sync-every > 1; state "
                         "rides checkpoints as fold:: arrays, canonical "
                         "table bytes unchanged")
    ap.add_argument("--auto-tier", action="store_true",
                    help="adaptive tiering (fps_tpu.tiering, "
                         "docs/performance.md): track pulled-id "
                         "frequencies online (device-side count-min, "
                         "psum-merged), derive per-table hot_tier / "
                         "hot_sync_every / dense route from the "
                         "sketched densities after a warmup (replacing "
                         "the hand-tuned --hot-tier/--hot-sync-every "
                         "knobs), and re-rank the hot set on drift — "
                         "re-ranks swap replicated data, never "
                         "recompile. Explicit --hot-tier/"
                         "--hot-sync-every still apply until the "
                         "planner's first decision")
    ap.add_argument("--guard", default=None, choices=["observe", "mask"],
                    help="on-device push-delta health guard "
                         "(fps_tpu.core.resilience): 'mask' drops "
                         "non-finite / norm-exploded update rows in-step, "
                         "'observe' only counts them onto the metrics "
                         "stream; default off (zero-cost)")
    ap.add_argument("--guard-norm-limit", type=float, default=None,
                    help="per-row L2 norm ceiling for push deltas "
                         "(requires --guard)")
    ap.add_argument("--guard-local", action="store_true",
                    help="extend the guard to worker-LOCAL state updates "
                         "(e.g. MF user factors): poisoned local rows are "
                         "counted — and in mask mode reverted — like "
                         "poisoned pushes (requires --guard)")
    ap.add_argument("--rollback-budget", type=int, default=None,
                    help="quarantine poisoned chunks via a host-loop "
                         "RollbackPolicy with this budget (requires "
                         "--guard); under a supervisor, indices "
                         "quarantined by previous attempts are always "
                         "carried in, budget flag or not")
    ap.add_argument("--heartbeat", default=None, metavar="PATH",
                    help="touch this progress-beacon file on every "
                         "chunk/epoch boundary (default: the "
                         "FPS_TPU_HEARTBEAT env var, set automatically "
                         "by tools/supervise.py)")
    ap.add_argument("--serve-port", type=int, default=None, metavar="PORT",
                    help="publish this run's snapshots to query traffic "
                         "WHILE training (fps_tpu.serve, docs/serving.md): "
                         "a SnapshotWatcher hot-swaps each new checkpoint "
                         "into a line-JSON TCP ReadServer on "
                         "127.0.0.1:PORT (0 = ephemeral; the bound port "
                         "is emitted). Requires --checkpoint-dir and "
                         "--checkpoint-every")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="telemetry output (fps_tpu.obs): JSONL event log, "
                         "per-process run journal, and Prometheus text "
                         "exposition under DIR; render with "
                         "tools/obs_report.py")
    ap.add_argument("--obs-watchdog-s", type=float, default=None,
                    help="flag any chunk whose dispatch+sync exceeds this "
                         "many seconds (stalled dispatch / hung multi-host "
                         "peer); forces a per-chunk metrics sync")
    return ap


def _make_heartbeat(args):
    """--heartbeat / the supervisor's FPS_TPU_HEARTBEAT env contract →
    a Heartbeat, or None when this run is unsupervised."""
    from fps_tpu.supervise import child

    path = getattr(args, "heartbeat", None)
    if path:
        return child.Heartbeat(path)
    return child.from_env()


def attach_obs(args, trainer=None, *, workload: str | None = None):
    """Resolve --obs-dir (and the supervised-heartbeat contract) into an
    installed recorder (or None).

    Opens the standard on-disk telemetry set under ``--obs-dir``
    (``fps_tpu.obs.open_run``), stamps the run journal with the CLI args
    as the config digest, installs it as the process-default recorder
    (checkpoint/rollback events flow automatically), and attaches it to
    ``trainer`` when given. Close via :func:`finish`.

    When the run is supervised (``--heartbeat`` or the supervisor's
    ``FPS_TPU_HEARTBEAT`` env var), a HeartbeatSink rides the recorder so
    every chunk/epoch journal event doubles as the supervisor's liveness
    signal; with no ``--obs-dir`` a minimal heartbeat-only recorder is
    returned instead — attaching one never changes training behavior.
    """
    hb = _make_heartbeat(args)
    if getattr(args, "obs_dir", None) is None:
        if getattr(args, "obs_watchdog_s", None) is not None:
            raise SystemExit("--obs-watchdog-s requires --obs-dir")
        if hb is None:
            return None
        from fps_tpu.obs import Recorder
        from fps_tpu.supervise import child

        rec = Recorder(sinks=[child.HeartbeatSink(hb)])
        if trainer is not None:
            trainer.recorder = rec
        return rec
    from fps_tpu import obs

    rec = obs.open_run(args.obs_dir, config=vars(args),
                       meta={"workload": workload} if workload else None)
    if hb is not None:
        from fps_tpu.supervise import child

        rec.sinks.append(child.HeartbeatSink(hb))
    if trainer is not None:
        trainer.recorder = rec
    emit({"event": "obs", "dir": args.obs_dir, "run_id": rec.run_id})
    return rec


def apply_host_pipeline(args, trainer):
    """Fold the host-pipeline CLI knobs (--prefetch) into the trainer's
    config. Host-side only — the compiled program is unchanged — so this
    is a plain config replace, no factory plumbing."""
    if getattr(args, "prefetch", 0):
        import dataclasses

        if args.prefetch < 0:
            raise SystemExit(f"--prefetch must be >= 0, got {args.prefetch}")
        trainer.config = dataclasses.replace(trainer.config,
                                             prefetch=args.prefetch)
    return trainer


def apply_hot_tier(args, trainer, store=None):
    """Fold the two-tier storage CLI knobs (--hot-tier/--hot-sync-every)
    into the trainer's store specs and config. Must run before the first
    compiled call (the tier resolution is part of the compile key).

    ``trainer=None`` (iALS: half-epoch normal-equation solves, no
    pull/push Trainer to tier) accepts-and-reports the flag instead of
    failing, so the CLI surface stays uniform across the six examples.
    """
    H = getattr(args, "hot_tier", 0)
    E = getattr(args, "hot_sync_every", 1)
    auto = getattr(args, "auto_tier", False)
    cold = getattr(args, "cold_budget", 0)
    fold = getattr(args, "hot_fold", None)
    if E < 1:
        raise SystemExit(f"--hot-sync-every must be >= 1, got {E}")
    if H < 0:
        raise SystemExit(f"--hot-tier must be >= 0, got {H}")
    if cold < 0:
        raise SystemExit(f"--cold-budget must be >= 0, got {cold}")
    if cold and not (H or auto):
        raise SystemExit("--cold-budget needs a hot tier: pass "
                         "--hot-tier H (partial head) or --auto-tier")
    if fold and not H:
        raise SystemExit("--hot-fold needs --hot-tier (fully-replicated: "
                         "H >= num_ids) and --hot-sync-every > 1")
    if not H and E == 1 and not auto:
        return trainer
    if trainer is None:
        emit({"event": "hot_tier_ignored",
              "reason": "this workload has no pull/push trainer "
                        "(iALS half-epoch solves)"})
        return None
    import dataclasses

    store = store or trainer.store
    if H:
        for name, spec in store.specs.items():
            store.specs[name] = dataclasses.replace(
                spec, hot_tier=min(H, spec.num_ids),
                cold_budget=cold)
    if fold:
        for name, sl in trainer.server_logic.items():
            trainer.server_logic[name] = dataclasses.replace(
                sl, hot_fold=fold)
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=E,
                                         auto_tier=auto)
    tiered = sorted(trainer._hot_tier_map())  # also validates vs push_delay
    emit({"event": "hot_tier", "hot_tier": H, "hot_sync_every": E,
          "auto_tier": auto, "tiered_tables": tiered,
          "cold_budget": cold, "hot_fold": fold,
          "compacted_tables": sorted(trainer._cold_compact_map()),
          "exact_mode": (E == 1 or not tiered) and not auto})
    return trainer


def maybe_serve(args, recorder=None):
    """Resolve ``--serve-port`` into a serve-while-train context manager.

    Inside the ``with`` block a background thread polls
    ``--checkpoint-dir`` (and the ``--obs-dir`` journal, when set — the
    ``checkpoint_saved`` events carry path/step/bytes so no directory
    re-stat is needed) and hot-swaps every new verified snapshot into a
    TCP ``ReadServer``; exit stops the watcher, closes the socket, and
    emits the serve stats. Returns a no-op context when the flag is off,
    so call sites wrap the training region unconditionally.
    """
    if getattr(args, "serve_port", None) is None:
        return contextlib.nullcontext()
    if not (args.checkpoint_dir and args.checkpoint_every > 0):
        raise SystemExit("--serve-port requires --checkpoint-dir and "
                         "--checkpoint-every (serving reads published "
                         "snapshots)")
    import threading

    from fps_tpu.serve import ReadServer, TcpServe

    server, watcher = ReadServer.over(
        args.checkpoint_dir, journal=getattr(args, "obs_dir", None),
        recorder=recorder)
    tcp = TcpServe(server, port=args.serve_port).start()
    stop = threading.Event()
    thread = threading.Thread(
        target=watcher.run, kwargs={"interval_s": 0.5, "stop": stop},
        name="fps-serve-watcher", daemon=True)
    thread.start()
    emit({"event": "serving", "host": tcp.host, "port": tcp.port,
          "ckpt_dir": args.checkpoint_dir})

    @contextlib.contextmanager
    def running():
        try:
            yield server
        finally:
            stop.set()
            thread.join(timeout=10.0)
            tcp.close()
            if not thread.is_alive():
                # Final swap: the end-of-run flush's snapshot. Skipped
                # if the watcher thread outlived the join timeout (a
                # multi-GB verify can) — poll() is single-threaded by
                # contract and must not run concurrently with it.
                watcher.poll()
            stats = server.stats()
            stats.update(swaps=dict(watcher.swaps),
                         rejected=watcher.rejected,
                         write_to_servable_s=watcher.write_to_servable_s)
            emit({"event": "served", **stats})

    return running()


def make_watchdog(args, recorder):
    """--obs-watchdog-s into a StepWatchdog bound to the run's recorder."""
    if getattr(args, "obs_watchdog_s", None) is None:
        return None
    from fps_tpu.obs import StepWatchdog

    return StepWatchdog(args.obs_watchdog_s, recorder=recorder)


def make_guard(args):
    """Resolve the --guard flags into a TrainerConfig.guard value."""
    if args.guard is None:
        if args.guard_norm_limit is not None:
            raise SystemExit("--guard-norm-limit requires --guard")
        if getattr(args, "guard_local", False):
            raise SystemExit("--guard-local requires --guard")
        return None
    from fps_tpu.core.resilience import GuardConfig

    return GuardConfig(mode=args.guard, norm_limit=args.guard_norm_limit,
                       local=getattr(args, "guard_local", False))


def make_rollback(args):
    """--rollback-budget plus any supervisor-carried quarantine set into a
    RollbackPolicy (or None). The preset alone (no budget flag, no guard)
    is legal: a supervised restart must honor quarantine decisions even
    when the operator never asked for health-based rollback."""
    from fps_tpu.core.resilience import RollbackPolicy
    from fps_tpu.supervise import child

    preset = child.quarantined_from_env()
    budget = getattr(args, "rollback_budget", None)
    if budget is None and not preset:
        return None
    if budget is not None and args.guard is None:
        raise SystemExit("--rollback-budget requires --guard")
    policy = RollbackPolicy(preset=preset)
    if budget is not None:
        policy.max_rollbacks = budget
    if preset:
        emit({"event": "quarantine_carried", "indices": sorted(preset)})
    return policy


def make_epoch_source(args, mesh, data, *, route_key=None, num_workers=None):
    """Restartable chunk source honoring --ingest and the batching flags.

    Returns ``source(start_epoch=0, epochs=1) -> chunk iterator``. The
    device path builds the dataset and epoch plan ONCE, so repeated calls
    (e.g. iALS consuming the stream twice per epoch) reuse the compiled
    chunk builder instead of retracing it.
    """
    from fps_tpu.core.driver import num_workers_of

    W = num_workers_of(mesh) if num_workers is None else num_workers
    if args.ingest == "device":
        from fps_tpu.core.device_ingest import (
            DeviceDataset,
            DeviceEpochPlan,
            device_epoch_chunks,
        )

        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(
            ds, num_workers=W, local_batch=args.local_batch,
            route_key=route_key, sync_every=args.sync_every, seed=args.seed,
        )

        def source(start_epoch=0, epochs=1):
            return device_epoch_chunks(
                ds, num_workers=W, local_batch=args.local_batch,
                steps_per_chunk=args.steps_per_chunk, route_key=route_key,
                sync_every=args.sync_every, seed=args.seed,
                start_epoch=start_epoch, epochs=epochs, plan=plan,
            )
    else:
        from fps_tpu.core.ingest import epoch_chunks

        def source(start_epoch=0, epochs=1):
            def it():
                for e in range(start_epoch, start_epoch + epochs):
                    yield from epoch_chunks(
                        data, num_workers=W, local_batch=args.local_batch,
                        steps_per_chunk=args.steps_per_chunk,
                        route_key=route_key, sync_every=args.sync_every,
                        seed=None if args.seed is None else args.seed + e,
                    )

            return it()

    return source


def make_chunks(args, mesh, data, *, route_key=None):
    """Chunk iterator over --epochs passes (one-shot form of
    :func:`make_epoch_source`)."""
    return make_epoch_source(args, mesh, data, route_key=route_key)(
        0, args.epochs
    )


def make_mesh(args):
    from fps_tpu.parallel.mesh import make_ps_mesh

    return make_ps_mesh(num_shards=args.num_shards, num_data=args.num_data)


def emit(record: dict) -> None:
    """One JSON line per event — the WOut metrics stream."""
    json.dump({k: _py(v) for k, v in record.items()}, sys.stdout)
    sys.stdout.write("\n")
    sys.stdout.flush()


def _py(v):
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def finish(args, store, trainer=None, local_state=None, recorder=None):
    """Handle --export and close the --obs-dir telemetry at end of run."""
    if args.export:
        from fps_tpu.core.checkpoint import export_model

        export_model(store, args.export)
        emit({"event": "export", "path": args.export})
    if recorder is not None:
        recorder.close()  # run_end journal record + final flush


def maybe_checkpointer(args):
    if args.checkpoint_dir and args.checkpoint_every > 0:
        from fps_tpu.core.checkpoint import AsyncCheckpointer, Checkpointer

        cls = (AsyncCheckpointer if getattr(args, "checkpoint_async", False)
               else Checkpointer)
        return cls(args.checkpoint_dir)
    if getattr(args, "checkpoint_async", False):
        raise SystemExit("--checkpoint-async requires --checkpoint-dir "
                         "and --checkpoint-every")
    return None


def maybe_warm_start(args, store, key) -> None:
    """Apply --warm-start after store init (tables must exist first)."""
    if args.warm_start:
        from fps_tpu.core.checkpoint import load_model

        load_model(store, args.warm_start)
        emit({"event": "warm_start", "path": args.warm_start})


def maybe_profile(args):
    """Context manager tracing the training region when --profile is set."""
    if getattr(args, "profile", None):
        from fps_tpu.obs import trace

        emit({"event": "profile", "dir": args.profile})
        return trace(args.profile)
    return contextlib.nullcontext()
