"""word2vec SGNS entrypoint (text8-style token stream).

The analog of the reference's word2vec example job (SURVEY.md §2 #10;
BASELINE.json workload "word2vec skip-gram negative sampling (text8)").
Reports words/sec alongside the training loss — the BASELINE.json headline
unit for this workload — and prints nearest neighbors of a few frequent
words at the end as a qualitative check.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from fps_tpu.examples.common import (
    apply_host_pipeline,
    apply_hot_tier,
    attach_obs,
    base_parser,
    emit,
    finish,
    make_guard,
    make_mesh,
    make_rollback,
    make_watchdog,
    maybe_checkpointer,
    maybe_profile,
    maybe_serve,
    maybe_warm_start,
)


def main(argv=None) -> int:
    ap = base_parser("word2vec SGNS on the TPU PS")
    ap.add_argument("--vocab-size", type=int, default=50_000)
    ap.add_argument("--num-tokens", type=int, default=None,
                    help="truncate the corpus to this many tokens; sizes "
                         "the synthetic stream when no --input is given "
                         "(default: whole file / 2M synthetic)")
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--window", type=int, default=5)
    ap.add_argument("--negatives", type=int, default=5)
    ap.add_argument("--learning-rate", type=float, default=0.025)
    ap.add_argument("--sketch-words", type=int, default=0,
                    help="track the P most frequent words' co-occurrence "
                         "similarity with a tug-of-war sketch riding the "
                         "training loop (pair AND fused block paths; "
                         "0 = off)")
    args = ap.parse_args(argv)

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.word2vec import (
        W2VConfig,
        Word2VecDevicePlan,
        accumulate_sketch_taps,
        cooccurrence_sketch_tap,
        nearest_neighbors,
        sketch_similarity,
        skipgram_chunks,
        word2vec,
        word2vec_block,
    )
    from fps_tpu.utils.datasets import load_text8

    tokens, vocab, uni = load_text8(args.input, args.vocab_size,
                                    args.num_tokens, seed=args.seed)
    mesh = make_mesh(args)
    W = num_workers_of(mesh)
    emit({"event": "start", "workload": "word2vec", "vocab_size": vocab,
          "tokens": len(tokens), "mesh": dict(mesh.shape)})

    cfg = W2VConfig(vocab_size=vocab, dim=args.dim, window=args.window,
                    negatives=args.negatives, learning_rate=args.learning_rate)

    sketch_probe = None
    step_tap = None
    if args.sketch_words > 0:
        # Rides BOTH paths: the pair batches directly, and the fused block
        # path via id-only pair-stream reconstruction from the raw block
        # batch (models.word2vec.block_pair_stream).
        from fps_tpu.sketch import TugOfWarSpec

        sketch_probe = np.argsort(-uni)[: args.sketch_words].astype(np.int32)
        step_tap = cooccurrence_sketch_tap(
            TugOfWarSpec(depth=5, width=1024, seed=args.seed),
            sketch_probe,
        )

    block_len = max(64, args.local_batch // (2 * cfg.window))
    if args.ingest == "device":
        # Block-granularity worker: one pull/push row per block position
        # (~10x fewer sparse row transactions than per-pair pull/push).
        trainer, store = word2vec_block(
            mesh, cfg, uni, block_len, sync_every=args.sync_every,
            max_steps_per_call=256, step_tap=step_tap,
            guard=make_guard(args),
        )
    else:
        trainer, store = word2vec(mesh, cfg, uni, sync_every=args.sync_every,
                                  max_steps_per_call=256, step_tap=step_tap,
                                  guard=make_guard(args))
    apply_hot_tier(args, trainer)
    apply_host_pipeline(args, trainer)
    rec = attach_obs(args, trainer, workload="word2vec")
    tables, local_state = trainer.init_state(jax.random.key(args.seed))
    maybe_warm_start(args, store, None)

    total_pairs = 0.0
    sketch_sum = None

    def report(i, m):
        nonlocal total_pairs, sketch_sum
        n = max(1.0, float(np.sum(m["n"])))
        total_pairs += n
        if sketch_probe is not None and "tap" in m:
            part = accumulate_sketch_taps([m])
            sketch_sum = part if sketch_sum is None else sketch_sum + part
        emit({"event": "chunk", "i": i,
              "sgns_loss": float(np.sum(m["loss"]) / n)})

    t0 = time.perf_counter()
    with maybe_profile(args), maybe_serve(args, rec):
        if args.ingest == "device":
            # Fused path: tokens resident on device, subsampling/compaction
            # and pair generation inside the compiled epoch.
            plan = Word2VecDevicePlan(
                tokens, uni, cfg, mesh, num_workers=W, block_len=block_len,
                seed=args.seed, sync_every=args.sync_every, mode="block",
            )
            tables, local_state, _ = trainer.run_indexed(
                tables, local_state, plan, jax.random.key(args.seed),
                epochs=args.epochs, on_epoch=report,
                checkpointer=maybe_checkpointer(args),
                # --checkpoint-every counts chunks on the host path; the
                # fused path snapshots per epoch when it is enabled at all.
                checkpoint_every=1 if args.checkpoint_every > 0 else 0,
                rollback=make_rollback(args),
                watchdog=make_watchdog(args, rec),
            )
        else:
            def all_epochs():
                for epoch in range(args.epochs):
                    yield from skipgram_chunks(
                        tokens, uni, cfg, num_workers=W,
                        local_batch=args.local_batch,
                        steps_per_chunk=args.steps_per_chunk,
                        sync_every=args.sync_every, seed=args.seed + epoch,
                    )

            tables, local_state, _ = trainer.fit_stream(
                tables, local_state, all_epochs(), jax.random.key(args.seed),
                checkpointer=maybe_checkpointer(args),
                checkpoint_every=args.checkpoint_every,
                on_chunk=report,
                rollback=make_rollback(args),
                watchdog=make_watchdog(args, rec),
            )
    dt = time.perf_counter() - t0
    emit({"event": "done", "pairs_per_sec": total_pairs / max(dt, 1e-9),
          "words_per_sec": args.epochs * len(tokens) / max(dt, 1e-9),
          "seconds": dt})

    if sketch_sum is not None:
        sims = sketch_similarity(sketch_sum)
        emit({"event": "cooccurrence_similarity",
              "probe_words": sketch_probe,
              "inner_products": np.round(sims, 1)})

    # Qualitative: neighbors of a few frequent words (ids 1..4; 0 may be UNK).
    probes = np.arange(1, 5)
    nn_ids, nn_sims = nearest_neighbors(store, probes, k=5)
    for p, row_i, row_s in zip(probes, nn_ids, nn_sims):
        emit({"event": "neighbors", "word": int(p), "nearest": row_i,
              "sims": np.round(row_s, 3)})

    finish(args, store, recorder=rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
