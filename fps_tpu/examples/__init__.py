"""Per-algorithm CLI entrypoints (the reference's L5 example-job layer).

The reference ships a ``main()`` per algorithm that parses CLI args (input
path, parallelism, learning rate, rank, ...) and wires the pipeline
(SURVEY.md §1 L5; upstream these are ``*Example`` objects next to each
algorithm, launched with ``flink run``). Here each module is runnable as

    python -m fps_tpu.examples.mf --epochs 2 --rank 10 ...
    python -m fps_tpu.examples.passive_aggressive --variant PA-I ...
    python -m fps_tpu.examples.word2vec --dim 100 --negatives 5 ...
    python -m fps_tpu.examples.logreg_ssp --sync-every 8 ...
    python -m fps_tpu.examples.ials --rank 16 --alpha 40 ...

Every entrypoint falls back to a synthetic dataset with matched statistics
when no input path is given (this environment has no network egress), prints
per-chunk metrics as JSON lines (the reference's ``WOut`` observability
stream), and can export the final model (the reference's close()-time
``(id, param)`` stream) with ``--export model.npz``.
"""
