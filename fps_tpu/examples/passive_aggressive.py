"""Passive-aggressive classifier entrypoint (RCV1-style sparse examples).

The analog of the reference's PA example job
(``PassiveAggressiveParameterServer.transformBinary`` / ``transformMulticlass``
wired from a ``main``, SURVEY.md §3.4). ``--num-classes 2`` (default) runs the
binary variant; ``>2`` the multiclass one.
"""

from __future__ import annotations

import jax
import numpy as np

from fps_tpu.examples.common import (
    apply_host_pipeline,
    apply_hot_tier,
    attach_obs,
    base_parser,
    make_guard,
    make_chunks,
    make_rollback,
    make_watchdog,
    maybe_profile,
    emit,
    finish,
    make_mesh,
    maybe_checkpointer,
    maybe_serve,
    maybe_warm_start,
)


def main(argv=None) -> int:
    ap = base_parser("Passive-aggressive classification on the TPU PS")
    ap.add_argument("--num-features", type=int, default=10_000)
    ap.add_argument("--num-classes", type=int, default=2)
    ap.add_argument("--num-examples", type=int, default=50_000)
    ap.add_argument("--nnz", type=int, default=16)
    ap.add_argument("--variant", default="PA-I", choices=["PA", "PA-I", "PA-II"])
    ap.add_argument("--C", type=float, default=1.0)
    ap.add_argument("--input-format", default="auto",
                    choices=["auto", "svmlight", "criteo"],
                    help="--input file format (RCV1 svmlight or Criteo TSV)")
    ap.add_argument("--nnz-cap", type=int, default=None,
                    help="svmlight rows keep at most this many features "
                         "(default: the file's max row length)")
    args = ap.parse_args(argv)

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.passive_aggressive import (
        PAConfig,
        passive_aggressive,
        predict_host,
    )
    from fps_tpu.utils.datasets import (
        load_sparse,
        synthetic_sparse_classification,
        synthetic_sparse_multiclass,
        train_test_split,
    )

    if args.input:
        # Real dataset (RCV1-style svmlight or Criteo TSV); binary {-1,+1}.
        # svmlight: the feature space comes from the file (ids verbatim);
        # criteo: the hashed space size is the --num-features knob.
        from fps_tpu.utils.datasets import sniff_sparse_format

        fmt = args.input_format
        if fmt == "auto":
            fmt = sniff_sparse_format(args.input)
        data, args.num_features = load_sparse(
            args.input, fmt=fmt,
            num_features=args.num_features if fmt == "criteo" else None,
            nnz_cap=args.nnz_cap,
        )
        if args.num_classes != 2:
            raise SystemExit("--input provides binary labels; --num-classes must be 2")
    elif args.num_classes == 2:
        data = synthetic_sparse_classification(
            args.num_examples, args.num_features, args.nnz, seed=args.seed
        )
    else:
        data = synthetic_sparse_multiclass(
            args.num_examples, args.num_features, args.num_classes, args.nnz,
            seed=args.seed,
        )
    train, test = train_test_split(data, test_frac=0.1, seed=args.seed + 1)

    mesh = make_mesh(args)
    W = num_workers_of(mesh)
    emit({"event": "start", "workload": "passive_aggressive",
          "variant": args.variant, "num_classes": args.num_classes,
          "mesh": dict(mesh.shape)})

    cfg = PAConfig(num_features=args.num_features, num_classes=args.num_classes,
                   variant=args.variant, C=args.C)
    trainer, store = passive_aggressive(
        mesh, cfg, sync_every=args.sync_every, guard=make_guard(args))
    apply_hot_tier(args, trainer)
    apply_host_pipeline(args, trainer)
    rec = attach_obs(args, trainer, workload="passive_aggressive")
    tables, local_state = trainer.init_state(jax.random.key(args.seed))
    maybe_warm_start(args, store, None)

    chunks = make_chunks(args, mesh, train)
    def report(i, m):
        n = max(1.0, float(np.sum(m["n"])))
        emit({"event": "chunk", "i": i,
              "error_rate": float(np.sum(m["mistakes"]) / n),
              "hinge_loss": float(np.sum(m["loss"]) / n)})

    with maybe_profile(args), maybe_serve(args, rec):
        tables, local_state, _ = trainer.fit_stream(
            tables, local_state, chunks, jax.random.key(args.seed),
            checkpointer=maybe_checkpointer(args),
            checkpoint_every=args.checkpoint_every,
            on_chunk=report,
            rollback=make_rollback(args),
            watchdog=make_watchdog(args, rec),
        )

    pred = predict_host(store, test["feat_ids"], test["feat_vals"],
                        num_classes=args.num_classes)
    acc = float(np.mean(pred == test["label"]))
    emit({"event": "done", "test_accuracy": acc})
    finish(args, store, recorder=rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
