"""Implicit-feedback iALS entrypoint (MovieLens-20M implicit workload).

BASELINE.json workload "Implicit-feedback iALS (MovieLens-20M)" — an
extension beyond the reference's algorithm set (SURVEY.md §6 flags it as
required-but-likely-absent upstream). Alternating sharded normal-equation
solves; see ``fps_tpu.models.ials``.
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np

from fps_tpu.examples.common import (
    apply_hot_tier,
    attach_obs,
    base_parser,
    emit,
    finish,
    make_mesh,
    make_watchdog,
    maybe_checkpointer,
    maybe_profile,
    maybe_serve,
    maybe_warm_start,
)


def main(argv=None) -> int:
    ap = base_parser("Implicit-feedback iALS on the TPU PS")
    ap.add_argument("--num-users", type=int, default=2_000)
    ap.add_argument("--num-items", type=int, default=1_000)
    ap.add_argument("--per-user", type=int, default=20)
    ap.add_argument("--rank", type=int, default=16)
    ap.add_argument("--alpha", type=float, default=40.0)
    ap.add_argument("--reg", type=float, default=0.1)
    ap.add_argument("--topk", type=int, default=10)
    args = ap.parse_args(argv)
    args.num_data = 1  # iALS uses the shard axis only

    from fps_tpu.models.ials import (
        IALSConfig,
        IALSSolver,
        recall_at_k,
    )
    from fps_tpu.utils.datasets import synthetic_implicit, train_test_split

    if args.input:
        from fps_tpu.utils.datasets import load_movielens

        data, nu, ni = load_movielens(args.input, "20m")
        data["rating"] = np.maximum(data["rating"], 0.0)
    else:
        nu, ni = args.num_users, args.num_items
        data = synthetic_implicit(nu, ni, args.per_user, seed=args.seed)
    train, test = train_test_split(data, test_frac=0.1, seed=args.seed + 1)

    mesh = make_mesh(args)
    emit({"event": "start", "workload": "ials", "num_users": nu,
          "num_items": ni, "mesh": dict(mesh.shape)})

    solver = IALSSolver(mesh, IALSConfig(num_users=nu, num_items=ni,
                                         rank=args.rank, alpha=args.alpha,
                                         reg=args.reg))
    # --prefetch: overlapped assembly+placement of the interaction chunks
    # (the solver drives its own loop, so the knob lands on it directly —
    # same validation as the Trainer CLIs' apply_host_pipeline).
    if args.prefetch < 0:
        raise SystemExit(f"--prefetch must be >= 0, got {args.prefetch}")
    solver.prefetch = args.prefetch
    # --hot-tier: accepted-and-reported (no pull/push Trainer to tier —
    # the half-epoch normal-equation solves already read/write whole
    # factor blocks, not Zipf-skewed id streams).
    apply_hot_tier(args, None)
    solver.init(jax.random.key(args.seed))
    # iALS drives its own solver loop (no Trainer) — the recorder still
    # journals the run and catches checkpoint events via the process
    # default; epoch boundaries are emitted below.
    rec = attach_obs(args, workload="ials")
    maybe_warm_start(args, solver.store, None)
    ckpt = maybe_checkpointer(args)

    from fps_tpu.examples.common import make_epoch_source

    # iALS has no worker-local state to route for; the interaction stream
    # splits over ALL devices (the source's default worker count) and is
    # consumed twice per epoch (one pass per side).
    source = make_epoch_source(args, mesh, train)

    wd = make_watchdog(args, rec)
    with maybe_serve(args, rec):
        for epoch in range(args.epochs):
            # --profile traces the first epoch only (one epoch is
            # representative and keeps the trace small).
            cm = (maybe_profile(args) if epoch == 0
                  else contextlib.nullcontext())
            wcm = (wd.watch("epoch", epoch) if wd is not None
                   else contextlib.nullcontext())
            with cm, wcm:
                solver.epoch(lambda _e=epoch: source(_e, 1))
            loss = solver.weighted_loss(train["user"], train["item"],
                                        train["rating"])
            emit({"event": "epoch", "epoch": epoch, "weighted_loss": loss})
            if rec is not None:
                rec.inc("driver.epochs")
                rec.event("epoch", index=epoch, weighted_loss=float(loss))
            if ckpt is not None and (epoch + 1) % args.checkpoint_every == 0:
                ckpt.save(epoch + 1, solver.store)
        if ckpt is not None:
            # iALS drives its own loop, so IT owns the durability barrier
            # the Trainer drivers provide: an async writer's last snapshot
            # must be on disk before the run reports done.
            ckpt.flush()

    r = recall_at_k(solver, test["user"][:2000], test["item"][:2000],
                    k=args.topk, exclude=(train["user"], train["item"]))
    emit({"event": "done", f"recall_at_{args.topk}": r})

    finish(args, solver.store, recorder=rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
