"""Bounded-staleness logistic regression entrypoint (Criteo CTR style).

The "async bounded-staleness SGD, multi-worker data-parallel" workload from
BASELINE.json's configs — the canonical SSP exerciser. ``--sync-every s``
bounds how stale a worker's parameter snapshot may get (the framework analog
of the reference's free-running asynchrony + pull limiter; SURVEY.md §2.2).
"""

from __future__ import annotations

import jax
import numpy as np

from fps_tpu.examples.common import (
    apply_host_pipeline,
    apply_hot_tier,
    attach_obs,
    base_parser,
    make_guard,
    make_chunks,
    make_rollback,
    make_watchdog,
    maybe_profile,
    emit,
    finish,
    make_mesh,
    maybe_checkpointer,
    maybe_serve,
    maybe_warm_start,
)


def main(argv=None) -> int:
    ap = base_parser("SSP logistic regression on the TPU PS")
    ap.add_argument("--num-features", type=int, default=1 << 18,
                    help="hashed feature space size")
    ap.add_argument("--num-examples", type=int, default=100_000)
    ap.add_argument("--nnz", type=int, default=32)
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--l2", type=float, default=0.0)
    ap.add_argument("--optimizer", default="sgd", choices=["sgd", "adagrad"],
                    help="adagrad keeps per-coordinate state in the sharded "
                         "table — strongly recommended with --input files "
                         "whose dense columns (e.g. Criteo numerics) make "
                         "plain SGD oscillate under SSP staleness")
    ap.add_argument("--input-format", default="auto",
                    choices=["auto", "svmlight", "criteo"],
                    help="--input file format (Criteo TSV or RCV1 svmlight)")
    ap.add_argument("--nnz-cap", type=int, default=None,
                    help="svmlight rows keep at most this many features "
                         "(default: the file's max row length)")
    args = ap.parse_args(argv)
    if args.sync_every is None:
        args.sync_every = 8  # this entrypoint exists to exercise SSP

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
        predict_proba_host,
    )
    from fps_tpu.utils.datasets import (
        load_sparse,
        sniff_sparse_format,
        synthetic_sparse_classification,
        train_test_split,
    )

    dense = 0
    if args.input:
        # Real dataset (Criteo TSV with hashed categoricals, or svmlight).
        fmt = args.input_format
        if fmt == "auto":
            fmt = sniff_sparse_format(args.input)
        data, args.num_features = load_sparse(
            args.input, fmt=fmt,
            num_features=args.num_features if fmt == "criteo" else None,
            nnz_cap=args.nnz_cap,
        )
        if fmt == "criteo":
            # The Criteo loader's fixed-slot layout (numeric column j at
            # slot j) lets the worker handle those 13 weights densely —
            # one static pull + one combined push per step instead of 13
            # scatter rows per example (LogRegConfig.dense_features).
            dense = 13
    else:
        data = synthetic_sparse_classification(
            args.num_examples, args.num_features, args.nnz, seed=args.seed
        )
    data["label"] = (data["label"] > 0).astype(np.float32)  # {0,1}
    train, test = train_test_split(data, test_frac=0.1, seed=args.seed + 1)

    mesh = make_mesh(args)
    W = num_workers_of(mesh)
    emit({"event": "start", "workload": "logreg_ssp",
          "sync_every": args.sync_every, "mesh": dict(mesh.shape)})

    cfg = LogRegConfig(num_features=args.num_features,
                       learning_rate=args.learning_rate, l2=args.l2,
                       optimizer=args.optimizer, dense_features=dense)
    trainer, store = logistic_regression(
        mesh, cfg, sync_every=args.sync_every, guard=make_guard(args))
    apply_hot_tier(args, trainer)
    apply_host_pipeline(args, trainer)
    rec = attach_obs(args, trainer, workload="logreg_ssp")
    tables, local_state = trainer.init_state(jax.random.key(args.seed))
    maybe_warm_start(args, store, None)

    chunks = make_chunks(args, mesh, train)
    def report(i, m):
        n = max(1.0, float(np.sum(m["n"])))
        emit({"event": "chunk", "i": i,
              "logloss": float(np.sum(m["logloss"]) / n),
              "error_rate": float(np.sum(m["mistakes"]) / n)})

    with maybe_profile(args), maybe_serve(args, rec):
        tables, local_state, _ = trainer.fit_stream(
            tables, local_state, chunks, jax.random.key(args.seed),
            checkpointer=maybe_checkpointer(args),
            checkpoint_every=args.checkpoint_every,
            on_chunk=report,
            rollback=make_rollback(args),
            watchdog=make_watchdog(args, rec),
        )

    p = predict_proba_host(store, test["feat_ids"], test["feat_vals"])
    acc = float(np.mean((p > 0.5) == (test["label"] > 0.5)))
    emit({"event": "done", "test_accuracy": acc})
    finish(args, store, recorder=rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
