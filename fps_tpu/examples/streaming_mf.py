"""Unbounded-stream online MF — the reference's core execution model, live.

The reference is a *streaming* system: training runs for as long as the
``DataStream`` produces records and stops via the ``iterationWaitTime``
timeout when it dries up (SURVEY.md §0, §2.3). This entrypoint demonstrates
the TPU-native analog end to end:

* an **unbounded source** (here a synthetic rating generator; swap in a
  socket reader / file tailer — anything yielding columnar batches),
* :func:`fps_tpu.core.ingest.stream_chunks` framing it into static-shape
  chunks as records buffer (keyed routing preserved),
* ``fit_stream`` training on each chunk as it arrives, with the ``WOut``
  metrics stream reported live,
* **termination** by data-driven stop: ``--max-records`` bounds the source
  (the analog of the stream drying up), or ``--target-rmse`` stops early by
  raising from the ``on_chunk`` tap — a *stronger* facility than the
  reference's timeout, which could only detect silence, not convergence.
"""

from __future__ import annotations

import jax
import numpy as np

from fps_tpu.examples.common import (apply_host_pipeline, apply_hot_tier,
                                     attach_obs,
                                     base_parser, emit, finish, make_guard,
                                     make_mesh, make_rollback, make_watchdog,
                                     maybe_checkpointer, maybe_profile,
                                     maybe_serve)


class _TargetReached(Exception):
    pass


def main(argv=None) -> int:
    ap = base_parser("Unbounded-stream online MF")
    ap.add_argument("--num-users", type=int, default=500)
    ap.add_argument("--num-items", type=int, default=300)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--max-records", type=int, default=500_000,
                    help="stop when the source has produced this many "
                         "records (None-like 0 = run until --target-rmse)")
    ap.add_argument("--target-rmse", type=float, default=None,
                    help="stop as soon as a chunk's train RMSE falls below")
    ap.add_argument("--source-batch", type=int, default=4096)
    args = ap.parse_args(argv)
    if args.max_records <= 0 and args.target_rmse is None:
        ap.error("an unbounded source (--max-records 0) needs --target-rmse "
                 "as its stop condition")

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import stream_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import streaming_rating_batches

    mesh = make_mesh(args)
    W = num_workers_of(mesh)
    emit({"event": "start", "workload": "streaming_mf",
          "mesh": dict(mesh.shape)})

    cfg = MFConfig(num_users=args.num_users, num_items=args.num_items,
                   rank=args.rank, learning_rate=args.learning_rate)
    trainer, store = online_mf(mesh, cfg, sync_every=args.sync_every,
                               guard=make_guard(args))
    apply_hot_tier(args, trainer)
    apply_host_pipeline(args, trainer)
    rec = attach_obs(args, trainer, workload="streaming_mf")
    tables, local_state = trainer.init_state(jax.random.key(args.seed))

    source = streaming_rating_batches(
        args.num_users, args.num_items, rank=args.rank, seed=args.seed,
        batch=args.source_batch,
        max_records=args.max_records if args.max_records > 0 else None,
    )
    chunks = stream_chunks(
        source, num_workers=W, local_batch=args.local_batch,
        steps_per_chunk=args.steps_per_chunk, route_key="user",
        sync_every=args.sync_every,
    )

    seen = 0.0

    def on_chunk(i, m):
        nonlocal seen
        n = max(1.0, float(np.sum(m["n"])))
        seen += n
        train_rmse = float(np.sqrt(np.sum(m["se"]) / n))
        emit({"event": "chunk", "i": i, "train_rmse": train_rmse,
              "records_seen": seen})
        if args.target_rmse is not None and train_rmse < args.target_rmse:
            raise _TargetReached

    try:
        with maybe_profile(args), maybe_serve(args, rec):
            tables, local_state, _ = trainer.fit_stream(
                tables, local_state, chunks, jax.random.key(args.seed),
                checkpointer=maybe_checkpointer(args),
                checkpoint_every=args.checkpoint_every,
                on_chunk=on_chunk,
                rollback=make_rollback(args),
                watchdog=make_watchdog(args, rec),
            )
        stopped = "stream_exhausted"
    except _TargetReached:
        stopped = "target_rmse"

    emit({"event": "done", "stopped_by": stopped, "records_seen": seen})
    finish(args, store, recorder=rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
