// Native host-side ingest kernels for fps_tpu.
//
// The reference's ingest rides Flink's JVM source operators; this framework's
// ingest is host-side Python/numpy (fps_tpu/core/ingest.py), whose two hot
// loops are worth native code on the TPU VM host:
//   * dataset file parsing (np.loadtxt is ~50x slower than a tight scanner
//     on MovieLens-20M-sized rating files), and
//   * skip-gram pair generation with frequent-word subsampling and a
//     dynamic window (a per-token branchy loop, word2vec's ingest shape).
//
// Exposed as a tiny C ABI (no pybind11 in this image) consumed via ctypes —
// see fps_tpu/native/__init__.py, which builds this file on demand with g++
// and falls back to the numpy implementations when no compiler is present.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// splitmix64 — deterministic, seedable, fast.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed + 0x9E3779B97F4A7C15ULL) {}
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
  // uniform integer in [1, hi]
  int one_to(int hi) { return 1 + static_cast<int>(next() % hi); }
};

}  // namespace

extern "C" {

namespace {

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// Parse an unsigned int at p; advances p. Returns -1 if no digits.
inline long parse_uint(const char*& p, const char* end) {
  if (p >= end || !is_digit(*p)) return -1;
  long v = 0;
  while (p < end && is_digit(*p)) v = v * 10 + (*p++ - '0');
  return v;
}

// Parse a simple decimal (digits[.digits]); advances p. NaN if no digits.
inline float parse_decimal(const char*& p, const char* end) {
  long ip = parse_uint(p, end);
  if (ip < 0) return -1.0f;
  double v = static_cast<double>(ip);
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && is_digit(*p)) {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
    }
  }
  return static_cast<float>(v);
}

inline void skip_sep(const char*& p, const char* end) {
  while (p < end && (*p == '\t' || *p == ',' || *p == ' ')) ++p;
}

inline void skip_line(const char*& p, const char* end) {
  while (p < end && *p != '\n') ++p;
  if (p < end) ++p;
}

}  // namespace

// Count newline-terminated lines in a file (capacity sizing for
// fps_parse_ratings — keeps the whole "how many rows might this file have"
// question on the native side, one warm-cache read instead of a Python
// chunk loop). Returns -1 if the file cannot be read.
long fps_count_lines(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  const size_t bufsz = 1 << 18;  // heap: callers may run on small-stack threads
  char* buf = static_cast<char*>(malloc(bufsz));
  if (!buf) {
    fclose(f);
    return -1;
  }
  long lines = 0;
  size_t got;
  char last = '\n';
  while ((got = fread(buf, 1, bufsz, f)) > 0) {
    for (size_t i = 0; i < got; ++i)
      if (buf[i] == '\n') ++lines;
    last = buf[got - 1];
  }
  free(buf);
  fclose(f);
  if (last != '\n') ++lines;  // unterminated final line
  return lines;
}

// Parse a ratings file: lines of "user sep item sep rating [sep extra...]"
// with sep in {tab, comma, space}. '#'-leading lines are comments and are
// skipped anywhere (np.loadtxt convention). Other non-digit-leading lines
// are treated as skippable headers ONLY before the first data row; after
// data has started they count in *malformed, as do lines that start like
// data but fail mid-parse. A file that yields ZERO data rows but had
// header-skipped lines also reports them as malformed — a quoted-field csv
// must error, not parse to an empty dataset. user/item are written verbatim
// (caller re-indexes). Returns rows written, or -1 if the file cannot be
// read. Writes at most cap rows. Whole-file buffered manual scanner —
// per-line stdio + strtol measured ~7x slower on ML-20M files.
long fps_parse_ratings(const char* path, int32_t* users, int32_t* items,
                       float* ratings, long cap, long* malformed) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return -1;
  }
  long size = ftell(f);
  if (size < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf) {
    fclose(f);
    return -1;
  }
  long got = static_cast<long>(fread(buf, 1, size, f));
  fclose(f);
  const char* p = buf;
  const char* end = buf + got;
  long headers = 0;
  long n = 0;
  long bad = 0;
  while (n < cap && p < end) {
    while (p < end && *p == ' ') ++p;
    if (p >= end) break;
    if (*p == '\n' || (*p == '\r' && (p + 1 >= end || p[1] == '\n'))) {
      skip_line(p, end);  // empty line (LF or CRLF)
      continue;
    }
    if (*p == '#') {  // comment line, valid anywhere
      skip_line(p, end);
      continue;
    }
    if (!is_digit(*p)) {
      if (n == 0) {
        ++headers;  // header line before any data
      } else {
        ++bad;  // non-data line mid-file: corrupt, not a header
      }
      skip_line(p, end);
      continue;
    }
    long u = parse_uint(p, end);
    skip_sep(p, end);
    long i = parse_uint(p, end);
    skip_sep(p, end);
    float r = parse_decimal(p, end);
    if (u < 0 || i < 0 || r < 0.0f) {  // malformed data line
      ++bad;
      skip_line(p, end);
      continue;
    }
    users[n] = static_cast<int32_t>(u);
    items[n] = static_cast<int32_t>(i);
    ratings[n] = r;
    ++n;
    skip_line(p, end);
  }
  free(buf);
  if (n == 0 && headers > 0) bad += headers;  // all-header file: not data
  if (malformed) *malformed = bad;
  return n;
}

namespace {

// Signed decimal with optional exponent ("-1", "+0.25", "1e-3"); advances p.
// Returns false if nothing parseable at p.
inline bool parse_signed(const char*& p, const char* end, double* out) {
  double sign = 1.0;
  if (p < end && (*p == '+' || *p == '-')) {
    if (*p == '-') sign = -1.0;
    ++p;
  }
  if (p >= end || (!is_digit(*p) && *p != '.')) return false;
  double v = 0.0;
  bool digits = false;  // "." / "-." must fail like Python float("."), not
                        // parse as 0.0 — native and fallback loaders must
                        // classify degenerate tokens identically.
  while (p < end && is_digit(*p)) {
    v = v * 10.0 + (*p++ - '0');
    digits = true;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && is_digit(*p)) {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
      digits = true;
    }
  }
  if (!digits) return false;
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    double esign = 1.0;
    if (p < end && (*p == '+' || *p == '-')) {
      if (*p == '-') esign = -1.0;
      ++p;
    }
    if (p >= end || !is_digit(*p)) return false;
    double e = 0.0;
    while (p < end && is_digit(*p)) e = e * 10.0 + (*p++ - '0');
    // Cap: beyond ~1e310 the scale is inf/0 anyway, and an O(e) loop on a
    // hostile exponent ("1e2000000000") must not hang the parser.
    long ecap = e > 310.0 ? 310 : static_cast<long>(e);
    double scale = 1.0;
    for (long k = 0; k < ecap; ++k) scale *= 10.0;
    v = esign > 0 ? v * scale : v / scale;
  }
  *out = sign * v;
  return true;
}

// FNV-1a 64-bit over bytes, finalized with splitmix64 — the categorical
// feature hash. fps_tpu/utils/datasets.py's fallback reimplements this
// bit-for-bit; the two must stay in sync.
inline uint64_t hash_bytes(uint64_t seed, const char* s, long len) {
  uint64_t h = 1469598103934665603ULL ^ seed;
  for (long i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ULL;
  }
  uint64_t z = h + 0x9E3779B97F4A7C15ULL;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

inline char* read_whole_file(const char* path, long* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  // An unseekable path (pipe, directory) must surface as an I/O error, not
  // as a valid empty dataset: ftell returns -1 there.
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return nullptr;
  }
  long size = ftell(f);
  if (size < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf) {
    fclose(f);
    return nullptr;
  }
  *out_len = static_cast<long>(fread(buf, 1, size, f));
  fclose(f);
  return buf;
}

}  // namespace

// svmlight/RCV1 scanner, pass 1: rows and the max feature count of any
// line, so the caller can size the padded (rows, nnz) arrays. Lines:
//   <label> <idx>:<val> <idx>:<val> ... [# comment]
// '#'-leading lines are comments; blank lines skipped. Returns data-looking
// row count, or -1 if the file cannot be read.
long fps_svmlight_dims(const char* path, long* max_nnz) {
  long size = 0;
  char* buf = read_whole_file(path, &size);
  if (!buf) return -1;
  const char* p = buf;
  const char* end = buf + size;
  long rows = 0, mx = 0;
  while (p < end) {
    while (p < end && (*p == ' ' || *p == '\r')) ++p;
    if (p >= end) break;
    if (*p == '\n' || *p == '#') {
      skip_line(p, end);
      continue;
    }
    long nnz = 0;
    const char* q = p;
    while (q < end && *q != '\n' && *q != '#') {
      if (*q == ':') ++nnz;
      ++q;
    }
    if (nnz > mx) mx = nnz;
    ++rows;
    skip_line(p, end);
  }
  free(buf);
  *max_nnz = mx;
  return rows;
}

// svmlight/RCV1 scanner, pass 2: fill CALLER-ZEROED padded row-major
// (cap_rows x nnz_cap) id/value arrays (pad slots stay id 0 / value 0 —
// inactive by the models' x != 0 convention) plus float labels. Feature
// ids are written verbatim (svmlight is conventionally 1-based; the caller
// re-indexes). Rows with more than nnz_cap features keep the FIRST nnz_cap
// and count in *truncated. Structurally malformed data lines (unparseable
// label, broken idx:val token) count in *malformed — callers refuse the
// file rather than silently drop lines, matching fps_parse_ratings.
long fps_parse_svmlight(const char* path, float* labels, int32_t* ids,
                        float* vals, long cap_rows, long nnz_cap,
                        long* truncated, long* malformed) {
  long size = 0;
  char* buf = read_whole_file(path, &size);
  if (!buf) return -1;
  const char* p = buf;
  const char* end = buf + size;
  long n = 0, bad = 0, trunc = 0;
  while (n < cap_rows && p < end) {
    while (p < end && (*p == ' ' || *p == '\r')) ++p;
    if (p >= end) break;
    if (*p == '\n' || *p == '#') {
      skip_line(p, end);
      continue;
    }
    double label;
    if (!parse_signed(p, end, &label)) {
      ++bad;
      skip_line(p, end);
      continue;
    }
    long nnz = 0;
    bool ok = true;
    while (p < end && *p != '\n' && *p != '#' && *p != '\r') {
      while (p < end && *p == ' ') ++p;
      if (p >= end || *p == '\n' || *p == '#' || *p == '\r') break;
      long idx = parse_uint(p, end);
      if (idx < 0 || p >= end || *p != ':') {
        ok = false;
        break;
      }
      ++p;  // ':'
      double v;
      if (!parse_signed(p, end, &v)) {
        ok = false;
        break;
      }
      if (nnz < nnz_cap) {
        ids[n * nnz_cap + nnz] = static_cast<int32_t>(idx);
        vals[n * nnz_cap + nnz] = static_cast<float>(v);
        ++nnz;
      } else {
        ++trunc;
        // keep scanning to validate the rest of the line
      }
    }
    if (!ok) {
      ++bad;
      // wipe any partial row
      for (long k = 0; k < nnz_cap; ++k) {
        ids[n * nnz_cap + k] = 0;
        vals[n * nnz_cap + k] = 0.0f;
      }
      skip_line(p, end);
      continue;
    }
    labels[n] = static_cast<float>(label);
    ++n;
    skip_line(p, end);
  }
  free(buf);
  if (truncated) *truncated = trunc;
  if (malformed) *malformed = bad;
  return n;
}

// Criteo click-logs scanner: one example per line,
//   <label> \t I1..I13 (ints, may be empty/negative) \t C1..C26 (hex tokens,
//   may be empty)
// Numeric column j (0-based) with value x >= 0 becomes feature id j with
// value log1p(x); negative or empty numerics are treated as missing.
// Categorical column j with token s becomes feature id
//   13 + hash(j, s) % (num_features - 13)      (FNV-1a + splitmix64)
// with value 1.0. Output is CALLER-ZEROED row-major (cap_rows x 39); missing
// fields leave inactive pad slots. Lines with a non-0/1 label or a wrong
// field count are malformed. Returns rows, or -1 on IO error.
long fps_parse_criteo(const char* path, float* labels, int32_t* ids,
                      float* vals, long cap_rows, long num_features,
                      long* malformed) {
  long size = 0;
  char* buf = read_whole_file(path, &size);
  if (!buf) return -1;
  const long kNum = 13, kCat = 26, kNnz = kNum + kCat;
  const long cat_space = num_features - kNum;
  const char* p = buf;
  const char* end = buf + size;
  long n = 0, bad = 0;
  while (n < cap_rows && p < end) {
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '\r' && (p + 1 >= end || p[1] == '\n')) {
      p += (p + 1 < end) ? 2 : 1;  // blank CRLF line, skip like the fallback
      continue;
    }
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    const char* le = line_end;
    if (le > p && le[-1] == '\r') --le;

    bool ok = true;
    // label
    long label = parse_uint(p, le);
    if (label != 0 && label != 1) ok = false;
    long nnz = 0;
    long field = 0;
    while (ok && field < kNnz) {
      if (p >= le || *p != '\t') {
        ok = false;
        break;
      }
      ++p;  // tab
      const char* fs = p;
      while (p < le && *p != '\t') ++p;
      long flen = p - fs;
      if (flen == 0) {
        ++field;
        continue;  // missing value
      }
      if (field < kNum) {
        const char* q = fs;
        double v;
        if (!parse_signed(q, fs + flen, &v) || q != fs + flen) {
          ok = false;
          break;
        }
        if (v >= 0.0) {
          // log1p, cheap enough inline
          double x = v, r = 0.0;
          r = __builtin_log1p(x);
          ids[n * kNnz + nnz] = static_cast<int32_t>(field);
          vals[n * kNnz + nnz] = static_cast<float>(r);
          ++nnz;
        }
      } else {
        uint64_t h = hash_bytes(static_cast<uint64_t>(field), fs, flen);
        ids[n * kNnz + nnz] =
            static_cast<int32_t>(kNum + static_cast<long>(h % cat_space));
        vals[n * kNnz + nnz] = 1.0f;
        ++nnz;
      }
      ++field;
    }
    if (ok && (field != kNnz || p != le)) ok = false;
    if (!ok) {
      ++bad;
      for (long k = 0; k < kNnz; ++k) {
        ids[n * kNnz + k] = 0;
        vals[n * kNnz + k] = 0.0f;
      }
      p = line_end < end ? line_end + 1 : end;
      continue;
    }
    labels[n] = static_cast<float>(label);
    ++n;
    p = line_end < end ? line_end + 1 : end;
  }
  free(buf);
  if (malformed) *malformed = bad;
  return n;
}

// Skip-gram pair generation over a token segment, mirroring
// fps_tpu/models/word2vec.py's skipgram_chunks inner loop:
//   1. drop position t with probability 1 - keep_p[token[t]]  (subsampling)
//   2. per kept position, draw half-width h ~ U{1..window}
//   3. for d in 1..h with t+d kept-in-range: emit (kept[t], kept[t+d]) and
//      (kept[t+d], kept[t])  (both directions, distance gated by the LEFT
//      element's half-width, exactly like the numpy implementation)
// Deterministic for a given seed. Returns pairs written (<= cap).
long fps_skipgram_pairs(const int32_t* tokens, long n, int window,
                        uint64_t seed, const float* keep_p, int32_t vocab,
                        int32_t* centers, int32_t* contexts, long cap) {
  if (n <= 0 || window <= 0) return 0;
  Rng rng(seed);
  // Pass 1: subsample into a kept buffer (indices compacted).
  int32_t* kept = static_cast<int32_t*>(malloc(sizeof(int32_t) * n));
  if (!kept) return -1;
  long m = 0;
  for (long t = 0; t < n; ++t) {
    int32_t tok = tokens[t];
    double kp = (keep_p && tok >= 0 && tok < vocab) ? keep_p[tok] : 1.0;
    if (kp >= 1.0 || rng.uniform() < kp) kept[m++] = tok;
  }
  long out = 0;
  for (long t = 0; t < m && out < cap; ++t) {
    int h = rng.one_to(window);
    for (int d = 1; d <= h && t + d < m; ++d) {
      if (out + 2 > cap) break;
      centers[out] = kept[t];
      contexts[out] = kept[t + d];
      ++out;
      centers[out] = kept[t + d];
      contexts[out] = kept[t];
      ++out;
    }
  }
  free(kept);
  return out;
}

}  // extern "C"
