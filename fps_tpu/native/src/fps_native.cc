// Native host-side ingest kernels for fps_tpu.
//
// The reference's ingest rides Flink's JVM source operators; this framework's
// ingest is host-side Python/numpy (fps_tpu/core/ingest.py), whose two hot
// loops are worth native code on the TPU VM host:
//   * dataset file parsing (np.loadtxt is ~50x slower than a tight scanner
//     on MovieLens-20M-sized rating files), and
//   * skip-gram pair generation with frequent-word subsampling and a
//     dynamic window (a per-token branchy loop, word2vec's ingest shape).
//
// Exposed as a tiny C ABI (no pybind11 in this image) consumed via ctypes —
// see fps_tpu/native/__init__.py, which builds this file on demand with g++
// and falls back to the numpy implementations when no compiler is present.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// splitmix64 — deterministic, seedable, fast.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed + 0x9E3779B97F4A7C15ULL) {}
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
  // uniform integer in [1, hi]
  int one_to(int hi) { return 1 + static_cast<int>(next() % hi); }
};

}  // namespace

extern "C" {

namespace {

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// Parse an unsigned int at p; advances p. Returns -1 if no digits.
inline long parse_uint(const char*& p, const char* end) {
  if (p >= end || !is_digit(*p)) return -1;
  long v = 0;
  while (p < end && is_digit(*p)) v = v * 10 + (*p++ - '0');
  return v;
}

// Parse a simple decimal (digits[.digits]); advances p. NaN if no digits.
inline float parse_decimal(const char*& p, const char* end) {
  long ip = parse_uint(p, end);
  if (ip < 0) return -1.0f;
  double v = static_cast<double>(ip);
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && is_digit(*p)) {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
    }
  }
  return static_cast<float>(v);
}

inline void skip_sep(const char*& p, const char* end) {
  while (p < end && (*p == '\t' || *p == ',' || *p == ' ')) ++p;
}

inline void skip_line(const char*& p, const char* end) {
  while (p < end && *p != '\n') ++p;
  if (p < end) ++p;
}

}  // namespace

// Count newline-terminated lines in a file (capacity sizing for
// fps_parse_ratings — keeps the whole "how many rows might this file have"
// question on the native side, one warm-cache read instead of a Python
// chunk loop). Returns -1 if the file cannot be read.
long fps_count_lines(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  const size_t bufsz = 1 << 18;  // heap: callers may run on small-stack threads
  char* buf = static_cast<char*>(malloc(bufsz));
  if (!buf) {
    fclose(f);
    return -1;
  }
  long lines = 0;
  size_t got;
  char last = '\n';
  while ((got = fread(buf, 1, bufsz, f)) > 0) {
    for (size_t i = 0; i < got; ++i)
      if (buf[i] == '\n') ++lines;
    last = buf[got - 1];
  }
  free(buf);
  fclose(f);
  if (last != '\n') ++lines;  // unterminated final line
  return lines;
}

// Parse a ratings file: lines of "user sep item sep rating [sep extra...]"
// with sep in {tab, comma, space}. '#'-leading lines are comments and are
// skipped anywhere (np.loadtxt convention). Other non-digit-leading lines
// are treated as skippable headers ONLY before the first data row; after
// data has started they count in *malformed, as do lines that start like
// data but fail mid-parse. A file that yields ZERO data rows but had
// header-skipped lines also reports them as malformed — a quoted-field csv
// must error, not parse to an empty dataset. user/item are written verbatim
// (caller re-indexes). Returns rows written, or -1 if the file cannot be
// read. Writes at most cap rows. Whole-file buffered manual scanner —
// per-line stdio + strtol measured ~7x slower on ML-20M files.
long fps_parse_ratings(const char* path, int32_t* users, int32_t* items,
                       float* ratings, long cap, long* malformed) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf) {
    fclose(f);
    return -1;
  }
  long got = static_cast<long>(fread(buf, 1, size, f));
  fclose(f);
  const char* p = buf;
  const char* end = buf + got;
  long headers = 0;
  long n = 0;
  long bad = 0;
  while (n < cap && p < end) {
    while (p < end && *p == ' ') ++p;
    if (p >= end) break;
    if (*p == '\n' || (*p == '\r' && (p + 1 >= end || p[1] == '\n'))) {
      skip_line(p, end);  // empty line (LF or CRLF)
      continue;
    }
    if (*p == '#') {  // comment line, valid anywhere
      skip_line(p, end);
      continue;
    }
    if (!is_digit(*p)) {
      if (n == 0) {
        ++headers;  // header line before any data
      } else {
        ++bad;  // non-data line mid-file: corrupt, not a header
      }
      skip_line(p, end);
      continue;
    }
    long u = parse_uint(p, end);
    skip_sep(p, end);
    long i = parse_uint(p, end);
    skip_sep(p, end);
    float r = parse_decimal(p, end);
    if (u < 0 || i < 0 || r < 0.0f) {  // malformed data line
      ++bad;
      skip_line(p, end);
      continue;
    }
    users[n] = static_cast<int32_t>(u);
    items[n] = static_cast<int32_t>(i);
    ratings[n] = r;
    ++n;
    skip_line(p, end);
  }
  free(buf);
  if (n == 0 && headers > 0) bad += headers;  // all-header file: not data
  if (malformed) *malformed = bad;
  return n;
}

// Skip-gram pair generation over a token segment, mirroring
// fps_tpu/models/word2vec.py's skipgram_chunks inner loop:
//   1. drop position t with probability 1 - keep_p[token[t]]  (subsampling)
//   2. per kept position, draw half-width h ~ U{1..window}
//   3. for d in 1..h with t+d kept-in-range: emit (kept[t], kept[t+d]) and
//      (kept[t+d], kept[t])  (both directions, distance gated by the LEFT
//      element's half-width, exactly like the numpy implementation)
// Deterministic for a given seed. Returns pairs written (<= cap).
long fps_skipgram_pairs(const int32_t* tokens, long n, int window,
                        uint64_t seed, const float* keep_p, int32_t vocab,
                        int32_t* centers, int32_t* contexts, long cap) {
  if (n <= 0 || window <= 0) return 0;
  Rng rng(seed);
  // Pass 1: subsample into a kept buffer (indices compacted).
  int32_t* kept = static_cast<int32_t*>(malloc(sizeof(int32_t) * n));
  if (!kept) return -1;
  long m = 0;
  for (long t = 0; t < n; ++t) {
    int32_t tok = tokens[t];
    double kp = (keep_p && tok >= 0 && tok < vocab) ? keep_p[tok] : 1.0;
    if (kp >= 1.0 || rng.uniform() < kp) kept[m++] = tok;
  }
  long out = 0;
  for (long t = 0; t < m && out < cap; ++t) {
    int h = rng.one_to(window);
    for (int d = 1; d <= h && t + d < m; ++d) {
      if (out + 2 > cap) break;
      centers[out] = kept[t];
      contexts[out] = kept[t + d];
      ++out;
      centers[out] = kept[t + d];
      contexts[out] = kept[t];
      ++out;
    }
  }
  free(kept);
  return out;
}

}  // extern "C"
