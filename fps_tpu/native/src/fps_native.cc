// Native host-side ingest kernels for fps_tpu.
//
// The reference's ingest rides Flink's JVM source operators; this framework's
// ingest is host-side Python/numpy (fps_tpu/core/ingest.py), whose two hot
// loops are worth native code on the TPU VM host:
//   * dataset file parsing (np.loadtxt is ~50x slower than a tight scanner
//     on MovieLens-20M-sized rating files), and
//   * skip-gram pair generation with frequent-word subsampling and a
//     dynamic window (a per-token branchy loop, word2vec's ingest shape).
//
// Exposed as a tiny C ABI (no pybind11 in this image) consumed via ctypes —
// see fps_tpu/native/__init__.py, which builds this file on demand with g++
// and falls back to the numpy implementations when no compiler is present.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

namespace {

// splitmix64 — deterministic, seedable, fast.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed + 0x9E3779B97F4A7C15ULL) {}
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
  // uniform integer in [1, hi]
  int one_to(int hi) { return 1 + static_cast<int>(next() % hi); }
};

}  // namespace

extern "C" {

namespace {

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// Parse an unsigned int at p; advances p. Returns -1 if no digits.
inline long parse_uint(const char*& p, const char* end) {
  if (p >= end || !is_digit(*p)) return -1;
  long v = 0;
  while (p < end && is_digit(*p)) v = v * 10 + (*p++ - '0');
  return v;
}

// Parse a simple decimal (digits[.digits]); advances p. NaN if no digits.
inline float parse_decimal(const char*& p, const char* end) {
  long ip = parse_uint(p, end);
  if (ip < 0) return -1.0f;
  double v = static_cast<double>(ip);
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && is_digit(*p)) {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
    }
  }
  return static_cast<float>(v);
}

inline void skip_sep(const char*& p, const char* end) {
  while (p < end && (*p == '\t' || *p == ',' || *p == ' ')) ++p;
}

inline void skip_line(const char*& p, const char* end) {
  while (p < end && *p != '\n') ++p;
  if (p < end) ++p;
}

}  // namespace

// Count newline-terminated lines in a file (capacity sizing for
// fps_parse_ratings — keeps the whole "how many rows might this file have"
// question on the native side, one warm-cache read instead of a Python
// chunk loop). Returns -1 if the file cannot be read.
long fps_count_lines(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  const size_t bufsz = 1 << 18;  // heap: callers may run on small-stack threads
  char* buf = static_cast<char*>(malloc(bufsz));
  if (!buf) {
    fclose(f);
    return -1;
  }
  long lines = 0;
  size_t got;
  char last = '\n';
  while ((got = fread(buf, 1, bufsz, f)) > 0) {
    for (size_t i = 0; i < got; ++i)
      if (buf[i] == '\n') ++lines;
    last = buf[got - 1];
  }
  free(buf);
  fclose(f);
  if (last != '\n') ++lines;  // unterminated final line
  return lines;
}

// Parse a ratings file: lines of "user sep item sep rating [sep extra...]"
// with sep in {tab, comma, space}. '#'-leading lines are comments and are
// skipped anywhere (np.loadtxt convention). Other non-digit-leading lines
// are treated as skippable headers ONLY before the first data row; after
// data has started they count in *malformed, as do lines that start like
// data but fail mid-parse. A file that yields ZERO data rows but had
// header-skipped lines also reports them as malformed — a quoted-field csv
// must error, not parse to an empty dataset. user/item are written verbatim
// (caller re-indexes). Returns rows written, or -1 if the file cannot be
// read. Writes at most cap rows. Whole-file buffered manual scanner —
// per-line stdio + strtol measured ~7x slower on ML-20M files.
long fps_parse_ratings(const char* path, int32_t* users, int32_t* items,
                       float* ratings, long cap, long* malformed) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return -1;
  }
  long size = ftell(f);
  if (size < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf) {
    fclose(f);
    return -1;
  }
  long got = static_cast<long>(fread(buf, 1, size, f));
  fclose(f);
  const char* p = buf;
  const char* end = buf + got;
  long headers = 0;
  long n = 0;
  long bad = 0;
  while (n < cap && p < end) {
    while (p < end && *p == ' ') ++p;
    if (p >= end) break;
    if (*p == '\n' || (*p == '\r' && (p + 1 >= end || p[1] == '\n'))) {
      skip_line(p, end);  // empty line (LF or CRLF)
      continue;
    }
    if (*p == '#') {  // comment line, valid anywhere
      skip_line(p, end);
      continue;
    }
    if (!is_digit(*p)) {
      if (n == 0) {
        ++headers;  // header line before any data
      } else {
        ++bad;  // non-data line mid-file: corrupt, not a header
      }
      skip_line(p, end);
      continue;
    }
    long u = parse_uint(p, end);
    skip_sep(p, end);
    long i = parse_uint(p, end);
    skip_sep(p, end);
    float r = parse_decimal(p, end);
    if (u < 0 || i < 0 || r < 0.0f) {  // malformed data line
      ++bad;
      skip_line(p, end);
      continue;
    }
    users[n] = static_cast<int32_t>(u);
    items[n] = static_cast<int32_t>(i);
    ratings[n] = r;
    ++n;
    skip_line(p, end);
  }
  free(buf);
  if (n == 0 && headers > 0) bad += headers;  // all-header file: not data
  if (malformed) *malformed = bad;
  return n;
}

namespace {

// Signed decimal with optional exponent ("-1", "+0.25", "1e-3"); advances p.
// Returns false if nothing parseable at p.
inline bool parse_signed(const char*& p, const char* end, double* out) {
  double sign = 1.0;
  if (p < end && (*p == '+' || *p == '-')) {
    if (*p == '-') sign = -1.0;
    ++p;
  }
  if (p >= end || (!is_digit(*p) && *p != '.')) return false;
  double v = 0.0;
  bool digits = false;  // "." / "-." must fail like Python float("."), not
                        // parse as 0.0 — native and fallback loaders must
                        // classify degenerate tokens identically.
  while (p < end && is_digit(*p)) {
    v = v * 10.0 + (*p++ - '0');
    digits = true;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && is_digit(*p)) {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
      digits = true;
    }
  }
  if (!digits) return false;
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    double esign = 1.0;
    if (p < end && (*p == '+' || *p == '-')) {
      if (*p == '-') esign = -1.0;
      ++p;
    }
    if (p >= end || !is_digit(*p)) return false;
    double e = 0.0;
    while (p < end && is_digit(*p)) e = e * 10.0 + (*p++ - '0');
    // Cap: beyond ~1e310 the scale is inf/0 anyway, and an O(e) loop on a
    // hostile exponent ("1e2000000000") must not hang the parser.
    long ecap = e > 310.0 ? 310 : static_cast<long>(e);
    double scale = 1.0;
    for (long k = 0; k < ecap; ++k) scale *= 10.0;
    v = esign > 0 ? v * scale : v / scale;
  }
  *out = sign * v;
  return true;
}

// FNV-1a 64-bit over bytes, finalized with splitmix64 — the categorical
// feature hash. fps_tpu/utils/datasets.py's fallback reimplements this
// bit-for-bit; the two must stay in sync.
inline uint64_t hash_bytes(uint64_t seed, const char* s, long len) {
  uint64_t h = 1469598103934665603ULL ^ seed;
  for (long i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ULL;
  }
  uint64_t z = h + 0x9E3779B97F4A7C15ULL;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

inline char* read_whole_file(const char* path, long* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  // An unseekable path (pipe, directory) must surface as an I/O error, not
  // as a valid empty dataset: ftell returns -1 there.
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return nullptr;
  }
  long size = ftell(f);
  if (size < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf) {
    fclose(f);
    return nullptr;
  }
  *out_len = static_cast<long>(fread(buf, 1, size, f));
  fclose(f);
  return buf;
}

}  // namespace

// svmlight/RCV1 scanner, pass 1: rows and the max feature count of any
// line, so the caller can size the padded (rows, nnz) arrays. Lines:
//   <label> <idx>:<val> <idx>:<val> ... [# comment]
// '#'-leading lines are comments; blank lines skipped. Returns data-looking
// row count, or -1 if the file cannot be read.
long fps_svmlight_dims(const char* path, long* max_nnz) {
  long size = 0;
  char* buf = read_whole_file(path, &size);
  if (!buf) return -1;
  const char* p = buf;
  const char* end = buf + size;
  long rows = 0, mx = 0;
  while (p < end) {
    while (p < end && (*p == ' ' || *p == '\r')) ++p;
    if (p >= end) break;
    if (*p == '\n' || *p == '#') {
      skip_line(p, end);
      continue;
    }
    long nnz = 0;
    const char* q = p;
    while (q < end && *q != '\n' && *q != '#') {
      if (*q == ':') ++nnz;
      ++q;
    }
    if (nnz > mx) mx = nnz;
    ++rows;
    skip_line(p, end);
  }
  free(buf);
  *max_nnz = mx;
  return rows;
}

// svmlight/RCV1 scanner, pass 2: fill CALLER-ZEROED padded row-major
// (cap_rows x nnz_cap) id/value arrays (pad slots stay id 0 / value 0 —
// inactive by the models' x != 0 convention) plus float labels. Feature
// ids are written verbatim (svmlight is conventionally 1-based; the caller
// re-indexes). Rows with more than nnz_cap features keep the FIRST nnz_cap
// and count in *truncated. Structurally malformed data lines (unparseable
// label, broken idx:val token) count in *malformed — callers refuse the
// file rather than silently drop lines, matching fps_parse_ratings.
long fps_parse_svmlight(const char* path, float* labels, int32_t* ids,
                        float* vals, long cap_rows, long nnz_cap,
                        long* truncated, long* malformed) {
  long size = 0;
  char* buf = read_whole_file(path, &size);
  if (!buf) return -1;
  const char* p = buf;
  const char* end = buf + size;
  long n = 0, bad = 0, trunc = 0;
  while (n < cap_rows && p < end) {
    while (p < end && (*p == ' ' || *p == '\r')) ++p;
    if (p >= end) break;
    if (*p == '\n' || *p == '#') {
      skip_line(p, end);
      continue;
    }
    double label;
    if (!parse_signed(p, end, &label)) {
      ++bad;
      skip_line(p, end);
      continue;
    }
    long nnz = 0;
    bool ok = true;
    while (p < end && *p != '\n' && *p != '#' && *p != '\r') {
      while (p < end && *p == ' ') ++p;
      if (p >= end || *p == '\n' || *p == '#' || *p == '\r') break;
      long idx = parse_uint(p, end);
      if (idx < 0 || p >= end || *p != ':') {
        ok = false;
        break;
      }
      ++p;  // ':'
      double v;
      if (!parse_signed(p, end, &v)) {
        ok = false;
        break;
      }
      if (nnz < nnz_cap) {
        ids[n * nnz_cap + nnz] = static_cast<int32_t>(idx);
        vals[n * nnz_cap + nnz] = static_cast<float>(v);
        ++nnz;
      } else {
        ++trunc;
        // keep scanning to validate the rest of the line
      }
    }
    if (!ok) {
      ++bad;
      // wipe any partial row
      for (long k = 0; k < nnz_cap; ++k) {
        ids[n * nnz_cap + k] = 0;
        vals[n * nnz_cap + k] = 0.0f;
      }
      skip_line(p, end);
      continue;
    }
    labels[n] = static_cast<float>(label);
    ++n;
    skip_line(p, end);
  }
  free(buf);
  if (truncated) *truncated = trunc;
  if (malformed) *malformed = bad;
  return n;
}

// Criteo click-logs scanner: one example per line,
//   <label> \t I1..I13 (ints, may be empty/negative) \t C1..C26 (hex tokens,
//   may be empty)
// Numeric column j (0-based) with value x >= 0 becomes feature id j with
// value log1p(x); negative or empty numerics are treated as missing.
// Categorical column j with token s becomes feature id
//   13 + hash(j, s) % (num_features - 13)      (FNV-1a + splitmix64)
// with value 1.0. Output is CALLER-ZEROED row-major (cap_rows x 39) with a
// FIXED-SLOT layout: numeric column j always sits at slot j (id j, value 0
// when missing — inactive by the models' x != 0 convention), categoricals
// append from slot 13; absent fields leave inactive pads. Lines with a
// non-0/1 label or a wrong field count are malformed. Returns rows, or -1
// on IO error.
long fps_parse_criteo(const char* path, float* labels, int32_t* ids,
                      float* vals, long cap_rows, long num_features,
                      long* malformed) {
  long size = 0;
  char* buf = read_whole_file(path, &size);
  if (!buf) return -1;
  const long kNum = 13, kCat = 26, kNnz = kNum + kCat;
  const long cat_space = num_features - kNum;
  const char* p = buf;
  const char* end = buf + size;
  long n = 0, bad = 0;
  while (n < cap_rows && p < end) {
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '\r' && (p + 1 >= end || p[1] == '\n')) {
      p += (p + 1 < end) ? 2 : 1;  // blank CRLF line, skip like the fallback
      continue;
    }
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    const char* le = line_end;
    if (le > p && le[-1] == '\r') --le;

    bool ok = true;
    // label
    long label = parse_uint(p, le);
    if (label != 0 && label != 1) ok = false;
    // FIXED-SLOT layout: numeric column j always occupies batch slot j
    // (id j; value 0 = inactive when missing/negative), so slot<->id is
    // deterministic for the dense head — models exploit it by pulling and
    // pushing the 13 numeric weights densely (LogRegConfig.dense_features)
    // instead of paying 13 scatter rows per example. Categorical features
    // append from slot 13 in field order; absent cats leave inactive pads.
    long nnz = kNum;  // cat slots start after the fixed numeric head
    long field = 0;
    while (ok && field < kNnz) {
      if (p >= le || *p != '\t') {
        ok = false;
        break;
      }
      ++p;  // tab
      const char* fs = p;
      while (p < le && *p != '\t') ++p;
      long flen = p - fs;
      if (field < kNum) {
        ids[n * kNnz + field] = static_cast<int32_t>(field);
        vals[n * kNnz + field] = 0.0f;  // inactive unless present below
      }
      if (flen == 0) {
        ++field;
        continue;  // missing value
      }
      if (field < kNum) {
        const char* q = fs;
        double v;
        if (!parse_signed(q, fs + flen, &v) || q != fs + flen) {
          ok = false;
          break;
        }
        if (v >= 0.0) {
          // log1p, cheap enough inline
          double x = v, r = 0.0;
          r = __builtin_log1p(x);
          vals[n * kNnz + field] = static_cast<float>(r);
        }
      } else {
        uint64_t h = hash_bytes(static_cast<uint64_t>(field), fs, flen);
        ids[n * kNnz + nnz] =
            static_cast<int32_t>(kNum + static_cast<long>(h % cat_space));
        vals[n * kNnz + nnz] = 1.0f;
        ++nnz;
      }
      ++field;
    }
    if (ok && (field != kNnz || p != le)) ok = false;
    if (!ok) {
      ++bad;
      for (long k = 0; k < kNnz; ++k) {
        ids[n * kNnz + k] = 0;
        vals[n * kNnz + k] = 0.0f;
      }
      p = line_end < end ? line_end + 1 : end;
      continue;
    }
    labels[n] = static_cast<float>(label);
    ++n;
    p = line_end < end ? line_end + 1 : end;
  }
  free(buf);
  if (malformed) *malformed = bad;
  return n;
}

// Skip-gram pair generation over a token segment, mirroring
// fps_tpu/models/word2vec.py's skipgram_chunks inner loop:
//   1. drop position t with probability 1 - keep_p[token[t]]  (subsampling)
//   2. per kept position, draw half-width h ~ U{1..window}
//   3. for d in 1..h with t+d kept-in-range: emit (kept[t], kept[t+d]) and
//      (kept[t+d], kept[t])  (both directions, distance gated by the LEFT
//      element's half-width, exactly like the numpy implementation)
// Deterministic for a given seed. Returns pairs written (<= cap).
long fps_skipgram_pairs(const int32_t* tokens, long n, int window,
                        uint64_t seed, const float* keep_p, int32_t vocab,
                        int32_t* centers, int32_t* contexts, long cap) {
  if (n <= 0 || window <= 0) return 0;
  Rng rng(seed);
  // Pass 1: subsample into a kept buffer (indices compacted).
  int32_t* kept = static_cast<int32_t*>(malloc(sizeof(int32_t) * n));
  if (!kept) return -1;
  long m = 0;
  for (long t = 0; t < n; ++t) {
    int32_t tok = tokens[t];
    double kp = (keep_p && tok >= 0 && tok < vocab) ? keep_p[tok] : 1.0;
    if (kp >= 1.0 || rng.uniform() < kp) kept[m++] = tok;
  }
  long out = 0;
  for (long t = 0; t < m && out < cap; ++t) {
    int h = rng.one_to(window);
    for (int d = 1; d <= h && t + d < m; ++d) {
      if (out + 2 > cap) break;
      centers[out] = kept[t];
      contexts[out] = kept[t + d];
      ++out;
      centers[out] = kept[t + d];
      contexts[out] = kept[t];
      ++out;
    }
  }
  free(kept);
  return out;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Measured sequential-baseline hot loops (bench.py's reference stand-in).
//
// The reference's hot path is a per-record parameter-server loop riding
// Flink operators: worker receives a record, sends a pull message through a
// keyed shuffle to the server operator, gets the row back, computes, sends a
// push message. Its JVM stack cannot run in this image, so bench.py needs a
// measured stand-in rather than a guessed constant. Two modes, both strictly
// GENEROUS to the reference:
//
//   mode 0 ("ideal"): the fused sequential loop — pull/update/push collapse
//     into direct array access. A floor no real deployment reaches (no
//     framework, no serialization, no network, tables cache-resident).
//   mode 1 ("ps"):    the same loop with every pull request, pull response
//     and push delta forced through a bounded ring of message slots with
//     real (noinline) memcpy on both ends — the cheapest possible model of
//     the reference's operator hops: serialize -> channel -> deserialize
//     becomes memcpy -> ring -> memcpy, with zero JVM, network or
//     coordination cost on top.
//
// Timing uses CLOCK_MONOTONIC and excludes allocation/init. Each loop also
// reports its own training-quality metric (online MSE / SGNS loss /
// logloss) so the caller can verify the baseline LEARNS — the equal-epochs
// credit in bench.py depends on it.

namespace {

inline double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

// Bounded message ring: NSLOT fixed-size slots, reused round-robin like a
// channel buffer. send/recv are noinline so -O3 cannot collapse the message
// path back into the ideal loop — each message pays two real calls and two
// real memcpys, the irreducible cost of an operator hop.
struct Ring {
  // >= largest message: id + kMaxClasses floats (multiclass PA row) and
  // id + 100 floats (rank-100 w2v/MF rows) both fit; static_asserts at
  // the consumers tie the caps to this size.
  static const long SLOT = 512;
  static const long NSLOT = 256;
  char* data;
  long w;
  Ring() : data(static_cast<char*>(malloc(SLOT * NSLOT))), w(0) {}
  ~Ring() { free(data); }
  bool ok() const { return data != nullptr; }
};

// Move an int32 id in and out of a float-typed message slot without
// violating strict aliasing; memcpy compiles to the same single store/load.
inline void put_id(float* slot, int32_t id) { memcpy(slot, &id, sizeof(id)); }
inline int32_t get_id(const float* slot) {
  int32_t id;
  memcpy(&id, slot, sizeof(id));
  return id;
}

__attribute__((noinline)) char* ring_send(Ring& r, const void* src,
                                          long nbytes) {
  char* slot = r.data + (r.w++ % Ring::NSLOT) * Ring::SLOT;
  memcpy(slot, src, nbytes);
  return slot;
}

__attribute__((noinline)) void ring_recv(void* dst, const char* slot,
                                         long nbytes) {
  memcpy(dst, slot, nbytes);
}

inline float fast_sigmoid_arg(float z) {
  // Guard exp against overflow; the loops' lr keep z small in practice.
  if (z > 30.0f) z = 30.0f;
  if (z < -30.0f) z = -30.0f;
  return z;
}

}  // namespace

extern "C" {

// Sequential per-record MF SGD (the reference's worker-local user factors /
// server-resident item factors split): per rating, pull the item row,
// compute the error, update the local user row, push the item delta.
// Runs `epochs` passes over the n ratings in the given order, writing
// per-epoch wall seconds and per-epoch ONLINE train MSE (pre-update error,
// the same semantic as the TPU path's metrics stream). Returns total train
// seconds, or -1 on allocation failure.
double fps_baseline_mf(const int32_t* users, const int32_t* items,
                       const float* ratings, long n, long num_users,
                       long num_items, int rank, float lr, float reg,
                       uint64_t seed, int epochs, int ps_mode,
                       double* per_epoch_s, double* per_epoch_mse) {
  if (rank > 120) return -1.0;  // qbuf/dbuf + ring slot budget (cf. w2v)
  float* P = static_cast<float*>(malloc(sizeof(float) * num_users * rank));
  float* Q = static_cast<float*>(malloc(sizeof(float) * num_items * rank));
  if (!P || !Q) {
    free(P);
    free(Q);
    return -1.0;
  }
  Rng rng(seed);
  for (long k = 0; k < num_users * rank; ++k)
    P[k] = static_cast<float>((rng.uniform() - 0.5) * 0.2);
  for (long k = 0; k < num_items * rank; ++k)
    Q[k] = static_cast<float>((rng.uniform() - 0.5) * 0.2);

  Ring ring;
  if (ps_mode && !ring.ok()) {
    free(P);
    free(Q);
    return -1.0;
  }
  float qbuf[128];
  float dbuf[129];
  double total = 0.0;
  for (int e = 0; e < epochs; ++e) {
    double se = 0.0;
    double t0 = now_s();
    for (long k = 0; k < n; ++k) {
      long u = users[k], i = items[k];
      float r = ratings[k];
      float* p = P + u * rank;
      const float* q;
      if (ps_mode) {
        // pull request (item id) -> server; response (rank floats) back.
        int32_t req = static_cast<int32_t>(i);
        char* s1 = ring_send(ring, &req, sizeof(req));
        int32_t got_i;
        ring_recv(&got_i, s1, sizeof(got_i));
        char* s2 = ring_send(ring, Q + got_i * rank, sizeof(float) * rank);
        ring_recv(qbuf, s2, sizeof(float) * rank);
        q = qbuf;
      } else {
        q = Q + i * rank;
      }
      float dot = 0.0f;
      for (int d = 0; d < rank; ++d) dot += p[d] * q[d];
      float err = r - dot;
      se += static_cast<double>(err) * err;
      if (ps_mode) {
        // local user update + push message (id + rank floats) -> server.
        put_id(&dbuf[0], static_cast<int32_t>(i));
        for (int d = 0; d < rank; ++d) {
          float pd = p[d];
          dbuf[1 + d] = lr * (err * pd - reg * q[d]);
          p[d] = pd + lr * (err * q[d] - reg * pd);
        }
        char* s3 = ring_send(ring, dbuf, sizeof(float) * (rank + 1));
        ring_recv(dbuf, s3, sizeof(float) * (rank + 1));
        float* qrow = Q + get_id(&dbuf[0]) * rank;
        for (int d = 0; d < rank; ++d) qrow[d] += dbuf[1 + d];
      } else {
        float* qrow = Q + i * rank;
        for (int d = 0; d < rank; ++d) {
          float pd = p[d], qd = qrow[d];
          p[d] = pd + lr * (err * qd - reg * pd);
          qrow[d] = qd + lr * (err * pd - reg * qd);
        }
      }
    }
    double dt = now_s() - t0;
    total += dt;
    if (per_epoch_s) per_epoch_s[e] = dt;
    if (per_epoch_mse) per_epoch_mse[e] = se / (n > 0 ? n : 1);
  }
  free(P);
  free(Q);
  return total;
}

// Sequential per-pair word2vec SGNS: per (center, context) pair, pull the
// center row and the 1+negatives output rows, update all of them, push them
// back. Negatives are drawn from the unigram^0.75 cdf by binary search
// (the reference's unigram-table draw). One pass over the given pairs.
// Writes the mean SGNS loss over the pass. Returns seconds, or -1.
double fps_baseline_w2v(const int32_t* centers, const int32_t* contexts,
                        long n_pairs, const double* uni_cdf, long vocab,
                        int dim, int negatives, float lr, uint64_t seed,
                        int ps_mode, double* mean_loss) {
  if (dim > 120) return -1.0;  // ring slot budget
  float* IN = static_cast<float*>(malloc(sizeof(float) * vocab * dim));
  float* OUT = static_cast<float*>(malloc(sizeof(float) * vocab * dim));
  if (!IN || !OUT) {
    free(IN);
    free(OUT);
    return -1.0;
  }
  Rng rng(seed);
  for (long k = 0; k < vocab * dim; ++k)
    IN[k] = static_cast<float>((rng.uniform() - 0.5) / dim);
  memset(OUT, 0, sizeof(float) * vocab * dim);

  Ring ring;
  if (ps_mode && !ring.ok()) {
    free(IN);
    free(OUT);
    return -1.0;
  }
  float vbuf[128], ubuf[128], dbuf[129];
  double loss = 0.0;
  double t0 = now_s();
  for (long k = 0; k < n_pairs; ++k) {
    long c = centers[k];
    float* v;
    if (ps_mode) {
      int32_t req = static_cast<int32_t>(c);
      char* s1 = ring_send(ring, &req, sizeof(req));
      int32_t gi;
      ring_recv(&gi, s1, sizeof(gi));
      char* s2 = ring_send(ring, IN + gi * dim, sizeof(float) * dim);
      ring_recv(vbuf, s2, sizeof(float) * dim);
      v = vbuf;
    } else {
      v = IN + c * dim;
    }
    float dv[128];
    for (int d = 0; d < dim; ++d) dv[d] = 0.0f;
    for (int j = 0; j <= negatives; ++j) {
      long o;
      if (j == 0) {
        o = contexts[k];
      } else {
        // binary search the cdf for a unigram^0.75 draw
        double x = rng.uniform();
        long lo = 0, hi = vocab - 1;
        while (lo < hi) {
          long mid = (lo + hi) >> 1;
          if (uni_cdf[mid] < x) lo = mid + 1; else hi = mid;
        }
        o = lo;
      }
      float* u;
      if (ps_mode) {
        int32_t req = static_cast<int32_t>(o);
        char* s1 = ring_send(ring, &req, sizeof(req));
        int32_t gi;
        ring_recv(&gi, s1, sizeof(gi));
        char* s2 = ring_send(ring, OUT + gi * dim, sizeof(float) * dim);
        ring_recv(ubuf, s2, sizeof(float) * dim);
        u = ubuf;
      } else {
        u = OUT + o * dim;
      }
      float z = 0.0f;
      for (int d = 0; d < dim; ++d) z += v[d] * u[d];
      z = fast_sigmoid_arg(z);
      float sig = 1.0f / (1.0f + __builtin_expf(-z));
      float label = (j == 0) ? 1.0f : 0.0f;
      float g = sig - label;
      loss += (label > 0.5f)
                  ? -__builtin_log(sig > 1e-7f ? sig : 1e-7f)
                  : -__builtin_log(1.0f - sig > 1e-7f ? 1.0f - sig : 1e-7f);
      for (int d = 0; d < dim; ++d) dv[d] -= lr * g * u[d];
      if (ps_mode) {
        put_id(&dbuf[0], static_cast<int32_t>(o));
        for (int d = 0; d < dim; ++d) dbuf[1 + d] = -lr * g * v[d];
        char* s3 = ring_send(ring, dbuf, sizeof(float) * (dim + 1));
        ring_recv(dbuf, s3, sizeof(float) * (dim + 1));
        float* orow = OUT + get_id(&dbuf[0]) * dim;
        for (int d = 0; d < dim; ++d) orow[d] += dbuf[1 + d];
      } else {
        for (int d = 0; d < dim; ++d) u[d] -= lr * g * v[d];
      }
    }
    if (ps_mode) {
      put_id(&dbuf[0], static_cast<int32_t>(c));
      for (int d = 0; d < dim; ++d) dbuf[1 + d] = dv[d];
      char* s3 = ring_send(ring, dbuf, sizeof(float) * (dim + 1));
      ring_recv(dbuf, s3, sizeof(float) * (dim + 1));
      float* crow = IN + get_id(&dbuf[0]) * dim;
      for (int d = 0; d < dim; ++d) crow[d] += dbuf[1 + d];
    } else {
      for (int d = 0; d < dim; ++d) v[d] += dv[d];
    }
  }
  double dt = now_s() - t0;
  if (mean_loss)
    *mean_loss = loss / ((n_pairs > 0 ? n_pairs : 1) * (1 + negatives));
  free(IN);
  free(OUT);
  return dt;
}

// Sequential per-example sparse logistic regression: the reference's
// worker pulls each active feature id INDIVIDUALLY and pushes one delta per
// feature (SURVEY §3.4's fan-out). Pad slots (value exactly 0) are skipped.
// One pass; writes mean logloss. Returns seconds, or -1.
double fps_baseline_logreg(const int32_t* ids, const float* vals,
                           const float* labels, long n, long nnz,
                           long num_features, float lr, int ps_mode,
                           double* mean_logloss) {
  float* w = static_cast<float*>(calloc(num_features, sizeof(float)));
  if (!w) return -1.0;
  Ring ring;
  if (ps_mode && !ring.ok()) {
    free(w);
    return -1.0;
  }
  double loss = 0.0;
  double t0 = now_s();
  for (long k = 0; k < n; ++k) {
    const int32_t* fid = ids + k * nnz;
    const float* fval = vals + k * nnz;
    float z = 0.0f;
    for (long j = 0; j < nnz; ++j) {
      if (fval[j] == 0.0f) continue;
      float wj;
      if (ps_mode) {
        char* s1 = ring_send(ring, &fid[j], sizeof(int32_t));
        int32_t gi;
        ring_recv(&gi, s1, sizeof(gi));
        char* s2 = ring_send(ring, &w[gi], sizeof(float));
        ring_recv(&wj, s2, sizeof(float));
      } else {
        wj = w[fid[j]];
      }
      z += wj * fval[j];
    }
    z = fast_sigmoid_arg(z);
    float sig = 1.0f / (1.0f + __builtin_expf(-z));
    float y = labels[k];
    float g = (sig - y) * lr;
    loss += (y > 0.5f)
                ? -__builtin_log(sig > 1e-7f ? sig : 1e-7f)
                : -__builtin_log(1.0f - sig > 1e-7f ? 1.0f - sig : 1e-7f);
    for (long j = 0; j < nnz; ++j) {
      if (fval[j] == 0.0f) continue;
      if (ps_mode) {
        float msg[2];
        put_id(&msg[0], fid[j]);
        msg[1] = -g * fval[j];
        char* s3 = ring_send(ring, msg, sizeof(msg));
        ring_recv(msg, s3, sizeof(msg));
        w[get_id(&msg[0])] += msg[1];
      } else {
        w[fid[j]] -= g * fval[j];
      }
    }
  }
  double dt = now_s() - t0;
  if (mean_logloss) *mean_logloss = loss / (n > 0 ? n : 1);
  free(w);
  return dt;
}


// Sequential per-example passive-aggressive (binary, Crammer et al. 2006):
// the reference's shape — pull each active feature individually, compute
// the margin and the closed-form step (variant 0=PA, 1=PA-I, 2=PA-II),
// push one delta per feature. Labels in {-1,+1}; pad slots (value 0)
// skipped. One pass; writes the mean hinge loss and the online mistake
// fraction. Returns seconds, or -1.
double fps_baseline_pa(const int32_t* ids, const float* vals,
                       const float* labels, long n, long nnz,
                       long num_features, float C, int variant, int ps_mode,
                       double* mean_hinge, double* mistake_frac) {
  float* w = static_cast<float*>(calloc(num_features, sizeof(float)));
  if (!w) return -1.0;
  Ring ring;
  if (ps_mode && !ring.ok()) {
    free(w);
    return -1.0;
  }
  double hinge = 0.0;
  long mistakes = 0;
  double t0 = now_s();
  for (long k = 0; k < n; ++k) {
    const int32_t* fid = ids + k * nnz;
    const float* fval = vals + k * nnz;
    float y = labels[k];
    float m = 0.0f, x2 = 0.0f;
    for (long j = 0; j < nnz; ++j) {
      if (fval[j] == 0.0f) continue;
      float wj;
      if (ps_mode) {
        char* s1 = ring_send(ring, &fid[j], sizeof(int32_t));
        int32_t gi;
        ring_recv(&gi, s1, sizeof(gi));
        char* s2 = ring_send(ring, &w[gi], sizeof(float));
        ring_recv(&wj, s2, sizeof(float));
      } else {
        wj = w[fid[j]];
      }
      m += wj * fval[j];
      x2 += fval[j] * fval[j];
    }
    float l = 1.0f - y * m;
    if (l < 0.0f) l = 0.0f;
    hinge += l;
    if (y * m <= 0.0f) ++mistakes;
    if (l > 0.0f && x2 > 0.0f) {
      float tau;
      if (variant == 0) {
        tau = l / x2;
      } else if (variant == 1) {
        tau = l / x2;
        if (tau > C) tau = C;
      } else {
        tau = l / (x2 + 0.5f / C);
      }
      float step = tau * y;
      for (long j = 0; j < nnz; ++j) {
        if (fval[j] == 0.0f) continue;
        if (ps_mode) {
          float msg[2];
          put_id(&msg[0], fid[j]);
          msg[1] = step * fval[j];
          char* s3 = ring_send(ring, msg, sizeof(msg));
          ring_recv(msg, s3, sizeof(msg));
          w[get_id(&msg[0])] += msg[1];
        } else {
          w[fid[j]] += step * fval[j];
        }
      }
    }
  }
  double dt = now_s() - t0;
  if (mean_hinge) *mean_hinge = hinge / (n > 0 ? n : 1);
  if (mistake_frac)
    *mistake_frac = static_cast<double>(mistakes) / (n > 0 ? n : 1);
  free(w);
  return dt;
}

// Sequential per-example MULTICLASS passive-aggressive (Crammer et al.
// 2006 max-margin-violation update — the closed form the TPU path's
// MulticlassPassiveAggressiveWorker computes in batch): per example, pull
// each active feature's num_classes-float class row, score all classes,
// take the true class r vs the highest-scoring wrong class s,
// l = max(0, 1 - (score_r - score_s)), tau per variant with ||x||^2
// DOUBLED (the update touches two class columns), then push one
// num_classes-float delta row per active feature (+tau*x_j in column r,
// -tau*x_j in column s). Labels are class indices in [0, num_classes).
// ps_mode forces every pull request/response and push delta through the
// message ring exactly like the binary loop, with row-sized messages.
// One pass; writes mean hinge loss and the online mistake fraction.
// Returns seconds, or -1.
double fps_baseline_pa_mc(const int32_t* ids, const float* vals,
                          const int32_t* labels, long n, long nnz,
                          long num_features, long num_classes, float C,
                          int variant, int ps_mode, double* mean_hinge,
                          double* mistake_frac) {
  // The class cap is tied to the fixed buffers below and the ring slot:
  // msg carries id + num_classes floats, rowbuf/scores hold num_classes.
  const long kMaxClasses = 120;
  static_assert(sizeof(float) * (kMaxClasses + 1) <= Ring::SLOT,
                "multiclass PA message must fit one ring slot");
  static_assert(kMaxClasses + 1 <= 128,
                "multiclass PA buffers are 128 floats");
  if (num_classes < 3 || num_classes > kMaxClasses) return -1.0;
  // Labels index the scores/msg stack arrays and the weight rows: an
  // out-of-range class (1-based labels, -1 missing sentinel) must surface
  // as the -1 error return, not as silent memory corruption.
  for (long k = 0; k < n; ++k) {
    if (labels[k] < 0 || labels[k] >= num_classes) return -1.0;
  }
  float* w =
      static_cast<float*>(calloc(num_features * num_classes, sizeof(float)));
  if (!w) return -1.0;
  Ring ring;
  if (ps_mode && !ring.ok()) {
    free(w);
    return -1.0;
  }
  float rowbuf[128];
  float msg[128];  // id + num_classes floats
  float scores[128];
  double hinge = 0.0;
  long mistakes = 0;
  double t0 = now_s();
  for (long k = 0; k < n; ++k) {
    const int32_t* fid = ids + k * nnz;
    const float* fval = vals + k * nnz;
    long r = labels[k];
    for (long c = 0; c < num_classes; ++c) scores[c] = 0.0f;
    float x2 = 0.0f;
    for (long j = 0; j < nnz; ++j) {
      if (fval[j] == 0.0f) continue;
      const float* row;
      if (ps_mode) {
        char* s1 = ring_send(ring, &fid[j], sizeof(int32_t));
        int32_t gi;
        ring_recv(&gi, s1, sizeof(gi));
        char* s2 = ring_send(ring, w + static_cast<long>(gi) * num_classes,
                             sizeof(float) * num_classes);
        ring_recv(rowbuf, s2, sizeof(float) * num_classes);
        row = rowbuf;
      } else {
        row = w + static_cast<long>(fid[j]) * num_classes;
      }
      float xv = fval[j];
      for (long c = 0; c < num_classes; ++c) scores[c] += row[c] * xv;
      x2 += xv * xv;
    }
    // Highest-scoring WRONG class s; prediction = overall argmax (first
    // max wins, matching jnp.argmax).
    long s = (r == 0) ? 1 : 0;
    long pred = 0;
    for (long c = 1; c < num_classes; ++c) {
      if (scores[c] > scores[pred]) pred = c;
      if (c != r && scores[c] > scores[s]) s = c;
    }
    if (pred != r) ++mistakes;
    float l = 1.0f - (scores[r] - scores[s]);
    if (l < 0.0f) l = 0.0f;
    hinge += l;
    if (l > 0.0f && x2 > 0.0f) {
      float x2m = 2.0f * x2;
      float tau;
      if (variant == 0) {
        tau = l / x2m;
      } else if (variant == 1) {
        tau = l / x2m;
        if (tau > C) tau = C;
      } else {
        tau = l / (x2m + 0.5f / C);
      }
      for (long j = 0; j < nnz; ++j) {
        if (fval[j] == 0.0f) continue;
        float step = tau * fval[j];
        if (ps_mode) {
          put_id(&msg[0], fid[j]);
          for (long c = 0; c < num_classes; ++c) msg[1 + c] = 0.0f;
          msg[1 + r] = step;
          msg[1 + s] = -step;
          char* s3 = ring_send(ring, msg, sizeof(float) * (num_classes + 1));
          ring_recv(msg, s3, sizeof(float) * (num_classes + 1));
          float* wrow =
              w + static_cast<long>(get_id(&msg[0])) * num_classes;
          for (long c = 0; c < num_classes; ++c) wrow[c] += msg[1 + c];
        } else {
          float* wrow = w + static_cast<long>(fid[j]) * num_classes;
          wrow[r] += step;
          wrow[s] -= step;
        }
      }
    }
  }
  double dt = now_s() - t0;
  if (mean_hinge) *mean_hinge = hinge / (n > 0 ? n : 1);
  if (mistake_frac)
    *mistake_frac = static_cast<double>(mistakes) / (n > 0 ? n : 1);
  free(w);
  return dt;
}

}  // extern "C"
