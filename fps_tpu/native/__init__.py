"""Native (C++) host-ingest acceleration, built on demand, numpy fallback.

The TPU compute path is JAX/XLA/Pallas; the *host* side of ingest (file
parsing, skip-gram pair generation) is plain CPU work on the TPU VM, and the
reference's equivalent layer runs as compiled JVM operators inside Flink.
This package gives the rebuild a comparable native layer without adding
dependencies: ``src/fps_native.cc`` is compiled with ``g++ -O3`` the first
time it's needed (result cached next to the source, rebuilt when the source
changes) and bound via ctypes. Everything degrades gracefully: if no
compiler is available, callers use the numpy implementations.

API:

* :func:`available` — True if the shared library could be built/loaded.
* :func:`parse_ratings` — single-pass scanner for MovieLens-style rating
  files (tab/comma/space separated, headers skipped, int or decimal
  ratings). ~10M rows/s, measured ~1.5x ``np.loadtxt`` on ML-20M-sized
  files — and unlike a fixed-dtype ``loadtxt`` call it handles both the
  ML-100K tab format and the ML-20M csv-with-header format.
* :func:`skipgram_pairs` — subsampled dynamic-window skip-gram pairs for a
  token segment (word2vec ingest), deterministic per seed; ~33M pairs/s,
  replacing the numpy per-segment vectorized loop.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "fps_native.cc")
_LIB = os.path.join(_DIR, "_fps_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # Compile to a unique temp path then rename: concurrent processes must
    # never dlopen a half-written .so (the failure would be cached for the
    # process lifetime).
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (
            not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.fps_count_lines.restype = ctypes.c_long
        lib.fps_count_lines.argtypes = [ctypes.c_char_p]
        lib.fps_parse_ratings.restype = ctypes.c_long
        lib.fps_parse_ratings.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.fps_skipgram_pairs.restype = ctypes.c_long
        lib.fps_skipgram_pairs.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long,
        ]
        lib.fps_svmlight_dims.restype = ctypes.c_long
        lib.fps_svmlight_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.fps_parse_svmlight.restype = ctypes.c_long
        lib.fps_parse_svmlight.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.fps_parse_criteo.restype = ctypes.c_long
        lib.fps_parse_criteo.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.fps_baseline_mf.restype = ctypes.c_double
        lib.fps_baseline_mf.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_long, ctypes.c_long,
            ctypes.c_int, ctypes.c_float, ctypes.c_float,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.fps_baseline_w2v.restype = ctypes.c_double
        lib.fps_baseline_w2v.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_uint64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.fps_baseline_pa.restype = ctypes.c_double
        lib.fps_baseline_pa.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_long, ctypes.c_long,
            ctypes.c_float, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.fps_baseline_pa_mc.restype = ctypes.c_double
        lib.fps_baseline_pa_mc.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
            ctypes.c_float, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.fps_baseline_logreg.restype = ctypes.c_double
        lib.fps_baseline_logreg.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_long, ctypes.c_long,
            ctypes.c_float, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def parse_ratings(path: str, max_rows: int | None = None):
    """Parse a ratings file into ``(users, items, ratings)`` int32/float32.

    Returns ``None`` if the native library is unavailable (caller falls back
    to numpy) or the file cannot be read. Raises ``ValueError`` if any
    data-looking line fails to parse — a corrupted file must not silently
    yield a truncated dataset. Ids are returned verbatim (1-based in
    MovieLens files; the caller re-indexes).
    """
    lib = _load()
    if lib is None:
        return None
    if max_rows is None:
        # Upper bound: line count, native single pass (the parse pass that
        # follows then reads a page-cache-warm file).
        max_rows = lib.fps_count_lines(path.encode())
        if max_rows < 0:
            return None
        max_rows = max(int(max_rows), 1)
    users = np.empty(max_rows, np.int32)
    items = np.empty(max_rows, np.int32)
    ratings = np.empty(max_rows, np.float32)
    malformed = ctypes.c_long(0)
    n = lib.fps_parse_ratings(
        path.encode(),
        _ptr(users, ctypes.c_int32),
        _ptr(items, ctypes.c_int32),
        _ptr(ratings, ctypes.c_float),
        max_rows,
        ctypes.byref(malformed),
    )
    if n < 0:
        return None
    if malformed.value:
        raise ValueError(
            f"{path}: {malformed.value} malformed data line(s) — refusing "
            "to return a silently-truncated dataset"
        )
    return users[:n], items[:n], ratings[:n]


def parse_svmlight(path: str, nnz_cap: int | None = None):
    """Parse an svmlight/RCV1 file into padded dense batch arrays.

    Returns ``(labels (N,) f32, ids (N, nnz) i32, vals (N, nnz) f32,
    truncated)`` with pad slots id 0 / value 0 (inactive by the models'
    ``x != 0`` convention), or ``None`` if the native library is
    unavailable. ``nnz_cap`` pads/truncates each row (default: the file's
    max row length); truncated rows keep their FIRST ``nnz_cap`` features
    and are counted in ``truncated``. Raises ``ValueError`` on malformed
    data lines — a corrupted file must not silently shrink. Feature ids
    are verbatim (svmlight is conventionally 1-based; callers re-index).
    """
    lib = _load()
    if lib is None:
        return None
    max_nnz = ctypes.c_long(0)
    rows = lib.fps_svmlight_dims(path.encode(), ctypes.byref(max_nnz))
    if rows < 0:
        return None
    rows = max(int(rows), 1)
    nnz = int(nnz_cap) if nnz_cap else max(int(max_nnz.value), 1)
    labels = np.zeros(rows, np.float32)
    ids = np.zeros((rows, nnz), np.int32)
    vals = np.zeros((rows, nnz), np.float32)
    truncated = ctypes.c_long(0)
    malformed = ctypes.c_long(0)
    n = lib.fps_parse_svmlight(
        path.encode(),
        _ptr(labels, ctypes.c_float),
        _ptr(ids, ctypes.c_int32),
        _ptr(vals, ctypes.c_float),
        rows,
        nnz,
        ctypes.byref(truncated),
        ctypes.byref(malformed),
    )
    if n < 0:
        return None
    if malformed.value:
        raise ValueError(
            f"{path}: {malformed.value} malformed svmlight line(s) — "
            "refusing to return a silently-truncated dataset"
        )
    return labels[:n], ids[:n], vals[:n], int(truncated.value)


CRITEO_NUM_COLS = 13
CRITEO_CAT_COLS = 26
CRITEO_NNZ = CRITEO_NUM_COLS + CRITEO_CAT_COLS


def parse_criteo(path: str, num_features: int):
    """Parse a Criteo click-log TSV into padded dense batch arrays.

    Returns ``(labels (N,) f32 in {0,1}, ids (N, 39) i32, vals (N, 39)
    f32)`` or ``None`` if the native library is unavailable. Numeric column
    j with value x >= 0 becomes id j / value log1p(x); categorical column j
    becomes id ``13 + hash(j, token) % (num_features - 13)`` / value 1.0
    (FNV-1a + splitmix64 — the numpy fallback in utils.datasets matches it
    bit-for-bit). Missing fields stay inactive. Raises ``ValueError`` on
    malformed lines.
    """
    if num_features <= CRITEO_NUM_COLS:
        raise ValueError("num_features must exceed 13 (the numeric columns)")
    lib = _load()
    if lib is None:
        return None
    rows = lib.fps_count_lines(path.encode())
    if rows < 0:
        return None
    rows = max(int(rows), 1)
    labels = np.zeros(rows, np.float32)
    ids = np.zeros((rows, CRITEO_NNZ), np.int32)
    vals = np.zeros((rows, CRITEO_NNZ), np.float32)
    malformed = ctypes.c_long(0)
    n = lib.fps_parse_criteo(
        path.encode(),
        _ptr(labels, ctypes.c_float),
        _ptr(ids, ctypes.c_int32),
        _ptr(vals, ctypes.c_float),
        rows,
        num_features,
        ctypes.byref(malformed),
    )
    if n < 0:
        return None
    if malformed.value:
        raise ValueError(
            f"{path}: {malformed.value} malformed Criteo line(s) — "
            "refusing to return a silently-truncated dataset"
        )
    return labels[:n], ids[:n], vals[:n]


def skipgram_pairs(
    tokens: np.ndarray,
    window: int,
    seed: int,
    keep_p: np.ndarray | None = None,
):
    """Generate (centers, contexts) for one token segment.

    Subsampling keeps position ``t`` with probability ``keep_p[token[t]]``;
    each kept position draws a half-width in ``1..window`` and emits both
    pair directions (matching the numpy implementation in
    ``fps_tpu/models/word2vec.py``). Deterministic per seed. Returns
    ``None`` when the native library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    tokens = np.ascontiguousarray(tokens, np.int32)
    n = len(tokens)
    cap = 2 * window * max(n, 1)
    centers = np.empty(cap, np.int32)
    contexts = np.empty(cap, np.int32)
    if keep_p is not None:
        keep_p = np.ascontiguousarray(keep_p, np.float32)
        vocab = len(keep_p)
        kp_ptr = _ptr(keep_p, ctypes.c_float)
    else:
        vocab = 0
        kp_ptr = ctypes.POINTER(ctypes.c_float)()
    m = lib.fps_skipgram_pairs(
        _ptr(tokens, ctypes.c_int32),
        n,
        window,
        seed & 0xFFFFFFFFFFFFFFFF,
        kp_ptr,
        vocab,
        _ptr(centers, ctypes.c_int32),
        _ptr(contexts, ctypes.c_int32),
        cap,
    )
    if m < 0:
        return None
    return centers[:m], contexts[:m]


def baseline_mf(users, items, ratings, num_users, num_items, *, rank,
                lr=0.05, reg=0.01, seed=0, epochs=1, ps_mode=True):
    """MEASURED sequential per-record MF baseline (bench.py's reference
    stand-in — see the C++ docstring for the generosity argument).

    Runs ``epochs`` passes of per-record SGD over the ratings and returns
    ``(per_epoch_seconds, per_epoch_mse)`` (lists of length ``epochs``), or
    ``None`` if the native library is unavailable. ``ps_mode=True`` forces
    every pull/push through the message ring (the reference's operator-hop
    structure); ``False`` measures the idealized fused loop."""
    lib = _load()
    if lib is None:
        return None
    users = np.ascontiguousarray(users, np.int32)
    items = np.ascontiguousarray(items, np.int32)
    ratings = np.ascontiguousarray(ratings, np.float32)
    n = len(users)
    secs = np.zeros(epochs, np.float64)
    mses = np.zeros(epochs, np.float64)
    total = lib.fps_baseline_mf(
        _ptr(users, ctypes.c_int32), _ptr(items, ctypes.c_int32),
        _ptr(ratings, ctypes.c_float), n, int(num_users), int(num_items),
        int(rank), float(lr), float(reg), seed & 0xFFFFFFFFFFFFFFFF,
        int(epochs), 1 if ps_mode else 0,
        _ptr(secs, ctypes.c_double), _ptr(mses, ctypes.c_double),
    )
    if total < 0:
        return None
    return secs.tolist(), mses.tolist()


def baseline_w2v(centers, contexts, uni, *, dim, negatives=5, lr=0.025,
                 seed=0, ps_mode=True):
    """MEASURED sequential per-pair SGNS baseline. One pass over the given
    pairs; negatives drawn from the unigram^0.75 cdf. Returns
    ``(seconds, mean_loss)`` or ``None`` if unavailable."""
    lib = _load()
    if lib is None:
        return None
    centers = np.ascontiguousarray(centers, np.int32)
    contexts = np.ascontiguousarray(contexts, np.int32)
    p = np.asarray(uni, np.float64) ** 0.75
    cdf = np.cumsum(p / p.sum())
    loss = ctypes.c_double(0.0)
    secs = lib.fps_baseline_w2v(
        _ptr(centers, ctypes.c_int32), _ptr(contexts, ctypes.c_int32),
        len(centers), _ptr(cdf, ctypes.c_double), len(cdf), int(dim),
        int(negatives), float(lr), seed & 0xFFFFFFFFFFFFFFFF,
        1 if ps_mode else 0, ctypes.byref(loss),
    )
    if secs < 0:
        return None
    return float(secs), float(loss.value)


def baseline_logreg(feat_ids, feat_vals, labels, num_features, *, lr=0.1,
                    ps_mode=True):
    """MEASURED sequential per-example sparse-logreg baseline (per-feature
    pull/push fan-out, the reference's shape). One pass; returns
    ``(seconds, mean_logloss)`` or ``None`` if unavailable."""
    lib = _load()
    if lib is None:
        return None
    feat_ids = np.ascontiguousarray(feat_ids, np.int32)
    feat_vals = np.ascontiguousarray(feat_vals, np.float32)
    labels = np.ascontiguousarray(labels, np.float32)
    n, nnz = feat_ids.shape
    loss = ctypes.c_double(0.0)
    secs = lib.fps_baseline_logreg(
        _ptr(feat_ids, ctypes.c_int32), _ptr(feat_vals, ctypes.c_float),
        _ptr(labels, ctypes.c_float), n, nnz, int(num_features), float(lr),
        1 if ps_mode else 0, ctypes.byref(loss),
    )
    if secs < 0:
        return None
    return float(secs), float(loss.value)


def baseline_pa(feat_ids, feat_vals, labels, num_features, *, C=1.0,
                variant="PA-I", ps_mode=True):
    """MEASURED sequential per-example passive-aggressive baseline
    (per-feature pull/push fan-out, the reference's shape; labels in
    {-1,+1}). One pass; returns ``(seconds, mean_hinge, mistake_frac)``
    or ``None`` if unavailable."""
    lib = _load()
    if lib is None:
        return None
    var = {"PA": 0, "PA-I": 1, "PA-II": 2}[variant]
    feat_ids = np.ascontiguousarray(feat_ids, np.int32)
    feat_vals = np.ascontiguousarray(feat_vals, np.float32)
    labels = np.ascontiguousarray(labels, np.float32)
    n, nnz = feat_ids.shape
    hinge = ctypes.c_double(0.0)
    mist = ctypes.c_double(0.0)
    secs = lib.fps_baseline_pa(
        _ptr(feat_ids, ctypes.c_int32), _ptr(feat_vals, ctypes.c_float),
        _ptr(labels, ctypes.c_float), n, nnz, int(num_features), float(C),
        var, 1 if ps_mode else 0, ctypes.byref(hinge), ctypes.byref(mist),
    )
    if secs < 0:
        return None
    return float(secs), float(hinge.value), float(mist.value)


# The native multiclass PA kernel's class-row message rides a fixed slot
# (id + kMaxClasses floats) — mirror of fps_native.cc's kMaxClasses.
PA_MC_MAX_CLASSES = 120


def baseline_pa_mc(feat_ids, feat_vals, labels, num_features, num_classes,
                   *, C=1.0, variant="PA-I", ps_mode=True):
    """MEASURED sequential per-example MULTICLASS passive-aggressive
    baseline (per-feature pull/push fan-out of ``num_classes``-float class
    rows; labels are class indices). One pass; returns
    ``(seconds, mean_hinge, mistake_frac)`` or ``None`` **only** for
    environment failures (library unavailable / allocation failure).

    Data bugs raise ``ValueError`` here on the Python side —
    ``num_classes`` outside ``[3, PA_MC_MAX_CLASSES]`` or labels outside
    ``[0, num_classes)`` must surface to the bench caller, not silently
    drop the baseline the way an environment failure does."""
    var = {"PA": 0, "PA-I": 1, "PA-II": 2}[variant]
    feat_ids = np.ascontiguousarray(feat_ids, np.int32)
    feat_vals = np.ascontiguousarray(feat_vals, np.float32)
    labels = np.ascontiguousarray(labels, np.int32)
    if not 3 <= int(num_classes) <= PA_MC_MAX_CLASSES:
        raise ValueError(
            f"num_classes={num_classes} outside the multiclass kernel's "
            f"[3, {PA_MC_MAX_CLASSES}] range (binary PA is baseline_pa)"
        )
    if labels.size and (labels.min() < 0 or labels.max() >= num_classes):
        raise ValueError(
            f"labels outside [0, {num_classes}): min={labels.min()}, "
            f"max={labels.max()} — a data bug, not a baseline failure"
        )
    lib = _load()
    if lib is None:
        return None
    n, nnz = feat_ids.shape
    hinge = ctypes.c_double(0.0)
    mist = ctypes.c_double(0.0)
    secs = lib.fps_baseline_pa_mc(
        _ptr(feat_ids, ctypes.c_int32), _ptr(feat_vals, ctypes.c_float),
        _ptr(labels, ctypes.c_int32), n, nnz, int(num_features),
        int(num_classes), float(C), var, 1 if ps_mode else 0,
        ctypes.byref(hinge), ctypes.byref(mist),
    )
    if secs < 0:
        return None
    return float(secs), float(hinge.value), float(mist.value)
