"""Version shims for the pinned container toolchain.

The framework targets the current jax API surface; the container pins an
older wheel where ``shard_map`` still lives in ``jax.experimental`` and
spells its replication check ``check_rep`` instead of ``check_vma``.
:func:`install` bridges exactly that gap — a no-op on wheels that already
expose ``jax.shard_map``.
"""

from __future__ import annotations

import jax


def _shard_map_compat(f, /, **kwargs):
    from jax.experimental.shard_map import shard_map as _sm

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _sm(f, **kwargs)


def _axis_size_compat(axis_name):
    # psum of 1 over the named axis: a traced constant XLA folds away —
    # equivalent to the modern static lax.axis_size for in-trace arithmetic.
    return jax.lax.psum(1, axis_name)


def install() -> None:
    """Expose ``jax.shard_map`` / ``jax.lax.axis_size`` on wheels that
    predate them (idempotent)."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size_compat
