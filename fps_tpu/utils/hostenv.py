"""Host-environment helpers for multi-device CPU meshes.

This container's sitecustomize (on PYTHONPATH) eagerly registers the
single-chip TPU backend at interpreter start, before any user code can
choose a platform. Running anything that needs an n-device mesh (tests,
the driver's multi-chip dryrun) therefore requires a fresh process with a
cleaned environment. This is the single home for that recipe — both
tests/conftest.py and __graft_entry__.py use it.
"""

from __future__ import annotations

import os
import re

# Marker set in child processes spawned with cpu_mesh_env(); holds the
# device count the child was spawned with, so callers can tell "already
# re-exec'd at this count — spawning again would loop" apart from "re-exec'd
# for a smaller mesh — spawning with a larger count is fine".
REEXEC_MARK = "_FPS_TPU_CPU_MESH_REEXEC"

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def cpu_mesh_env(n_devices: int, env: dict | None = None) -> dict:
    """Return a copy of ``env`` cleaned for an ``n_devices`` CPU mesh.

    Strips the sitecustomize dir from PYTHONPATH, forces JAX_PLATFORMS=cpu,
    drops the TPU pool variable, and force-sets (not merely appends) the
    host-platform device count — a pre-existing count of the wrong size must
    not win.
    """
    env = dict(os.environ if env is None else env)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = re.sub(rf"{_COUNT_FLAG}=\d+", "", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
    env[REEXEC_MARK] = str(n_devices)
    return env


def in_reexec() -> bool:
    return REEXEC_MARK in os.environ


def reexec_count() -> int:
    """Device count of the cleaned re-exec this process runs in (0 if none)."""
    try:
        return int(os.environ.get(REEXEC_MARK, "0"))
    except ValueError:
        return 0
