"""Dataset loaders & synthetic generators for the benchmark workloads.

BASELINE.json configs: MovieLens-100K / MovieLens-20M ratings (MF, iALS),
RCV1 (passive-aggressive), text8 (word2vec SGNS), Criteo CTR (logreg SSP).

This environment has zero network egress, so each loader first looks for a
real dataset file on disk and otherwise falls back to a *synthetic* generator
with matched shape/statistics (latent-structured ratings, Zipfian token
stream, sparse labeled examples). The synthetic sets have known structure so
convergence tests can assert learning actually happens.
"""

from __future__ import annotations

import os
import re

import numpy as np


# ---------------------------------------------------------------------------
# MovieLens-style ratings.
# ---------------------------------------------------------------------------

def synthetic_ratings(
    num_users: int,
    num_items: int,
    num_ratings: int,
    *,
    rank: int = 6,
    noise: float = 0.1,
    seed: int = 0,
    dtype=np.float32,
):
    """Ratings with planted low-rank structure: r = <p_u, q_i> + noise.

    Popularity is Zipfian over items (like MovieLens) so the scatter-add path
    sees realistic hot-id skew.
    """
    rng = np.random.default_rng(seed)
    p = rng.normal(0, 1.0 / np.sqrt(rank), (num_users, rank))
    q = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank))
    users = rng.integers(0, num_users, num_ratings)
    item_pop = 1.0 / np.arange(1, num_items + 1) ** 0.8
    item_pop /= item_pop.sum()
    items = rng.choice(num_items, num_ratings, p=item_pop)
    ratings = np.sum(p[users] * q[items], axis=-1) + rng.normal(
        0, noise, num_ratings
    )
    return {
        "user": users.astype(np.int32),
        "item": items.astype(np.int32),
        "rating": ratings.astype(dtype),
    }


def load_movielens(path: str | None = None, scale: str = "100k"):
    """Load MovieLens ``u.data``-format ratings if present, else synthesize.

    Returns (data dict, num_users, num_items). Synthetic sizes follow the
    named scale: 100k -> (943, 1682, 100_000) like ML-100K;
    20m -> (138_493, 26_744, 20_000_263) like ML-20M.
    """
    if path and os.path.exists(path):
        from fps_tpu import native

        parsed = native.parse_ratings(path)
        if parsed is not None:
            users, items, ratings = parsed
            users = users - 1
            items = items - 1
        else:  # no compiler on this host: numpy fallback
            raw = np.loadtxt(path, dtype=np.int64)
            users = raw[:, 0].astype(np.int32) - 1
            items = raw[:, 1].astype(np.int32) - 1
            ratings = raw[:, 2].astype(np.float32)
        data = {"user": users, "item": items, "rating": ratings}
        return data, int(users.max()) + 1, int(items.max()) + 1
    sizes = {
        "100k": (943, 1682, 100_000),
        "1m": (6040, 3706, 1_000_209),
        "20m": (138_493, 26_744, 20_000_263),
    }
    nu, ni, nr = sizes[scale]
    return synthetic_ratings(nu, ni, nr), nu, ni


def synthetic_implicit(
    num_users: int,
    num_items: int,
    interactions_per_user: int,
    *,
    rank: int = 4,
    seed: int = 0,
):
    """Implicit-feedback interactions with planted low-rank preference.

    Each user interacts with items sampled by softmax of a latent affinity,
    with a count-like positive "rating" (confidence signal, like play counts).
    Returns a dict with ``user``, ``item``, ``rating`` columns — the iALS
    (MovieLens-20M implicit) workload shape.
    """
    rng = np.random.default_rng(seed)
    p = rng.normal(0, 1.0, (num_users, rank))
    q = rng.normal(0, 1.0, (num_items, rank))
    users = np.repeat(np.arange(num_users), interactions_per_user)
    # Blocked over users: the dense (U, I) softmax would be O(U*I) memory
    # (4+ GB at ML-20M-class sizes); per-block cdf + vectorized inverse-cdf
    # sampling keeps it bounded and fast at any scale.
    block = max(1, min(num_users, (1 << 25) // max(num_items, 1)))
    item_blocks = []
    pf, qf = p.astype(np.float32), q.astype(np.float32)
    for lo in range(0, num_users, block):
        b = min(lo + block, num_users) - lo
        logits = pf[lo:lo + b] @ qf.T  # (b, I) — f32: sampling noise
        probs = np.exp(logits - logits.max(axis=1, keepdims=True))
        cdf = np.cumsum(probs, axis=1)  # dwarfs f32 cdf rounding
        cdf /= cdf[:, -1:]
        draws = rng.random((b, interactions_per_user))
        # Row-wise inverse cdf in ONE flat searchsorted: shift each row's
        # cdf (and its draws) by the row index so rows occupy disjoint
        # strictly-increasing value ranges, then map flat positions back.
        # The shift must happen in f64 — at row offsets in the tens of
        # thousands an f32 sum has ~2^-7 ulp, coarser than the cdf steps.
        offs = np.arange(b, dtype=np.float64)[:, None]
        flat = np.searchsorted((cdf.astype(np.float64) + offs).ravel(),
                               (draws + offs).ravel())
        rows = np.repeat(np.arange(b, dtype=np.int64), interactions_per_user)
        item_blocks.append(np.clip(flat - rows * num_items, 0,
                                   num_items - 1))
    items = np.concatenate(item_blocks)
    rating = rng.poisson(2.0, len(users)).astype(np.float32) + 1.0
    return {
        "user": users.astype(np.int32),
        "item": items.astype(np.int32),
        "rating": rating,
    }


def train_test_split(data: dict, test_frac: float = 0.1, seed: int = 1):
    n = len(next(iter(data.values())))
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    cut = int(n * (1 - test_frac))
    tr, te = order[:cut], order[cut:]
    return (
        {k: v[tr] for k, v in data.items()},
        {k: v[te] for k, v in data.items()},
    )


# ---------------------------------------------------------------------------
# Zipfian token stream (text8-style) for word2vec.
# ---------------------------------------------------------------------------

def synthetic_corpus(
    vocab_size: int,
    num_tokens: int,
    *,
    num_topics: int = 16,
    seed: int = 0,
):
    """Token stream with Zipfian unigram frequencies and topical locality
    (nearby tokens share a topic), so skip-gram has real signal to learn."""
    rng = np.random.default_rng(seed)
    # Zipf over the vocab.
    freq = 1.0 / np.arange(1, vocab_size + 1) ** 1.0
    freq /= freq.sum()
    # Each topic reweights a random slice of the vocab.
    topic_boost = rng.gamma(0.3, 1.0, (num_topics, vocab_size))
    topic_dist = freq * topic_boost
    topic_dist /= topic_dist.sum(axis=1, keepdims=True)
    # Markov chain over topics with sticky self-transitions.
    tokens = np.empty(num_tokens, dtype=np.int32)
    seg = 64
    topic = 0
    for start in range(0, num_tokens, seg):
        if rng.random() < 0.3:
            topic = rng.integers(num_topics)
        end = min(start + seg, num_tokens)
        tokens[start:end] = rng.choice(
            vocab_size, end - start, p=topic_dist[topic]
        )
    return tokens


def load_text8(path: str | None = None, vocab_size: int = 50_000,
               num_tokens: int | None = 2_000_000, seed: int = 0):
    """Load and tokenize text8 if present, else synthesize a Zipfian stream.

    ``num_tokens`` sizes the synthetic stream and truncates a real file's
    token stream (``None`` = use the whole file). Returns
    (tokens int32 array, vocab_size, unigram_counts).
    """
    if path and os.path.exists(path):
        with open(path) as f:
            words = f.read().split()
        if num_tokens is not None:
            words = words[:num_tokens]
        from collections import Counter

        counts = Counter(words)
        vocab = [w for w, _ in counts.most_common(vocab_size - 1)]
        w2i = {w: i + 1 for i, w in enumerate(vocab)}  # 0 = UNK
        tokens = np.fromiter((w2i.get(w, 0) for w in words), np.int32, len(words))
        uni = np.bincount(tokens, minlength=vocab_size).astype(np.float64)
        return tokens, vocab_size, uni
    tokens = synthetic_corpus(vocab_size, num_tokens or 2_000_000, seed=seed)
    uni = np.bincount(tokens, minlength=vocab_size).astype(np.float64)
    return tokens, vocab_size, uni


# ---------------------------------------------------------------------------
# Sparse labeled examples (RCV1 / Criteo style) for PA + logreg.
# ---------------------------------------------------------------------------

# Schema constants live in fps_tpu.native (importable without the compiled
# library) so the native and fallback loaders cannot desynchronize.
from fps_tpu.native import CRITEO_CAT_COLS, CRITEO_NNZ, CRITEO_NUM_COLS  # noqa: E402,F401

_MASK64 = (1 << 64) - 1


def _criteo_hash(col: int, token: bytes) -> int:
    """FNV-1a 64 + splitmix64 finalizer — bit-for-bit the native
    ``hash_bytes`` in ``fps_tpu/native/src/fps_native.cc``; the two must
    stay in sync or native and fallback loads diverge."""
    h = (1469598103934665603 ^ col) & _MASK64
    for b in token:
        h = ((h ^ b) * 1099511628211) & _MASK64
    z = (h + 0x9E3779B97F4A7C15) & _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


_SVM_NUM = re.compile(rb"^[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?$")
_SVM_IDX = re.compile(rb"^\d+$")


def _parse_svmlight_py(path: str, nnz_cap: int | None):
    """Pure-python svmlight parse (fallback). Same conventions as the
    native scanner: malformed data lines raise; rows longer than nnz_cap
    keep their first nnz_cap features (count returned as ``truncated``).
    Tokens are validated against the exact grammar the native scanner
    accepts (``_SVM_NUM``/``_SVM_IDX``) BEFORE float()/int() — Python's
    conversions are more permissive ("1_0", "inf", "+5" as an index) and
    the two loaders must classify every token identically."""
    rows = []
    malformed = 0
    with open(path, "rb") as f:
        for raw in f:
            line = raw.split(b"#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            try:
                if not _SVM_NUM.match(parts[0]):
                    raise ValueError
                label = float(parts[0])
                feats = []
                for tok in parts[1:]:
                    idx, val = tok.split(b":", 1)
                    if not _SVM_IDX.match(idx) or not _SVM_NUM.match(val):
                        raise ValueError
                    feats.append((int(idx), float(val)))
            except (ValueError, IndexError):
                malformed += 1
                continue
            rows.append((label, feats))
    if malformed:
        raise ValueError(
            f"{path}: {malformed} malformed svmlight line(s) — refusing "
            "to return a silently-truncated dataset"
        )
    n = len(rows)
    max_nnz = max((len(f) for _, f in rows), default=0)
    nnz = int(nnz_cap) if nnz_cap else max(max_nnz, 1)
    labels = np.zeros(n, np.float32)
    ids = np.zeros((n, nnz), np.int32)
    vals = np.zeros((n, nnz), np.float32)
    truncated = 0
    for r, (label, feats) in enumerate(rows):
        labels[r] = label
        truncated += max(0, len(feats) - nnz)
        for k, (idx, val) in enumerate(feats[:nnz]):
            ids[r, k] = idx
            vals[r, k] = val
    return labels, ids, vals, truncated


def load_svmlight(path: str, *, num_features: int | None = None,
                  nnz_cap: int | None = None, use_native: bool | None = None):
    """Load an svmlight/RCV1 file into the framework's sparse batch shape.

    Returns ``(data, num_features)`` where data has ``feat_ids (N, nnz)``,
    ``feat_vals (N, nnz)``, ``label (N,)`` in {-1, +1} (svmlight labels
    mapped by sign; 0 maps to -1). Pad slots are id 0 / value 0 — inactive
    under the models' ``x != 0`` convention. Ids are kept verbatim
    (1-based in RCV1), so ``num_features`` defaults to ``max_id + 1``.
    ``use_native=None`` prefers the C++ scanner when available.
    """
    from fps_tpu import native

    if use_native is None:
        use_native = native.available()
    elif use_native and not native.available():
        raise RuntimeError("use_native=True but fps_tpu.native is unavailable")
    parsed = native.parse_svmlight(path, nnz_cap) if use_native else None
    if parsed is None:
        parsed = _parse_svmlight_py(path, nnz_cap)
    labels, ids, vals, truncated = parsed
    if truncated:
        import warnings

        warnings.warn(
            f"{path}: nnz_cap={nnz_cap} dropped {truncated} feature "
            "value(s) from over-long rows",
            stacklevel=2,
        )
    max_id = int(ids.max()) if len(ids) else 0
    if num_features is not None and max_id >= num_features:
        raise ValueError(
            f"{path}: feature id {max_id} >= num_features={num_features} — "
            "oversized ids would silently index past the parameter table"
        )
    nf = num_features or max_id + 1
    data = {
        "feat_ids": ids,
        "feat_vals": vals,
        "label": np.where(labels > 0, 1.0, -1.0).astype(np.float32),
    }
    return data, nf


def _parse_criteo_py(path: str, num_features: int):
    """Pure-python Criteo TSV parse (fallback) — conventions identical to
    the native scanner, including the categorical hash and the FIXED-SLOT
    layout: numeric column j always sits at batch slot j (id j, value 0 =
    inactive when missing), categoricals append from slot 13. The fixed
    head is what lets ``LogRegConfig.dense_features`` pull/push the
    numeric weights densely instead of via per-example scatter rows."""
    cat_space = num_features - CRITEO_NUM_COLS
    labels, ids_rows, vals_rows = [], [], []
    malformed = 0
    with open(path, "rb") as f:
        for raw in f:
            line = raw.rstrip(b"\r\n")
            if not line:
                continue
            fields = line.split(b"\t")
            ok = len(fields) == 1 + CRITEO_NNZ and fields[0] in (b"0", b"1")
            row_ids = np.zeros(CRITEO_NNZ, np.int32)
            row_vals = np.zeros(CRITEO_NNZ, np.float32)
            row_ids[:CRITEO_NUM_COLS] = np.arange(CRITEO_NUM_COLS)
            nnz = CRITEO_NUM_COLS  # cat slots start after the fixed head
            if ok:
                for j, tok in enumerate(fields[1 : 1 + CRITEO_NUM_COLS]):
                    if not tok:
                        continue
                    # Same strict grammar as the native parse_signed —
                    # float() alone would admit "1_0"/"inf"/"nan".
                    if not _SVM_NUM.match(tok):
                        ok = False
                        break
                    v = float(tok)
                    if v >= 0:
                        row_vals[j] = np.log1p(v)
            if ok:
                for j, tok in enumerate(fields[1 + CRITEO_NUM_COLS:],
                                        start=CRITEO_NUM_COLS):
                    if not tok:
                        continue
                    h = _criteo_hash(j, tok)
                    row_ids[nnz] = CRITEO_NUM_COLS + (h % cat_space)
                    row_vals[nnz] = 1.0
                    nnz += 1
            if not ok:
                malformed += 1
                continue
            labels.append(float(fields[0]))
            ids_rows.append(row_ids)
            vals_rows.append(row_vals)
    if malformed:
        raise ValueError(
            f"{path}: {malformed} malformed Criteo line(s) — refusing to "
            "return a silently-truncated dataset"
        )
    n = len(labels)
    return (
        np.asarray(labels, np.float32),
        np.stack(ids_rows) if n else np.zeros((0, CRITEO_NNZ), np.int32),
        np.stack(vals_rows) if n else np.zeros((0, CRITEO_NNZ), np.float32),
    )


def load_criteo(path: str, *, num_features: int = 1 << 20,
                use_native: bool | None = None):
    """Load a Criteo click-log TSV (label + 13 numeric + 26 categorical).

    Returns ``(data, num_features)`` with ``feat_ids (N, 39)``,
    ``feat_vals (N, 39)``, ``label (N,)`` in {-1, +1} (clicks +1). Numeric
    column j: id j, value log1p(x), negatives/missing inactive; categorical
    column j: id ``13 + hash(j, token) % (num_features - 13)``, value 1.
    """
    from fps_tpu import native

    if num_features <= CRITEO_NUM_COLS:
        raise ValueError("num_features must exceed 13 (the numeric columns)")
    if use_native is None:
        use_native = native.available()
    elif use_native and not native.available():
        raise RuntimeError("use_native=True but fps_tpu.native is unavailable")
    parsed = (
        native.parse_criteo(path, num_features) if use_native else None
    )
    if parsed is None:
        parsed = _parse_criteo_py(path, num_features)
    labels, ids, vals = parsed
    data = {
        "feat_ids": ids,
        "feat_vals": vals,
        "label": np.where(labels > 0, 1.0, -1.0).astype(np.float32),
    }
    return data, num_features


def sniff_sparse_format(path: str) -> str:
    """Best-effort format detection: ``"svmlight"`` (idx:val tokens) or
    ``"criteo"`` (>= 39 tab-separated fields)."""
    with open(path, "rb") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith(b"#"):
                continue
            if line.count(b"\t") >= CRITEO_NNZ:
                return "criteo"
            if b":" in line:
                return "svmlight"
            break
    raise ValueError(f"{path}: cannot determine sparse dataset format")


def load_sparse(path: str, *, fmt: str = "auto",
                num_features: int | None = None,
                nnz_cap: int | None = None,
                use_native: bool | None = None):
    """Dispatch to :func:`load_svmlight` / :func:`load_criteo` by format.

    Returns ``(data, num_features)`` in the framework's sparse batch shape
    (labels in {-1, +1}; logreg callers map to {0, 1}).
    """
    if fmt == "auto":
        fmt = sniff_sparse_format(path)
    if fmt == "svmlight":
        return load_svmlight(path, num_features=num_features,
                             nnz_cap=nnz_cap, use_native=use_native)
    if fmt == "criteo":
        return load_criteo(path, num_features=num_features or (1 << 20),
                           use_native=use_native)
    raise ValueError(f"unknown sparse dataset format {fmt!r}")

def synthetic_sparse_classification(
    num_examples: int,
    num_features: int,
    nnz_per_example: int,
    *,
    seed: int = 0,
    noise: float = 0.1,
    dense_features: int = 0,
):
    """Linearly separable-ish sparse examples with Zipfian feature frequency.

    ``dense_features=d`` mirrors the Criteo TSV loader's FIXED-SLOT layout:
    batch slot ``j < d`` always carries feature id ``j`` (a dense numeric
    column, present in ~every example; occasionally value 0 = missing),
    and the remaining ``nnz - d`` slots draw Zipfian ids from ``[d, NF)``
    — the shape `LogRegConfig.dense_features` exploits. Default 0 keeps
    the fully-random layout.

    Returns dict with ``feat_ids (N, nnz)``, ``feat_vals (N, nnz)``,
    ``label (N,)`` in {-1, +1}.
    """
    if not 0 <= dense_features <= min(nnz_per_example, num_features):
        raise ValueError(f"dense_features={dense_features} out of range")
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0, 1, num_features)
    d = dense_features
    tail_nnz = nnz_per_example - d
    tail_nf = num_features - d
    feat_pop = 1.0 / np.arange(1, tail_nf + 1) ** 0.9
    feat_pop /= feat_pop.sum()
    tail_ids = d + rng.choice(tail_nf, (num_examples, tail_nnz), p=feat_pop)
    head_ids = np.broadcast_to(np.arange(d, dtype=np.int64),
                               (num_examples, d))
    ids = np.concatenate([head_ids, tail_ids], axis=1)
    vals = rng.normal(0, 1, (num_examples, nnz_per_example)).astype(np.float32)
    if d:
        # ~5% missing numerics (value 0 = inactive), like real Criteo rows.
        vals[:, :d] = np.where(rng.random((num_examples, d)) < 0.05, 0.0,
                               vals[:, :d])
    margin = np.sum(w_true[ids] * vals, axis=-1) / np.sqrt(nnz_per_example)
    flip = rng.random(num_examples) < noise
    label = np.where((margin > 0) ^ flip, 1.0, -1.0).astype(np.float32)
    return {
        "feat_ids": ids.astype(np.int32),
        "feat_vals": vals,
        "label": label,
    }


def head_sort_slots(data: dict, head_features: int):
    """Reorder each example's nnz slots so frequency-head ids come first.

    For frequency-ranked feature spaces (the shipped loaders and synthetic
    generators put the hottest ids lowest), stable-partitioning every
    example's slots into (ids < head_features) then (ids >= head_features)
    makes the first ``q = min_examples(head_count)`` slot COLUMNS carry
    head ids in EVERY example — a static guarantee the sparse workers turn
    into ``ops.gather_rows``/``scatter_add`` ``head_prefix`` routing
    (head-only kernels whose cost scales with the head's row tiles, not
    the table's). Measured at ~15% of the end-to-end PA headline
    (BASELINE.md round-5 section). Slot order within an example is
    semantically irrelevant (the models sum over slots), so this is a
    pure relayout.

    Returns ``(data2, q)`` — data with ``feat_ids``/``feat_vals`` columns
    reordered per example (other columns untouched), and the guaranteed
    head-prefix column count (0 if any example has no head feature).
    """
    ids = np.asarray(data["feat_ids"])
    vals = np.asarray(data["feat_vals"])
    is_tail = ids >= head_features
    order = np.argsort(is_tail, axis=1, kind="stable")
    out = dict(data)
    out["feat_ids"] = np.take_along_axis(ids, order, axis=1)
    out["feat_vals"] = np.take_along_axis(vals, order, axis=1)
    q = int((~is_tail).sum(axis=1).min())
    return out, q


def synthetic_sparse_multiclass(
    num_examples: int,
    num_features: int,
    num_classes: int,
    nnz_per_example: int,
    *,
    seed: int = 0,
    noise: float = 0.05,
):
    """Sparse multiclass examples: label = argmax_c <w_c, x> with label noise."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0, 1, (num_features, num_classes))
    feat_pop = 1.0 / np.arange(1, num_features + 1) ** 0.9
    feat_pop /= feat_pop.sum()
    ids = rng.choice(num_features, (num_examples, nnz_per_example), p=feat_pop)
    vals = rng.normal(0, 1, (num_examples, nnz_per_example)).astype(np.float32)
    scores = np.einsum("bn,bnc->bc", vals, w_true[ids])
    label = np.argmax(scores, axis=-1)
    flip = rng.random(num_examples) < noise
    label = np.where(flip, rng.integers(0, num_classes, num_examples), label)
    return {
        "feat_ids": ids.astype(np.int32),
        "feat_vals": vals,
        "label": label.astype(np.int32),
    }


def streaming_rating_batches(
    num_users: int,
    num_items: int,
    *,
    rank: int = 6,
    noise: float = 0.05,
    seed: int = 0,
    batch: int = 4096,
    max_records: int | None = None,
):
    """Unbounded-style generator of rating batches from one planted model.

    The streaming analog of :func:`synthetic_ratings` — same planted
    low-rank structure and Zipfian item popularity, yielded as an endless
    (or ``max_records``-bounded) sequence of columnar batches for
    :func:`fps_tpu.core.ingest.stream_chunks`.
    """
    rng = np.random.default_rng(seed)
    p = rng.normal(0, 1.0 / np.sqrt(rank), (num_users, rank))
    q = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank))
    item_pop = 1.0 / np.arange(1, num_items + 1) ** 0.8
    item_pop /= item_pop.sum()
    produced = 0
    while max_records is None or produced < max_records:
        n = batch if max_records is None else min(batch, max_records - produced)
        users = rng.integers(0, num_users, n)
        items = rng.choice(num_items, n, p=item_pop)
        ratings = (np.sum(p[users] * q[items], -1)
                   + rng.normal(0, noise, n)).astype(np.float32)
        produced += n
        yield {"user": users.astype(np.int32),
               "item": items.astype(np.int32),
               "rating": ratings}
