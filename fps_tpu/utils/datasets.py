"""Dataset loaders & synthetic generators for the benchmark workloads.

BASELINE.json configs: MovieLens-100K / MovieLens-20M ratings (MF, iALS),
RCV1 (passive-aggressive), text8 (word2vec SGNS), Criteo CTR (logreg SSP).

This environment has zero network egress, so each loader first looks for a
real dataset file on disk and otherwise falls back to a *synthetic* generator
with matched shape/statistics (latent-structured ratings, Zipfian token
stream, sparse labeled examples). The synthetic sets have known structure so
convergence tests can assert learning actually happens.
"""

from __future__ import annotations

import os

import numpy as np


# ---------------------------------------------------------------------------
# MovieLens-style ratings.
# ---------------------------------------------------------------------------

def synthetic_ratings(
    num_users: int,
    num_items: int,
    num_ratings: int,
    *,
    rank: int = 6,
    noise: float = 0.1,
    seed: int = 0,
    dtype=np.float32,
):
    """Ratings with planted low-rank structure: r = <p_u, q_i> + noise.

    Popularity is Zipfian over items (like MovieLens) so the scatter-add path
    sees realistic hot-id skew.
    """
    rng = np.random.default_rng(seed)
    p = rng.normal(0, 1.0 / np.sqrt(rank), (num_users, rank))
    q = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank))
    users = rng.integers(0, num_users, num_ratings)
    item_pop = 1.0 / np.arange(1, num_items + 1) ** 0.8
    item_pop /= item_pop.sum()
    items = rng.choice(num_items, num_ratings, p=item_pop)
    ratings = np.sum(p[users] * q[items], axis=-1) + rng.normal(
        0, noise, num_ratings
    )
    return {
        "user": users.astype(np.int32),
        "item": items.astype(np.int32),
        "rating": ratings.astype(dtype),
    }


def load_movielens(path: str | None = None, scale: str = "100k"):
    """Load MovieLens ``u.data``-format ratings if present, else synthesize.

    Returns (data dict, num_users, num_items). Synthetic sizes follow the
    named scale: 100k -> (943, 1682, 100_000) like ML-100K;
    20m -> (138_493, 26_744, 20_000_263) like ML-20M.
    """
    if path and os.path.exists(path):
        from fps_tpu import native

        parsed = native.parse_ratings(path)
        if parsed is not None:
            users, items, ratings = parsed
            users = users - 1
            items = items - 1
        else:  # no compiler on this host: numpy fallback
            raw = np.loadtxt(path, dtype=np.int64)
            users = raw[:, 0].astype(np.int32) - 1
            items = raw[:, 1].astype(np.int32) - 1
            ratings = raw[:, 2].astype(np.float32)
        data = {"user": users, "item": items, "rating": ratings}
        return data, int(users.max()) + 1, int(items.max()) + 1
    sizes = {
        "100k": (943, 1682, 100_000),
        "1m": (6040, 3706, 1_000_209),
        "20m": (138_493, 26_744, 20_000_263),
    }
    nu, ni, nr = sizes[scale]
    return synthetic_ratings(nu, ni, nr), nu, ni


def synthetic_implicit(
    num_users: int,
    num_items: int,
    interactions_per_user: int,
    *,
    rank: int = 4,
    seed: int = 0,
):
    """Implicit-feedback interactions with planted low-rank preference.

    Each user interacts with items sampled by softmax of a latent affinity,
    with a count-like positive "rating" (confidence signal, like play counts).
    Returns a dict with ``user``, ``item``, ``rating`` columns — the iALS
    (MovieLens-20M implicit) workload shape.
    """
    rng = np.random.default_rng(seed)
    p = rng.normal(0, 1.0, (num_users, rank))
    q = rng.normal(0, 1.0, (num_items, rank))
    logits = p @ q.T  # (U, I)
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    users = np.repeat(np.arange(num_users), interactions_per_user)
    items = np.concatenate(
        [
            rng.choice(num_items, interactions_per_user, p=probs[u])
            for u in range(num_users)
        ]
    )
    rating = rng.poisson(2.0, len(users)).astype(np.float32) + 1.0
    return {
        "user": users.astype(np.int32),
        "item": items.astype(np.int32),
        "rating": rating,
    }


def train_test_split(data: dict, test_frac: float = 0.1, seed: int = 1):
    n = len(next(iter(data.values())))
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    cut = int(n * (1 - test_frac))
    tr, te = order[:cut], order[cut:]
    return (
        {k: v[tr] for k, v in data.items()},
        {k: v[te] for k, v in data.items()},
    )


# ---------------------------------------------------------------------------
# Zipfian token stream (text8-style) for word2vec.
# ---------------------------------------------------------------------------

def synthetic_corpus(
    vocab_size: int,
    num_tokens: int,
    *,
    num_topics: int = 16,
    seed: int = 0,
):
    """Token stream with Zipfian unigram frequencies and topical locality
    (nearby tokens share a topic), so skip-gram has real signal to learn."""
    rng = np.random.default_rng(seed)
    # Zipf over the vocab.
    freq = 1.0 / np.arange(1, vocab_size + 1) ** 1.0
    freq /= freq.sum()
    # Each topic reweights a random slice of the vocab.
    topic_boost = rng.gamma(0.3, 1.0, (num_topics, vocab_size))
    topic_dist = freq * topic_boost
    topic_dist /= topic_dist.sum(axis=1, keepdims=True)
    # Markov chain over topics with sticky self-transitions.
    tokens = np.empty(num_tokens, dtype=np.int32)
    seg = 64
    topic = 0
    for start in range(0, num_tokens, seg):
        if rng.random() < 0.3:
            topic = rng.integers(num_topics)
        end = min(start + seg, num_tokens)
        tokens[start:end] = rng.choice(
            vocab_size, end - start, p=topic_dist[topic]
        )
    return tokens


def load_text8(path: str | None = None, vocab_size: int = 50_000,
               num_tokens: int | None = 2_000_000, seed: int = 0):
    """Load and tokenize text8 if present, else synthesize a Zipfian stream.

    ``num_tokens`` sizes the synthetic stream and truncates a real file's
    token stream (``None`` = use the whole file). Returns
    (tokens int32 array, vocab_size, unigram_counts).
    """
    if path and os.path.exists(path):
        with open(path) as f:
            words = f.read().split()
        if num_tokens is not None:
            words = words[:num_tokens]
        from collections import Counter

        counts = Counter(words)
        vocab = [w for w, _ in counts.most_common(vocab_size - 1)]
        w2i = {w: i + 1 for i, w in enumerate(vocab)}  # 0 = UNK
        tokens = np.fromiter((w2i.get(w, 0) for w in words), np.int32, len(words))
        uni = np.bincount(tokens, minlength=vocab_size).astype(np.float64)
        return tokens, vocab_size, uni
    tokens = synthetic_corpus(vocab_size, num_tokens or 2_000_000, seed=seed)
    uni = np.bincount(tokens, minlength=vocab_size).astype(np.float64)
    return tokens, vocab_size, uni


# ---------------------------------------------------------------------------
# Sparse labeled examples (RCV1 / Criteo style) for PA + logreg.
# ---------------------------------------------------------------------------

def synthetic_sparse_classification(
    num_examples: int,
    num_features: int,
    nnz_per_example: int,
    *,
    seed: int = 0,
    noise: float = 0.1,
):
    """Linearly separable-ish sparse examples with Zipfian feature frequency.

    Returns dict with ``feat_ids (N, nnz)``, ``feat_vals (N, nnz)``,
    ``label (N,)`` in {-1, +1}.
    """
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0, 1, num_features)
    feat_pop = 1.0 / np.arange(1, num_features + 1) ** 0.9
    feat_pop /= feat_pop.sum()
    ids = rng.choice(num_features, (num_examples, nnz_per_example), p=feat_pop)
    vals = rng.normal(0, 1, (num_examples, nnz_per_example)).astype(np.float32)
    margin = np.sum(w_true[ids] * vals, axis=-1) / np.sqrt(nnz_per_example)
    flip = rng.random(num_examples) < noise
    label = np.where((margin > 0) ^ flip, 1.0, -1.0).astype(np.float32)
    return {
        "feat_ids": ids.astype(np.int32),
        "feat_vals": vals,
        "label": label,
    }


def synthetic_sparse_multiclass(
    num_examples: int,
    num_features: int,
    num_classes: int,
    nnz_per_example: int,
    *,
    seed: int = 0,
    noise: float = 0.05,
):
    """Sparse multiclass examples: label = argmax_c <w_c, x> with label noise."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0, 1, (num_features, num_classes))
    feat_pop = 1.0 / np.arange(1, num_features + 1) ** 0.9
    feat_pop /= feat_pop.sum()
    ids = rng.choice(num_features, (num_examples, nnz_per_example), p=feat_pop)
    vals = rng.normal(0, 1, (num_examples, nnz_per_example)).astype(np.float32)
    scores = np.einsum("bn,bnc->bc", vals, w_true[ids])
    label = np.argmax(scores, axis=-1)
    flip = rng.random(num_examples) < noise
    label = np.where(flip, rng.integers(0, num_classes, num_examples), label)
    return {
        "feat_ids": ids.astype(np.int32),
        "feat_vals": vals,
        "label": label.astype(np.int32),
    }


def streaming_rating_batches(
    num_users: int,
    num_items: int,
    *,
    rank: int = 6,
    noise: float = 0.05,
    seed: int = 0,
    batch: int = 4096,
    max_records: int | None = None,
):
    """Unbounded-style generator of rating batches from one planted model.

    The streaming analog of :func:`synthetic_ratings` — same planted
    low-rank structure and Zipfian item popularity, yielded as an endless
    (or ``max_records``-bounded) sequence of columnar batches for
    :func:`fps_tpu.core.ingest.stream_chunks`.
    """
    rng = np.random.default_rng(seed)
    p = rng.normal(0, 1.0 / np.sqrt(rank), (num_users, rank))
    q = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank))
    item_pop = 1.0 / np.arange(1, num_items + 1) ** 0.8
    item_pop /= item_pop.sum()
    produced = 0
    while max_records is None or produced < max_records:
        n = batch if max_records is None else min(batch, max_records - produced)
        users = rng.integers(0, num_users, n)
        items = rng.choice(num_items, n, p=item_pop)
        ratings = (np.sum(p[users] * q[items], -1)
                   + rng.normal(0, noise, n)).astype(np.float32)
        produced += n
        yield {"user": users.astype(np.int32),
               "item": items.astype(np.int32),
               "rating": ratings}
