"""Profiling & throughput observability.

The reference has no tracing subsystem — only Flink's built-in operator
metrics (SURVEY.md §5 tracing row). On TPU we get device-level tracing from
``jax.profiler`` for free; this module packages it plus the host-side
throughput accounting the framework's chunked driver makes natural.

* :func:`trace` — context manager writing a Perfetto/XProf-compatible trace
  of everything (XLA ops, collectives, host callbacks) under a directory.
* :class:`Throughput` — per-chunk wall-clock + examples/sec accounting,
  designed to plug into ``Trainer.fit_stream(on_chunk=...)``::

      tp = Throughput(count_key="n")
      trainer.fit_stream(..., on_chunk=tp)
      print(tp.summary())
"""

from __future__ import annotations

import contextlib
import time

import numpy as np


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device+host profile under ``log_dir`` (view with XProf /
    Perfetto). Usable around any training region::

        with profiling.trace("/tmp/trace"):
            trainer.run_chunk(...)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Throughput:
    """Callable chunk hook accumulating wall-clock and example counts.

    ``count_key`` names the metrics leaf holding per-step example counts
    (every shipped model emits ``"n"``). The first chunk is recorded
    separately (``first_s``) since it includes compilation.
    """

    def __init__(self, count_key: str = "n"):
        self.count_key = count_key
        self.chunks = 0
        self.first_s: float | None = None
        self._first_examples = 0.0
        self.steady_s = 0.0
        self._steady_examples = 0.0
        self._last: float | None = None

    def start(self) -> None:
        """Mark the stream start. Called lazily on the first chunk, so setup
        time between constructing the hook and calling fit_stream is not
        counted; call explicitly right before a *second* fit_stream reusing
        this hook, or the inter-run gap lands in steady_s."""
        self._last = time.perf_counter()

    def __call__(self, step: int, metrics) -> None:
        now = time.perf_counter()
        if self._last is None:
            # First observation with no start(): we cannot know when this
            # chunk began, so count its examples but no wall time.
            self._last = now
        dt = now - self._last
        self._last = now
        count = (
            float(np.sum(metrics[self.count_key]))
            if self.count_key in metrics
            else 0.0
        )
        if self.first_s is None:
            self.first_s = dt
            self._first_examples = count
        else:
            self.steady_s += dt
            self._steady_examples += count
        self.chunks += 1

    @property
    def examples(self) -> float:
        return self._first_examples + self._steady_examples

    @property
    def examples_per_sec(self) -> float:
        """Steady-state throughput (excludes the compile-laden first chunk)."""
        return self._steady_examples / self.steady_s if self.steady_s else 0.0

    def summary(self) -> dict:
        return {
            "chunks": self.chunks,
            "examples": self.examples,
            "first_chunk_s": round(self.first_s or 0.0, 4),
            "steady_s": round(self.steady_s, 4),
            "examples_per_sec": round(self.examples_per_sec, 1),
        }
