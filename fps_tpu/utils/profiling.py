"""Compat shim — the profiling helpers grew into :mod:`fps_tpu.obs`.

``trace`` and ``Throughput`` now live in :mod:`fps_tpu.obs.timing`
alongside the phase timers, recorder, and run journal; import them from
``fps_tpu.obs`` going forward. This module re-exports them so existing
call sites (and muscle memory) keep working.
"""

from __future__ import annotations

from fps_tpu.obs.timing import Throughput, trace

__all__ = ["trace", "Throughput"]
