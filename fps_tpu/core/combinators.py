"""WorkerLogic combinators — the reference's worker-wrapper layer.

The reference's ``object WorkerLogic`` companion (SURVEY.md §2 #2; expected
upstream ``src/main/scala/hu/sztaki/ilab/ps/WorkerLogic.scala``) ships
wrappers that decorate a user's worker logic without changing it — most
notably ``addPullLimiter(logic, limit)``, which caps in-flight pulls to
bound staleness and memory.

SPMD mapping of the pull limiter: in a compiled loop there are no in-flight
messages to cap — every pull is answered within the step, and staleness is
governed by the schedule, not by queue depths. The limiter's *purpose*
(bounding how stale the values a worker computes with can get) is served by
``TrainerConfig.sync_every`` (the SSP bound); its *memory* purpose is served
by the static batch shape. What remains genuinely useful as worker wrappers
on TPU are delta- and output-transformations, provided here in the same
decorate-don't-touch style:

* :func:`clip_pushes` — per-row L2 clip of pushed deltas (the PS-world
  gradient-clipping knob; stabilizes Zipfian-hot rows under large batches).
* :func:`scale_pushes` — constant scaling of pushed deltas (e.g. 1/W
  worker-count normalization).
* :func:`tap_outputs` — augment the ``WOut`` metrics stream with extra
  per-step statistics (push-norm, pull-count) without touching the logic.
"""

from __future__ import annotations

from typing import Callable, Mapping

import jax
import jax.numpy as jnp

from fps_tpu.core.api import StepOutput, WorkerLogic

Array = jax.Array


class _Wrapped(WorkerLogic):
    """Delegates everything to the inner logic; subclasses override step()."""

    def __init__(self, inner: WorkerLogic):
        self.inner = inner

    def init_local_state(self, key, num_workers):
        return self.inner.init_local_state(key, num_workers)

    def prepare(self, batch, key):
        return self.inner.prepare(batch, key)

    def pull_ids(self, batch):
        return self.inner.pull_ids(batch)

    def step(self, batch, pulled, local_state, key) -> StepOutput:
        return self.inner.step(batch, pulled, local_state, key)


def _map_pushes(out: StepOutput, fn) -> StepOutput:
    pushes = {
        name: (ids, fn(name, ids, deltas))
        for name, (ids, deltas) in out.pushes.items()
    }
    return StepOutput(pushes=pushes, local_state=out.local_state, out=out.out)


def clip_pushes(logic: WorkerLogic, max_norm: float,
                tables: tuple[str, ...] | None = None) -> WorkerLogic:
    """Clip each pushed row to L2 norm ``max_norm`` (per delta row).

    ``tables`` limits clipping to the named tables (default: all).
    """

    class Clipped(_Wrapped):
        def step(self, batch, pulled, local_state, key):
            out = self.inner.step(batch, pulled, local_state, key)

            def clip(name, ids, deltas):
                if tables is not None and name not in tables:
                    return deltas
                norm = jnp.linalg.norm(
                    deltas.astype(jnp.float32), axis=-1, keepdims=True
                )
                scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
                return (deltas * scale.astype(deltas.dtype))

            return _map_pushes(out, clip)

    return Clipped(logic)


def scale_pushes(logic: WorkerLogic, scale: float,
                 tables: tuple[str, ...] | None = None) -> WorkerLogic:
    """Multiply pushed deltas by a constant (e.g. 1/num_workers)."""

    class Scaled(_Wrapped):
        def step(self, batch, pulled, local_state, key):
            out = self.inner.step(batch, pulled, local_state, key)

            def f(name, ids, deltas):
                if tables is not None and name not in tables:
                    return deltas
                return deltas * jnp.asarray(scale, deltas.dtype)

            return _map_pushes(out, f)

    return Scaled(logic)


def tap_outputs(
    logic: WorkerLogic,
    tap: Callable[[Mapping[str, tuple[Array, Array]]], Mapping[str, Array]]
    | None = None,
) -> WorkerLogic:
    """Augment the ``WOut`` stream with per-step push statistics.

    Default tap adds, per table, ``push_norm/<table>`` (L2 norm of all
    pushed deltas) and ``push_count/<table>`` (rows actually pushed, i.e.
    id >= 0) — the observability hook the reference gets by making metrics
    "just another stream" (SURVEY.md §5 metrics row).
    """

    def default_tap(pushes):
        extra = {}
        for name, (ids, deltas) in pushes.items():
            live = (ids >= 0).astype(jnp.float32)
            extra[f"push_norm/{name}"] = jnp.sqrt(
                jnp.sum((deltas.astype(jnp.float32) ** 2) * live[:, None])
            )
            extra[f"push_count/{name}"] = jnp.sum(live)
        return extra

    tap_fn = tap or default_tap

    class Tapped(_Wrapped):
        def step(self, batch, pulled, local_state, key):
            out = self.inner.step(batch, pulled, local_state, key)
            if not isinstance(out.out, Mapping):
                raise TypeError(
                    "tap_outputs requires the wrapped logic's StepOutput.out "
                    f"to be a Mapping (got {type(out.out).__name__}); wrap "
                    "your metrics in a dict or pass a custom tap"
                )
            merged = dict(out.out)
            merged.update(tap_fn(out.pushes))
            return StepOutput(
                pushes=out.pushes, local_state=out.local_state, out=merged
            )

    return Tapped(logic)
