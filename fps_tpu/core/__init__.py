from fps_tpu.core.api import ServerLogic, WorkerLogic, StepOutput
from fps_tpu.core.store import TableSpec, ParamStore, pull, push

__all__ = [
    "ServerLogic",
    "WorkerLogic",
    "StepOutput",
    "TableSpec",
    "ParamStore",
    "pull",
    "push",
]
