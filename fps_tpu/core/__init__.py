from fps_tpu.core.api import ServerLogic, WorkerLogic, StepOutput
from fps_tpu.core.checkpoint import (
    Checkpointer,
    export_model,
    load_model,
    load_rows,
    load_saved_model,
)
from fps_tpu.core.resilience import (
    GuardConfig,
    PoisonedStreamError,
    RollbackPolicy,
    SnapshotCorruptionError,
)
from fps_tpu.core.store import TableSpec, ParamStore, pull, push

__all__ = [
    "ServerLogic",
    "WorkerLogic",
    "StepOutput",
    "TableSpec",
    "ParamStore",
    "pull",
    "push",
    "Checkpointer",
    "export_model",
    "load_model",
    "load_rows",
    "load_saved_model",
    "GuardConfig",
    "PoisonedStreamError",
    "RollbackPolicy",
    "SnapshotCorruptionError",
]
