"""Sharded parameter store — the TPU-native replacement for the PS server side.

Reference semantics being rebuilt (from SURVEY.md; expected upstream paths
``src/main/scala/hu/sztaki/ilab/ps/server/SimplePSLogic.scala`` and
``.../ps/entities/``):

* the parameter space is a map ``id -> P`` hash-partitioned across
  ``psParallelism`` server instances (``hash(paramId) % psParallelism``),
* ``Pull(id)`` routes to the owning shard, which answers with the value
  (initializing it on first touch via a deterministic ``paramInit(id)``),
* ``Push(id, delta)`` routes to the owning shard, which folds the delta in
  via ``paramUpdate`` (``_ + _`` for every shipped algorithm).

TPU-native design
-----------------
A table is one jax array of shape ``(rows, dim)`` laid out **owner-major
cyclic**: parameter id ``i`` lives at physical row ``(i % S) * rps + i // S``
where ``S`` is the shard count and ``rps`` rows-per-shard. Under a
``NamedSharding(P('shard', None))`` this puts id ``i`` on device ``i % S`` —
exactly the reference's hash partitioning, and it balances Zipfian id
frequencies the way block partitioning would not.

Inside ``shard_map``:

* :func:`pull`  = ``all_gather(ids)`` → local gather of owned rows →
  ``psum_scatter`` so each worker receives exactly its requested values.
  This is the collective-gather collapse of the reference's
  pull/partitionCustom/answerPull round trip.
* :func:`push`  = ``all_gather(ids, deltas)`` (over the data axis too, so
  every replica applies every delta) → masked local ``scatter-add``.
  Duplicate ids within a batch accumulate, matching the reference's
  per-message ``paramUpdate`` fold.

Everything is static-shape and jit-compatible; XLA lowers the collectives
onto ICI when the mesh spans a pod slice.

Two-tier hot storage (``TableSpec.hot_tier``)
---------------------------------------------
Real id streams are Zipf-skewed (ML20M users, text8 vocab, Criteo
features), and NuPS (arxiv.org/pdf/2104.00501) shows the winning PS
design manages hot and cold keys differently: **replicate the hot head,
shard the tail**. A table with ``hot_tier = H > 0`` additionally keeps
its leading ``H`` global ids as an ``(H, dim)`` array **replicated**
across every device (stored beside the sharded table under
``hot_key(name)``), plus a per-device pending-delta buffer inside the
compiled loop:

* :func:`pull_hot` serves ``id < H`` reads from the local replica —
  **zero collectives**; cold ids ride the existing gathered/dense routes
  with the hot slots masked to ``-1`` (the documented zero-row /
  dropped-push contract).
* :func:`accumulate_hot` folds ``id < H`` pushes into the local delta
  buffer; :func:`reconcile_hot` ``psum``-reduces the buffers every
  ``TrainerConfig.hot_sync_every`` steps and applies the combined delta
  to the replica AND to the owner shard's head rows of the canonical
  sharded table — the paper's SSP bound applied to the parameter plane.

The sharded table stays the single source of truth: every compiled call
ends with a flush reconcile, so at chunk/epoch boundaries the replica is
a pure projection of the canonical table's head rows (checkpoints save
one canonical table; restore re-splits — ``Trainer._attach_hot``).
``hot_sync_every = 1`` is the exact mode: the driver lowers the
IDENTICAL untiered program (a per-step psum reconcile could not be
bit-identical to the gathered scatter's summation order — same
reasoning as the dense push path's fixed-order NOTE below — so the
exact mode is implemented as the untiered path itself, making its
zero-cost claim provable by lowered-HLO comparison).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fps_tpu import ops
from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS, replicate_to_mesh

Array = jax.Array


# ---------------------------------------------------------------------------
# Physical layout helpers (owner-major cyclic).
# ---------------------------------------------------------------------------

def rows_per_shard(num_ids: int, num_shards: int) -> int:
    return -(-num_ids // num_shards)  # ceil


def padded_rows(num_ids: int, num_shards: int) -> int:
    return rows_per_shard(num_ids, num_shards) * num_shards


def id_to_phys(ids: Array, num_shards: int, rps: int) -> Array:
    """Global physical row index of each parameter id."""
    return (ids % num_shards) * rps + ids // num_shards


def phys_to_id(phys: Array, num_shards: int, rps: int) -> Array:
    """Inverse of :func:`id_to_phys` (may exceed num_ids for padding rows)."""
    return (phys % rps) * num_shards + phys // rps


# ---------------------------------------------------------------------------
# Two-tier hot storage (replicated head + sharded tail; see module docstring).
# ---------------------------------------------------------------------------

# Replica entries ride the same tables dict as the sharded tables they
# mirror, under a reserved key — checkpoint/export iterate ``store.specs``
# and therefore never serialize them (the sharded table is canonical).
HOT_KEY_SUFFIX = "::hot"
# Adaptive (mapped) tier aux entries (fps_tpu.tiering): the replica's
# membership is an arbitrary hot id SET carried as replicated DATA
# arrays — a slot map (global id -> replica slot, -1 = cold) and its
# inverse (replica slot -> global id) — so a re-rank swaps arrays
# without changing the traced program. ``::sketch`` is the device-side
# frequency window (count-min) the online tracker accumulates inside
# the compiled step. All ride the tables dict under reserved suffixes;
# like ``::hot`` they are never serialized (specs stay canonical).
MAP_KEY_SUFFIX = "::hotmap"
IDS_KEY_SUFFIX = "::hotids"
SKETCH_KEY_SUFFIX = "::sketch"
# Stateful hot-fold optimizer state (``ServerLogic.hot_fold``): per-row
# Adagrad/Adam state for the hot head, SHARDED over the shard axis in
# reduce-scatter slice order (never replicated). Persisted in snapshots
# as separate ``fold::`` arrays — never part of the canonical table
# bytes (``checkpoint._table_arrays`` iterates specs only).
FOLD_KEY_SUFFIX = "::fold"
AUX_KEY_SUFFIXES = (HOT_KEY_SUFFIX, MAP_KEY_SUFFIX, IDS_KEY_SUFFIX,
                    SKETCH_KEY_SUFFIX, FOLD_KEY_SUFFIX)


def hot_key(name: str) -> str:
    """Tables-dict key of ``name``'s replicated hot-head array."""
    return name + HOT_KEY_SUFFIX


def map_key(name: str) -> str:
    """Tables-dict key of ``name``'s replicated id->slot map."""
    return name + MAP_KEY_SUFFIX


def ids_key(name: str) -> str:
    """Tables-dict key of ``name``'s replicated slot->global-id array."""
    return name + IDS_KEY_SUFFIX


def sketch_key(name: str) -> str:
    """Tables-dict key of ``name``'s device-side frequency sketch."""
    return name + SKETCH_KEY_SUFFIX


def fold_key(name: str) -> str:
    """Tables-dict key of ``name``'s sharded hot-fold optimizer state."""
    return name + FOLD_KEY_SUFFIX


def is_hot_key(key: str) -> bool:
    return key.endswith(HOT_KEY_SUFFIX)


def is_aux_key(key: str) -> bool:
    """True for ANY reserved tiering entry (replica, maps, sketch)."""
    return any(key.endswith(s) for s in AUX_KEY_SUFFIXES)


def hot_base(key: str) -> str:
    """Inverse of :func:`hot_key`."""
    return key[: -len(HOT_KEY_SUFFIX)]


def split_tiering(
    tables: Mapping[str, Any]
) -> tuple[dict, dict, dict, dict, dict, dict]:
    """Split a tables dict into ``(canonical, hot, maps, gids, sketches,
    folds)`` — each aux dict keyed by base table name. (The old two-way
    ``split_hot`` was retired when this superseded it: a narrower split
    would misclassify the adaptive tier's aux entries as canonical
    tables.)"""
    canonical, hot, maps, gids, sketches, folds = {}, {}, {}, {}, {}, {}
    for k, v in tables.items():
        if k.endswith(HOT_KEY_SUFFIX):
            hot[k[: -len(HOT_KEY_SUFFIX)]] = v
        elif k.endswith(MAP_KEY_SUFFIX):
            maps[k[: -len(MAP_KEY_SUFFIX)]] = v
        elif k.endswith(IDS_KEY_SUFFIX):
            gids[k[: -len(IDS_KEY_SUFFIX)]] = v
        elif k.endswith(SKETCH_KEY_SUFFIX):
            sketches[k[: -len(SKETCH_KEY_SUFFIX)]] = v
        elif k.endswith(FOLD_KEY_SUFFIX):
            folds[k[: -len(FOLD_KEY_SUFFIX)]] = v
        else:
            canonical[k] = v
    return canonical, hot, maps, gids, sketches, folds


def hot_slot_map(num_ids: int, hot_gids: np.ndarray) -> np.ndarray:
    """``(num_ids + 1,)`` int32 id->slot map for an arbitrary hot id set.

    Entry ``i`` is the replica slot of global id ``i`` (``-1`` = cold);
    the trailing sentinel row stays ``-1`` so device code can index with
    ``where(ids >= 0, ids, num_ids)`` and padding ids resolve to cold
    without a second mask."""
    gids = np.asarray(hot_gids, np.int64)
    if gids.size and (gids.min() < 0 or gids.max() >= num_ids):
        raise ValueError(
            f"hot ids outside [0, {num_ids}): "
            f"[{gids.min()}, {gids.max()}]")
    if len(np.unique(gids)) != len(gids):
        raise ValueError("hot id set contains duplicates")
    m = np.full(num_ids + 1, -1, np.int32)
    m[gids] = np.arange(len(gids), dtype=np.int32)
    return m


def lookup_hot_slots(slot_map: Array, ids: Array) -> Array:
    """Device-side ``(B,)`` replica slots for ``ids`` (-1 = cold or
    padding). ``slot_map`` is :func:`hot_slot_map`'s array."""
    sentinel = slot_map.shape[0] - 1
    return jnp.take(slot_map, jnp.where(ids >= 0, ids, sentinel), axis=0)


def device_slot_map(num_ids: int, hot_gids: Array) -> Array:
    """Traced analog of :func:`hot_slot_map` for the in-graph tier tick:
    rebuild the ``(num_ids + 1,)`` id->slot map from a replicated hot
    gid array (all entries in ``[0, num_ids)`` — the tick selects from
    ``arange``, so no validation is traced). Deterministic function of
    the gid ORDER, so rebuilding for an unchanged set reproduces the
    incoming map bit-for-bit."""
    m = jnp.full((num_ids + 1,), -1, jnp.int32)
    return m.at[hot_gids].set(
        jnp.arange(hot_gids.shape[0], dtype=jnp.int32))


def replica_from_shard(local_shard: Array, hot_gids: Array, *,
                       num_shards: int,
                       shard_axis: str = SHARD_AXIS) -> Array:
    """In-graph re-split: gather arbitrary global ids' canonical rows
    into a replicated ``(H, dim)`` replica from inside ``shard_map`` —
    the traced analog of :meth:`ParamStore.rows_replica` for the
    megastep's tier tick. Each shard contributes the rows it owns under
    the owner-major cyclic layout (zero rows elsewhere); one psum makes
    the result replicated. Bit-exact: every replica row is one owned
    row plus zeros, and the boundary invariant (replica row ==
    canonical row after a reconcile) makes the re-derivation of an
    UNCHANGED hot set the identity."""
    me = lax.axis_index(shard_axis)
    owned = (hot_gids % num_shards) == me
    lidx = jnp.where(owned, hot_gids // num_shards,
                     jnp.asarray(-1, hot_gids.dtype))
    vals = ops.gather_rows(local_shard, lidx)  # -1 slots read zero rows
    return lax.psum(vals, shard_axis)


def split_hot_push_slots(
    ids: Array, deltas: Array, slots: Array
) -> tuple[tuple[Array, Array], tuple[Array, Array]]:
    """Mapped-tier analog of :func:`split_hot_push`: partition one push
    stream on ``slots >= 0`` (slot-map membership instead of ``id < H``).

    Returns ``((cold_ids, cold_deltas), (hot_slots, hot_deltas))`` with
    the other tier's entries masked to ``-1``/zero — the hot half is
    already in SLOT space, ready for :func:`accumulate_hot`."""
    hot = slots >= 0
    cold = (
        jnp.where(hot, jnp.asarray(-1, ids.dtype), ids),
        jnp.where(hot[:, None], 0, deltas).astype(deltas.dtype),
    )
    hots = (
        jnp.where(hot, slots, jnp.asarray(-1, slots.dtype)),
        jnp.where(hot[:, None], deltas, 0).astype(deltas.dtype),
    )
    return cold, hots


def reconcile_hot_mapped(
    cold_shard: Array,
    replica: Array,
    delta_buf: Array,
    hot_gids: Array,
    *,
    num_shards: int,
    shard_axis: str = SHARD_AXIS,
    data_axis: str | None = None,
    combine: str = "sum",
    fold=None,
    fold_state: Array | None = None,
) -> tuple[Array, Array, Array, Array | None]:
    """Window-end reconcile for an arbitrary hot id set (mapped tier).

    Identical contract to :func:`reconcile_hot` except the replica's slot
    ``j`` holds global id ``hot_gids[j]`` instead of id ``j``: the
    combined window delta is applied to the replica (bitwise-identical on
    every device — it comes out of the reconcile's all-gather) AND
    scattered into this shard's OWNED rows of the canonical table — under
    the owner-major cyclic layout id ``g`` lives on shard ``g % S`` at
    local row ``g // S``. ``hot_gids`` is replicated DATA, so a re-rank
    changes which rows reconcile without recompiling.

    Returns ``(new_cold_shard, new_replica, reset_delta_buf,
    new_fold_state)``.
    """
    combined, new_replica, new_state = _reconcile_combine(
        replica, delta_buf, num_shards=num_shards, shard_axis=shard_axis,
        data_axis=data_axis, combine=combine, fold=fold,
        fold_state=fold_state)
    me = lax.axis_index(shard_axis)
    owned = (hot_gids >= 0) & ((hot_gids % num_shards) == me)
    lidx = jnp.where(owned, hot_gids // num_shards,
                     jnp.asarray(-1, hot_gids.dtype))
    new_cold = ops.scatter_add(cold_shard, lidx,
                               combined.astype(cold_shard.dtype))
    return new_cold, new_replica, _reset_delta(delta_buf, combine), new_state


def pull_hot(replica: Array, ids: Array, *, hot_ids: int) -> tuple[Array, Array]:
    """Serve ``id < hot_ids`` reads from the local replica — no collectives.

    Returns ``(values, hot_mask)``: ``values`` holds the replica rows for
    hot ids and ZERO rows elsewhere (ids outside the head are gathered as
    ``-1``, the zero-row contract), so the caller can ``where`` it against
    the cold route's rows (which are zero exactly on the hot slots).
    """
    hot = (ids >= 0) & (ids < hot_ids)
    return ops.gather_rows(replica, jnp.where(hot, ids, -1)), hot


def split_hot_push(
    ids: Array, deltas: Array, *, hot_ids: int
) -> tuple[tuple[Array, Array], tuple[Array, Array]]:
    """Partition one push stream on ``id < hot_ids``.

    Returns ``((cold_ids, cold_deltas), (hot_ids_arr, hot_deltas))`` with
    the other tier's slots masked to ``-1``/zero — both the collective
    push and :func:`fps_tpu.ops.scatter_add` drop ``-1`` rows, and the
    deltas are zeroed too so the lane-packed routes never multiply a live
    indicator into a masked row's payload (same hazard the guard's mask
    path documents).
    """
    hot = (ids >= 0) & (ids < hot_ids)
    cold = (
        jnp.where(hot, jnp.asarray(-1, ids.dtype), ids),
        jnp.where(hot[:, None], 0, deltas).astype(deltas.dtype),
    )
    hots = (
        jnp.where(hot, ids, jnp.asarray(-1, ids.dtype)),
        jnp.where(hot[:, None], deltas, 0).astype(deltas.dtype),
    )
    return cold, hots


def delta_counted(combine: str, fold) -> bool:
    """Whether a table's pending-delta buffer carries the appended
    push-count column: the ``"mean"`` combine needs it to normalize, and
    every stateful fold needs it to apply lazily (touched rows only)."""
    return combine == "mean" or fold is not None


def compact_cold(
    ids: Array, deltas: Array | None, *, budget: int
) -> tuple[Array, Array | None, Array, Array]:
    """Pack a masked cold-id stream into a fixed ``budget``-wide lane.

    ``ids`` is a ``(B,)`` stream whose hot/padding slots are already
    masked to ``-1`` (the :func:`split_hot_push` / :func:`pull_hot`
    convention); the live entries are packed ORDER-PRESERVING (stable
    cumsum positions) into a ``(budget,)`` lane with ``-1`` padding, so
    the collective routes carry ``O(cold traffic)`` payload instead of
    ``O(batch)``. Live entries beyond the budget are DROPPED (their lane
    position is out of range, their pulls read zero rows) — callers must
    only dispatch the compacted program for batches the host certifier
    proved fit the budget (``Trainer._certify_cold``; the overflow count
    is returned for the device-side observability net).

    Returns ``(lane_ids, lane_deltas, pos, overflowed)``: ``pos`` maps
    each original slot to its lane position (``-1`` = masked or dropped)
    for scattering pulled lane rows back to batch positions;
    ``overflowed`` is the scalar count of dropped live entries.
    """
    live = ids >= 0
    pos = jnp.cumsum(live.astype(jnp.int32)) - 1
    pos = jnp.where(live & (pos < budget), pos, -1)
    # Negative .at[] indices WRAP (numpy semantics) — map masked slots to
    # ``budget`` so mode="drop" actually drops them (the
    # ops._xla_scatter_add pattern).
    safe = jnp.where(pos >= 0, pos, budget)
    lane_ids = jnp.full((budget,), -1, ids.dtype).at[safe].set(
        ids, mode="drop")
    lane_deltas = None
    if deltas is not None:
        lane_deltas = jnp.zeros(
            (budget,) + deltas.shape[1:], deltas.dtype
        ).at[safe].set(deltas, mode="drop")
    overflowed = jnp.maximum(
        jnp.sum(live.astype(jnp.int32)) - budget, 0)
    return lane_ids, lane_deltas, pos, overflowed


def hot_delta_init(hot_rows: int, dim: int, dtype, *, combine: str = "sum",
                   fold=None) -> Array:
    """Fresh per-device pending-delta buffer for one tiered table.

    Accumulates in at least f32 (never below the table's own precision —
    same promotion rule as the non-"sum" combine folds in :func:`push`).
    The ``mean`` combine (and any stateful fold) carries a push-count
    column appended to the payload so the reconcile can normalize / fold
    lazily per touched row per window. The ``max``/``min`` combines keep
    an elementwise-extremum buffer instead: filled with the extremal
    sentinel, plus a touched-indicator column (the same one-scatter trick
    as :func:`push`'s extremum path).
    """
    acc_dt = jnp.promote_types(dtype, jnp.float32)
    if combine in ("max", "min"):
        lim = jnp.finfo(acc_dt).max
        fill = -lim if combine == "max" else lim
        return jnp.full((hot_rows, dim + 1), fill, acc_dt)
    cols = dim + (1 if delta_counted(combine, fold) else 0)
    return jnp.zeros((hot_rows, cols), acc_dt)


def _reset_delta(delta_buf: Array, combine: str) -> Array:
    """Window-end buffer reset: zeros for the additive combines, the
    extremal sentinel fill for ``max``/``min``."""
    if combine in ("max", "min"):
        lim = jnp.finfo(delta_buf.dtype).max
        fill = -lim if combine == "max" else lim
        return jnp.full_like(delta_buf, fill)
    return jnp.zeros_like(delta_buf)


def accumulate_hot(
    delta_buf: Array, hot_ids_arr: Array, hot_deltas: Array, *,
    combine: str = "sum", fold=None
) -> Array:
    """Fold one step's hot-tier pushes into the local pending buffer.

    ``hot_ids_arr``/``hot_deltas`` come from :func:`split_hot_push` (cold
    slots already ``-1``/zero, dropped by the scatter). Purely local —
    the collective happens once per window, in :func:`reconcile_hot`.
    ``max``/``min`` combine via a native scatter-max/min with the
    touched indicator riding as an appended ones column.
    """
    vals = hot_deltas.astype(delta_buf.dtype)
    if combine in ("max", "min"):
        ones = jnp.ones(hot_ids_arr.shape, delta_buf.dtype)[:, None]
        filled = jnp.concatenate([vals, ones], axis=1)
        # Negative .at[] indices wrap — map the masked -1 slots out of
        # range so mode="drop" drops them (ops._xla_scatter_add pattern).
        safe = jnp.where(hot_ids_arr >= 0, hot_ids_arr,
                         delta_buf.shape[0])
        if combine == "max":
            return delta_buf.at[safe].max(filled, mode="drop")
        return delta_buf.at[safe].min(filled, mode="drop")
    if delta_counted(combine, fold):
        # One scatter carries values AND counts (appended ones column) —
        # the same one-scatter trick as push()'s non-"sum" folds.
        cnt = (hot_ids_arr >= 0).astype(delta_buf.dtype)[:, None]
        vals = jnp.concatenate([vals, cnt], axis=1)
    return ops.scatter_add(delta_buf, hot_ids_arr, vals)


def hot_fold_state_shape(fold, H: int, dim: int,
                         num_shards: int) -> tuple[int, int]:
    """GLOBAL shape of one table's hot-fold state: ``ceil(H/S)`` rows per
    shard in reduce-scatter slice order (slice ``s`` holds head rows
    ``[s*Hs, (s+1)*Hs)``), padded to a multiple of ``S``; columns per
    :meth:`fps_tpu.core.api.HotFold.state_cols`."""
    Hs = rows_per_shard(H, num_shards)
    return (Hs * num_shards, fold.state_cols(dim))


def apply_hot_fold(fold, state: Array, g: Array,
                   counts: Array) -> tuple[Array, Array]:
    """Apply a stateful fold to the owned reconcile slice.

    ``g`` is the window's combined delta for this device's contiguous
    head slice (post ``combine`` normalization), ``counts`` the per-row
    push counts, ``state`` the device's slice of the sharded optimizer
    state. LAZY semantics: rows with no pushes this window keep their
    state (and receive a zero step) — the sparse-table convention, so a
    zero-traffic row can never drift. Returns ``(step, new_state)``.
    """
    dt = g.dtype
    touched = counts > 0
    t1 = touched[:, None]
    if fold.kind == "adagrad":
        G = state + jnp.where(t1, g * g, 0.0).astype(state.dtype)
        step = jnp.where(
            t1, fold.lr * g / (jnp.sqrt(G).astype(dt) + fold.eps), 0.0)
        return step, G
    dim = g.shape[1]
    m, v = state[:, :dim], state[:, dim:2 * dim]
    t = state[:, 2 * dim]
    t_new = t + touched.astype(state.dtype)
    m_new = jnp.where(t1, fold.beta1 * m + (1.0 - fold.beta1) * g, m)
    v_new = jnp.where(t1, fold.beta2 * v + (1.0 - fold.beta2) * g * g, v)
    tc = jnp.maximum(t_new, 1.0)
    mhat = m_new / (1.0 - fold.beta1 ** tc)[:, None]
    vhat = v_new / (1.0 - fold.beta2 ** tc)[:, None]
    step = jnp.where(
        t1, fold.lr * mhat.astype(dt) / (jnp.sqrt(vhat).astype(dt)
                                         + fold.eps), 0.0)
    new_state = jnp.concatenate([m_new, v_new, t_new[:, None]], axis=1)
    return step, new_state


def _reconcile_combine(
    replica: Array,
    delta_buf: Array,
    *,
    num_shards: int,
    shard_axis: str,
    data_axis: str | None,
    combine: str,
    fold=None,
    fold_state: Array | None = None,
) -> tuple[Array, Array, Array | None]:
    """Shared half of the window-end reconcile, SHARDED over the replica
    axis (arXiv:2004.13336's cross-replica weight-update sharding applied
    to the hot tier): instead of one psum that hands every device the
    full ``(H, dim')`` window delta, the pending buffers are

    1. **reduce-scattered** over the shard axis — device ``s`` receives
       the summed slice for head rows ``[s*Hs, (s+1)*Hs)`` only;
    2. psum'd over the (replicated) data axis — now ``1/S`` the payload
       the old full-head data psum moved;
    3. normalized ("mean" count column) and, for a stateful
       :class:`~fps_tpu.core.api.HotFold`, folded against the device's
       DISJOINT slice of the sharded optimizer state — the property the
       sharding buys: per-row Adagrad/Adam state exists exactly once
       across the mesh, and each device does ``1/S`` of the fold work;
    4. **all-gathered** back so every replica applies the identical
       combined step.

    The ``max``/``min`` combines keep a full-head pmax/pmin instead
    (extremum does not reduce-scatter); they carry no fold state.

    Returns ``(combined_step, new_replica, new_fold_state_slice)`` — the
    static and mapped reconciles differ only in how the combined step
    addresses the canonical shard, so the summation / normalization /
    fold semantics live in exactly one place and cannot drift between
    them."""
    H, dim = replica.shape
    if combine in ("max", "min"):
        red = lax.pmax if combine == "max" else lax.pmin
        g = red(delta_buf, shard_axis)
        if data_axis is not None:
            g = red(g, data_axis)
        # Touched rows carry indicator 1.0; untouched still the sentinel.
        touched = jnp.abs(g[:, dim]) <= 1.0
        combined = jnp.where(touched[:, None], g[:, :dim],
                             0.0).astype(replica.dtype)
        return combined, replica + combined, fold_state
    dimp = delta_buf.shape[1]
    Hs = rows_per_shard(H, num_shards)
    pad = Hs * num_shards - H
    buf = delta_buf
    if pad:
        buf = jnp.concatenate(
            [buf, jnp.zeros((pad, dimp), buf.dtype)], axis=0)
    sl = lax.psum_scatter(buf, shard_axis, scatter_dimension=0, tiled=True)
    if data_axis is not None:
        sl = lax.psum(sl, data_axis)
    counts = sl[:, dim] if dimp > dim else None
    g = sl[:, :dim]
    if combine == "mean":
        g = g * (1.0 / jnp.maximum(counts, 1.0))[:, None]
    new_state = fold_state
    if fold is not None:
        g, new_state = apply_hot_fold(fold, fold_state, g, counts)
    g = g.astype(replica.dtype)
    full = lax.all_gather(g, shard_axis, tiled=True)
    combined = full[:H] if pad else full
    return combined, replica + combined, new_state


def reconcile_hot(
    cold_shard: Array,
    replica: Array,
    delta_buf: Array,
    *,
    num_shards: int,
    shard_axis: str = SHARD_AXIS,
    data_axis: str | None = None,
    combine: str = "sum",
    fold=None,
    fold_state: Array | None = None,
) -> tuple[Array, Array, Array, Array | None]:
    """Window-end reconcile: reduce-scatter the pending buffers, apply
    the owned 1/S slice, all-gather the combined step everywhere.

    One reduce-scatter + all-gather pair over the shard axis (see
    :func:`_reconcile_combine` — the cross-replica sharded form of the
    old full-head psum) replaces ``hot_sync_every`` steps' worth of
    per-step push collectives for the head rows. The combined step is
    applied to the replica (identically on every device — all-gather
    results are bitwise-identical across participants, so the replica
    stays replicated by construction) AND to this shard's OWNED head
    rows of the canonical table: under the owner-major cyclic layout,
    global id ``h`` lives on shard ``h % S`` at local row ``h // S``, so
    the shard's head ids occupy exactly local rows ``[0, ceil(H/S))``.

    ``combine="mean"``: the buffer's appended count column turns the
    window's sum into one count-normalized step per touched row (the
    windowed analog of the "mean" combine's one-averaged-step-per-push;
    untouched rows have count 0 and receive exactly zero).
    ``combine="max"/"min"``: one pmax/pmin of the extremum buffer — the
    windowed analog of the extremum combine (one extremal step per
    touched row per window). ``fold``: stateful Adagrad/Adam on the
    owned slice (:func:`apply_hot_fold`), state sharded over the shard
    axis in slice order.

    Returns ``(new_cold_shard, new_replica, reset_delta_buf,
    new_fold_state)``.
    """
    H, _ = replica.shape
    combined, new_replica, new_state = _reconcile_combine(
        replica, delta_buf, num_shards=num_shards, shard_axis=shard_axis,
        data_axis=data_axis, combine=combine, fold=fold,
        fold_state=fold_state)
    hl = -(-H // num_shards)  # local head rows on every shard
    me = lax.axis_index(shard_axis)
    # Global id of local head row j is j*S + me; rows past H (when S does
    # not divide H) gather id -1 -> a zero row, i.e. no update.
    gids = jnp.arange(hl, dtype=jnp.int32) * num_shards + me
    mine = ops.gather_rows(combined, jnp.where(gids < H, gids, -1))
    new_cold = cold_shard.at[:hl].add(mine.astype(cold_shard.dtype))
    return new_cold, new_replica, _reset_delta(delta_buf, combine), new_state


# ---------------------------------------------------------------------------
# Collective pull / push (call inside shard_map).
# ---------------------------------------------------------------------------

def pull(
    local_shard: Array,
    ids: Array,
    *,
    num_shards: int,
    shard_axis: str = SHARD_AXIS,
    dense: bool = False,
    hot_rows: int = 0,
    head_prefix: int = 0,
    exact: bool = False,
) -> Array:
    """Gather parameter rows for ``ids`` from the sharded table.

    Args:
      local_shard: this device's ``(rps, dim)`` block of the table.
      ids: ``(B,)`` int32 parameter ids requested by this worker.
      num_shards: size of the shard axis (static).
      dense: replicate-on-read route for SMALL tables: all_gather the
        whole table (one table-sized collective riding ICI) and gather
        locally — ``O(B)`` row transactions per worker instead of the
        gathered route's ``O(W * B)`` per shard (every shard processes
        every worker's ids). Policy: ``TableSpec.dense_collectives``,
        resolved against :data:`fps_tpu.ops.DENSE_TABLE_BYTES`.
      exact: bit-exact reads — forward to :func:`fps_tpu.ops.gather_rows`
        so read-only pulls (eval, export) skip the lossy dim-1 route
        instead of inheriting training's precision contract.

    Returns:
      ``(B, dim)`` values, one row per requested id.

    Replaces the reference's ``ParameterServerClient.pull`` →
    ``ParameterServerLogic.onPullRecv`` → ``answerPull`` round trip
    (expected upstream ``.../ps/FlinkParameterServer.scala``).
    """
    if dense:
        # (S*rps, dim) in PHYSICAL (owner-major) layout: tiled all_gather
        # concatenates shard s's block at rows [s*rps, (s+1)*rps).
        full = lax.all_gather(local_shard, shard_axis, tiled=True)
        rps = local_shard.shape[0]
        # Negative ids read as zero rows on every route (id_to_phys would
        # wrap them into range via the Python-semantics modulo).
        phys = jnp.where(ids >= 0, id_to_phys(ids, num_shards, rps), -1)
        return ops.gather_rows(full, phys, exact=exact)
    me = lax.axis_index(shard_axis)
    # Every shard sees every worker's request ids: (S*B,).
    all_ids = lax.all_gather(ids, shard_axis, tiled=True)
    owned = (all_ids % num_shards) == me
    local_idx = jnp.where(owned, all_ids // num_shards, 0)
    # The head-prefix guarantee only survives when the gathered stream IS
    # the caller's stream (single shard; local_idx == ids there).
    vals = ops.gather_rows(
        local_shard, local_idx, hot_rows=hot_rows,
        head_prefix=head_prefix if num_shards == 1 else 0,
        exact=exact,
    )
    vals = jnp.where(owned[:, None], vals, jnp.zeros_like(vals))
    # Each worker ends up with its own (B, dim) slice, summed over shards
    # (exactly one shard contributed each row).
    return lax.psum_scatter(vals, shard_axis, scatter_dimension=0, tiled=True)


def pull_local(
    local_shard: Array,
    ids: Array,
    *,
    num_shards: int,
) -> Array:
    """Gather rows the calling device already owns (no communication).

    For worker-local tables (e.g. MF user factors, reference
    ``.../matrix/factorization/`` keeps user vectors in worker state): the
    ingest layer routes examples so that ``ids % num_shards`` equals the
    worker index, making every lookup local.
    """
    return ops.gather_rows(local_shard, ids // num_shards)


def push(
    local_shard: Array,
    ids: Array,
    deltas: Array,
    *,
    num_shards: int,
    shard_axis: str = SHARD_AXIS,
    data_axis: str | None = DATA_AXIS,
    apply_fn: Callable[[Array, Array], Array] | None = None,
    combine: str | Callable[[Array, Array], Array] = "sum",
    hot_rows: int = 0,
    dense: bool = False,
    head_prefix: int = 0,
) -> Array:
    """Scatter-add ``deltas`` for ``ids`` into the sharded table.

    Args:
      local_shard: this device's ``(rps, dim)`` block.
      ids: ``(B,)`` ids this worker is pushing to. **Negative ids are
        dropped entirely** — use ``-1`` for padding rows so that even
        non-additive ``apply_fn`` folds never see them.
      deltas: ``(B, dim)`` deltas.
      data_axis: if the mesh has a replicated data axis, deltas are gathered
        across it too so all replicas stay bit-identical.
      apply_fn: fold function ``(current_rows, summed_delta) -> new_rows``;
        defaults to addition (the reference's ``paramUpdate = _ + _``,
        ``SimplePSLogic``). Non-additive folds see the batch-combined delta
        once per id (duplicates are pre-combined with ``segment_sum``) and
        are applied only to rows with at least one non-dropped push.
      combine: how duplicate ids within one push combine — the analog of
        the reference's pluggable combining senders (user-supplied
        ``CombinationLogic``, expected upstream ``.../ps/client/sender/``):
        * ``"sum"`` — every message folds in (reference semantics);
        * ``"mean"`` — per-id average: one averaged step per touched row
          per push, stable for Zipfian-hot ids under large batches;
        * ``"max"`` / ``"min"`` — elementwise extremum of the id's deltas
          (a native scatter-max/min, no serial fold);
        * a callable ``(summed, counts) -> combined`` mapping each
          shard-local row's per-id delta SUM ``(rps, dim)`` and push
          COUNT ``(rps,)`` to the combined delta — the general
          user-extensible strategy (count-normalized steps, clipping,
          learning-rate-by-frequency, ...). Untouched rows (count 0) are
          masked out after the callable, so it need not special-case
          them.
      hot_rows: number of LOCAL leading rows of this shard treated as
        write-hot (see :func:`fps_tpu.ops.scatter_add`); under the
        owner-major cyclic layout, global hot ids ``[0, H)`` land exactly
        in local rows ``[0, ceil(H / num_shards))`` on every shard.
      dense: dense-reduce route for SMALL tables with the ADDITIVE fold:
        each worker scatters its OWN ``B`` deltas into a table-shaped
        zeros buffer (physical layout); an ``all_to_all`` of per-shard
        windows plus fixed-order in-program sums (see the NOTE in the
        body — deliberately NOT psum/psum_scatter) deliver every shard
        its summed slice — ``O(B)`` row transactions per worker instead
        of ``O(W * B)`` per shard, at the price of table-sized
        collectives. Non-additive folds (``apply_fn``/non-"sum"
        ``combine`` need per-id combine-then-apply semantics over the
        gathered union) silently keep the gathered route.

    Returns:
      Updated ``(rps, dim)`` local block.
    """
    if dense and apply_fn is None and combine == "sum":
        # NOTE deliberate collective choice: all_to_all/all_gather move
        # position-indexed data (order-insensitive), and the cross-worker
        # sums below run as FIXED-ORDER in-program reductions — a psum /
        # psum_scatter here would delegate the f32 reduction order to the
        # backend topology and break the tested bit-identity of 1-process
        # vs multi-process runs on the same mesh
        # (tests/test_multiprocess.py). Payloads are table-sized either
        # way; only small tables take this route.
        rps = local_shard.shape[0]
        phys = jnp.where(ids >= 0, id_to_phys(ids, num_shards, rps), -1)
        buf = ops.scatter_add(
            jnp.zeros((rps * num_shards, deltas.shape[1]),
                      local_shard.dtype),
            phys,
            deltas,
        )
        if num_shards > 1:
            # Route each shard's window of my contributions to its owner:
            # every shard receives (S, rps, dim) — all workers' deltas for
            # ITS rows — and folds them in shard-index order.
            parts = lax.all_to_all(
                buf.reshape(num_shards, rps, -1), shard_axis,
                split_axis=0, concat_axis=0, tiled=False,
            )
            mine = jnp.sum(parts, axis=0)
        else:
            mine = buf
        if data_axis is not None:
            mine = jnp.sum(lax.all_gather(mine, data_axis), axis=0)
        return local_shard + mine

    gathered_ids = ids
    gathered_deltas = deltas
    if data_axis is not None:
        gathered_ids = lax.all_gather(gathered_ids, data_axis, tiled=True)
        gathered_deltas = lax.all_gather(gathered_deltas, data_axis, tiled=True)
    gathered_ids = lax.all_gather(gathered_ids, shard_axis, tiled=True)
    gathered_deltas = lax.all_gather(gathered_deltas, shard_axis, tiled=True)

    me = lax.axis_index(shard_axis)
    rps = local_shard.shape[0]
    owned = ((gathered_ids % num_shards) == me) & (gathered_ids >= 0)
    # Unowned/dropped rows get an out-of-range index, dropped by the scatter.
    local_idx = jnp.where(owned, gathered_ids // num_shards, rps)
    masked = jnp.where(owned[:, None], gathered_deltas, jnp.zeros_like(gathered_deltas))

    if not callable(combine) and combine not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unknown combine mode {combine!r}")

    if apply_fn is None and combine == "sum":
        # Head-prefix guarantee survives only when the gathered stream is
        # the caller's own (single shard, no data axis — the driver also
        # gates it to single-device meshes).
        keep_prefix = (num_shards == 1 and data_axis is None)
        return ops.scatter_add(local_shard, local_idx, masked,
                               hot_rows=hot_rows,
                               head_prefix=head_prefix if keep_prefix else 0)

    dim = masked.shape[1]
    # Accumulate in at least f32, but never BELOW the table's own precision:
    # a float64 table must fold its duplicates in float64 (hard-coding f32
    # here would silently shave 29 mantissa bits off every non-"sum" push).
    acc_dt = jnp.promote_types(local_shard.dtype, jnp.float32)
    if combine in ("max", "min"):
        # Extremum fold: ONE scatter-max/min of the raw deltas (duplicates
        # combine natively, no serialized pairwise fold) with the touched
        # indicator riding as an appended column (owned rows contribute
        # 1.0 vs the fill sentinel — same one-scatter trick as the sum
        # path's count column; the scatter is per-row-transaction bound).
        # Sentinel beyond any representable delta IN THE ACCUMULATOR dtype —
        # a hard-coded f32-range constant would silently clamp f64 deltas of
        # magnitude > 3e38 to the sentinel.
        lim = jnp.finfo(acc_dt).max
        fill = jnp.asarray(-lim if combine == "max" else lim, acc_dt)
        ind = jnp.where(owned, 1.0, fill)[:, None]
        filled = jnp.where(
            owned[:, None],
            jnp.concatenate(
                [gathered_deltas.astype(acc_dt), ind], axis=1
            ),
            fill,
        )
        target = jnp.full((rps, dim + 1), fill, acc_dt)
        if combine == "max":
            ext = target.at[local_idx].max(filled, mode="drop")
        else:
            ext = target.at[local_idx].min(filled, mode="drop")
        counts = (jnp.abs(ext[:, dim]) <= 1.0).astype(acc_dt)
        combined = jnp.where((counts > 0)[:, None], ext[:, :dim], 0.0)
    else:
        # Combine duplicate ids first, then apply once per touched row. The
        # per-id sums and counts ride ONE scatter (counts as an appended
        # ones column) — the scatter is per-row-transaction bound on TPU,
        # so a second scatter for counts would double its cost.
        withcnt = jnp.concatenate(
            [masked.astype(acc_dt), owned.astype(acc_dt)[:, None]],
            axis=1,
        )
        acc = ops.scatter_add(
            jnp.zeros((rps, dim + 1), acc_dt), local_idx, withcnt,
            hot_rows=hot_rows,
        )
        combined, counts = acc[:, :dim], acc[:, dim]
        if combine == "mean":
            combined = combined * (1.0 / jnp.maximum(counts, 1.0))[:, None]
        elif callable(combine):
            combined = jnp.where(
                (counts > 0)[:, None], combine(combined, counts), 0.0
            )
    if apply_fn is None:
        # Additive fold: untouched rows receive exactly zero, so no mask is
        # needed (a full-table where() is a measurable per-step cost).
        return local_shard + combined.astype(local_shard.dtype)
    new_rows = apply_fn(local_shard, combined.astype(local_shard.dtype))
    return jnp.where((counts > 0)[:, None], new_rows, local_shard)


# ---------------------------------------------------------------------------
# Table spec + host-side store container.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TableSpec:
    """Declaration of one parameter table (one sharded ``id -> vector`` map).

    ``init_fn(key, ids) -> (len(ids), dim) values`` must be deterministic in
    ``ids`` — the reference seeds its factor initializers with the parameter
    id so that initialization is reproducible regardless of which shard
    first touches an id (expected upstream
    ``.../matrix/factorization/factors/``); we keep that contract.
    """

    name: str
    num_ids: int
    dim: int
    init_fn: Callable[[Array, Array], Array] = None  # (key, ids) -> values
    dtype: Any = jnp.float32
    # Write-hot routing for push scatters (:func:`fps_tpu.ops.scatter_add`):
    #   * int H > 0 — NuPS-style split: the leading H GLOBAL ids ride the
    #     lane-packed MXU contraction, the tail keeps the XLA scatter.
    #     Meaningful when ids are frequency-ranked (hottest first) — the
    #     shipped loaders and synthetic generators lay ids out that way —
    #     but drop/duplicate semantics hold for any distribution; a wrong
    #     guess costs only MXU work, capped by SCATTER_FLOP_BUDGET.
    #   * "auto" — whole-shard packed routing whenever the per-shard row
    #     slice is below the MEASURED single-chip crossover
    #     (:func:`fps_tpu.ops.packed_crossover_rows`, from
    #     ``tools/bench_scatter.py sweep``) — i.e. enabled exactly in the
    #     many-shard regime it wins in, off on fat single-chip shards.
    # Default 0 (pure XLA): the packed path carries f32 deltas as bf16
    # hi+lo (~16 mantissa bits) and would break bit-reproducibility across
    # shard counts, so it is opt-in. (f32 SCALAR tables are the exception:
    # they auto-route to the dim-1 kernels on TPU — see
    # ``fps_tpu.ops._route_dim1`` for the precise invariant scope and the
    # xla-backend escape hatch.)
    hot_ids: int | str = 0
    # Dense collective route (replicate-on-read / dense-reduce-on-write,
    # :func:`pull`/:func:`push` ``dense=``): per-worker row transactions
    # drop from the gathered route's O(W * B) per shard to O(B), at the
    # price of table-sized collectives per step — the right trade exactly
    # when the table is small (PA/logreg weight vectors, MF item factors).
    #   * "auto" — on multi-device meshes, dense whenever the padded table
    #     is at most :data:`fps_tpu.ops.DENSE_TABLE_BYTES`; single-device
    #     meshes always take the (collective-free) gathered route.
    #   * True / False — force. Forcing True on an embedding-scale table
    #     turns every step into a full-table broadcast; measure first.
    # Only the additive fold takes the dense write path; non-additive
    # folds keep gathered writes (reads may still go dense).
    dense_collectives: bool | str = "auto"
    # Two-tier hot storage (module docstring; docs/performance.md): an
    # int H > 0 replicates the leading H GLOBAL ids across the shard axis
    # beside the sharded table. Hot reads become local gathers with zero
    # collectives; hot pushes accumulate into a per-device delta buffer
    # reconciled by one psum every ``TrainerConfig.hot_sync_every`` steps
    # (bounded parameter-plane staleness). Meaningful when ids are
    # frequency-ranked (hottest first — the same head convention as
    # ``hot_ids``); H >= num_ids replicates the whole table (the NuPS
    # small-hot-table regime) and statically elides the collective
    # pull/push routes entirely. Engages only when the trainer resolves
    # it on: multi-device mesh, ``hot_sync_every > 1``, and an additive
    # ("sum") or "mean" server fold — otherwise (incl. the
    # ``hot_sync_every = 1`` exact mode) the untiered program is lowered
    # unchanged. Default 0: off.
    hot_tier: int = 0
    # Payload-proportional cold routing (docs/performance.md
    # "Payload-proportional routing"): with a PARTIAL hot head
    # (0 < H < num_ids), the cold routes otherwise keep the full-batch
    # static collective payload even at a 0.99 hit rate. A positive
    # ``cold_budget`` bounds the per-worker-per-step cold-id lane: each
    # batch's cold ids/deltas are compacted on device into a
    # ``(cold_budget,)`` stream before the collective pull/push, so the
    # gathered routes carry O(cold traffic) bytes instead of O(batch).
    # Ingest-certified like ``head_prefix``: the compacted program only
    # dispatches for chunks the host proved fit the budget
    # (``WorkerLogic.pulled_ids_host``); overflowing chunks fall back to
    # the static route bit-identically, with a
    # ``cold_route.overflow_chunks`` obs counter. Engages only when the
    # tier resolves on with a partial head on a non-dense route; 0 (the
    # default) keeps the static cold routes.
    cold_budget: int = 0

    def zeros_init(self) -> "TableSpec":
        return dataclasses.replace(
            self, init_fn=lambda key, ids: jnp.zeros((ids.shape[0], self.dim), self.dtype)
        )


def ranged_uniform_init(min_val: float, max_val: float, dim: int, dtype=jnp.float32):
    """Per-id seeded uniform init in ``[min_val, max_val)`` — mirrors the
    reference's ranged-random factor initializer (seeded by parameter id so
    initialization is reproducible across any shard count; expected upstream
    ``.../matrix/factorization/factors/``)."""

    def init(key: Array, ids: Array) -> Array:
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(ids)
        return jax.vmap(
            lambda k: jax.random.uniform(
                k, (dim,), jnp.float32, minval=min_val, maxval=max_val
            )
        )(keys).astype(dtype)

    return init


def _default_init(key: Array, ids: Array, dim: int, dtype) -> Array:
    return ranged_uniform_init(-0.01, 0.01, dim, dtype)(key, ids)


def make_table_values(
    key: Array,
    num_ids: int,
    dim: int,
    num_shards: int,
    init_fn: Callable[[Array, Array], Array] | None = None,
    dtype=jnp.float32,
) -> Array:
    """Build a full ``(rps*num_shards, dim)`` table in owner-major layout.

    Usable both for PS tables (sharded over the shard axis) and for
    worker-local tables (sharded over all worker devices, e.g. MF user
    factors). Initialization is per-id deterministic: padding rows and real
    rows alike get ``init_fn(fold_in(key, id))``-style values, so the result
    is identical regardless of shard count (matching the reference's
    id-seeded reproducible factor initializers).
    """
    rps = rows_per_shard(num_ids, num_shards)
    phys = jnp.arange(rps * num_shards, dtype=jnp.int32)
    ids = phys_to_id(phys, num_shards, rps)
    fn = init_fn or partial(_default_init, dim=dim, dtype=dtype)
    return fn(key, ids).astype(dtype)


class ParamStore:
    """Host-side container creating and tracking sharded parameter tables.

    The device-side compute never touches this class — it works on the pytree
    of arrays (``store.tables``) passed through the jitted step functions.
    """

    def __init__(self, mesh: Mesh, specs: Mapping[str, TableSpec] | list[TableSpec]):
        if not isinstance(specs, Mapping):
            specs = {s.name: s for s in specs}
        self.mesh = mesh
        self.specs = dict(specs)
        self.num_shards = mesh.shape[SHARD_AXIS]
        self.sharding = NamedSharding(mesh, P(SHARD_AXIS, None))
        self.tables: dict[str, Array] = {}
        self._head_replica_fns: dict = {}  # (name, hot_rows) -> jitted gather
        self._rows_replica_fns: dict = {}  # (name, nrows) -> jitted gather

    def init(self, key: Array) -> dict[str, Array]:
        """Materialize all tables directly in their sharded layout."""
        for name, spec in self.specs.items():
            tkey = jax.random.fold_in(key, _stable_hash(name))
            make = partial(
                make_table_values,
                tkey,
                spec.num_ids,
                spec.dim,
                self.num_shards,
                spec.init_fn,
                spec.dtype,
            )
            self.tables[name] = jax.jit(make, out_shardings=self.sharding)()
        return self.tables

    def head_replica(self, name: str, hot_rows: int, table: Array | None = None) -> Array:
        """Replicated ``(hot_rows, dim)`` array of ``name``'s leading ids.

        The re-split half of the two-tier contract: derives the hot
        replica from the CANONICAL sharded table (valid at any compiled-
        call boundary — pending deltas are always reconciled before a
        call returns). Multi-controller: the replicating jit is a
        COLLECTIVE; every process reaches the run entry together, same
        as the checkpoint dump.
        """
        spec = self.specs[name]
        if not 0 < hot_rows <= spec.num_ids:
            raise ValueError(
                f"table {name!r}: hot_rows={hot_rows} outside "
                f"(0, {spec.num_ids}]"
            )
        table = self.tables[name] if table is None else table
        fn = self._head_replica_fns.get((name, hot_rows))
        if fn is None:
            # Cache the jitted gather per (table, head size): repeat
            # derivations (every restore / restart / warm-start) hit the
            # jit cache instead of re-tracing the same trivial program.
            rps = rows_per_shard(spec.num_ids, self.num_shards)
            phys = np.asarray(
                id_to_phys(np.arange(hot_rows, dtype=np.int64),
                           self.num_shards, rps)
            )
            fn = jax.jit(
                lambda t: t[phys],
                out_shardings=NamedSharding(self.mesh, P()),
            )
            self._head_replica_fns[(name, hot_rows)] = fn
        return fn(table)

    def rows_replica(self, name: str, ids: np.ndarray,
                     table: Array | None = None) -> Array:
        """Replicated ``(len(ids), dim)`` array of arbitrary global ids of
        ``name`` — the re-split half of the ADAPTIVE tier (the mapped
        analog of :meth:`head_replica`, whose head is always ``[0, H)``).

        The physical row indices ride as a jit ARGUMENT (not a baked
        constant), so every re-rank at the same head size H reuses one
        compiled gather — the no-recompile contract. Valid at any
        compiled-call boundary (pending deltas are always reconciled
        before a call returns). Multi-controller: collective, like
        :meth:`head_replica`.
        """
        spec = self.specs[name]
        ids = np.asarray(ids, np.int64)
        if ids.size == 0 or ids.min() < 0 or ids.max() >= spec.num_ids:
            raise ValueError(
                f"table {name!r}: replica ids must be a non-empty subset "
                f"of [0, {spec.num_ids})")
        table = self.tables[name] if table is None else table
        rps = rows_per_shard(spec.num_ids, self.num_shards)
        phys = np.asarray(id_to_phys(ids, self.num_shards, rps),
                          dtype=np.int32)
        fn = self._rows_replica_fns.get((name, len(ids)))
        if fn is None:
            fn = jax.jit(
                lambda t, p: t[p],
                out_shardings=NamedSharding(self.mesh, P()),
            )
            self._rows_replica_fns[(name, len(ids))] = fn
        return fn(table, phys)

    def table_specs_static(self) -> dict[str, tuple[int, int]]:
        """(num_shards, rows_per_shard) per table, for device-side code."""
        return {
            name: (self.num_shards, rows_per_shard(spec.num_ids, self.num_shards))
            for name, spec in self.specs.items()
        }

    def _host_table(self, name: str) -> np.ndarray:
        """Full table as numpy.

        Multi-controller: cross-host tables are first replicated through a
        jitted identity — a COLLECTIVE, so every process must make this
        call (via ``lookup_host``/``dump_model``/checkpoint save) together;
        a subset of processes calling alone blocks on the others' shards.
        """
        table = self.tables[name]
        if not table.sharding.is_fully_addressable:
            table = replicate_to_mesh(table, self.mesh)
        return np.asarray(table)

    def lookup_host(self, name: str, ids: np.ndarray) -> np.ndarray:
        """Host-side (numpy) read of current values — for eval / model dump.

        Replaces the reference's end-of-job model emission
        (``ParameterServerLogic.close`` → ``output((id, param))``).
        """
        spec = self.specs[name]
        rps = rows_per_shard(spec.num_ids, self.num_shards)
        table = self._host_table(name)
        phys = np.asarray(id_to_phys(np.asarray(ids), self.num_shards, rps))
        return table[phys]

    def dump_model(self, name: str) -> tuple[np.ndarray, np.ndarray]:
        """Return ``(ids, values)`` for the whole table (padding rows excluded)."""
        spec = self.specs[name]
        ids = np.arange(spec.num_ids)
        return ids, self.lookup_host(name, ids)


def _stable_hash(s: str) -> int:
    h = 0
    for c in s.encode():
        h = (h * 131 + c) % (2**31 - 1)
    return h
