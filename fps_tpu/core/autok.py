"""Measured megastep K derivation (``chunks_per_dispatch="auto"``).

The megastep amortizes per-dispatch host overhead (Python dispatch,
arg placement, host sync) over K in-graph chunk segments: one dispatch
of K chunks costs roughly ``h + K*c`` wall seconds, where ``h`` is the
per-dispatch overhead and ``c`` the per-chunk device compute. The
host-serial share of a dispatch is therefore modeled as

    share(K) = h / (h + K*c)

and the smallest K that drives it under a target share ``s`` is

    K >= h * (1 - s) / (s * c)

Instead of asking the operator to guess K (the old flag), a short
calibration window measures ``h`` and ``c`` directly: time a dispatch
of one cadence block and a dispatch of two cadence blocks (post-
compile, median of a few samples) — the difference is the marginal
block cost ``c``, the extrapolated zero-block intercept is ``h``.

The chosen K is ALWAYS a multiple of the tick cadence
(``tick.check_every``) so every in-graph tier tick lands on a static
boundary, and the run it drives is bit-identical to passing the same
K explicitly — calibration dispatches run on :func:`tree_copy`
throwaways (the megastep program donates its inputs), so they never
touch the real state, host RNG, or the trainer's store.

Resume caveat: calibration is for FRESH runs. ``start_megastep`` is
counted in megasteps of K chunks, so a resumed run must reuse the
original run's chosen K (recorded as the ``megastep.auto_k`` gauge and
returned here) — re-calibrating under different load could move the
checkpoint boundaries.
"""
from __future__ import annotations

import math
import statistics
import time

import jax

__all__ = [
    "derive_chunks_per_dispatch",
    "calibrate_chunks_per_dispatch",
]

#: Modeled host-serial share a chosen K must clear.
DEFAULT_TARGET_SHARE = 0.05

#: Upper bound on the chosen K: one compiled program of max_k segments
#: is already >=95% amortized for any workload that needs it, and
#: larger programs cost compile time and trace memory superlinearly.
DEFAULT_MAX_K = 64

#: Timed samples per calibration point (median taken; first untimed
#: call pays the compile).
DEFAULT_SAMPLES = 3


def derive_chunks_per_dispatch(overhead_s: float, per_chunk_s: float, *,
                               target_share: float = DEFAULT_TARGET_SHARE,
                               max_k: int = DEFAULT_MAX_K,
                               multiple_of: int = 1,
                               n_calls: int | None = None) -> int:
    """Smallest K with modeled host-serial share <= ``target_share``.

    Pure — the measured-trace half of auto-K feeds this, and the
    fixed-trace tests pin it. ``multiple_of`` (the tick cadence) is
    always honored by rounding UP; ``max_k`` is rounded DOWN to the
    cadence (never below one block). ``n_calls`` (chunk calls per
    epoch) caps K at one epoch's work, rounded up to the cadence —
    beyond that every extra segment is a trailing phantom.
    """
    if multiple_of < 1:
        raise ValueError(f"multiple_of must be >= 1, got {multiple_of}")
    if not (0.0 < target_share < 1.0):
        raise ValueError(
            f"target_share must be in (0, 1), got {target_share}")
    if max_k < 1:
        raise ValueError(f"max_k must be >= 1, got {max_k}")
    cap = max((max_k // multiple_of) * multiple_of, multiple_of)
    if n_calls is not None and n_calls >= 1:
        cap = min(cap, -(-n_calls // multiple_of) * multiple_of)
    h = max(float(overhead_s), 0.0)
    c = float(per_chunk_s)
    if h <= 0.0:
        return multiple_of  # no measurable overhead: smallest legal K
    if c <= 0.0:
        return cap  # dispatch-bound: no K clears the share; take the cap
    k_needed = math.ceil(h * (1.0 - target_share) / (target_share * c))
    blocks = max(1, -(-k_needed // multiple_of))
    return min(blocks * multiple_of, cap)


def _measure_dispatch(trainer, fn, tables, local_state, iargs, ekey,
                      tick_ops, samples: int,
                      clock=time.perf_counter) -> float:
    """Median wall seconds of ``samples`` post-compile dispatches of
    ``fn`` on throwaway copies (the program donates its inputs).
    Module-level so the fixed-trace tests can replace the measurement
    while exercising the real derivation and dispatch plumbing."""
    from fps_tpu.core.resilience import tree_copy

    def once():
        out = fn(tree_copy(tables), tree_copy(local_state), iargs,
                 jax.numpy.int32(0), ekey, tick_ops)
        jax.block_until_ready(out)

    once()  # compile + first-touch, untimed
    walls = []
    for _ in range(max(1, samples)):
        t0 = clock()
        once()
        walls.append(clock() - t0)
    return statistics.median(walls)


def calibrate_chunks_per_dispatch(trainer, tables, local_state, plan,
                                  key, *, mode: str, tick=None,
                                  n_calls: int | None = None,
                                  target_share: float =
                                  DEFAULT_TARGET_SHARE,
                                  max_k: int = DEFAULT_MAX_K,
                                  samples: int = DEFAULT_SAMPLES):
    """Measure ``(h, c)`` and derive K. Returns
    ``(K, overhead_s, per_chunk_s)``.

    Times one-cadence-block and two-cadence-block megastep programs on
    epoch-0 args: ``wall(B blocks) = h + B*block*c`` gives
    ``c = (wall2 - wall1) / block`` and ``h = 2*wall1 - wall2``.
    Negative noise is clamped (h >= 0; c >= a tiny positive floor so a
    noisy fast machine degrades to the max-K cap, never a crash).
    """
    from fps_tpu.parallel.mesh import key_to_replicated

    block = int(tick.check_every) if tick is not None else 1
    iargs = plan.epoch_args(0)
    ekey = key_to_replicated(jax.random.fold_in(key, 0), trainer.mesh)
    tick_ops = tick.tick_ops(trainer) if tick is not None else {}
    walls = []
    for blocks in (1, 2):
        fn = trainer._get_megastep_fn(plan, mode, blocks * block, tick)
        walls.append(_measure_dispatch(trainer, fn, tables, local_state,
                                       iargs, ekey, tick_ops, samples))
    wall1, wall2 = walls
    per_chunk_s = max((wall2 - wall1) / block, 1e-9)
    overhead_s = max(2.0 * wall1 - wall2, 0.0)
    k = derive_chunks_per_dispatch(overhead_s, per_chunk_s,
                                   target_share=target_share,
                                   max_k=max_k, multiple_of=block,
                                   n_calls=n_calls)
    return k, overhead_s, per_chunk_s
