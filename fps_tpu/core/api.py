"""The user contract: WorkerLogic / ServerLogic, functionalized for SPMD.

Reference contract being preserved (SURVEY.md §2 #2–#4; expected upstream
``src/main/scala/hu/sztaki/ilab/ps/WorkerLogic.scala`` and the
``ParameterServerLogic`` / ``ParameterServerClient`` traits):

* ``WorkerLogic.onRecv(data, psClient)`` — consume a training record, issue
  ``psClient.pull(id)`` / ``psClient.push(id, delta)`` / ``psClient.output(o)``.
* ``WorkerLogic.onPullRecv(id, value, psClient)`` — continue once the pulled
  value arrives.
* ``ParameterServerLogic`` — per-shard state with ``onPullRecv`` /
  ``onPushRecv``; the shipped default ``SimplePSLogic`` is just
  ``paramInit: Int => P`` + ``paramUpdate: (P, P) => P``.

TPU functionalization
---------------------
The callback pair (onRecv → pull → onPullRecv) exists only because the
reference is asynchronous message passing. Under SPMD the round trip is a
collective with a known latency, so the two callbacks collapse into one pure
batch-step function and the client object disappears:

* ``WorkerLogic.pull_ids(batch)``  — which rows each table needs (the
  pull phase; one vectorized ``pull`` per table replaces per-record
  ``psClient.pull`` calls).
* ``WorkerLogic.step(batch, pulled, local_state, key)`` — the fused
  onRecv+onPullRecv body: compute updates, return pushes + outputs.
* ``ServerLogic`` — exactly ``SimplePSLogic``: per-table ``init_fn`` +
  fold for pushed deltas (additive by default, like every shipped
  reference algorithm).

Worker-local state (the reference keeps e.g. MF user vectors in worker
operator state) is the ``local_state`` pytree: arrays sharded over the
worker axes that only their owning device reads/writes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax

Array = jax.Array
Pytree = Any


@dataclasses.dataclass
class StepOutput:
    """What one worker step returns.

    Attributes:
      pushes: per-table ``(ids, deltas)`` — ids ``(B,)`` int32, deltas
        ``(B, dim)``. Zero-weight (padding) rows must carry id ``-1``
        (dropped by the store even for non-additive server folds); zero
        deltas alone are only a no-op for the additive default.
      local_state: updated worker-local pytree.
      out: the reference's ``WOut`` channel (``ParameterServerClient.output``)
        — a metrics/prediction pytree, summed or collected by the driver.
    """

    pushes: Mapping[str, tuple[Array, Array]]
    local_state: Pytree
    out: Pytree


class WorkerLogic:
    """Base class for worker-side algorithm logic (pure functions only)."""

    def init_local_state(self, key: Array, num_workers: int) -> Pytree:
        """Per-device local state; called once under the driver's sharding."""
        return ()

    def prepare(self, batch: Pytree, key: Array) -> Pytree:
        """Augment the batch before pulling (e.g. sample negative ids
        on-device). Runs inside the compiled step; default is identity."""
        return batch

    def pull_ids(self, batch: Pytree) -> Mapping[str, Array]:
        """Map table name -> (B,) int32 ids to pull for this batch."""
        raise NotImplementedError

    def pulled_ids_host(self, chunk: Pytree) -> Mapping[str, Any] | None:
        """Optional HOST-side certification stream for the compacted cold
        routes (``TableSpec.cold_budget``; docs/performance.md
        "Payload-proportional routing").

        Return ``{table: int id array}`` per compactable table, computed
        from the RAW (un-``prepare``-d) host chunk: the LAST axis must be
        the worker-major per-step id stream (the global batch dim for
        one-id-per-example logics; multi-id columns shaped ``(T, B, k)``
        reshape to ``(T, B*k)`` — worker-major blocks survive the
        flatten) and the leading axes the chunk's step dims, and the
        stream must cover every id the compiled step pulls OR pushes for
        that table at each position. Padding positions may carry any id — the certifier
        counts them conservatively (a padding id outside the hot set
        consumes cold-lane budget, exactly as it would on device).

        ``None`` (default): chunks from this logic are not host-
        certifiable, so every chunk dispatches the static (full-payload)
        cold routes even when a ``cold_budget`` is configured. Logics
        whose ``prepare`` synthesizes ids on device (e.g. negative
        sampling) must return ``None`` unless the synthesized ids are
        provably hot."""
        return None

    def pulled_ids_traced(self, batch: Pytree) -> Mapping[str, Array] | None:
        """Optional TRACED certification stream for the compacted cold
        routes where no host id stream exists — the device-side half of
        :meth:`pulled_ids_host`, consumed by the megastep driver's
        in-graph overflow VOTE (``fps_tpu.core.megastep``).

        Called inside the compiled program with one worker's RAW
        (un-``prepare``-d) per-step batch; return ``{table: int id
        array}`` covering every id the step will pull OR push for that
        table (any shape — the vote flattens), or ``None`` when the
        logic cannot certify (ids synthesized in :meth:`prepare`).
        Whether ``None`` is returned must be decided by the logic's
        STATIC configuration, never by batch values — the megastep
        probes it once by abstract evaluation to choose between the
        voted and the always-static program. Padding positions may
        carry any id; the vote counts them conservatively, exactly like
        the host certifier."""
        return None

    def head_prefix(self, batch: Pytree) -> Mapping[str, int]:
        """Optional STATIC guarantee: table name -> count of LEADING ids
        (in both :meth:`pull_ids` order and the step's push order) that
        lie in ``[0, spec.hot_ids) ∪ {-1}`` — the frequency-ranked head a
        sorted-slot batch layout (``head_sort_slots``) puts first. The
        driver turns it into head-only kernel routing on single-device
        meshes (collective routes reorder the id streams, voiding the
        guarantee) — see ``fps_tpu.ops.gather_rows``. Counts must be
        plain ints derived from batch SHAPES (trace-time static).
        Default: no guarantee."""
        return {}

    def step(
        self,
        batch: Pytree,
        pulled: Mapping[str, Array],
        local_state: Pytree,
        key: Array,
    ) -> StepOutput:
        """Fused onRecv/onPullRecv body — must be jit-traceable."""
        raise NotImplementedError

    def touched_local_rows(self, batch: Pytree):
        """Optional: which axis-0 rows of each local-state leaf this
        batch's :meth:`step` can touch — the ids-aware refinement of the
        local guard (``GuardConfig(local=True)``). Return a sequence with
        ONE entry per flattened local-state leaf: an int id array
        (``-1`` = no row, e.g. padding examples) restricting that leaf's
        row screening to the touched rows, or ``None`` to screen every
        row of that leaf. Default ``None``: no guarantee, the guard
        screens (and in mask mode may revert) every row. Must be
        jit-traceable (called inside the compiled step). Rows OUTSIDE the
        returned set are still covered by the guard's leaf-tier
        non-finite net — they can be *counted*, never *masked* (an
        untouched row's pre-step value is its post-step value, so there
        is nothing to revert to)."""
        return None

    # -- checkpoint portability (optional overrides) -----------------------

    def export_local_state(self, local_state: Pytree) -> Pytree:
        """Host-side, worker-count-INDEPENDENT form of the local state for
        checkpointing (e.g. MF re-orders its worker-sharded user table to
        logical user order). Default: the raw pytree — restorable only at
        the same worker count."""
        return local_state

    def import_local_state(self, leaves: list, num_workers: int):
        """Inverse of :meth:`export_local_state`: rebuild the device-layout
        local-state pytree (host numpy) for ``num_workers`` workers from
        the exported leaves. Return ``NotImplemented`` (the default) to
        keep the raw same-worker-count restore path."""
        return NotImplemented


@dataclasses.dataclass(frozen=True)
class HotFold:
    """Stateful hot-tier optimizer fold (Adagrad / Adam server state).

    With the sharded reconcile (reduce-scatter → apply the owned 1/S
    slice → all-gather, docs/performance.md "Sharded reconcile"), every
    replica applies a DISJOINT slice of the hot head per window — so
    per-row optimizer state can live sharded over the replica axis
    instead of being replicated. A ``HotFold`` turns the window's
    combined delta ``g`` (after the ``combine`` normalization) into an
    adaptively-scaled step on the slice:

    * ``"adagrad"`` — ``G += g²; step = lr · g / (sqrt(G) + eps)``;
    * ``"adam"`` — lazy per-row Adam: rows untouched in a window keep
      their moments and step count unchanged (sparse-table convention —
      decaying untouched rows would make zero-traffic rows drift), rows
      touched update ``m``/``v`` with bias correction by the row's own
      window count ``t``.

    The state is never replicated, never part of the canonical table
    bytes, and flush-reconciled like the pending-delta buffers: the
    canonical sharded table at any call boundary already holds the
    folded steps, so checkpoints stay byte-canonical (an untiered
    trainer restores them); the state itself rides the snapshot as
    separate ``fold::`` arrays so a supervised resume is bit-identical.

    Requires the hot tier to resolve ON for the table (multi-device,
    ``hot_sync_every > 1``, full replication — partial heads would give
    head rows an adaptive step and tail rows a raw one, a silent
    semantic fork, so they are rejected at resolution).
    """

    kind: str  # "adagrad" | "adam"
    lr: float = 1.0
    eps: float = 1e-8
    beta1: float = 0.9
    beta2: float = 0.999

    def __post_init__(self):
        if self.kind not in ("adagrad", "adam"):
            raise ValueError(
                f"HotFold.kind {self.kind!r} — expected 'adagrad' or 'adam'"
            )

    def state_cols(self, dim: int) -> int:
        """Columns of per-row optimizer state: Adagrad keeps ``G``;
        Adam keeps ``(m, v, t)`` with the window count as a column."""
        return dim if self.kind == "adagrad" else 2 * dim + 1


def as_hot_fold(fold) -> HotFold | None:
    """Normalize the ``ServerLogic.hot_fold`` shorthand: a string names
    the fold kind with default hyperparameters; None passes through."""
    if fold is None or isinstance(fold, HotFold):
        return fold
    if isinstance(fold, str):
        return HotFold(kind=fold)
    raise TypeError(
        f"hot_fold must be a HotFold, a kind string, or None — got "
        f"{type(fold).__name__}"
    )


@dataclasses.dataclass(frozen=True)
class ServerLogic:
    """Per-table server fold — the reference's ``SimplePSLogic`` plus its
    pluggable combining senders.

    ``apply_fn(current_rows, combined_deltas) -> new_rows``; ``None`` means
    plain addition (``paramUpdate = _ + _``), which every algorithm shipped
    with the reference uses and which takes the fastest scatter-add path.

    ``combine`` controls how duplicate ids in one batch merge before the
    fold — the user-extensible analog of the reference's combination
    logic (expected upstream ``.../ps/client/sender/``): ``"sum"``
    (reference semantics), ``"mean"`` (per-id averaged step — stable for
    Zipfian hot ids under large batches), ``"max"`` / ``"min"``
    (elementwise extremum), or a callable ``(summed, counts) -> combined``
    over each row's per-id delta sum and push count (see
    :func:`fps_tpu.core.store.push`).

    ``hot_fold`` (a :class:`HotFold`, or its kind string) adds Adagrad /
    Adam optimizer state to the table's HOT TIER, sharded over the
    replica axis by the sharded reconcile — see :class:`HotFold` for the
    exact semantics and the resolution requirements. Ignored (with the
    tier's usual loud resolution errors) when the tier is off.
    """

    apply_fn: Callable[[Array, Array], Array] | None = None
    combine: str | Callable[[Array, Array], Array] = "sum"
    hot_fold: "HotFold | str | None" = None


ADDITIVE = ServerLogic(apply_fn=None)
MEAN_COMBINE = ServerLogic(apply_fn=None, combine="mean")
