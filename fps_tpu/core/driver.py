"""Step drivers: the TPU-native replacement for Flink's iteration loop.

The reference wires worker and server operators into a cyclic dataflow
(``ConnectedIterativeStreams`` + ``closeWith`` feedback edge, expected
upstream ``src/main/scala/hu/sztaki/ilab/ps/FlinkParameterServer.scala``) and
lets records circulate asynchronously until an ``iterationWaitTime`` timeout.

Here the loop is compiled: one ``jax.lax.scan`` over a chunk of microbatches,
inside one ``shard_map`` over the ``(data, shard)`` mesh, jitted once and fed
by a host-side ingest loop. Two execution modes:

* **sync** — every step pulls fresh values through the sharded store
  (collective gather) and pushes immediately (collective scatter-add). This
  is the ``staleness = 0`` point the reference cannot even express.
* **ssp**  — bounded staleness: workers read from a device-local replicated
  *snapshot* of the tables, refreshed by an ``all_gather`` every
  ``sync_every`` steps; pushes still land in the authoritative sharded
  tables every step, so no update is ever lost. A worker therefore reads
  values at most ``sync_every`` steps stale — a *stronger* guarantee than
  the reference's free-running asynchrony, whose only flow control is the
  worker pull limiter (``WorkerLogic.addPullLimiter``, expected upstream
  ``.../ps/WorkerLogic.scala``).
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import logging
import warnings
from typing import Any, Callable, Iterable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu import ops
from fps_tpu.core import resilience
from fps_tpu.core.api import ServerLogic, WorkerLogic, as_hot_fold
from fps_tpu.core.prefetch import ChunkPrefetcher, PlacedChunk
from fps_tpu.core.resilience import GuardConfig, RollbackPolicy
from fps_tpu import sketch as _sketch
from fps_tpu.core.store import (
    FOLD_KEY_SUFFIX,
    IDS_KEY_SUFFIX,
    MAP_KEY_SUFFIX,
    SKETCH_KEY_SUFFIX,
    ParamStore,
    accumulate_hot,
    compact_cold,
    delta_counted,
    fold_key,
    hot_base,
    hot_delta_init,
    hot_fold_state_shape,
    hot_key,
    hot_slot_map,
    id_to_phys,
    ids_key,
    is_aux_key,
    is_hot_key,
    lookup_hot_slots,
    map_key,
    pull,
    pull_hot,
    push,
    reconcile_hot,
    reconcile_hot_mapped,
    sketch_key,
    split_hot_push,
    split_hot_push_slots,
    split_tiering,
)
from fps_tpu.obs.health import (
    HEALTH_ABORT,
    HEALTH_ESCALATE,
    HealthMonitor,
    StepWatchdog,
)
from fps_tpu.obs.timing import PhaseTimer
from fps_tpu.parallel.mesh import (
    DATA_AXIS,
    SHARD_AXIS,
    host_to_sharded,
    key_to_replicated,
)

Array = jax.Array
Pytree = Any

_log = logging.getLogger("fps_tpu.driver")

WORKER_AXES = (DATA_AXIS, SHARD_AXIS)

# End-of-iterator sentinel for the timed ingest pull in fit_stream.
_STREAM_END = object()


def calls_per_epoch_of(plan, steps_per_call: int) -> int:
    """One chunk-grid definition for every indexed-plan consumer
    (run_indexed, the megastep driver): the plan's own
    ``calls_per_epoch`` when it has one (``DeviceEpochPlan``), else the
    ceil-divide fallback for duck-typed plans (e.g. the w2v device
    plan) that only expose ``steps_per_epoch``."""
    if hasattr(plan, "calls_per_epoch"):
        return plan.calls_per_epoch(steps_per_call)
    return -(-plan.steps_per_epoch // steps_per_call)


def _phase(timer: PhaseTimer | None, name: str):
    """Timer phase scope, or a free no-op when telemetry is off."""
    return timer.phase(name) if timer is not None else contextlib.nullcontext()


def _watch(watchdog: StepWatchdog | None, what: str, index: int):
    return (watchdog.watch(what, index) if watchdog is not None
            else contextlib.nullcontext())


def _find_heartbeat(rec):
    """The supervised-run progress beacon riding ``rec``'s sinks, if any.

    Duck-typed on the sink's ``heartbeat`` attribute (the
    ``fps_tpu.supervise.child.HeartbeatSink`` shape) so the driver never
    imports the supervise package. With a beacon in hand the drivers beat
    at SUB-chunk boundaries (prefetch wait / dispatch) with a ``phase``
    field, so a death between chunk boundaries attributes to the right
    sub-phase in the supervisor's quarantine evidence."""
    for s in getattr(rec, "sinks", ()) if rec is not None else ():
        hb = getattr(s, "heartbeat", None)
        if hb is not None and hasattr(hb, "beat"):
            return hb
    return None


def _beat(hb, index: int, phase: str) -> None:
    """Sub-phase liveness beat (no-op without a beacon). Carries the
    index being worked on — the beat-before-work convention the
    supervisor's quarantine keys on — plus the sub-phase name."""
    if hb is not None:
        hb.beat(index=int(index), phase=phase)


def worker_index() -> Array:
    """Linear worker index of the calling device (inside shard_map)."""
    return lax.axis_index(DATA_AXIS) * lax.axis_size(SHARD_AXIS) + lax.axis_index(
        SHARD_AXIS
    )


def num_workers_of(mesh) -> int:
    return mesh.shape[DATA_AXIS] * mesh.shape[SHARD_AXIS]


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """Execution-mode knobs (the reference exposes workerParallelism /
    psParallelism / iterationWaitTime on ``transform``; parallelism here
    comes from the mesh, and the timeout has no analog in a compiled loop).
    """

    sync_every: int | None = None  # None => fully synchronous mode
    # Emulates the reference's in-flight pushes: a worker's pushes reach the
    # authoritative tables ``push_delay`` steps after they were computed
    # (0 = immediately, the sync/SSP default). Worker-LOCAL state updates
    # stay immediate — in the reference, too, only PS traffic rides the
    # network while worker operator state is updated in place. Combined
    # with ``sync_every`` this brackets the reference's free-running
    # asynchrony from both sides: stale reads AND delayed writes. Delayed
    # pushes ride a ring buffer in the compiled loop's carry; whatever is
    # still in flight when a compiled call ends is flushed then (a chunk /
    # dispatch boundary acts as a quiesce point).
    push_delay: int = 0
    # Optional per-step tap with TABLE access, traced into the compiled
    # loop: ``tap(tables, batch, local_state, t) -> pytree``. Unlike the
    # worker's ``out`` channel (global sums), tap outputs are all-gathered
    # across the worker axes — metrics gain a ``"tap"`` entry whose leaves
    # carry a leading per-worker axis ``(T, W, ...)``. This is how
    # per-worker emissions that need the live tables ride the output
    # stream — e.g. online top-K recommendations interleaved with training
    # (the reference's ``...AndTopK`` jobs emit exactly such records on
    # WOut; see fps_tpu.models.recommendation.make_online_topk_tap).
    # ``batch`` is the raw (pre-``prepare``) batch; ``tables`` are the
    # values after this step's push DELIVERY — with ``push_delay > 0``
    # that is the push from ``push_delay`` steps ago, not this step's
    # (in-flight pushes are invisible, exactly like the async reference).
    step_tap: Callable[..., Any] | None = None
    # On-device push-delta health guard (fps_tpu.core.resilience): None
    # (default) traces the exact guard-free program of old — zero cost
    # when off; "observe"/"mask" (or a full GuardConfig) screens every
    # table's push deltas per step inside the compiled scan, counts
    # non-finite / norm-exploded rows into a "health" entry on the
    # metrics stream, and in mask mode drops the offending rows
    # (id → -1, delta → 0) before they reach the server fold — a poison
    # batch degrades to a lost update instead of table death. Requires
    # the worker's out channel to be a dict (same constraint as
    # step_tap). Part of the compile-cache key.
    guard: GuardConfig | str | None = None
    donate: bool = True
    # --- host-pipeline knobs (fps_tpu.core.prefetch; docs/performance.md).
    # None of these touch the traced program or the compile cache: the
    # compiled HLO is identical whatever their values (tested).
    #
    # Depth of the background prefetch+place pipeline feeding fit_stream:
    # chunk assembly and host->device placement run up to this many chunks
    # ahead on a worker thread, so the device never idles waiting on host
    # ingest. 0 (default) keeps the fully synchronous host loop; numerics
    # and chunk order are bit-identical either way.
    prefetch: int = 0
    # Adaptive prefetch depth bound: > 0 lets the pipeline raise its
    # depth from `prefetch` up to this many chunks when the consumer
    # keeps draining the buffer empty (measured queue-empty stalls),
    # vetoed by available host memory — see ChunkPrefetcher.max_depth.
    # 0 (default) keeps the depth fixed at `prefetch`.
    prefetch_max: int = 0
    # Staleness (in chunks) of the forced host metrics sync that health /
    # watchdog / rollback consumers require: 0 (default) inspects chunk
    # i's metrics before dispatching i+1 (today's serial behavior); 1
    # inspects chunk i-1's metrics WHILE chunk i computes (bounded-
    # staleness health, the paper's SSP semantics applied to the control
    # plane). Quarantine under lag restores the pre-(i-1) snapshot and
    # deterministically recomputes chunk i from it, so lag on/off produce
    # identical tables and metrics (tested).
    health_lag: int = 0
    # Deferred-metrics drain cadence for fit_stream without a per-chunk
    # syncing consumer: every N chunks the buffered device metrics are
    # pulled to host so an unbounded stream cannot accumulate device
    # buffers (was a hardcoded 8). 0 = never drain mid-stream (bounded
    # streams whose caller wants zero mid-stream syncs).
    metrics_drain_every: int = 8
    # Reconcile cadence, in steps, of the two-tier hot storage
    # (TableSpec.hot_tier; docs/performance.md "Two-tier storage"): hot
    # pushes accumulate into per-device delta buffers that one psum folds
    # into the replica + the canonical table every hot_sync_every steps —
    # the SSP staleness bound applied to the parameter plane. 1 (default)
    # is the EXACT mode: the tier disengages and the driver lowers the
    # identical untiered program (bit-identical tables/metrics/
    # checkpoints by construction — a per-step psum reconcile could not
    # reproduce the gathered scatter's summation order; see the store
    # module docstring). In SSP mode the reconcile rides the sync_every
    # round boundary (the snapshot gather must see reconciled head rows),
    # so the effective parameter-plane bound there is sync_every; this
    # knob still gates the tier on/off. Every compiled call ends with a
    # flush reconcile, so chunk/epoch boundaries always hold one
    # canonical table (checkpoints/rollback need no special casing).
    # Part of the compile-cache key.
    hot_sync_every: int = 1
    # Adaptive tiering (fps_tpu.tiering; docs/performance.md "Adaptive
    # tiering"): True auto-attaches a Retierer at run entry — online
    # pulled-id frequency tracking (device-side count-min windows,
    # psum-merged), an auto-tiering plan derived from the sketched
    # densities after a warmup (per-table hot_tier / hot_sync_every /
    # dense route — replaces hand-tuning those three knobs), and
    # churn-triggered hot-set re-ranks that swap the replica + slot-map
    # DATA without recompiling. Attach ``trainer.retierer`` directly for
    # non-default cadences/thresholds/persistence. Host-only flag: the
    # compile key derives from the retierer's resolution, not this bool.
    auto_tier: bool = False
    # Upper bound on scan steps per compiled call in run_indexed. A single
    # device program must not run for minutes (the TPU runtime enforces a
    # per-dispatch execution deadline — observed ~45s on tunneled chips,
    # killing the worker process); epochs longer than this are split into
    # several dispatches of one compiled program (trailing steps past the
    # epoch are weight-0 no-ops, so every call has identical static shape).
    max_steps_per_call: int | None = None


class Trainer:
    """Compiles and runs the PS training loop for one WorkerLogic.

    Equivalent of ``FlinkParameterServer.transform(trainingData, workerLogic,
    psLogic, workerParallelism, psParallelism, iterationWaitTime)`` — but the
    "transform" output stream is returned as a per-chunk metrics pytree (the
    reference's ``WOut`` channel) plus the live sharded tables (the
    reference's end-of-job model stream).
    """

    def __init__(
        self,
        mesh,
        param_store: ParamStore,
        worker_logic: WorkerLogic,
        server_logic: Mapping[str, ServerLogic] | ServerLogic = ServerLogic(),
        config: TrainerConfig | None = None,
        recorder=None,
        audit=None,
    ):
        # Telemetry (fps_tpu.obs.Recorder) — host-side only, never part of
        # the traced program or the compile cache key; None (default) means
        # the drivers skip every obs call. Assignable after construction
        # (``trainer.recorder = rec``) and overridable per fit_stream /
        # run_indexed call.
        self.recorder = recorder
        # Opt-in compile-time program certification (fps_tpu.analysis):
        # a ProgramAuditor / ProgramContract / True / "strict". Every
        # program this trainer compiles is lowered once more on its first
        # call, run through the static-analysis pass suite against the
        # contract (default: contract_for_trainer — donation, host
        # transfers, dtype drift, and the hot-tier reconcile psum when
        # tiering resolves on), and reported through the recorder as
        # analysis.certified_programs / analysis.contract_violations
        # metrics plus an analysis.contract_violation event per finding
        # ("strict" raises ContractViolationError instead). Host-side
        # only — the executed program is untouched. Set it BEFORE the
        # first compiled call: like the guard, certification attaches at
        # program build time (already-cached programs are not re-audited).
        if audit is not None:
            from fps_tpu import analysis

            # Fail fast on typos here, not on the first dispatch;
            # False normalizes to None (disabled), so boolean flags
            # wire straight through.
            audit = analysis.as_auditor(audit)
        self.audit = audit
        self.mesh = mesh
        self.store = param_store
        self.logic = worker_logic
        if isinstance(server_logic, ServerLogic):
            server_logic = {name: server_logic for name in param_store.specs}
        self.server_logic = dict(server_logic)
        self.config = config or TrainerConfig()
        guard = resilience.as_guard(self.config.guard)  # fail fast on typos
        if guard is not None and guard.tables is not None:
            unknown = set(guard.tables) - set(param_store.specs)
            if unknown:
                raise ValueError(
                    f"guard.tables names unknown tables {sorted(unknown)} — "
                    f"store has {sorted(param_store.specs)}; a typo here "
                    "would silently disable the guard"
                )
        if (guard is not None and guard.local
                and resilience.LOCAL_STATE_KEY in param_store.specs):
            raise ValueError(
                f"guard.local reserves the {resilience.LOCAL_STATE_KEY!r} "
                "health-channel entry, but the store has a table of that "
                "name — rename the table or disable the local guard"
            )
        self.num_shards = mesh.shape[SHARD_AXIS]
        self.num_workers = num_workers_of(mesh)
        # Adaptive tiering (fps_tpu.tiering.Retierer) — host-side hot-set
        # manager. Assignable after construction, BEFORE the first
        # compiled call (mapped-tier/tracking resolution is part of the
        # compile key, like the guard); TrainerConfig.auto_tier attaches
        # a default one at run entry.
        self.retierer = None
        self._tier_warned: set[str] = set()

        self._table_sharding = NamedSharding(mesh, P(SHARD_AXIS, None))
        self._worker_sharding = NamedSharding(mesh, P(WORKER_AXES))
        self._replicated = NamedSharding(mesh, P())
        self._compiled = {}

    # -- state ------------------------------------------------------------

    def init_state(self, key: Array) -> tuple[dict[str, Array], Pytree]:
        tables = self.store.init(jax.random.fold_in(key, 0))
        ls_key = jax.random.fold_in(key, 1)

        def make_local_state():
            return self.logic.init_local_state(ls_key, self.num_workers)

        local_state = jax.jit(
            make_local_state,
            out_shardings=jax.tree.map(lambda _: self._worker_sharding,
                                       jax.eval_shape(make_local_state)),
        )()
        return tables, local_state

    # -- checkpoint plumbing ----------------------------------------------

    def _host_local_state(self, local_state):
        """Local state as host numpy — multi-controller safe (cross-host
        leaves replicate through a jitted identity, a COLLECTIVE: every
        process must reach the checkpoint boundary together, same as the
        table dump)."""
        from fps_tpu.parallel.mesh import replicate_to_mesh

        def to_host(leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                leaf = replicate_to_mesh(leaf, self.mesh)
            return np.asarray(leaf)

        return jax.tree.map(to_host, local_state)

    def _save_checkpoint(self, checkpointer, step: int, local_state, *,
                         tables=None, touched=None, final=False) -> None:
        """Snapshot tables + local state, with the local state in the
        logic's worker-count-independent export form (default: the raw
        layout, tagged either way so a mismatched restore fails loudly).

        ``tables``: optional on-device boundary copies to snapshot from
        instead of the live store — the overlapped pipeline takes them at
        the chunk boundary and runs the save after the NEXT dispatch, by
        which time the live tables already hold a later chunk's state.
        With an :class:`~fps_tpu.core.checkpoint.AsyncCheckpointer` and
        fully-addressable state, the device→host capture itself defers
        onto the WRITER thread (``save_deferred``) — the training thread
        pays one enqueue and the capture overlaps device compute; the
        multi-controller dump replicates through a COLLECTIVE and must
        stay inline, so non-addressable state falls back to the
        store-swap path below. Otherwise the store's table view is
        swapped in for the duration of the dump (single-threaded: only
        the driver thread touches the store).

        ``touched``: delta-chain sourcing — an ``(ids_by_table, marker,
        tracker)`` capture from a :class:`~fps_tpu.core.checkpoint.
        TouchedRowsTracker`, taken at the SAME boundary as the state
        being saved (the overlapped paths capture alongside their
        on-device boundary copies). The tracker prefix is committed only
        after the checkpointer ACCEPTED the save, so a failed/raced save
        never loses touched ids for the next publication.

        ``final``: the end-of-run save — forced to LAND (``when_full=
        "block"``) even on a checkpointer configured to skip saves while
        its writer is busy; the run's terminal state must be durable."""
        kwargs = {}
        if touched is not None:
            kwargs["touched_rows"] = touched[0]
        if final and hasattr(checkpointer, "when_full"):
            kwargs["when_full"] = "block"
        try:
            if (tables is not None
                    and hasattr(checkpointer, "save_deferred")
                    and self._fully_addressable(tables, local_state)):
                # Writer-side capture: hand the writer a private store
                # view over the boundary copies (shallow copy — specs /
                # mesh / shard layout are stable; only ``tables`` is
                # swapped) plus the on-device local-state copy. The
                # closure runs on the writer thread; everything it
                # touches is either frozen (the copies) or immutable.
                view = copy.copy(self.store)
                view.tables = dict(tables)
                ckpt, logic = checkpointer, self.logic
                ls_dev = local_state

                def collect():
                    return ckpt._collect(
                        view,
                        logic.export_local_state(
                            self._host_local_state(ls_dev)),
                        "exported",
                    )

                checkpointer.save_deferred(step, collect, **kwargs)
            else:
                prev = None
                if tables is not None:
                    prev = self.store.tables
                    self.store.tables = dict(tables)
                try:
                    checkpointer.save(
                        step, self.store,
                        self.logic.export_local_state(
                            self._host_local_state(local_state)
                        ),
                        local_state_format="exported",
                        **kwargs,
                    )
                finally:
                    if prev is not None:
                        self.store.tables = prev
            if touched is not None:
                touched[2].commit(touched[1])
        except Exception as e:
            # A pod fence refusal (StaleEpochError, possibly re-raised
            # from the async writer wrapped in RuntimeError) means this
            # whole PROCESS belongs to an aborted pod attempt: name that
            # plainly at the driver altitude before propagating — the
            # training loop is over either way, and the pod scenarios
            # grep for this line as the zombie's epitaph.
            from fps_tpu.supervise.child import StaleEpochError

            cause = e
            while cause is not None:
                if isinstance(cause, StaleEpochError):
                    _log.error(
                        "run fenced off by the pod at step %d: %s",
                        step, cause)
                    break
                cause = cause.__cause__
            raise

    @staticmethod
    def _fully_addressable(tables, local_state) -> bool:
        """True when every array leaf is fully addressable — the gate
        for writer-thread capture (a non-addressable leaf's dump path
        runs ``replicate_to_mesh``, a COLLECTIVE every process must
        reach together on the training thread)."""
        for leaf in (list(tables.values())
                     + jax.tree.leaves(local_state)):
            if (isinstance(leaf, jax.Array)
                    and not leaf.is_fully_addressable):
                return False
        return True

    def restore_checkpoint(self, checkpointer, local_state_like, *,
                           step: int | None = None):
        """Restore a snapshot onto THIS trainer's mesh — elastic across
        shard counts (tables always) and worker counts (for "exported"
        snapshots whose logic implements ``import_local_state``; raw
        leaves must match ``local_state_like``'s shapes, i.e. same worker
        count).

        ``local_state_like`` supplies structure/shardings — pass the
        local state from :meth:`init_state`. Returns
        ``(tables, local_state, step)``.
        """
        step, values, leaves, fmt = checkpointer.read_snapshot(step)
        tables = checkpointer.load_tables(self.store, step, values)
        imported = NotImplemented
        if fmt == "exported":
            imported = self.logic.import_local_state(
                leaves, self.num_workers
            )
        if imported is NotImplemented:
            # Raw device layout (or an identity-export logic): shapes must
            # match the current worker count's local state exactly.
            like_leaves, treedef = jax.tree.flatten(local_state_like)
            if len(like_leaves) != len(leaves):
                raise ValueError(
                    f"checkpoint step {step} has {len(leaves)} local-state "
                    f"leaves, local_state_like has {len(like_leaves)}"
                )
            for saved, like in zip(leaves, like_leaves):
                if hasattr(like, "shape") and saved.shape != like.shape:
                    raise ValueError(
                        f"checkpoint local-state leaf shape {saved.shape} "
                        f"!= expected {like.shape} — was the snapshot taken "
                        "at a different worker count with a logic that has "
                        "no import_local_state?"
                    )
            imported = jax.tree.unflatten(treedef, leaves)
        placed = jax.tree.map(
            lambda leaf, like: host_to_sharded(
                np.asarray(leaf, getattr(like, "dtype", None)), like.sharding
            ) if isinstance(like, jax.Array) else leaf,
            imported,
            local_state_like,
        )
        return tables, placed, step

    # -- device-side bodies ----------------------------------------------

    def _resolve_hot_rows(self, spec) -> int:
        """LOCAL hot-row count for a table's push scatter.

        ``hot_ids="auto"`` routes the WHOLE shard slice through the packed
        MXU scatter when it is thinner than the measured crossover
        (:func:`fps_tpu.ops.packed_crossover_rows`) — the many-shard
        regime; on fat shards it resolves to 0 (plain XLA scatter, exact).
        An int is the NuPS-style global head count: global hot ids [0, H)
        sit in local rows [0, ceil(H/S)) under the owner-major cyclic
        layout.
        """
        from fps_tpu.core.store import rows_per_shard

        if spec.hot_ids == "auto":
            rps = rows_per_shard(spec.num_ids, self.num_shards)
            return rps if rps <= ops.packed_crossover_rows(spec.dim) else 0
        if isinstance(spec.hot_ids, str):
            # Fail at the right altitude — inside the jitted push this
            # would surface as a cryptic TypeError on a unary minus.
            raise ValueError(
                f"table {spec.name!r}: hot_ids={spec.hot_ids!r} — "
                "expected an int or the literal 'auto'"
            )
        return -(-spec.hot_ids // self.num_shards) if spec.hot_ids else 0

    def _resolve_dense(self, spec) -> bool:
        """Dense-collective route for this table on this mesh (see
        ``TableSpec.dense_collectives``). Static per trainer — part of the
        traced program, keyed into the compile cache via the mesh+spec."""
        from fps_tpu.core.store import rows_per_shard

        if self.num_shards * self.mesh.shape[DATA_AXIS] == 1:
            return False  # no collectives to save; gathered route is free
        if spec.dense_collectives == "auto":
            rps = rows_per_shard(spec.num_ids, self.num_shards)
            table_bytes = (
                rps * self.num_shards * spec.dim
                * jnp.dtype(spec.dtype).itemsize
            )
            return table_bytes <= ops.DENSE_TABLE_BYTES
        if isinstance(spec.dense_collectives, str):
            raise ValueError(
                f"table {spec.name!r}: dense_collectives="
                f"{spec.dense_collectives!r} — expected a bool or 'auto'"
            )
        return bool(spec.dense_collectives)

    def _resolve_hot_tier(self, spec) -> int:
        """GLOBAL replicated-head row count for this table on this mesh
        under the current config (0 = untiered). Static per compiled
        program — keyed into the compile cache alongside hot_sync_every.

        The tier engages only where it can win AND stay correct:

        * multi-device meshes (a single device already pulls/pushes with
          zero collectives);
        * ``hot_sync_every > 1`` — 1 is the exact mode, implemented as
          the untiered program itself (see TrainerConfig);
        * "sum" / "mean" / "max" / "min" server folds: the windowed
          pending buffer carries delta sums (+ counts) or elementwise
          extrema, which commute with those combines; ``apply_fn`` and
          callable combines need per-push combine-then-apply over the
          gathered union, so those tables keep the gathered route
          untouched (the one demotion left — PR 10 moved max/min onto
          the tier via the extremum pending buffer).
        """
        H = spec.hot_tier
        if isinstance(H, str):
            # Fail at the right altitude, like hot_ids/dense_collectives.
            raise ValueError(
                f"table {spec.name!r}: hot_tier={H!r} — expected an int"
            )
        if H < 0:
            raise ValueError(
                f"table {spec.name!r}: hot_tier={H} must be >= 0"
            )
        if not H:
            self._check_hot_fold(spec, 0)
            return 0
        if self.num_shards * self.mesh.shape[DATA_AXIS] == 1:
            self._check_hot_fold(spec, 0)
            return 0
        if self.config.hot_sync_every <= 1:
            self._check_hot_fold(spec, 0)
            return 0
        sl = self.server_logic[spec.name]
        if sl.apply_fn is not None or not (
                isinstance(sl.combine, str)
                and sl.combine in ("sum", "mean", "max", "min")):
            # The one SURPRISING disengagement: single-device meshes and
            # hot_sync_every=1 are documented expected states, but a
            # requested tier silently falling back because of the server
            # fold hides a real semantic limit (windowed accumulation
            # cannot reproduce per-push apply_fn / callable-combine
            # semantics over the gathered union) — say so once,
            # explicitly.
            if spec.name not in self._tier_warned:
                self._tier_warned.add(spec.name)
                fold = ("apply_fn" if sl.apply_fn is not None
                        else "a callable combine")
                msg = (
                    f"table {spec.name!r}: hot_tier={H} requested but the "
                    f"per-push server fold ({fold}) keeps the gathered "
                    "route — windowed hot-delta accumulation commutes "
                    "with the 'sum'/'mean'/'max'/'min' combines only, so "
                    "the tier is disabled for this table (the program "
                    "lowers untiered)"
                )
                warnings.warn(msg, stacklevel=2)
                _log.warning("%s", msg)
            self._check_hot_fold(spec, 0)
            return 0
        H = min(int(H), spec.num_ids)
        self._check_hot_fold(spec, H)
        return H

    def _check_hot_fold(self, spec, resolved_H: int) -> None:
        """Fail loudly when a stateful hot fold cannot engage: silently
        downgrading Adagrad/Adam to plain addition would change the
        optimizer, not just the data plane. Requires the tier to resolve
        ON with FULL replication (a partial head would give head rows the
        adaptive step and cold-tail rows the raw delta — a semantic
        fork), and a sum/mean combine (the fold consumes window delta
        sums)."""
        fold = as_hot_fold(self.server_logic[spec.name].hot_fold)
        if fold is None:
            return
        sl = self.server_logic[spec.name]
        if not resolved_H:
            raise ValueError(
                f"table {spec.name!r}: hot_fold={fold.kind!r} requires the "
                "hot tier to resolve ON (multi-device mesh, hot_tier > 0, "
                "hot_sync_every > 1, no apply_fn) — a silently-ignored "
                "server optimizer would change training semantics"
            )
        if resolved_H < spec.num_ids:
            raise ValueError(
                f"table {spec.name!r}: hot_fold={fold.kind!r} with a "
                f"PARTIAL head (H={resolved_H} < {spec.num_ids}): head "
                "rows would take the adaptive step while cold-tail pushes "
                "fold additively — set hot_tier >= num_ids (the fold's "
                "state shards over the replica axis, so full replication "
                "does not replicate it)"
            )
        if sl.combine not in ("sum", "mean"):
            raise ValueError(
                f"table {spec.name!r}: hot_fold={fold.kind!r} needs a "
                f"'sum'/'mean' combine (got {sl.combine!r}) — the fold "
                "consumes the window's combined delta sum"
            )

    def _hot_tier_map(self) -> dict[str, int]:
        """{table: replicated head rows} for every table the tier resolves
        ON for. Empty dict = the untiered program of old, byte-identical."""
        tier = {}
        for name, spec in self.store.specs.items():
            H = self._resolve_hot_tier(spec)
            if H:
                tier[name] = H
        if tier and self.config.push_delay:
            raise ValueError(
                "hot_tier and push_delay cannot combine: delayed delivery "
                "would re-order the windowed reconcile against the ring "
                "buffer. Disable one (hot tier tables: "
                f"{sorted(tier)})"
            )
        return tier

    def _hot_fold_map(self) -> dict:
        """{table: HotFold} for tables whose resolved tier carries a
        stateful server fold (validated by :meth:`_check_hot_fold`).
        Part of the compile-cache key via :meth:`_server_logic_key`."""
        out = {}
        for name in self._hot_tier_map():
            fold = as_hot_fold(self.server_logic[name].hot_fold)
            if fold is not None:
                out[name] = fold
        return out

    def _cold_compact_map(self) -> dict[str, int]:
        """{table: per-worker cold-lane width} for tables on the
        COMPACTED cold routes (``TableSpec.cold_budget``; docs/
        performance.md "Payload-proportional routing"): a partial hot
        head (0 < H < num_ids) on a non-dense route with a positive
        budget. The compacted program is a distinct compile-cache entry;
        whether a given chunk may dispatch it is the host certifier's
        per-chunk call (:meth:`_certify_cold`)."""
        out = {}
        for name, H in sorted(self._hot_tier_map().items()):
            spec = self.store.specs[name]
            C = int(getattr(spec, "cold_budget", 0) or 0)
            if C <= 0 or H >= spec.num_ids:
                continue
            if self._resolve_dense(spec):
                continue  # dense routes move table-sized payloads anyway
            out[name] = C
        return out

    def _certify_cold(self, host_ids) -> tuple[bool, list[str]]:
        """Host-side per-chunk certification for the compacted cold
        routes: every (step, worker) slice's cold-id count must fit the
        lane. ``host_ids`` is ``WorkerLogic.pulled_ids_host``'s dict (or
        None = uncertifiable). Counts are conservative — padding
        positions count like real ids, exactly as the device-side
        compaction sees them. Returns ``(fits, overflowed_tables)``;
        an uncertifiable chunk reports every compacted table."""
        from fps_tpu.core.ingest import per_worker_cold_counts

        compact = self._cold_compact_map()
        overflowed = []
        for name, C in compact.items():
            arr = None if host_ids is None else host_ids.get(name)
            if arr is None:
                overflowed.append(name)
                continue
            H = self._hot_tier_map()[name]
            member = None
            if name in self._mapped_tables() and self.retierer is not None:
                num_ids = self.store.specs[name].num_ids
                member = np.zeros(num_ids + 1, bool)
                member[self.retierer.hot_ids_for(name, H)] = True
            counts = per_worker_cold_counts(
                arr, self.num_workers, hot_head=H, hot_member=member)
            if int(counts.max(initial=0)) > C:
                overflowed.append(name)
        return not overflowed, overflowed

    def _host_cert_ids(self, chunk):
        """The logic's host certification stream for a raw host chunk
        (None when the logic cannot certify, or nothing is compacted)."""
        if not self._cold_compact_map():
            return None
        return self.logic.pulled_ids_host(chunk)

    def _mapped_tables(self) -> dict[str, int]:
        """{table: H} for tables on the ADAPTIVE (mapped) tier: the
        replica's membership is an arbitrary hot id set carried as
        replicated slot-map/gid DATA arrays, so the attached Retierer
        can re-rank without recompiling. Engages for tiered tables with
        a partial head (0 < H < num_ids) under a Retierer; full
        replication keeps the static elision (every id is hot — there
        is nothing to re-rank), and without a Retierer the static
        frequency-ranked head of old is lowered unchanged. Part of the
        compile-cache key."""
        if self.retierer is None:
            return {}
        out = {}
        for name, H in sorted(self._hot_tier_map().items()):
            if (H < self.store.specs[name].num_ids
                    and self.retierer.manages(name)):
                out[name] = H
        return out

    def _track_specs(self) -> dict:
        """{table: CountMinSpec} for tables whose pulled ids the
        compiled step sketches device-side (fps_tpu.sketch count-min
        windows, psum-merged across the mesh at the end of each call).
        Empty without a Retierer — the tracked program differs from the
        untiered one, so this is part of the compile-cache key.

        Sketching is paid only where a decision can consume it: during
        an auto-plan warmup every managed table (the planner needs
        densities for all of them); afterwards — or when the knobs were
        set by hand — only tables the RESOLVED tier actually maps
        (0 < H < num_ids, the re-rankable regime). Gating on the
        resolution, not the raw spec, keeps the documented
        disengagement states honest: hot_sync_every=1 / single-device /
        non-additive folds still lower the exact untiered program even
        with a Retierer attached (tested)."""
        if self.retierer is None:
            return {}
        track = self.retierer.track_specs(self.store.specs)
        if self.retierer.auto_plan and not self.retierer.planned:
            return track
        mapped = self._mapped_tables()
        return {n: cm for n, cm in sorted(track.items()) if n in mapped}

    def _attach_hot(self, tables, timer=None):
        """Entry-point re-split: make ``tables`` carry exactly the
        tiering aux entries the current resolution calls for — hot
        replicas (static AND mapped), the adaptive tier's slot-map/gid
        arrays, and the tracker's device sketch windows.

        Replicas are derived from the canonical sharded table — valid at
        any call boundary because every compiled call ends with a flush
        reconcile. Covers every way state reaches a run: ``init_state``,
        ``restore_checkpoint`` (a checkpoint is one canonical table;
        this is the re-split — mapped membership and sketch windows come
        from the Retierer, sidecar-restored under supervision), warm
        starts, and config changes between runs (stale/resized entries
        are dropped and re-derived; a tier turned off strips its entries
        so the lowered program is the untiered one again). Idempotent
        and O(specs) when nothing changed, so the per-chunk call from
        ``run_chunk`` costs dict lookups only.
        """
        tier = self._hot_tier_map()
        mapped = self._mapped_tables()
        track = self._track_specs()
        folds = self._hot_fold_map()
        if not (tier or track) and not any(is_aux_key(k) for k in tables):
            return tables
        out = {}
        for k, v in tables.items():
            if not is_aux_key(k):
                out[k] = v
            elif is_hot_key(k):
                name = hot_base(k)
                if name in tier and v.shape[0] == tier[name]:
                    out[k] = v  # live, correctly-sized replica: keep
            elif k.endswith(MAP_KEY_SUFFIX):
                name = k[: -len(MAP_KEY_SUFFIX)]
                if (name in mapped and v.shape[0]
                        == self.store.specs[name].num_ids + 1):
                    out[k] = v
            elif k.endswith(IDS_KEY_SUFFIX):
                name = k[: -len(IDS_KEY_SUFFIX)]
                if name in mapped and v.shape[0] == mapped[name]:
                    out[k] = v
            elif k.endswith(SKETCH_KEY_SUFFIX):
                name = k[: -len(SKETCH_KEY_SUFFIX)]
                cm = track.get(name)
                if cm is not None and v.shape == (cm.depth, cm.width):
                    out[k] = v
            elif k.endswith(FOLD_KEY_SUFFIX):
                name = k[: -len(FOLD_KEY_SUFFIX)]
                fold = folds.get(name)
                if fold is not None and tuple(v.shape) == tuple(
                        hot_fold_state_shape(
                            fold, tier[name],
                            self.store.specs[name].dim,
                            self.num_shards)):
                    out[k] = v  # live/restored state: keep (not derivable)
        missing_hot = [n for n in sorted(tier) if hot_key(n) not in out]
        missing_map = [n for n in sorted(mapped)
                       if map_key(n) not in out or ids_key(n) not in out]
        missing_sk = [n for n in sorted(track)
                      if sketch_key(n) not in out]
        missing_fold = [n for n in sorted(folds)
                        if fold_key(n) not in out]
        if not (missing_hot or missing_map or missing_sk or missing_fold):
            return out
        # Only an actual derivation pays (and records) the reconcile
        # phase — the steady-state per-chunk call is pure dict checks.
        with _phase(timer, "reconcile"):
            for name in missing_hot:
                if name in mapped:
                    gids = self.retierer.hot_ids_for(name, mapped[name])
                    out[hot_key(name)] = self.store.rows_replica(
                        name, gids, out[name])
                else:
                    out[hot_key(name)] = self.store.head_replica(
                        name, tier[name], out[name])
            for name in missing_map:
                gids = self.retierer.hot_ids_for(name, mapped[name])
                out[ids_key(name)] = jax.device_put(
                    np.asarray(gids, np.int32), self._replicated)
                out[map_key(name)] = jax.device_put(
                    hot_slot_map(self.store.specs[name].num_ids, gids),
                    self._replicated)
            for name in missing_sk:
                cm = track[name]
                win = (self.retierer.device_window(name)
                       if self.retierer is not None else None)
                if win is None or win.shape != (cm.depth, cm.width):
                    win = np.zeros((cm.depth, cm.width), np.float32)
                out[sketch_key(name)] = jax.device_put(
                    np.asarray(win, np.float32), self._replicated)
            for name in missing_fold:
                # Fresh (zero) optimizer state, SHARDED over the shard
                # axis in reduce-scatter slice order; restored states
                # arrive already in ``tables`` (checkpoint ``fold::``
                # arrays) and were kept above.
                shape = hot_fold_state_shape(
                    folds[name], tier[name],
                    self.store.specs[name].dim, self.num_shards)
                out[fold_key(name)] = jax.device_put(
                    np.zeros(shape, np.float32), self._table_sharding)
        return out

    def _enter_tiering(self) -> None:
        """Run-entry adaptive-tiering hook (both drivers): auto-attach
        the default Retierer when ``TrainerConfig.auto_tier`` asks for
        one, and re-apply a (sidecar-)restored plan so the tier
        resolution — and with it the compile key — matches the
        interrupted run before the first compiled call."""
        if self.config.auto_tier and self.retierer is None:
            from fps_tpu.tiering import Retierer

            self.retierer = Retierer.auto_for(self)
        if self.retierer is not None:
            if self.retierer.auto_plan and self.config.push_delay:
                # Same contract as the explicit hot_tier+push_delay
                # rejection, enforced at run entry instead of blowing up
                # at the first check boundary when the planner's tier
                # lands mid-run.
                raise ValueError(
                    "auto_tier and push_delay cannot combine: the "
                    "planner would enable a hot tier whose windowed "
                    "reconcile re-orders against the delayed-push ring "
                    "buffer. Disable one."
                )
            self.retierer.on_run_entry(self)

    def _head_prefix(self, batch) -> dict:
        """Resolve the worker's head-prefix guarantee for this batch.

        Honored only on single-device meshes: the collective pull/push
        routes reorder the id streams (all_gather across workers, physical
        re-indexing in the dense route), voiding the leading-ids
        guarantee. Requires the table to declare its frequency head via
        ``spec.hot_ids`` (an int H — the prefix ids must lie in
        ``[0, H) ∪ {-1}``)."""
        if self.num_shards * self.mesh.shape[DATA_AXIS] != 1:
            return {}
        out = {}
        for name, n in (self.logic.head_prefix(batch) or {}).items():
            spec = self.store.specs.get(name)
            if (spec is not None and isinstance(spec.hot_ids, int)
                    and spec.hot_ids > 0 and n):
                out[name] = int(n)
        return out

    def _apply_pushes(self, tables, pushes, head_prefix=None):
        head_prefix = head_prefix or {}
        new_tables = dict(tables)
        # named_scope: pure HLO metadata — the device-profile analog of the
        # host PhaseTimer (pull/compute/push are fused into one dispatch,
        # so their split is only visible on the traced timeline).
        with jax.named_scope("fps.push"):
            new_tables.update(self._apply_pushes_inner(tables, pushes,
                                                       head_prefix))
        return new_tables

    def _apply_pushes_inner(self, tables, pushes, head_prefix):
        new_tables = {}
        for name, (pids, pdeltas) in pushes.items():
            spec = self.store.specs[name]
            hot_local = self._resolve_hot_rows(spec)
            new_tables[name] = push(
                tables[name],
                pids,
                pdeltas,
                num_shards=self.num_shards,
                shard_axis=SHARD_AXIS,
                data_axis=DATA_AXIS if self.mesh.shape[DATA_AXIS] > 1 else None,
                apply_fn=self.server_logic[name].apply_fn,
                combine=self.server_logic[name].combine,
                hot_rows=hot_local,
                dense=self._resolve_dense(spec),
                head_prefix=head_prefix.get(name, 0),
            )
        return new_tables

    def _compute_step(self, tables, snapshot, local_state, batch, key,
                      hot=None, tier=None, maps=None, track=None,
                      sk=None, compact=None):
        """Pull (from live tables, or the SSP ``snapshot`` when given), run
        the worker step, and return its pushes WITHOUT applying them,
        plus the (static) head-prefix guarantee for those pushes, the
        hot-tier pull accounting ({} when the tier is off — nothing extra
        is traced then), and the updated sketch accumulators.

        ``hot``/``tier``: the replicated hot-head arrays and the resolved
        {table: H} map. Sync-mode pulls partition on hot membership: hot
        rows are a LOCAL replica gather (zero collectives — when H covers
        the whole table the collective route is statically elided
        outright); cold rows ride the existing routes with hot slots
        masked to -1 (the zero-row contract). Membership is ``id < H``
        on the static tier, or a replicated slot-map lookup on the
        ADAPTIVE tier (``maps`` — arbitrary hot id set as DATA, so
        re-ranks never recompile). SSP pulls already read a local
        snapshot whose head rows match the replica (reconcile precedes
        each round's gather), so they stay untouched.

        ``track``/``sk``: online frequency tracking — every tracked
        table's pulled ids fold into its count-min window accumulator
        (a local scatter-add; the psum merge happens once per call).
        """
        tier = tier or {}
        maps = maps or {}
        track = track or {}
        compact = compact or {}
        key, prep_key = jax.random.split(key)
        batch = self.logic.prepare(batch, prep_key)
        ids = self.logic.pull_ids(batch)
        hp = self._head_prefix(batch)
        if track:
            sk = dict(sk)
            with jax.named_scope("fps.sketch"):
                for name in sorted(track):
                    if name in ids:
                        sk[name] = _sketch.cm_update(
                            track[name], sk[name], ids[name])
        hot_counts = {}
        # fps.pull / fps.compute named scopes: device-timeline attribution
        # for the phases the host PhaseTimer cannot split (pull, worker
        # compute, and push fuse into one dispatch) — pure op metadata,
        # visible under obs.trace() / --profile, free otherwise.
        with jax.named_scope("fps.pull"):
            if snapshot is None:
                pulled = {}
                for name, tids in ids.items():
                    H = tier.get(name, 0)
                    spec = self.store.specs[name]
                    # Hit-rate accounting only where the replica actually
                    # serves the reads: SSP pulls come from the per-round
                    # snapshot, so counting them would misattribute
                    # snapshot gathers as collective-free tier hits.
                    if H:
                        live = jnp.sum(tids >= 0, dtype=jnp.int32)
                    if H >= spec.num_ids:
                        # Fully-replicated table: the collective route is
                        # statically gone — a plain local gather.
                        pulled[name] = ops.gather_rows(hot[name], tids)
                        hot_counts[name] = {"hot_rows": live,
                                            "pulled_rows": live}
                        continue
                    if H and name in maps:
                        # Adaptive tier: membership by slot-map lookup.
                        slot = lookup_hot_slots(maps[name], tids)
                        hmask = slot >= 0
                        hot_vals = ops.gather_rows(
                            hot[name],
                            jnp.where(hmask, slot,
                                      jnp.asarray(-1, slot.dtype)))
                        tids = jnp.where(hmask,
                                         jnp.asarray(-1, tids.dtype), tids)
                    elif H:
                        hot_vals, hmask = pull_hot(hot[name], tids,
                                                   hot_ids=H)
                        tids = jnp.where(hmask,
                                         jnp.asarray(-1, tids.dtype), tids)
                    if H:
                        hot_counts[name] = {
                            "hot_rows": jnp.sum(hmask, dtype=jnp.int32),
                            "pulled_rows": live,
                        }
                    if H and name in compact:
                        # Payload-proportional cold pull: pack the cold
                        # residue into the certified lane, pull O(lane)
                        # through the collective route, scatter the lane
                        # rows back to their batch positions (masked /
                        # dropped slots read zero rows — the -1
                        # contract).
                        lane_ids, _, pos, over = compact_cold(
                            tids, None, budget=compact[name])
                        lane_vals = pull(
                            tables[name], lane_ids,
                            num_shards=self.num_shards,
                            dense=False,
                            hot_rows=self._resolve_hot_rows(spec),
                        )
                        vals = ops.gather_rows(lane_vals, pos)
                        hot_counts[name]["cold_dropped"] = over
                    else:
                        vals = pull(
                            tables[name], tids, num_shards=self.num_shards,
                            dense=self._resolve_dense(spec),
                            hot_rows=self._resolve_hot_rows(spec),
                            head_prefix=hp.get(name, 0),
                        )
                    if H:
                        vals = jnp.where(hmask[:, None], hot_vals, vals)
                    pulled[name] = vals
            else:
                pulled = {}
                for name, tids in ids.items():
                    rps = tables[name].shape[0]
                    # -1 padding ids must stay -1 (the zero-row pull
                    # contract): id_to_phys's floor-mod would wrap them onto
                    # the live row (S-1)*rps-1 when num_shards > 1 — the
                    # same hazard the dense pull in store.py guards.
                    phys = jnp.where(
                        tids >= 0, id_to_phys(tids, self.num_shards, rps), -1)
                    # ops.gather_rows (not a bare take): dim-1 snapshot reads
                    # ride the same lane-packed kernel as live pulls on TPU.
                    # phys == ids on the single-device meshes where hp is
                    # nonempty, so the head guarantee survives the mapping.
                    pulled[name] = ops.gather_rows(
                        snapshot[name], phys,
                        hot_rows=self._resolve_hot_rows(
                            self.store.specs[name]),
                        head_prefix=hp.get(name, 0),
                    )
        with jax.named_scope("fps.compute"):
            out = self.logic.step(batch, pulled, local_state, key)
        pushes, outch, new_local = out.pushes, out.out, out.local_state
        guard = resilience.as_guard(self.config.guard)
        if guard is not None:
            # Trace-time static: guard=None compiles byte-identically to a
            # guard-free build (tested via lowered-HLO comparison).
            pushes, health = resilience.guard_pushes(pushes, guard)
            if guard.local:
                # Same screening for the worker-LOCAL plane: revert (mask)
                # or count (observe) poisoned local-state rows, mounted on
                # the health channel under the reserved "local_state" key
                # (collision with a table name rejected at construction).
                # Logics that expose which rows a batch touches
                # (touched_local_rows) get ids-aware screening: row
                # masking restricted to the touched set, untouched rows
                # still netted by the leaf-tier non-finite count.
                new_local, local_health = resilience.guard_local_state(
                    local_state, new_local, guard,
                    touched=self.logic.touched_local_rows(batch),
                )
                if local_health is not None:
                    health[resilience.LOCAL_STATE_KEY] = local_health
            if health:
                if not isinstance(outch, dict):
                    raise TypeError(
                        "TrainerConfig.guard requires the worker's out "
                        "channel to be a dict so the health counters can "
                        f"ride it (got {type(outch).__name__})"
                    )
                if resilience.HEALTH_KEY in outch:
                    raise ValueError(
                        "the worker's out channel already has a 'health' "
                        "key — it would collide with the guard's counters"
                    )
                outch = dict(outch, **{resilience.HEALTH_KEY: health})
        return pushes, new_local, outch, hp, hot_counts, sk

    # -- delayed pushes (async in-flight emulation) ------------------------

    def _init_push_bufs(self, tables, local_state, batch_like, key):
        """Ring buffers of the last ``push_delay`` steps' pushes per table.

        Shapes come from a collective-free ``eval_shape`` probe of the
        worker logic. Slots start as dropped pushes (ids ``-1``), so the
        first ``push_delay`` steps deliver nothing — a cold asynchronous
        start, like the reference's empty network queues.
        """
        d = self.config.push_delay

        def probe(batch, local_state, key):
            key, prep_key = jax.random.split(key)
            b = self.logic.prepare(batch, prep_key)
            ids = self.logic.pull_ids(b)
            pulled = {
                name: jnp.zeros(
                    tids.shape + (tables[name].shape[-1],),
                    tables[name].dtype,
                )
                for name, tids in ids.items()
            }
            return self.logic.step(b, pulled, local_state, key).pushes

        shapes = jax.eval_shape(probe, batch_like, local_state, key)
        return {
            name: (
                jnp.full((d,) + ids_s.shape, -1, ids_s.dtype),
                jnp.zeros((d,) + del_s.shape, del_s.dtype),
            )
            for name, (ids_s, del_s) in shapes.items()
        }

    def _gather_workers(self, x):
        """Stack a per-worker leaf into (W, ...) in worker_index order."""
        x = lax.all_gather(x, SHARD_AXIS)  # (S, ...)
        x = lax.all_gather(x, DATA_AXIS)  # (D, S, ...)
        return x.reshape((self.num_workers,) + x.shape[2:])

    def _run_tap(self, out, tables, batch, local_state, t):
        tap = self.config.step_tap
        if tap is None:
            return out
        if not isinstance(out, dict):
            raise TypeError(
                "step_tap requires the worker's out channel to be a dict "
                f"(got {type(out).__name__})"
            )
        if "tap" in out:
            raise ValueError(
                "the worker's out channel already has a 'tap' key — it "
                "would be silently clobbered by the step_tap output"
            )
        tapped = tap(tables, batch, local_state, t)
        return dict(out, tap=jax.tree.map(self._gather_workers, tapped))

    def _apply_or_buffer(self, tables, bufs, t, pushes, head_prefix=None):
        """Apply ``pushes`` now (push_delay 0) or deliver the pushes from
        ``push_delay`` steps ago and enqueue the new ones in their slot.
        Ring slots preserve the push layout, so the head-prefix guarantee
        carries over to delayed deliveries unchanged."""
        d = self.config.push_delay
        if not d:
            return self._apply_pushes(tables, pushes, head_prefix), bufs
        slot = t % d
        new_bufs = {}
        delayed = {}
        for name, (ids, deltas) in pushes.items():
            bids, bdel = bufs[name]
            delayed[name] = (
                lax.dynamic_index_in_dim(bids, slot, 0, keepdims=False),
                lax.dynamic_index_in_dim(bdel, slot, 0, keepdims=False),
            )
            new_bufs[name] = (
                lax.dynamic_update_index_in_dim(bids, ids, slot, 0),
                lax.dynamic_update_index_in_dim(bdel, deltas, slot, 0),
            )
        return self._apply_pushes(tables, delayed, head_prefix), new_bufs

    def _flush_push_bufs(self, tables, bufs, t, head_prefix=None):
        """Deliver everything still in flight, oldest first (end of call).

        Cold ring slots hold all ``-1`` ids with zero deltas — inside the
        head-prefix contract, so the guarantee applies to them too."""
        d = self.config.push_delay
        if not d:
            return tables

        def body(k, tables):
            slot = (t + k) % d
            pending = {
                name: (
                    lax.dynamic_index_in_dim(bids, slot, 0, keepdims=False),
                    lax.dynamic_index_in_dim(bdel, slot, 0, keepdims=False),
                )
                for name, (bids, bdel) in sorted(bufs.items())
            }
            return self._apply_pushes(tables, pending, head_prefix)

        return lax.fori_loop(0, d, body, tables)

    # -- two-tier hot storage (device-side step/window plumbing) ----------

    def _hot_combine(self, name: str) -> str:
        return self.server_logic[name].combine

    def _hot_fold(self, name: str):
        return self._hot_fold_map().get(name)

    def _init_hot_deltas(self, tables, tier):
        """Fresh per-device pending-delta buffers ({} when untiered).
        Created inside the traced call and flushed before it returns, so
        they never exist at a host-visible boundary."""
        return {
            name: hot_delta_init(
                H, tables[name].shape[1], tables[name].dtype,
                combine=self._hot_combine(name),
                fold=self._hot_fold(name),
            )
            for name, H in tier.items()
        }

    def _apply_hot_split(self, tables, delta, pushes, tier, hp,
                         maps=None, compact=None):
        """Partition each table's pushes on hot membership (``id < H``
        statically, or the adaptive tier's slot-map lookup), apply the
        cold part through the existing routes (statically elided when H
        covers the table, COMPACTED to the ``cold_budget`` lane when the
        table rides the payload-proportional route) and fold the hot
        part into the pending buffers. Returns the per-table count of
        budget-overflow drops alongside (always zero for host-certified
        chunks — the device-side observability net)."""
        if not tier:
            return self._apply_pushes(tables, pushes, hp), delta, {}
        maps = maps or {}
        compact = compact or {}
        cold_pushes = {}
        dropped = {}
        new_delta = dict(delta)
        with jax.named_scope("fps.hot_accumulate"):
            for name, (pids, pdeltas) in pushes.items():
                H = tier.get(name, 0)
                if not H:
                    cold_pushes[name] = (pids, pdeltas)
                    continue
                spec = self.store.specs[name]
                if H >= spec.num_ids:
                    hots = (pids, pdeltas)  # no cold residue to push
                elif name in maps:
                    # Adaptive tier: the hot half lands in SLOT space —
                    # the delta buffer is slot-indexed like the replica.
                    slot = lookup_hot_slots(maps[name], pids)
                    cold_pushes[name], hots = split_hot_push_slots(
                        pids, pdeltas, slot
                    )
                else:
                    cold_pushes[name], hots = split_hot_push(
                        pids, pdeltas, hot_ids=H
                    )
                if name in cold_pushes and name in compact:
                    # Payload-proportional cold push: pack the residue
                    # into the certified lane before the collective.
                    cids, cdeltas = cold_pushes[name]
                    lane_ids, lane_deltas, _, over = compact_cold(
                        cids, cdeltas, budget=compact[name])
                    cold_pushes[name] = (lane_ids, lane_deltas)
                    dropped[name] = dropped.get(name, 0) + over
                new_delta[name] = accumulate_hot(
                    delta[name], *hots,
                    combine=self._hot_combine(name),
                    fold=self._hot_fold(name),
                )
        return self._apply_pushes(tables, cold_pushes, hp), new_delta, dropped

    def _reconcile_carry(self, carry, tier, gids=None):
        """Window-boundary reconcile over every tiered table (identity
        when untiered): one reduce-scatter → owned-slice apply →
        all-gather per table (pmax/pmin for the extremum combines) folds
        the pending buffers into replica + canonical table, advances any
        sharded fold state, and resets the buffers. ``gids`` maps
        adaptive-tier tables to their replicated slot->global-id arrays
        (DATA — the mapped reconcile scatters into whichever canonical
        rows the current ranking names, without recompiling)."""
        if not tier:
            return carry
        gids = gids or {}
        tables, hot, delta, folds = (carry[0], carry[1], carry[2],
                                     carry[3])
        tables, hot, delta = dict(tables), dict(hot), dict(delta)
        folds = dict(folds)
        data_axis = DATA_AXIS if self.mesh.shape[DATA_AXIS] > 1 else None
        with jax.named_scope("fps.reconcile"):
            for name, H in sorted(tier.items()):
                fold = self._hot_fold(name)
                fstate = folds.get(name)
                if name in gids:
                    (tables[name], hot[name], delta[name],
                     fstate) = reconcile_hot_mapped(
                        tables[name], hot[name], delta[name],
                        gids[name],
                        num_shards=self.num_shards,
                        data_axis=data_axis,
                        combine=self._hot_combine(name),
                        fold=fold, fold_state=fstate,
                    )
                else:
                    (tables[name], hot[name], delta[name],
                     fstate) = reconcile_hot(
                        tables[name], hot[name], delta[name],
                        num_shards=self.num_shards,
                        data_axis=data_axis,
                        combine=self._hot_combine(name),
                        fold=fold, fold_state=fstate,
                    )
                if fstate is not None:
                    folds[name] = fstate
        return (tables, hot, delta, folds) + tuple(carry[4:])

    def _windowed_scan(self, step, carry0, tier, *, head, tail,
                       gids=None):
        """Scan in reconcile windows: ``head`` is the stacked xs of the
        full windows (leading dims ``(R, E)``, or None when R == 0),
        ``tail`` the ragged remainder's xs (or None). Each window — and
        the tail — ends in a reconcile, so the final carry always holds
        one canonical table. Shared by the chunked and indexed sync
        builders so the window/flush semantics cannot drift between the
        two drivers."""

        def window_body(c, xs_w):
            c, o = lax.scan(step, c, xs_w)
            return self._reconcile_carry(c, tier, gids), o

        parts, carry = [], carry0
        if head is not None:
            carry, outs_h = lax.scan(window_body, carry, head)
            parts.append(jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), outs_h))
        if tail is not None:
            carry, outs_t = lax.scan(step, carry, tail)
            carry = self._reconcile_carry(carry, tier, gids)
            parts.append(outs_t)
        outs = parts[0] if len(parts) == 1 else jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        return carry, outs

    def _mount_hot_channel(self, out, hot_counts, delta, tier,
                           dropped=None):
        """Attach the hot-tier telemetry to the worker out channel (the
        health channel's transport): per-table hit counts plus the
        pending-buffer magnitude — the parameter-plane staleness gauge —
        and, on the compacted cold routes, the budget-overflow drop
        count (zero for every host-certified chunk). Traced only when
        the tier is on; same dict/collision contract as the guard's
        health entry."""
        if not tier:
            return out
        if not isinstance(out, dict):
            raise TypeError(
                "TableSpec.hot_tier requires the worker's out channel to "
                "be a dict so the hot-tier counters can ride it (got "
                f"{type(out).__name__})"
            )
        if resilience.HOT_TIER_KEY in out:
            raise ValueError(
                "the worker's out channel already has a 'hot_tier' key — "
                "it would collide with the tier's counters"
            )
        dropped = dropped or {}
        chan = {}
        for name, H in sorted(tier.items()):
            counts = dict(hot_counts.get(name, {}))
            if name in dropped:
                counts["cold_dropped"] = (
                    counts.get("cold_dropped", 0) + dropped[name])
            buf = delta[name]
            combine = self._hot_combine(name)
            dim = buf.shape[1] - (
                1 if (combine in ("max", "min")
                      or delta_counted(combine, self._hot_fold(name)))
                else 0)
            vals = buf[:, :dim].astype(jnp.float32)
            if combine in ("max", "min"):
                # The extremum buffer is sentinel-filled; only touched
                # rows (indicator column == 1) carry real magnitudes.
                touched = jnp.abs(buf[:, dim]) <= 1.0
                vals = jnp.where(touched[:, None], vals, 0.0)
            # Per-device sum of squared pending deltas (psum'd with the
            # rest of the out channel into the global magnitude).
            counts["delta_sq"] = jnp.sum(vals ** 2)
            chan[name] = counts
        return dict(out, **{resilience.HOT_TIER_KEY: chan})

    def _merge_sketches(self, sketches, sk):
        """End-of-call sketch merge: psum each tracked table's LOCAL
        window accumulator over the worker axes and fold it into the
        (replicated) incoming window — exactly the sketch module's
        additive psum-merge contract, once per compiled call (the
        per-step updates are local scatter-adds). Returns the
        ``::sketch``-keyed output entries; {} when tracking is off, so
        untracked programs trace nothing extra."""
        if not sk:
            return {}
        with jax.named_scope("fps.sketch_merge"):
            return {
                sketch_key(name): sketches[name] + lax.psum(
                    lax.psum(sk[name], SHARD_AXIS), DATA_AXIS)
                for name in sorted(sk)
            }

    # -- compiled chunk runners ------------------------------------------

    def _build_chunk_fn(self, mode: str, compact=None):
        nbatch_dims = 1 if mode == "sync" else 2
        tier = self._hot_tier_map()
        mapped = self._mapped_tables()
        track = self._track_specs()
        folds_on = self._hot_fold_map()
        compact = dict(compact or {})
        E = self.config.hot_sync_every

        def chunk_device(tables, local_state, batches, key):
            # Per-device key stream, decorrelated across workers.
            key = jax.random.fold_in(key, worker_index())
            (tables, hot, maps, gids, sketches,
             fstates) = split_tiering(tables)
            delta = self._init_hot_deltas(tables, tier)
            # Sketch accumulators start at ZERO: each device folds only
            # its own ids, and the end-of-call psum merges exactly the
            # call's traffic into the (replicated) incoming window.
            sk0 = {name: jnp.zeros_like(sketches[name])
                   for name in sorted(track)}
            bufs = None
            if self.config.push_delay:
                batch0 = jax.tree.map(
                    lambda x: x[(0,) * nbatch_dims], batches
                )
                bufs = self._init_push_bufs(tables, local_state, batch0, key)

            hp_seen = {}

            def step_fn(carry, batch_t, snapshot=None):
                (tables, hot, delta, fstates, sk, bufs, local_state,
                 key, t) = carry
                key, sub = jax.random.split(key)
                (pushes, local_state, out, hp, hcounts,
                 sk) = self._compute_step(
                    tables, snapshot, local_state, batch_t, sub,
                    hot=hot, tier=tier, maps=maps, track=track, sk=sk,
                    compact=compact,
                )
                hp_seen.update(hp)  # static, identical every traced step
                dropped = {}
                if tier:
                    tables, delta, dropped = self._apply_hot_split(
                        tables, delta, pushes, tier, hp, maps, compact)
                else:
                    tables, bufs = self._apply_or_buffer(
                        tables, bufs, t, pushes, hp)
                out = self._mount_hot_channel(out, hcounts, delta, tier,
                                              dropped)
                out = jax.tree.map(
                    lambda x: lax.psum(lax.psum(x, SHARD_AXIS), DATA_AXIS), out
                )
                out = self._run_tap(out, tables, batch_t, local_state, t)
                return (tables, hot, delta, fstates, sk, bufs,
                        local_state, key, t + 1), out

            carry0 = (tables, hot, delta, fstates, sk0, bufs,
                      local_state, key, jnp.int32(0))
            if mode == "sync":
                if not tier:
                    carry, outs = lax.scan(step_fn, carry0, batches)
                else:
                    # Windows of E steps, a flush reconcile on the ragged
                    # tail: the call always returns one canonical table.
                    T = jax.tree.leaves(batches)[0].shape[0]
                    R, rem = divmod(T, E)
                    carry, outs = self._windowed_scan(
                        step_fn, carry0, tier,
                        head=jax.tree.map(
                            lambda x: x[:R * E].reshape(
                                (R, E) + x.shape[1:]),
                            batches) if R else None,
                        tail=jax.tree.map(lambda x: x[R * E:], batches)
                        if rem else None,
                        gids=gids,
                    )
                (tables, hot, delta, fstates, sk, bufs, local_state, _,
                 t) = carry
            else:
                # SSP: batches leaves are (R, s, B_local, ...).
                def round_body(carry, batches_r):
                    tables = carry[0]
                    snapshot = {
                        name: lax.all_gather(tb, SHARD_AXIS, tiled=True)
                        for name, tb in sorted(tables.items())
                    }
                    carry, outs = lax.scan(
                        lambda c, b: step_fn(c, b, snapshot), carry,
                        batches_r
                    )
                    # Hot reconcile rides the round boundary: the next
                    # round's snapshot gather must see reconciled head
                    # rows (identity when untiered).
                    return self._reconcile_carry(carry, tier, gids), outs

                (tables, hot, delta, fstates, sk, bufs, local_state, _,
                 t), outs = (
                    lax.scan(round_body, carry0, batches))
                outs = jax.tree.map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), outs
                )
            tables = self._flush_push_bufs(tables, bufs, t, hp_seen)
            tables = {**tables,
                      **{hot_key(n): v for n, v in sorted(hot.items())},
                      **{map_key(n): v for n, v in sorted(maps.items())},
                      **{ids_key(n): v for n, v in sorted(gids.items())},
                      **{fold_key(n): v
                         for n, v in sorted(fstates.items())},
                      **self._merge_sketches(sketches, sk)}
            return tables, local_state, outs

        table_specs = {name: P(SHARD_AXIS, None) for name in self.store.specs}
        table_specs.update({hot_key(name): P() for name in tier})
        table_specs.update({map_key(name): P() for name in sorted(mapped)})
        table_specs.update({ids_key(name): P() for name in sorted(mapped)})
        table_specs.update({sketch_key(name): P()
                            for name in sorted(track)})
        # Fold state: SHARDED over the shard axis (reduce-scatter slice
        # order), replicated over data — never a full copy per device.
        table_specs.update({fold_key(name): P(SHARD_AXIS, None)
                            for name in sorted(folds_on)})
        ls_spec = P(WORKER_AXES)

        def specs_for_batches(batches):
            return jax.tree.map(
                lambda _: P(*([None] * nbatch_dims), WORKER_AXES), batches
            )

        def run(tables, local_state, batches, key):
            shmapped = jax.shard_map(
                chunk_device,
                mesh=self.mesh,
                in_specs=(
                    table_specs,
                    jax.tree.map(lambda _: ls_spec, local_state),
                    specs_for_batches(batches),
                    P(),
                ),
                out_specs=(
                    table_specs,
                    jax.tree.map(lambda _: ls_spec, local_state),
                    P(),  # metrics: psum'd, identical on all devices
                ),
                check_vma=False,
            )
            return shmapped(tables, local_state, batches, key)

        donate = (0, 1) if self.config.donate else ()
        return jax.jit(run, donate_argnums=donate)

    def _server_logic_key(self):
        """Identity key over the per-table server logics: combine modes and
        apply_fns are baked into the compiled program as constants, so
        swapping ``trainer.server_logic['t']`` after a compile must miss
        the cache (same reason the ops backend is in the key). Callables go
        into the key AS OBJECTS (identity hash + a live reference) — a bare
        ``id()`` could be reused by a later callable after the original is
        garbage-collected, silently hitting a stale compiled program."""
        return tuple(
            (name, sl.combine, sl.apply_fn, as_hot_fold(sl.hot_fold))
            for name, sl in sorted(self.server_logic.items())
        )

    def _get_compiled(self, mode: str, compact_ok: bool = True):
        # Keyed on the ops backend, push_delay, and server logic too:
        # set_backend() or a config/logic change after a compile must take
        # effect on the next chunk, not be shadowed by the jit cache.
        # ``compact_ok``: the host certifier's per-chunk verdict — False
        # selects the static (full-payload) cold-route program, so a
        # budget-overflowing chunk dispatches exactly the cold_budget=0
        # program (bit-identical fallback by construction).
        compact = self._cold_compact_map() if compact_ok else {}
        key = (mode, ops.get_backend(), self.config.push_delay,
               self.config.step_tap, resilience.as_guard(self.config.guard),
               self._server_logic_key(), self.config.hot_sync_every,
               tuple(sorted(self._hot_tier_map().items())),
               # Adaptive tiering: the MAPPED set and the tracked sketch
               # specs shape the traced program; the hot id membership
               # itself is DATA, so re-ranks hit this same cache entry —
               # the no-recompile contract tests/test_tiering.py pins.
               tuple(sorted(self._mapped_tables().items())),
               tuple(sorted(self._track_specs().items())),
               tuple(sorted(compact.items())))
        if key not in self._compiled:
            label = "chunk/" + mode + ("+compact" if compact else "")
            self._compiled[key] = self._wrap_audit(
                self._build_chunk_fn(mode, compact), label)
        return self._compiled[key]

    # -- compile-time program certification (fps_tpu.analysis) ------------

    def _wrap_audit(self, fn, label: str):
        """Certify ``fn``'s lowered program on its first call (no-op
        passthrough when ``self.audit`` is unset at build time).

        The wrapper lowers once more — trace cost only, paid once per
        compiled program — and hands the StableHLO text to the auditor;
        the actual dispatch path is the unmodified jitted callable, so
        donation/caching behavior is untouched. ``.lower`` passes
        through for callers (bench.py) that inspect programs directly.
        """
        if self.audit is None:
            return fn
        state = {"done": False}

        def audited(*args):
            if not state["done"]:
                state["done"] = True
                self._audit_program(label, fn, args)
            return fn(*args)

        audited.lower = fn.lower
        audited.__wrapped__ = fn
        audited._fps_audited = True
        return audited

    def _audit_program(self, label: str, fn, args) -> None:
        from fps_tpu import analysis

        auditor = analysis.as_auditor(self.audit)
        if auditor is None:  # disabled after the wrapper was installed
            return
        self.audit = auditor  # keep one auditor (and its certificates)
        try:
            text = fn.lower(*args).as_text()
        except Exception:
            # Lowering for audit must never take down a run the real
            # dispatch would have survived (strict contract FAILURES, by
            # contrast, raise from certify below — that is the point).
            _log.exception("program audit: lowering %r failed; skipping "
                           "certification", label)
            return
        contract = auditor.contract
        if contract is None:
            contract = analysis.contract_for_trainer(
                self, label.split("/", 1)[-1])
        auditor.certify(label, text, contract=contract,
                        recorder=self.recorder)

    def lowered_chunk_text(self, chunk, mode: str = "sync") -> str:
        """StableHLO text of the exact per-chunk program ``fit_stream``
        dispatches for ``chunk``: fresh state with hot replicas
        attached, the chunk placed, the ``mode`` program lowered.

        The one entry point for the static-analysis tools
        (``tools/audit_programs.py``, ``tools/chaos_sweep.py``'s digest
        certificate, ``bench.py``'s tiered A/B) — keeping the
        init/attach/place/lower choreography in one place so a tiered
        trainer can't be lowered without its hot replicas. Read-only on
        the trainer: ``store.init`` writes fresh tables into
        ``store.tables`` in place, so they are restored afterwards —
        certifying after a run must not clobber the trained weights."""
        saved = dict(self.store.tables)
        try:
            tables, ls = self.init_state(jax.random.key(0))
            tables = self._attach_hot(tables)
            placed = self._place_chunk(chunk, mode)
            key = key_to_replicated(jax.random.key(1), self.mesh)
            # Same program selection as run_chunk: a compacted-route
            # trainer lowers the program THIS chunk would dispatch
            # (compacted when it certifies, static otherwise).
            compact_ok = True
            if self._cold_compact_map():
                compact_ok, _ = self._certify_cold(
                    self._host_cert_ids(chunk))
            return self._get_compiled(mode, compact_ok).lower(
                tables, ls, placed, key).as_text()
        finally:
            self.store.tables = saved

    # -- index-fed epochs (ingest fused into the compiled loop) -----------

    def _indexed_call_steps(self, plan) -> int:
        """Steps per compiled call: the whole epoch, capped by
        ``max_steps_per_call`` (rounded to a sync_every multiple)."""
        T = plan.steps_per_epoch
        cap = self.config.max_steps_per_call
        if cap is None or cap >= T:
            return T
        s = self.config.sync_every
        if s:
            if s > cap:
                import warnings

                warnings.warn(
                    f"sync_every={s} exceeds max_steps_per_call={cap}; "
                    "dispatches must contain whole SSP rounds, so each call "
                    f"runs {s} steps — lower sync_every if this risks the "
                    "per-dispatch execution deadline",
                    stacklevel=3,
                )
            cap = max(s, (cap // s) * s)
        return cap

    def _build_indexed_fn(self, plan, mode: str):
        """One jitted program running (a slice of) an epoch: per-step
        batches are gathered from the device-resident dataset inside the
        scan, so an epoch costs a handful of dispatches and zero host↔device
        traffic (:class:`fps_tpu.core.device_ingest.DeviceEpochPlan`)."""
        T = self._indexed_call_steps(plan)
        s = self.config.sync_every
        tier = self._hot_tier_map()
        mapped = self._mapped_tables()
        track = self._track_specs()
        folds_on = self._hot_fold_map()
        E = self.config.hot_sync_every

        def epoch_device(tables, local_state, iargs, start, key):
            widx = worker_index()
            key = jax.random.fold_in(key, widx)
            (tables, hot, maps, gids, sketches,
             fstates) = split_tiering(tables)
            delta = self._init_hot_deltas(tables, tier)
            sk0 = {name: jnp.zeros_like(sketches[name])
                   for name in sorted(track)}
            bufs = None
            if self.config.push_delay:
                # Probe batch for push shapes (unused value, DCE'd by XLA).
                batch0 = plan.local_batch_at(iargs, widx, start)
                bufs = self._init_push_bufs(tables, local_state, batch0, key)

            hp_seen = {}

            def step_t(carry, t, snapshot=None):
                (tables, hot, delta, fstates, sk, bufs, local_state,
                 key) = carry
                key, sub = jax.random.split(key)
                batch = plan.local_batch_at(iargs, widx, t)
                (pushes, local_state, out, hp, hcounts,
                 sk) = self._compute_step(
                    tables, snapshot, local_state, batch, sub,
                    hot=hot, tier=tier, maps=maps, track=track, sk=sk,
                )
                hp_seen.update(hp)  # static, identical every traced step
                dropped = {}
                if tier:
                    tables, delta, dropped = self._apply_hot_split(
                        tables, delta, pushes, tier, hp, maps)
                else:
                    tables, bufs = self._apply_or_buffer(
                        tables, bufs, t, pushes, hp)
                out = self._mount_hot_channel(out, hcounts, delta, tier,
                                              dropped)
                out = jax.tree.map(
                    lambda x: lax.psum(lax.psum(x, SHARD_AXIS), DATA_AXIS), out
                )
                out = self._run_tap(out, tables, batch, local_state, t)
                return (tables, hot, delta, fstates, sk, bufs,
                        local_state, key), out

            def finish(carry, outs):
                (tables, hot, delta, fstates, sk, bufs, local_state,
                 _) = carry
                tables = self._flush_push_bufs(tables, bufs, start + T,
                                               hp_seen)
                tables = {**tables,
                          **{hot_key(n): v for n, v in sorted(hot.items())},
                          **{map_key(n): v for n, v in sorted(maps.items())},
                          **{ids_key(n): v for n, v in sorted(gids.items())},
                          **{fold_key(n): v
                             for n, v in sorted(fstates.items())},
                          **self._merge_sketches(sketches, sk)}
                return tables, local_state, outs

            carry0 = (tables, hot, delta, fstates, sk0, bufs,
                      local_state, key)
            if mode == "sync":
                if not tier:
                    carry, outs = lax.scan(
                        step_t, carry0,
                        start + jnp.arange(T, dtype=jnp.int32),
                    )
                    return finish(carry, outs)
                # Windows of E steps + a flush reconcile on the ragged
                # tail — every call returns one canonical table. The
                # scanned xs are the step indices themselves, stacked
                # (R, E) for the full windows.
                R, rem = divmod(T, E)
                carry, outs = self._windowed_scan(
                    step_t, carry0, tier,
                    head=(start + jnp.arange(R * E, dtype=jnp.int32)
                          .reshape(R, E)) if R else None,
                    tail=(start + R * E
                          + jnp.arange(rem, dtype=jnp.int32))
                    if rem else None,
                    gids=gids,
                )
                return finish(carry, outs)

            def round_body(carry, r):
                tables = carry[0]
                snapshot = {
                    name: lax.all_gather(tb, SHARD_AXIS, tiled=True)
                    for name, tb in sorted(tables.items())
                }
                carry, outs = lax.scan(
                    lambda c, t: step_t(c, t, snapshot), carry,
                    start + r * s + jnp.arange(s, dtype=jnp.int32),
                )
                # Hot reconcile rides the round boundary (identity when
                # untiered): the next snapshot gather sees reconciled
                # head rows.
                return self._reconcile_carry(carry, tier, gids), outs

            carry, outs = lax.scan(
                round_body, carry0, jnp.arange(T // s, dtype=jnp.int32),
            )
            outs = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), outs)
            return finish(carry, outs)

        table_specs = {name: P(SHARD_AXIS, None) for name in self.store.specs}
        table_specs.update({hot_key(name): P() for name in tier})
        table_specs.update({map_key(name): P() for name in sorted(mapped)})
        table_specs.update({ids_key(name): P() for name in sorted(mapped)})
        table_specs.update({sketch_key(name): P()
                            for name in sorted(track)})
        table_specs.update({fold_key(name): P(SHARD_AXIS, None)
                            for name in sorted(folds_on)})
        ls_spec = P(WORKER_AXES)

        def run(tables, local_state, iargs, start, key):
            shmapped = jax.shard_map(
                epoch_device,
                mesh=self.mesh,
                in_specs=(
                    table_specs,
                    jax.tree.map(lambda _: ls_spec, local_state),
                    jax.tree.map(lambda _: P(), iargs),
                    P(),
                    P(),
                ),
                out_specs=(
                    table_specs,
                    jax.tree.map(lambda _: ls_spec, local_state),
                    P(),
                ),
                check_vma=False,
            )
            return shmapped(tables, local_state, iargs, start, key)

        donate = (0, 1) if self.config.donate else ()
        return jax.jit(run, donate_argnums=donate)

    def _check_rollback(self, rollback) -> None:
        if rollback is None:
            return
        if not isinstance(rollback, RollbackPolicy):
            raise TypeError(
                f"rollback must be a RollbackPolicy, got "
                f"{type(rollback).__name__}"
            )
        if resilience.as_guard(self.config.guard) is None and not rollback.preset:
            # Preset-only policies are legal without a guard: skipping
            # already-adjudicated indices needs no health channel. Health-
            # based quarantine does.
            raise ValueError(
                "a rollback policy needs the health channel: set "
                "TrainerConfig.guard ('observe' for pure quarantine "
                "semantics, 'mask' to also drop poison rows in-step)"
            )

    def _check_health(self, health) -> None:
        if health is None:
            return
        if not isinstance(health, HealthMonitor):
            raise TypeError(
                f"health must be a fps_tpu.obs.HealthMonitor, got "
                f"{type(health).__name__}"
            )
        if resilience.as_guard(self.config.guard) is None:
            raise ValueError(
                "a HealthMonitor needs the health channel: set "
                "TrainerConfig.guard ('observe' to run cheap until the "
                "monitor escalates to mask, or 'mask' outright)"
            )

    def _record_health(self, rec, metrics) -> int:
        """Fold one HOST metrics pytree's health channel into the recorder
        (per-table counters) and return the total poisoned-row count the
        HealthMonitor thresholds (nonfinite + norm tiers)."""
        h = (metrics.get(resilience.HEALTH_KEY)
             if isinstance(metrics, Mapping) else None)
        if not h:
            return 0
        poison = 0
        for table, counters in h.items():
            nf = int(np.sum(np.asarray(counters.get("nonfinite", 0))))
            nm = int(np.sum(np.asarray(counters.get("norm", 0))))
            mk = int(np.sum(np.asarray(counters.get("masked", 0))))
            if rec is not None:
                # Zero increments too: a clean guarded run's digest should
                # show the table at 0, not pretend the guard was off.
                rec.inc("health.nonfinite_rows", nf, table=table)
                rec.inc("health.norm_rows", nm, table=table)
                rec.inc("health.masked_rows", mk, table=table)
            poison += nf + nm
        return poison

    def _fold_metrics_accounting(self, rec, metrics, ev=None) -> int:
        """The one per-chunk/epoch telemetry fold for a HOST metrics tree:
        per-table health counters (+ health.poisoned_chunks), example/step
        counters from the ``"n"`` leaf, and — when a journal event dict is
        given — its ``examples``/``poison_rows`` fields. One helper so the
        sync / callback / deferred paths of both drivers cannot drift.
        Returns the poisoned-row total (what HealthMonitor thresholds)."""
        poison = self._record_health(rec, metrics)
        ht = (metrics.get(resilience.HOT_TIER_KEY)
              if isinstance(metrics, Mapping) else None)
        if ht and rec is not None:
            for table, counters in ht.items():
                # .get: a tiered table the worker pushes to but never
                # pulls (or an SSP run, where reads come from the round
                # snapshot, not the replica) carries no pull counters.
                rec.inc("hot_tier.hot_rows",
                        float(np.sum(np.asarray(
                            counters.get("hot_rows", 0)))),
                        table=table)
                rec.inc("hot_tier.pulled_rows",
                        float(np.sum(np.asarray(
                            counters.get("pulled_rows", 0)))),
                        table=table)
                if "cold_dropped" in counters:
                    # Compacted-route overflow drops — ALWAYS zero for
                    # host-certified chunks; nonzero means a certifier
                    # bug, surfaced rather than silently losing updates.
                    rec.inc("hot_tier.cold_dropped",
                            float(np.sum(np.asarray(
                                counters["cold_dropped"]))),
                            table=table)
                # Peak pending-delta magnitude across the call's steps —
                # the parameter-plane staleness gauge (always 0 at the
                # boundary itself: the flush reconcile drained it).
                ds = np.asarray(counters.get("delta_sq", 0.0))
                rec.set("hot_tier.pending_delta",
                        float(np.sqrt(np.max(ds))) if ds.size else 0.0,
                        table=table)
        if rec is not None:
            if poison:
                rec.inc("health.poisoned_chunks")
                if ev is not None:
                    ev["poison_rows"] = poison
            if isinstance(metrics, Mapping) and "n" in metrics:
                n = float(np.sum(metrics["n"]))
                rec.inc("driver.examples", n)
                rec.inc("driver.steps", int(np.shape(metrics["n"])[0]))
                if ev is not None:
                    ev["examples"] = n
        return poison

    def _apply_health_decision(self, health, rec, index, poison, what):
        """Threshold step: feed the monitor and apply its decision —
        escalate swaps this trainer's guard observe→mask (the next
        chunk/epoch recompiles through the guard-keyed cache), abort
        raises PoisonedStreamError after flushing telemetry."""
        if health is None:
            return
        decision = health.update(index, poison)
        if decision == HEALTH_ESCALATE:
            guard = resilience.as_guard(self.config.guard)
            if guard is not None and guard.mode == "observe":
                self.config = dataclasses.replace(
                    self.config,
                    guard=dataclasses.replace(guard, mode="mask"),
                )
                _log.warning(
                    "health monitor: escalating guard observe->mask at "
                    "%s %d (%d poisoned rows >= %d)", what, index,
                    health.poison_rows, health.escalate_after_rows,
                )
                if rec is not None:
                    rec.event("guard_escalated", index=int(index), what=what,
                              poison_rows=health.poison_rows)
        elif decision == HEALTH_ABORT:
            if rec is not None:
                rec.event("health_abort", index=int(index), what=what,
                          poisoned_chunks=health.poisoned_chunks,
                          poison_rows=health.poison_rows)
                rec.flush()
            raise resilience.PoisonedStreamError(
                f"health monitor abort at {what} {index}: "
                f"{health.poisoned_chunks} poisoned {what}s (threshold "
                f"{health.abort_after_chunks}), {health.poison_rows} "
                "poisoned rows total"
            )

    def _maybe_quarantine(self, rollback, last_good, metrics, index, what):
        """Shared rollback step for fit_stream (chunks) and run_indexed
        (epochs): host-sync the metrics and, when the health channel
        reports poison, restore the pre-call state and record the
        quarantine. Returns ``(host_metrics, restored_state_or_None)``.

        Ordering matters: the state (and the store's host-side view) is
        restored BEFORE ``record()``, whose budget check may raise — a
        caller catching PoisonedStreamError must find last-good state, not
        donated or poisoned buffers."""
        metrics = jax.tree.map(np.asarray, metrics)
        poison = resilience.health_total(metrics)
        if not poison:
            return metrics, None
        tables, local_state = last_good
        self.store.tables = dict(tables)
        _log.warning(
            "%s %d poisoned (%d bad push rows): rolled back and "
            "quarantined", what, index, poison,
        )
        rollback.record(index)
        return metrics, (tables, local_state)

    def _get_indexed_fn(self, plan, mode: str):
        """Compiled epoch program for the CURRENT config (looked up per
        epoch, not per run: a HealthMonitor escalation swaps the guard
        mid-run and the next epoch must recompile, keyed on the plan
        object itself — its geometry is baked into the program as
        constants, so identity is the correct key)."""
        ck = ("indexed", mode, plan, ops.get_backend(),
              self.config.push_delay, self.config.step_tap,
              resilience.as_guard(self.config.guard),
              self._server_logic_key(), self.config.hot_sync_every,
              tuple(sorted(self._hot_tier_map().items())),
              tuple(sorted(self._mapped_tables().items())),
              tuple(sorted(self._track_specs().items())))
        if ck not in self._compiled:
            self._compiled[ck] = self._wrap_audit(
                self._build_indexed_fn(plan, mode), f"indexed/{mode}")
        return self._compiled[ck]

    def _get_megastep_fn(self, plan, mode: str, K: int, tick=None):
        """Compiled K-chunk megastep program (fps_tpu.core.megastep) for
        the CURRENT config — cache-keyed like the indexed program, plus
        the chunk count and the tick contract (its decayed-sketch spec,
        cadence, and threshold are trace constants; the hot membership
        and decayed state stay DATA, so in-graph re-ranks never miss
        this entry)."""
        tick_key = None
        if tick is not None:
            tick_key = (tick.spec, tick.check_every,
                        tick.churn_threshold, tick.tables)
        ck = ("megastep", mode, plan, K, ops.get_backend(),
              self.config.step_tap,
              resilience.as_guard(self.config.guard),
              self._server_logic_key(), self.config.hot_sync_every,
              tuple(sorted(self._hot_tier_map().items())),
              tuple(sorted(self._mapped_tables().items())),
              tuple(sorted(self._track_specs().items())),
              tuple(sorted(self._cold_compact_map().items())),
              tick_key)
        if ck not in self._compiled:
            from fps_tpu.core import megastep as _megastep

            self._compiled[ck] = self._wrap_audit(
                _megastep.build_megastep_fn(self, plan, mode, K, tick),
                f"megastep/{mode}")
        return self._compiled[ck]

    def run_megastep(self, tables, local_state, plan, key, *,
                     epochs: int = 1, chunks_per_dispatch: int = 4,
                     on_megastep=None, checkpointer=None,
                     checkpoint_every: int = 0, start_megastep: int = 0,
                     as_numpy: bool = True,
                     rollback: RollbackPolicy | None = None,
                     recorder=None,
                     health: HealthMonitor | None = None,
                     watchdog: StepWatchdog | None = None,
                     tick=None):
        """Run ``epochs`` passes in K-chunk device-resident megasteps —
        one compiled program per ``chunks_per_dispatch`` chunks, with
        reconcile / sketch / tier-tick boundaries executed in-graph and
        a device-side overflow vote selecting the compacted or static
        cold routes per chunk. Bit-identical to the same run driven by
        per-chunk ``run_indexed`` dispatches; see
        :func:`fps_tpu.core.megastep.run_megastep` for the full
        contract."""
        from fps_tpu.core import megastep as _megastep

        return _megastep.run_megastep(
            self, tables, local_state, plan, key, epochs=epochs,
            chunks_per_dispatch=chunks_per_dispatch,
            on_megastep=on_megastep, checkpointer=checkpointer,
            checkpoint_every=checkpoint_every,
            start_megastep=start_megastep, as_numpy=as_numpy,
            rollback=rollback, recorder=recorder, health=health,
            watchdog=watchdog, tick=tick)

    def lowered_megastep_text(self, plan, *, chunks_per_dispatch: int,
                              mode: str = "sync", tick=None) -> str:
        """StableHLO text of the exact megastep program ``run_megastep``
        dispatches — the static-analysis entry point (the megastep rows
        of ``tools/audit_programs.py`` pin its collective census as
        K-independent). Read-only on the trainer, like
        :meth:`lowered_chunk_text`."""
        saved = dict(self.store.tables)
        saved_rt = self.retierer
        try:
            if tick is not None and self.retierer is None:
                self.retierer = tick
            tables, ls = self.init_state(jax.random.key(0))
            tables = self._attach_hot(tables)
            iargs = plan.epoch_args(0)
            ekey = key_to_replicated(
                jax.random.fold_in(jax.random.key(1), 0), self.mesh)
            tick_ops = tick.tick_ops(self) if tick is not None else {}
            fn = self._get_megastep_fn(plan, mode, chunks_per_dispatch,
                                       tick)
            return fn.lower(tables, ls, iargs, np.int32(0), ekey,
                            tick_ops).as_text()
        finally:
            self.store.tables = saved
            self.retierer = saved_rt

    def run_indexed(self, tables, local_state, plan, key, *, epochs: int = 1,
                    on_epoch=None, checkpointer=None,
                    checkpoint_every: int = 0, start_epoch: int = 0,
                    as_numpy: bool = True,
                    rollback: RollbackPolicy | None = None,
                    recorder=None,
                    health: HealthMonitor | None = None,
                    watchdog: StepWatchdog | None = None):
        """Run ``epochs`` full passes with ingest fused into the jit.

        ``plan.sync_every`` must match the trainer's config. Pass a
        ``Checkpointer`` (+ ``checkpoint_every=k`` epochs) to snapshot
        tables and local state every k epochs and once at the end, like
        ``fit_stream`` does per chunk. To resume, restore from the
        checkpointer and pass ``start_epoch=<restored epoch>`` — both the
        per-epoch shuffles (``plan.epoch_args(e)``) and the PRNG stream
        (``fold_in(key, e)``) continue where the interrupted run left off.
        Returns (tables, local_state, per-epoch host metrics list).

        ``as_numpy=False`` returns the metrics as DEVICE arrays without
        blocking on them (no effect when ``on_epoch`` is given — callbacks
        need host values). The call then returns as soon as the last
        epoch is dispatched, letting the caller overlap host work — e.g.
        evaluating epoch ``e``'s metrics while the device races ahead on
        ``e+1`` (speculative epoch pipelining; the per-dispatch +
        metric-sync round trip otherwise serializes between epochs).

        ``rollback`` (a :class:`~fps_tpu.core.resilience.RollbackPolicy`,
        requires ``TrainerConfig.guard``): when an epoch's health channel
        reports poison, restore the pre-epoch state, quarantine the epoch
        (recorded in ``rollback.quarantined``, no metrics entry, no
        checkpoint), and continue — later epochs' shuffles and PRNG keys
        derive from the epoch index, so the streams are unaffected by the
        skip. Forces a per-epoch host metrics sync and an on-device state
        copy per epoch (degradation mode, not a fast path).

        Telemetry (``fps_tpu.obs``): ``recorder`` (default
        ``self.recorder``) records phase timers (dispatch / host_sync /
        checkpoint / callback — ingest is fused into the jit here), epoch
        journal events, and per-table health counters; it never changes
        sync behavior, so attaching one costs only host bookkeeping.
        ``health`` (a :class:`~fps_tpu.obs.HealthMonitor`, requires a
        guard) thresholds the health channel — escalating this trainer's
        guard observe→mask or aborting with PoisonedStreamError — and
        ``watchdog`` (a :class:`~fps_tpu.obs.StepWatchdog`) deadline-flags
        each epoch's dispatch+sync region; both force a per-epoch host
        metrics sync like ``rollback`` does (they must see the values as
        they happen).
        """
        self._check_rollback(rollback)
        self._check_health(health)
        rec = recorder if recorder is not None else self.recorder
        timer = PhaseTimer(rec) if rec is not None else None
        hb = _find_heartbeat(rec)
        # Health-based quarantine needs the guard's health channel; a
        # preset-only policy (guard off) must not pay the per-epoch state
        # copy + forced sync that the health path requires.
        quarantine = (rollback if rollback is not None and
                      resilience.as_guard(self.config.guard) is not None
                      else None)
        sync_each = (quarantine is not None or health is not None
                     or watchdog is not None)
        saved_at = None  # step of the last periodic save (quarantine-aware)
        mode = "sync" if self.config.sync_every is None else "ssp"
        if (self.config.sync_every or None) != (plan.sync_every or None):
            raise ValueError("plan.sync_every must match TrainerConfig")
        T = plan.steps_per_epoch
        T_call = self._indexed_call_steps(plan)
        n_calls = calls_per_epoch_of(plan, T_call)
        all_metrics = []
        end_epoch = start_epoch + epochs
        self._enter_tiering()
        # Two-tier re-split at run entry (restore/warm-start/config
        # changes); per-epoch calls keep the attached structure.
        tables = self._attach_hot(tables, timer)
        try:
            for e in range(start_epoch, end_epoch):
                if rollback is not None and e in rollback.preset:
                    # Quarantined by a previous attempt (supervisor-carried):
                    # consume the index without dispatching — PRNG/shuffle key
                    # off e, so later epochs are unaffected by the skip.
                    rollback.skip(e)
                    if rec is not None:
                        rec.inc("rollback.preset_skipped")
                        rec.flush()
                    continue
                fn = self._get_indexed_fn(plan, mode)
                if quarantine is not None:
                    last_good = (resilience.tree_copy(tables),
                                 resilience.tree_copy(local_state))
                iargs = plan.epoch_args(e)
                parts = []
                restored = None
                _beat(hb, e, "dispatch")
                with _watch(watchdog, "epoch", e):
                    for ci in range(n_calls):
                        ckey = key_to_replicated(
                            jax.random.fold_in(jax.random.fold_in(key, e), ci),
                            self.mesh,
                        )
                        start = np.int32(ci * T_call)
                        with _phase(timer, "dispatch"):
                            tables, local_state, metrics = fn(
                                tables, local_state, iargs, start, ckey
                            )
                        parts.append(metrics)
                    metrics = parts[0] if len(parts) == 1 else jax.tree.map(
                        lambda *xs: jnp.concatenate(xs), *parts
                    )
                    # Drop phantom trailing steps from the last (padded) call so
                    # metrics always have exactly steps_per_epoch rows.
                    if n_calls * T_call > T:
                        metrics = jax.tree.map(lambda x: x[:T], metrics)
                    if quarantine is not None:
                        with _phase(timer, "host_sync"):
                            metrics, restored = self._maybe_quarantine(
                                quarantine, last_good, metrics, e, "epoch"
                            )
                    elif sync_each:
                        with _phase(timer, "host_sync"):
                            metrics = jax.tree.map(np.asarray, metrics)
                ev = {"index": e} if rec is not None else None
                poison = 0
                if sync_each and (rec is not None or health is not None):
                    poison = self._fold_metrics_accounting(rec, metrics, ev)
                if rec is not None:
                    rec.inc("driver.epochs")
                    if restored is not None:
                        rec.inc("rollback.quarantined")
                        ev["quarantined"] = True
                self._apply_health_decision(health, rec, e, poison, "epoch")
                if restored is not None:
                    if rec is not None:
                        rec.event("epoch", phases=timer.chunk_summary(), **ev)
                        rec.flush()
                    tables, local_state = restored
                    continue
                all_metrics.append(metrics)
                # The donated pre-call buffers are dead; repoint the store's
                # host-side view (lookup_host / predict_*_host) at the live
                # arrays BEFORE any callback runs — per-epoch validation via the
                # store is the natural on_epoch pattern, and doing it here also
                # leaves the store consistent if on_epoch raises (early stop).
                self.store.tables = dict(tables)
                if on_epoch is not None:
                    with _phase(timer, "host_sync"):
                        host = jax.tree.map(np.asarray, metrics)
                    if rec is not None and not sync_each:
                        # on_epoch already paid the host sync; fold the same
                        # accounting the forced-sync paths get.
                        self._fold_metrics_accounting(rec, host, ev)
                    all_metrics[-1] = host
                    with _phase(timer, "callback"):
                        on_epoch(e, host)
                if checkpointer is not None and checkpoint_every > 0 and (
                    (e + 1) % checkpoint_every == 0
                ):
                    with _phase(timer, "checkpoint"):
                        self._save_checkpoint(checkpointer, e + 1, local_state)
                    saved_at = e + 1
                if rec is not None:
                    # Emitted AFTER the callback/checkpoint phases so the
                    # epoch event's phase breakdown covers the whole epoch;
                    # flushed per boundary so the Prometheus exposition is
                    # live-scrapable mid-run and a kill loses at most one
                    # epoch of buffered JSONL.
                    rec.event("epoch", phases=timer.chunk_summary(), **ev)
                    rec.flush()
                if self.retierer is not None:
                    # Adaptive-tiering boundary: fold the epoch's sketch
                    # windows, maybe re-rank/re-plan (fps_tpu.tiering).
                    # Quarantined epochs never reach here — their sketch
                    # rolled back with the rest of the aux state.
                    with _phase(timer, "retier"):
                        tables = self.retierer.on_boundary(
                            self, tables, e, recorder=rec)
                    self.store.tables = dict(tables)
            self.store.tables = dict(tables)  # epochs == 0: loop never ran
            # End-of-run save whenever the last epoch's state isn't already on
            # disk — including when a quarantined final epoch skipped its
            # periodic save (the snapshot then holds the rolled-back state
            # under the final step number, so a resume skips the poison).
            if checkpointer is not None and epochs > 0 and saved_at != end_epoch:
                with _phase(timer, "checkpoint"):
                    self._save_checkpoint(checkpointer, end_epoch, local_state)
        finally:
            if checkpointer is not None:
                # Durability barrier: an AsyncCheckpointer's in-flight
                # write must be on disk before the run reports done
                # (no-op for the synchronous base class) — in a finally
                # so accepted saves survive a mid-run abort too.
                with _phase(timer, "checkpoint"):
                    checkpointer.flush()
        if on_epoch is None and as_numpy:
            with _phase(timer, "host_sync"):
                all_metrics = [jax.tree.map(np.asarray, m)
                               for m in all_metrics]
            if rec is not None and not sync_each:
                # Deferred-sync runs still get whole-run health totals and
                # example counts (per-epoch attribution needs a syncing
                # consumer: on_epoch, rollback, health, watchdog).
                for m in all_metrics:
                    self._fold_metrics_accounting(rec, m)
        if rec is not None:
            rec.flush()
        return tables, local_state, all_metrics

    # -- host API ---------------------------------------------------------

    def run_chunk(self, tables, local_state, batches, key, *, timer=None,
                  recorder=None):
        """Run one compiled chunk.

        Args:
          tables: dict of sharded tables (as returned by ``init_state`` /
            previous chunks).
          local_state: worker-local pytree.
          batches: pytree of host arrays with leading dims ``(T, B)`` (sync)
            or ``(R, s, B)`` (ssp) — ``B`` is the *global* batch size,
            divided across all workers.
          key: PRNG key (host scalar).
          timer: optional :class:`fps_tpu.obs.PhaseTimer` — attributes the
            host→device upload to ``place`` and the jitted call (enqueue +
            first-call compile) to ``dispatch``. ``fit_stream`` passes its
            own; standalone callers may too.
          recorder: optional :class:`fps_tpu.obs.Recorder` for the
            cold-route certification counters (default
            ``self.recorder``).

        Returns:
          (tables, local_state, metrics) — metrics leaves have leading dim
          equal to the number of steps in the chunk (global sums per step).
        """
        mode = "sync" if self.config.sync_every is None else "ssp"
        rec = recorder if recorder is not None else self.recorder
        # Two-tier re-split (no-op dict bookkeeping when already attached
        # or untiered): the compiled program's table structure must match
        # the current hot-tier resolution exactly.
        tables = self._attach_hot(tables, timer)
        # Payload-proportional cold routing: certify this chunk against
        # the cold_budget lanes at DISPATCH time (hot membership may have
        # re-ranked since placement) and select the compacted or static
        # program accordingly — the head_prefix pattern, per chunk.
        compact_ok = True
        if self._cold_compact_map():
            if isinstance(batches, PlacedChunk):
                host_ids = batches.host_ids
            elif all(not isinstance(x, jax.Array)
                     for x in jax.tree.leaves(batches)):
                host_ids = self._host_cert_ids(batches)
            else:
                host_ids = None  # device-resident chunk: uncertifiable
            compact_ok, overflowed = self._certify_cold(host_ids)
            if rec is not None:
                if compact_ok:
                    rec.inc("cold_route.compact_chunks")
                else:
                    for t in overflowed:
                        rec.inc("cold_route.overflow_chunks", table=t)
        with _phase(timer, "place"):
            if isinstance(batches, PlacedChunk):
                # The prefetch pipeline already ran _place_chunk on its
                # worker thread — same function, same sharded arrays.
                batches = batches.batches
            else:
                batches = self._place_chunk(batches, mode)
            key = key_to_replicated(key, self.mesh)
        with _phase(timer, "dispatch"):
            tables, local_state, metrics = self._get_compiled(
                mode, compact_ok)(
                tables, local_state, batches, key
            )
        # The donated input buffers are dead now; keep the store's host-side
        # view (lookup_host / dump_model — the reference's model-out stream)
        # pointed at the live arrays.
        self.store.tables = dict(tables)
        return tables, local_state, metrics

    def _batch_sharding_for(self, mode):
        nlead = 1 if mode == "sync" else 2
        spec = P(*([None] * nlead), WORKER_AXES)
        return NamedSharding(self.mesh, spec)

    def _place_chunk(self, batches, mode: str | None = None):
        """Place one chunk's batches onto the batch sharding — the
        host→device upload both the synchronous path (run_chunk) and the
        background pipeline's worker thread run, so prefetch on/off
        produces byte-identical device inputs by construction."""
        if mode is None:
            mode = "sync" if self.config.sync_every is None else "ssp"
        sharding = self._batch_sharding_for(mode)

        def place(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                # Device-ingest chunks are already global arrays on the
                # mesh (multi-controller); leave them where they are.
                return x
            return host_to_sharded(x, sharding)

        return jax.tree.map(place, batches)

    def fit_stream(
        self,
        tables,
        local_state,
        chunks: Iterable[Pytree],
        key: Array,
        metrics_reduce=None,
        checkpointer=None,
        checkpoint_every: int = 0,
        start_step: int = 0,
        on_chunk=None,
        rollback: RollbackPolicy | None = None,
        recorder=None,
        health: HealthMonitor | None = None,
        watchdog: StepWatchdog | None = None,
    ):
        """Drive the compiled loop over a host-side stream of chunks.

        This is the ingest loop that replaces the Flink DataStream source —
        one-pass streaming (the reference's model) or multi-epoch, depending
        on what the iterator yields.

        Pass a ``fps_tpu.core.checkpoint.Checkpointer`` plus
        ``checkpoint_every=k`` to snapshot tables + local state every k
        chunks (and once more at the end of the stream). To resume, restore
        from the checkpointer and pass ``start_step=<restored step>`` with a
        chunk iterator positioned after the already-consumed chunks — both
        the per-chunk PRNG stream (``fold_in(key, step)``) and the snapshot
        numbering continue where the interrupted run left off.

        ``on_chunk(step, metrics)`` is called after every chunk with the
        host-side metrics pytree — the live tap on the reference's ``WOut``
        observability stream (per-chunk progress reporting, early stopping
        via raising, etc.). When no ``on_chunk`` is given, metrics stay on
        device until the stream ends so the host never blocks mid-stream
        and chunk dispatch pipelines (device-resident ingest then runs the
        whole epoch without a single host↔device round trip).

        ``rollback`` (a :class:`~fps_tpu.core.resilience.RollbackPolicy`,
        requires ``TrainerConfig.guard``): when a chunk's health channel
        reports poison, restore the state captured just before that chunk,
        quarantine it (recorded in ``rollback.quarantined``, no metrics
        entry, no checkpoint), and continue — the per-chunk PRNG stream
        keys off the chunk index, so later chunks are unaffected by the
        skip. Forces a per-chunk host metrics sync and an on-device state
        copy per chunk (degradation mode, not a fast path).

        Telemetry (``fps_tpu.obs``): ``recorder`` (default
        ``self.recorder``) times each chunk's phases (ingest / place /
        dispatch / host_sync / checkpoint / callback), journals chunk
        events, and folds the health channel into per-table counters. It
        never forces extra host syncs — phases cover whatever blocking the
        loop already does, so a recorder costs only host bookkeeping.
        ``health`` (a :class:`~fps_tpu.obs.HealthMonitor`, requires a
        guard) thresholds the health channel: escalate this trainer's
        guard observe→mask after N poisoned rows, abort (raising
        PoisonedStreamError) after M poisoned chunks. ``watchdog`` (a
        :class:`~fps_tpu.obs.StepWatchdog`) deadline-flags each chunk's
        dispatch+sync region — the straggler tripwire. Health and
        watchdog (like ``rollback``) force a per-chunk host metrics sync:
        they must observe values as they happen.

        Host pipeline (``TrainerConfig``, ``docs/performance.md``):
        ``prefetch=N`` moves chunk assembly + placement onto a background
        worker running N chunks ahead (:mod:`fps_tpu.core.prefetch`) —
        numerics, chunk order, and the compiled program are identical;
        every exit path joins the worker. ``health_lag=1`` makes the
        forced sync the syncing consumers above require lag-by-one:
        chunk ``i-1``'s host metrics are inspected while chunk ``i``
        computes (a quarantined ``i-1`` restores its pre-chunk snapshot
        and chunk ``i`` is deterministically recomputed from it, so
        guard/quarantine results match ``health_lag=0`` bit for bit;
        consumers — and ``on_chunk``/store readers — see state one chunk
        late). Two lag caveats: a HealthMonitor's observe→mask
        escalation lands one DISPATCH later than at lag 0 (a run where
        escalation fires mid-stream is not bit-identical across lag
        settings — one more chunk runs unmasked), and journal chunk
        events attribute concurrently-running phase segments to the
        adjudication boundary, so chunk ``i-1``'s event carries chunk
        ``i``'s dispatch time (overlap makes per-chunk attribution
        inherently fuzzy; run-level phase totals stay exact). With
        either knob on, boundary checkpoints dump from on-device copies
        taken at the boundary and run after the next dispatch, so the
        stream no longer stalls on the device→host ``jax.device_get``
        (the crash window grows by at most one chunk; the end-of-stream
        flush is unchanged).
        """
        self._check_rollback(rollback)
        self._check_health(health)
        cfg = self.config
        if cfg.prefetch < 0:
            raise ValueError(
                f"TrainerConfig.prefetch must be >= 0, got {cfg.prefetch}")
        if cfg.health_lag not in (0, 1):
            raise ValueError(
                f"TrainerConfig.health_lag must be 0 or 1, got "
                f"{cfg.health_lag}")
        if cfg.metrics_drain_every < 0:
            raise ValueError(
                f"TrainerConfig.metrics_drain_every must be >= 0, got "
                f"{cfg.metrics_drain_every}")
        rec = recorder if recorder is not None else self.recorder
        timer = PhaseTimer(rec) if rec is not None else None
        hb = _find_heartbeat(rec)
        # Health-based quarantine needs the guard's health channel; a
        # preset-only policy (guard off) must not pay the per-chunk state
        # copy + forced sync that the health path requires.
        quarantine = (rollback if rollback is not None and
                      resilience.as_guard(cfg.guard) is not None
                      else None)
        sync_each = (quarantine is not None or health is not None
                     or watchdog is not None)
        # Lag-by-one control plane: only meaningful when something forces
        # a per-chunk sync in the first place.
        lag = 1 if (cfg.health_lag and sync_each) else 0
        # Overlapped checkpoint dump: with the pipeline on, boundary saves
        # run from on-device boundary copies after the NEXT dispatch;
        # otherwise the save stays inline at the boundary (legacy timing:
        # crash window of at most one chunk).
        overlap_ckpt = (checkpointer is not None and checkpoint_every > 0
                        and (cfg.prefetch > 0 or lag > 0))
        saved_at = None  # step of the last periodic save (quarantine-aware)
        all_metrics = []
        # Delta-snapshot sourcing (DeltaPolicy on the checkpointer): the
        # tracker accumulates each dispatched chunk's pulled-id stream
        # (WorkerLogic.pulled_ids_host — the same exact host stream the
        # cold-route certifier consumes) so every save can publish a
        # row-sparse delta whose touched set is O(traffic), not
        # O(table). Uncertifiable chunks degrade that table to the
        # checkpointer's exact-diff fallback, never to corruption.
        delta_touched = None
        if (checkpointer is not None and checkpoint_every > 0
                and getattr(checkpointer, "delta_policy", None) is not None):
            from fps_tpu.core.checkpoint import TouchedRowsTracker

            delta_touched = TouchedRowsTracker(self.store.specs)

        def capture_touched():
            if delta_touched is None:
                return None
            ids, marker = delta_touched.capture()
            return (ids, marker, delta_touched)

        def chunk_touched_ids(c):
            if isinstance(c, PlacedChunk):
                return c.host_ids
            if any(isinstance(x, jax.Array)
                   for x in jax.tree.leaves(c)):
                # Device-resident chunk: pulling the id columns back to
                # host per chunk would reintroduce the dispatch-time
                # stall (and raises outright on non-addressable sharded
                # arrays) — same guard as the cold-route certifier.
                # None = the exact-diff fallback at save time.
                return None
            return self.logic.pulled_ids_host(c)

        it = iter(chunks)
        pf = None
        if cfg.prefetch:
            mode = "sync" if cfg.sync_every is None else "ssp"

            def _place_for_pf(b, _m=mode):
                # Placement on the worker thread, but retain the raw id
                # columns the cold-route certifier needs: certification
                # itself runs at dispatch (hot membership can re-rank
                # between placement and dispatch). With delta tracking
                # on, the same capture feeds the touched-rows tracker.
                ids = self._host_cert_ids(b)
                if ids is None and delta_touched is not None:
                    ids = self.logic.pulled_ids_host(b)
                return PlacedChunk(self._place_chunk(b, _m),
                                   host_ids=ids)

            pf = ChunkPrefetcher(
                it, _place_for_pf,
                depth=cfg.prefetch,
                max_depth=cfg.prefetch_max or None,
                recorder=rec, timer=timer,
                start_index=start_step,
                # Preset-quarantined chunks are consumed but never
                # dispatched — don't pay their host→device upload.
                skip_place=(rollback.preset if rollback is not None
                            else frozenset()),
            )
            it = pf
        i = start_step - 1
        pending = None       # lag-by-one: one dispatched, unadjudicated chunk
        pending_save = None  # deferred (overlapped) boundary snapshot
        self._enter_tiering()
        # Two-tier re-split at stream entry; run_chunk keeps the attached
        # structure live across the loop.
        tables = self._attach_hot(tables, timer)

        def retier_boundary(j):
            """Adaptive-tiering boundary for an adjudicated-clean chunk:
            fold sketch windows, maybe re-rank/re-plan (fps_tpu.tiering).
            Quarantined chunks skip it — their sketch window rolled back
            with the rest of the aux state. Under health_lag=1 this runs
            at chunk j's ADJUDICATION (one dispatch late, like every
            other lag consumer), so re-rank decisions see one extra
            chunk of traffic relative to lag 0."""
            nonlocal tables
            if self.retierer is None:
                return
            with _phase(timer, "retier"):
                tables = self.retierer.on_boundary(
                    self, tables, j, recorder=rec)
            self.store.tables = dict(tables)

        def save_due(j):
            return (checkpointer is not None and checkpoint_every > 0
                    and (j + 1) % checkpoint_every == 0)

        def boundary_copy(j):
            """Post-chunk-``j`` state as fresh on-device buffers (futures —
            no host block): the double-buffered snapshot the overlapped
            dump writes from after the next dispatch. The touched-rows
            capture rides along — it must describe the SAME boundary as
            the copied state, not whatever the tracker holds when the
            deferred write finally runs."""
            return (j + 1, resilience.tree_copy(tables),
                    resilience.tree_copy(local_state), capture_touched())

        def flush_save():
            """Write the deferred boundary snapshot (when set, always a
            clean, already-adjudicated boundary)."""
            nonlocal pending_save, saved_at
            if pending_save is None:
                return
            step, tb, lsd, tc = pending_save
            pending_save = None
            with _phase(timer, "checkpoint"):
                self._save_checkpoint(checkpointer, step, lsd, tables=tb,
                                      touched=tc)
            saved_at = step

        def sync_entry(entry):
            """Forced host sync for one dispatched chunk; on poison,
            _maybe_quarantine repoints the STORE at the restored state —
            the loop's tables/local_state are swapped by account_entry.
            Returns (metrics, restored_or_None)."""
            metrics = entry["metrics"]
            restored = None
            if quarantine is not None:
                with _phase(timer, "host_sync"):
                    metrics, restored = self._maybe_quarantine(
                        quarantine, entry["last_good"], metrics,
                        entry["index"], "chunk"
                    )
            elif sync_each:
                with _phase(timer, "host_sync"):
                    metrics = jax.tree.map(np.asarray, metrics)
            return metrics, restored

        def account_entry(entry, metrics, restored):
            """Accounting, callbacks, and boundary checkpoint for one
            adjudicated chunk; returns True when it was quarantined (the
            state is then already restored)."""
            nonlocal tables, local_state, pending_save, saved_at
            j = entry["index"]
            ev = {"index": j} if rec is not None else None
            poison = 0
            if sync_each and (rec is not None or health is not None):
                poison = self._fold_metrics_accounting(rec, metrics, ev)
            if rec is not None:
                rec.inc("driver.chunks")
                if restored is not None:
                    rec.inc("rollback.quarantined")
                    ev["quarantined"] = True
            self._apply_health_decision(health, rec, j, poison, "chunk")
            if restored is not None:
                if rec is not None:
                    rec.event("chunk", phases=timer.chunk_summary(), **ev)
                    rec.flush()
                if (self.retierer is not None
                        and entry.get("retier_state") is not None):
                    # The tracker rolls back WITH the tables: under
                    # health_lag=1 the restored aux entries predate the
                    # previous boundary's fold/re-rank, and a tracker
                    # that kept the newer hot_ids/tick would
                    # desynchronize from the ::hotids the program
                    # carries (the un-folded traffic still sits in the
                    # restored ::sketch window, so nothing is lost).
                    self.retierer.restore_snapshot(entry["retier_state"])
                tables, local_state = restored
                return True
            if on_chunk is not None:
                with _phase(timer, "host_sync"):
                    host_metrics = jax.tree.map(np.asarray, metrics)
                if rec is not None and not sync_each:
                    # on_chunk already paid the host sync; give the chunk
                    # event the same accounting the forced-sync paths get.
                    self._fold_metrics_accounting(rec, host_metrics, ev)
                all_metrics.append(host_metrics)
                with _phase(timer, "callback"):
                    on_chunk(j, host_metrics)
            else:
                # Deferred conversion keeps the dispatch pipeline full, but
                # an unbounded stream must not accumulate device buffers (or
                # run the host arbitrarily far ahead of the device): drain
                # to host every metrics_drain_every chunks (0 = never).
                all_metrics.append(metrics)
                de = cfg.metrics_drain_every
                if de and (j - start_step) % de == de - 1:
                    with _phase(timer, "host_sync"):
                        all_metrics[-de:] = [
                            jax.tree.map(np.asarray, m)
                            for m in all_metrics[-de:]
                        ]
            if save_due(j):
                if entry.get("save") is not None:
                    # Lag path: boundary copies were captured at dispatch
                    # time (the live tables have moved on since).
                    pending_save = entry["save"]
                    flush_save()
                elif overlap_ckpt:
                    # Immediate-adjudication path: capture now, write after
                    # the next dispatch — the dump's device_get then waits
                    # alongside device compute instead of in front of it.
                    pending_save = boundary_copy(j)
                else:
                    with _phase(timer, "checkpoint"):
                        self._save_checkpoint(checkpointer, j + 1,
                                              local_state,
                                              touched=capture_touched())
                    saved_at = j + 1
            if rec is not None:
                # Emitted AFTER the checkpoint/callback phases so the
                # chunk event's phase breakdown covers the whole chunk;
                # flushed per boundary so the Prometheus exposition is
                # live-scrapable mid-run and a kill loses at most one
                # chunk of buffered JSONL.
                rec.event("chunk", phases=timer.chunk_summary(), **ev)
                rec.flush()
            return False

        try:
            while True:
                with _phase(timer, "ingest"):
                    _beat(hb, i + 1, "prefetch" if pf is not None
                          else "ingest")
                    chunk = next(it, _STREAM_END)
                if chunk is _STREAM_END:
                    break
                i += 1
                if rollback is not None and i in rollback.preset:
                    # Quarantined by a previous attempt (supervisor-carried):
                    # the chunk is consumed but never dispatched — the per-
                    # chunk PRNG keys off i, so later chunks are unaffected.
                    rollback.skip(i)
                    if rec is not None:
                        rec.inc("rollback.preset_skipped")
                        rec.flush()
                    continue
                if delta_touched is not None:
                    # Every DISPATCHED chunk's pulled ids feed the delta
                    # tracker (a quarantined chunk's ids are a harmless
                    # superset — its rows revert to pre-chunk values).
                    delta_touched.observe(chunk_touched_ids(chunk))
                if quarantine is not None:
                    last_good = (resilience.tree_copy(tables),
                                 resilience.tree_copy(local_state))
                    rt_snap = (self.retierer.snapshot()
                               if self.retierer is not None else None)
                else:
                    last_good = None
                    rt_snap = None
                ckey = jax.random.fold_in(key, i)
                _beat(hb, i, "dispatch")
                if lag:
                    prev, pending = pending, None
                    with _watch(watchdog, "chunk", i):
                        tables, local_state, metrics = self.run_chunk(
                            tables, local_state, chunk, ckey, timer=timer,
                            recorder=rec,
                        )
                        save = boundary_copy(i) if save_due(i) else None
                        # Adjudicate chunk i-1 NOW — its host sync waits
                        # while the device is already busy with chunk i.
                        pmetrics = prestored = None
                        if prev is not None:
                            pmetrics, prestored = sync_entry(prev)
                    if prev is not None:
                        if account_entry(prev, pmetrics, prestored):
                            # prev was poisoned and the pre-prev snapshot
                            # is restored — chunk i ran on poisoned
                            # state, so recompute it deterministically
                            # (same chunk, same key) from the restored
                            # state: exactly what the lag-0 path would
                            # have dispatched.
                            if quarantine is not None:
                                last_good = (
                                    resilience.tree_copy(tables),
                                    resilience.tree_copy(local_state))
                                rt_snap = (self.retierer.snapshot()
                                           if self.retierer is not None
                                           else None)
                            with _watch(watchdog, "chunk", i):
                                tables, local_state, metrics = (
                                    self.run_chunk(tables, local_state,
                                                   chunk, ckey,
                                                   timer=timer,
                                                   recorder=rec))
                            save = boundary_copy(i) if save_due(i) else None
                        else:
                            retier_boundary(prev["index"])
                    pending = {"index": i, "metrics": metrics,
                               "last_good": last_good, "save": save,
                               "retier_state": rt_snap}
                else:
                    with _watch(watchdog, "chunk", i):
                        tables, local_state, metrics = self.run_chunk(
                            tables, local_state, chunk, ckey, timer=timer,
                            recorder=rec,
                        )
                        entry = {"index": i, "metrics": metrics,
                                 "last_good": last_good, "save": None,
                                 "retier_state": rt_snap}
                        metrics, restored = sync_entry(entry)
                    flush_save()  # previous boundary's deferred dump —
                    # overlapped: the device is already past that boundary
                    if not account_entry(entry, metrics, restored):
                        retier_boundary(i)
            # Lag-by-one: the final chunk is still unadjudicated. Its
            # forced sync keeps watchdog coverage, like every other sync.
            if pending is not None:
                prev, pending = pending, None
                with _watch(watchdog, "chunk", prev["index"]):
                    pmetrics, prestored = sync_entry(prev)
                if not account_entry(prev, pmetrics, prestored):
                    retier_boundary(prev["index"])
            flush_save()
            # End-of-stream save whenever the last chunk's state isn't already
            # on disk — including when a quarantined final chunk skipped its
            # periodic save (the snapshot then holds the rolled-back state
            # under the final step number, so a resume skips the poison).
            if checkpointer is not None and i >= start_step and saved_at != i + 1:
                with _phase(timer, "checkpoint"):
                    self._save_checkpoint(checkpointer, i + 1, local_state,
                                          touched=capture_touched(),
                                          final=True)
        finally:
            if pf is not None:
                # Every exit path — normal end, raising on_chunk, health
                # abort, quarantine-budget abort — joins the prefetch
                # worker; no thread leaks (tested).
                pf.close()
            if checkpointer is not None:
                try:
                    # A clean, accepted boundary snapshot must not vanish
                    # just because the stream aborted before its deferred
                    # dump ran (the inline path would already have it on
                    # disk). Best-effort: teardown must not mask the
                    # original exception.
                    flush_save()
                except Exception:
                    _log.exception(
                        "deferred checkpoint dump failed during stream "
                        "teardown")
                # Durability barrier: an AsyncCheckpointer's in-flight
                # write must be on disk before the stream reports done
                # (no-op for the synchronous base class) — in a finally
                # so accepted (journaled checkpoint_enqueued) saves are
                # never silently dropped when the run dies mid-stream
                # (health abort, early-stop callback raise, ...).
                with _phase(timer, "checkpoint"):
                    checkpointer.flush()
        if on_chunk is None:
            with _phase(timer, "host_sync"):
                all_metrics = [jax.tree.map(np.asarray, m)
                               for m in all_metrics]
            if rec is not None and not sync_each:
                # Deferred-sync streams still get whole-run health totals
                # and example counts (per-chunk attribution needs a
                # syncing consumer: on_chunk, rollback, health, watchdog).
                for m in all_metrics:
                    self._fold_metrics_accounting(rec, m)
        if rec is not None:
            rec.flush()
        if metrics_reduce is not None and all_metrics:
            return tables, local_state, metrics_reduce(all_metrics)
        return tables, local_state, all_metrics
